"""Round benchmark — prints ONE JSON line for the driver.

Workload: synthetic Higgs-shaped binary classification (28 dense features,
255 bins, 255 leaves — the `docs/Experiments.rst:104-116` configuration) at
TWO scales in one run:

  * 1M rows  — the steady-state headline (``value``/``vs_baseline``);
  * 10.5M rows — the reference's REAL Higgs row count, reported under
    ``value_10p5m``/``vs_baseline_10p5m`` so the scale ratio is
    driver-captured every round (round-4 verdict: no perf number may live
    only in PROFILE.md prose).

Metric: boosting iterations/second, steady-state (compile excluded).

Baseline: the reference's 28-core CPU Higgs number — 500 iterations over
10.5M rows in 238.5 s (`docs/Experiments.rst:106`) = 2.10 iters/s.  Histogram
work scales linearly in rows, so at R rows the equivalent reference
throughput is 2.10 × 10.5e6/R; ``vs_baseline`` is ours divided by that.
(BASELINE.json's target is ≥5× a single socket; the table's machine is a
dual socket, so parity with 22.0 at 1M ≈ 2× the single-socket bar.)

Usage: ``python bench.py``          — both scales, one JSON line.
       ``python bench.py ROWS [IT]`` — one scale (profiling convenience).
       ``--telemetry-out PATH``      — train with ``telemetry=True`` and
       write the per-scale JSON telemetry reports (phase timings, wave /
       stall counters, collective accounting — observability/schema.json)
       next to the headline metric, so BENCH_r*.json rounds carry phase
       breakdowns.
       ``--tree-learner MODE``       — parallel-mode passthrough
       (serial/data/feature/voting/data_feature) so the driver captures
       per-mode JSON lines without editing this script; recorded in the
       ``metric`` string.
       ``--parallel-mesh SHAPE``     — mesh-shape passthrough ("8", "2x4";
       data×feature for data_feature).
       ``--quantized-grad MODE``     — ``tpu_quantized_grad`` passthrough
       (on/off/auto) so quantized-vs-f32 A/B legs land as driver-captured
       JSON lines (BENCH_r08); recorded in the ``metric`` string.
       ``--num-hosts N --coordinator HOST:PORT --process-id R`` —
       multi-host passthrough (`parallel/multihost.py`): the same bench
       command runs on every pod host (only ``--process-id`` differs), the
       mesh spans processes, and the host layout lands in the ``metric``
       string.  ``--parallel-mesh`` should put the host count on the data
       axis ("2x4" on 2 hosts x 4 local devices).
       ``--out-of-core``             — write the synthetic problem to disk
       once and ingest it through the streaming two-pass loader
       (``two_round=true``, `dataset.py:from_stream`) instead of from
       memory, so loader-path regressions show up in bench rounds.
       ``--sync-every N``            — sampled-sync cadence
       (``telemetry_sync_every``; defaults to 8 whenever telemetry is on):
       every Nth iteration is bracketed with forced device syncs and the
       per-leg runtime attribution table + rank-skew gauges are embedded
       in the JSON line itself (``attribution`` / ``rank_skew`` keys), so
       BENCH rounds carry the collective/phase attribution evidence
       inline (observability/attribution.py).
"""

import gc
import json
import sys
import time


import numpy as np


def run_scale(rows: int, iters: int, warmup: int = 2,
              telemetry: bool = False, extra_params: dict = None,
              out_of_core: bool = False):
    """Train steady-state iterations at one scale; returns
    (iters/sec, telemetry report or None)."""
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(7)
    f = 28
    X = rng.randn(rows, f).astype(np.float64)
    logit = (X[:, 0] * 1.5 + X[:, 1] * X[:, 2] * 0.5 + np.sin(X[:, 3])
             + 0.5 * rng.randn(rows))
    y = (logit > 0).astype(np.float64)

    params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
              "learning_rate": 0.1, "min_data_in_leaf": 20,
              "verbosity": -1, "metric": "none", "telemetry": telemetry}
    if extra_params:
        params.update(extra_params)
    if out_of_core:
        # spill the problem to disk, ingest through the streaming loader
        import os
        import tempfile

        path = os.path.join(tempfile.mkdtemp(prefix="bench_ooc_"),
                            "train.csv")
        np.savetxt(path, np.column_stack([y, X]), delimiter=",",
                   fmt="%.17g")
        del X, y
        gc.collect()
        params["two_round"] = True
        ds = lgb.Dataset(path, params=params)
    else:
        ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params, ds)

    # the boosting loop is async (device-resident score updates, lazy host
    # tree assembly) — and `jax.block_until_ready` is a NO-OP on the axon
    # tunnel, so force completion with a real (tiny) device->host fetch
    sync = lambda: float(np.asarray(bst.gbdt.train_score.score[0, 0]))

    for _ in range(warmup):  # compile + cache
        bst.update()
    sync()
    t0 = time.time()
    for _ in range(iters):
        bst.update()
    sync()
    dt = time.time() - t0
    report = bst.gbdt.get_telemetry() if telemetry else None
    del bst, ds  # release device buffers before the next scale
    if not out_of_core:
        del X, y
    gc.collect()
    return iters / dt, report


def ref_ips(rows: int) -> float:
    return (500.0 / 238.5) * (10.5e6 / rows)  # reference CPU, row-scaled


def _pop_opt_arg(argv, flag):
    """Extract ``--flag VALUE`` / ``--flag=VALUE`` from an argv list."""
    out = None
    rest = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith(flag):
            if "=" in a:
                out = a.split("=", 1)[1]
            elif i + 1 < len(argv):
                i += 1
                out = argv[i]
        else:
            rest.append(a)
        i += 1
    return out, rest


def _pop_flag(argv, flag):
    """Extract a valueless ``--flag`` from an argv list."""
    return flag in argv, [a for a in argv if a != flag]


def main():
    telemetry_out, argv = _pop_opt_arg(sys.argv[1:], "--telemetry-out")
    tree_learner, argv = _pop_opt_arg(argv, "--tree-learner")
    parallel_mesh, argv = _pop_opt_arg(argv, "--parallel-mesh")
    quantized, argv = _pop_opt_arg(argv, "--quantized-grad")
    num_hosts, argv = _pop_opt_arg(argv, "--num-hosts")
    coordinator, argv = _pop_opt_arg(argv, "--coordinator")
    process_id, argv = _pop_opt_arg(argv, "--process-id")
    out_of_core, argv = _pop_flag(argv, "--out-of-core")
    sync_every, argv = _pop_opt_arg(argv, "--sync-every")
    telem = telemetry_out is not None
    extra = {}
    mode_tag = ""
    if telem:
        # sampled-sync attribution on by default for telemetry benches:
        # 1-in-8 iterations pays the sync, the rest stay pipelined
        extra["telemetry_sync_every"] = int(sync_every) if sync_every else 8
        if sync_every:
            mode_tag += f", sync_every={sync_every}"
    if tree_learner:
        extra["tree_learner"] = tree_learner
        mode_tag = f", tree_learner={tree_learner}"
    if parallel_mesh:
        extra["parallel_mesh"] = parallel_mesh
        mode_tag += f", mesh={parallel_mesh}"
    if quantized:
        extra["tpu_quantized_grad"] = quantized
        mode_tag += f", quantized_grad={quantized}"
    if num_hosts or coordinator or process_id:
        # multi-host passthrough: the same command runs on every pod host;
        # resolve_multihost rejects a partial spec loudly rather than
        # silently benching single-host
        if coordinator:
            extra["coordinator_address"] = coordinator
        if num_hosts:
            extra["num_hosts"] = int(num_hosts)
        if process_id is not None:
            extra["process_id"] = int(process_id)
        mode_tag += (f", hosts={num_hosts or '?'}"
                     f", host_rank={process_id or '?'}")
    if out_of_core:
        mode_tag += ", out_of_core"
    reports = {}
    if argv:  # single-scale profiling mode
        rows = int(argv[0])
        iters = int(argv[1]) if len(argv) > 1 else 10
        ips, rep = run_scale(rows, iters, telemetry=telem,
                             extra_params=extra, out_of_core=out_of_core)
        if rep is not None:
            reports[str(rows)] = rep
        line = {
            "metric": f"boosting iters/sec (synthetic Higgs-like {rows}x28, "
                      f"255 leaves, 255 bins{mode_tag})",
            "value": round(ips, 4),
            "unit": "iters/sec",
            "vs_baseline": round(ips / ref_ips(rows), 4),
        }
    else:
        # the reference's Higgs number times 500 iterations end-to-end; the
        # axon tunnel's flat ~105 ms device->host sync lands ONCE per timed
        # loop, so more steady-state iterations = closer to the reference's
        # methodology (at 10 iters the artifact alone was ~10.5 ms/iter, ~8%)
        ips_1m, rep_1m = run_scale(1_000_000, 30, telemetry=telem,
                                   extra_params=extra,
                                   out_of_core=out_of_core)
        ips_full, rep_full = run_scale(10_500_000, 6, telemetry=telem,
                                       extra_params=extra,
                                       out_of_core=out_of_core)
        if rep_1m is not None:
            reports["1000000"] = rep_1m
            reports["10500000"] = rep_full
        line = {
            "metric": f"boosting iters/sec (synthetic Higgs-like 1Mx28, "
                      f"255 leaves, 255 bins; _10p5m = reference row count"
                      f"{mode_tag})",
            "value": round(ips_1m, 4),
            "unit": "iters/sec",
            "vs_baseline": round(ips_1m / ref_ips(1_000_000), 4),
            "value_10p5m": round(ips_full, 4),
            "vs_baseline_10p5m": round(ips_full / ref_ips(10_500_000), 4),
        }
    if telem:
        from lightgbm_tpu.observability import validate_report
        for rep in reports.values():
            assert "provenance" in rep, \
                "telemetry report lost its provenance block (schema v7)"
            # schema v11: the perf artifact must name the exact cost
            # ledger (analysis/costs.json sha256) it was gated against
            assert "cost_ledger_sha256" in rep["provenance"], \
                "telemetry provenance lost cost_ledger_sha256 (schema v11)"
            errs = validate_report(rep)
            assert not errs, errs
        with open(telemetry_out, "w") as fh:
            json.dump(reports, fh, indent=2, sort_keys=True)
            fh.write("\n")
        line["telemetry_out"] = telemetry_out
        # the runtime attribution table + rank-skew gauges ride the
        # driver-captured line itself (round-4 verdict: no perf evidence
        # may live only in a side file)
        attribution = {}
        rank_skew = {}
        for scale, rep in reports.items():
            dist = rep.get("distributed", {})
            if dist.get("attribution"):
                attribution[scale] = dist["attribution"]
            if dist.get("skew_ratio") is not None:
                rank_skew[scale] = {
                    "skew_ratio": dist["skew_ratio"],
                    "slowest_rank": dist.get("slowest_rank")}
        if attribution:
            line["attribution"] = attribution
        if rank_skew:
            line["rank_skew"] = rank_skew
    print(json.dumps(line))


if __name__ == "__main__":
    main()
