"""Round benchmark — prints ONE JSON line for the driver.

Workload: synthetic Higgs-shaped binary classification (28 dense features,
255 bins, 255 leaves — the `docs/Experiments.rst:104-116` configuration) at
1M rows.  Metric: boosting iterations/second, steady-state (compile excluded).

Baseline: the reference's 28-core CPU Higgs number — 500 iterations over
10.5M rows in 238.5 s (`docs/Experiments.rst:106`) = 0.477 s/iter.  Histogram
work scales linearly in rows, so at this benchmark's 1M rows the equivalent
reference throughput is 500/238.5 × 10.5 ≈ 22.0 iters/s; ``vs_baseline`` is
ours divided by that.  (BASELINE.json's target is ≥5× a single socket; the
table's machine is a dual socket, so parity with 22.0 ≈ 2× the single-socket
bar.)
"""

import json
import sys
import time

import numpy as np


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    warmup = 2

    import lightgbm_tpu as lgb

    rng = np.random.RandomState(7)
    f = 28
    X = rng.randn(rows, f).astype(np.float64)
    logit = (X[:, 0] * 1.5 + X[:, 1] * X[:, 2] * 0.5 + np.sin(X[:, 3])
             + 0.5 * rng.randn(rows))
    y = (logit > 0).astype(np.float64)

    params = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
              "learning_rate": 0.1, "min_data_in_leaf": 20,
              "verbosity": -1, "metric": "none"}
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params, ds)

    # the boosting loop is async (device-resident score updates, lazy host
    # tree assembly) — and `jax.block_until_ready` is a NO-OP on the axon
    # tunnel, so force completion with a real (tiny) device->host fetch
    sync = lambda: float(np.asarray(bst.gbdt.train_score.score[0, 0]))

    for _ in range(warmup):  # compile + cache
        bst.update()
    sync()
    t0 = time.time()
    for _ in range(iters):
        bst.update()
    sync()
    dt = time.time() - t0

    ips = iters / dt
    ref_equiv = (500.0 / 238.5) * (10.5e6 / rows)  # reference CPU, row-scaled
    print(json.dumps({
        "metric": f"boosting iters/sec (synthetic Higgs-like {rows}x{f}, "
                  f"255 leaves, 255 bins)",
        "value": round(ips, 4),
        "unit": "iters/sec",
        "vs_baseline": round(ips / ref_equiv, 4),
    }))


if __name__ == "__main__":
    main()
