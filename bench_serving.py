"""Serving load generator — closed + open loop, one BENCH_SERVING JSON.

The training side has had trajectory discipline since round 1: every perf
claim moves `bench.py`'s JSON line and lands in a ``BENCH_r*.json``.  This
is the same arbiter for the serving path (ROADMAP item 3 "measured like a
service"): an in-process ``PredictionServer`` is driven by

  * a **closed loop** — N client threads, each issuing sequential
    predicts; measures the latency the service delivers when clients wait
    for responses (throughput ∝ clients / latency), and
  * an **open loop** — requests fired on a fixed schedule at a target
    QPS regardless of completions (the honest arrival model for external
    traffic).  Latency is measured from the request's SCHEDULED send time,
    so coordinated omission is counted, not hidden; sheds
    (``ServerOverloaded``) and errors are tallied separately.

Both loops record exact p50/p95/p99 (``observability.LatencyHistogram``),
and the server's own stats supply batch occupancy and compile-cache
counts.  The output validates against
``observability.BENCH_SERVING_SCHEMA`` and is written atomically.

Usage:
  python bench_serving.py                         # defaults, writes
                                                  # BENCH_SERVING_r01.json
  python bench_serving.py --out F.json --round 2 --clients 8 \
      --requests 800 --qps 200 --open-seconds 5 --rows-per-request 8
  python bench_serving.py --model model.txt       # serve an existing model
  python bench_serving.py --trace-out trace.json  # capture spans too
  python bench_serving.py --replicas 4 --protocol binary   # fleet gateway
  python bench_serving.py --compare --out BENCH_SERVING_r02.json --round 2
      # pickle-vs-binary x 1-vs-N replica legs (headline = binary + N)

Tiny smoke (CI): --train-rows 2000 --trees 5 --requests 40 --qps 40
--open-seconds 1.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


def build_booster(args):
    import lightgbm_tpu as lgb

    if args.model:
        return lgb.Booster(model_file=args.model)
    rng = np.random.RandomState(11)
    n, f = args.train_rows, args.num_features
    X = rng.randn(n, f)
    logit = X[:, 0] * 1.5 + X[:, 1] * X[:, 2 % f] * 0.5 + 0.3 * rng.randn(n)
    y = (logit > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 31, "max_bin": 255,
              "learning_rate": 0.1, "min_data_in_leaf": 20,
              "verbosity": -1, "metric": "none"}
    return lgb.train(params, lgb.Dataset(X, label=y), args.trees)


def _request_matrix(rng: np.random.RandomState, rows: int,
                    f: int) -> np.ndarray:
    return rng.randn(rows, f)


class _LoopStats:
    """Latency + outcome accounting for one load phase (thread-safe)."""

    def __init__(self):
        from lightgbm_tpu.observability import LatencyHistogram
        self.hist = LatencyHistogram()
        self._lock = threading.Lock()
        self.ok = 0
        self.shed = 0
        self.errors = 0

    def done(self, latency_ms: float, outcome: str) -> None:
        self.hist.record(latency_ms)
        with self._lock:
            setattr(self, outcome, getattr(self, outcome) + 1)

    def section(self, duration_s: float, **extra) -> Dict[str, Any]:
        with self._lock:
            ok, shed, errors = self.ok, self.shed, self.errors
        total = ok + shed + errors
        return {"requests": total, "ok": ok, "shed": shed, "errors": errors,
                "duration_s": round(duration_s, 4),
                "qps": round(total / duration_s, 3) if duration_s else 0.0,
                "shed_rate": round(shed / total, 5) if total else 0.0,
                "latency_ms": _round_latency(self.hist.snapshot()), **extra}


def _round_latency(snap: Dict[str, Any]) -> Dict[str, Any]:
    return {k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in snap.items()}


def _issue(client, X, stats: _LoopStats, t_ref: float) -> None:
    """One request; latency measured from ``t_ref`` (enqueue time for the
    closed loop, SCHEDULED send time for the open loop)."""
    from lightgbm_tpu.serving import ServerOverloaded
    try:
        client.predict(X)
        stats.done((time.perf_counter() - t_ref) * 1e3, "ok")
    except ServerOverloaded:
        stats.done((time.perf_counter() - t_ref) * 1e3, "shed")
    except Exception:
        stats.done((time.perf_counter() - t_ref) * 1e3, "errors")


def run_closed_loop(host, port, args) -> Dict[str, Any]:
    from lightgbm_tpu.serving import ServingClient

    stats = _LoopStats()
    per_client = max(args.requests // args.clients, 1)

    def worker(seed: int) -> None:
        rng = np.random.RandomState(1000 + seed)
        with ServingClient(host, port, timeout=60,
                           protocol=args.protocol) as c:
            for _ in range(per_client):
                X = _request_matrix(rng, args.rows_per_request,
                                    args.num_features)
                _issue(c, X, stats, time.perf_counter())

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(args.clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return stats.section(time.perf_counter() - t0, clients=args.clients)


def run_open_loop(host, port, args) -> Dict[str, Any]:
    from lightgbm_tpu.serving import ServingClient

    stats = _LoopStats()
    n = max(int(args.qps * args.open_seconds), 1)
    interval = 1.0 / args.qps
    next_idx = [0]
    idx_lock = threading.Lock()
    pool = max(min(args.open_pool, n), 1)
    clients: List[Any] = []

    t0 = time.perf_counter()

    def worker(wid: int) -> None:
        rng = np.random.RandomState(2000 + wid)
        c = clients[wid]
        while True:
            with idx_lock:
                i = next_idx[0]
                if i >= n:
                    return
                next_idx[0] = i + 1
            sched = t0 + i * interval
            delay = sched - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            X = _request_matrix(rng, args.rows_per_request,
                                args.num_features)
            # latency from the SCHEDULED time: a saturated pool shows up
            # as latency (coordinated omission counted), not hidden
            _issue(c, X, stats, sched)

    for w in range(pool):
        clients.append(ServingClient(host, port, timeout=60,
                                     protocol=args.protocol))
    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(pool)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dur = time.perf_counter() - t0
    for c in clients:
        c.close()
    return stats.section(dur, target_qps=float(args.qps))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_serving.py",
        description="closed+open-loop serving load generator "
                    "(BENCH_SERVING_r*.json)")
    ap.add_argument("--out", default="BENCH_SERVING_r01.json")
    ap.add_argument("--round", type=int, default=1)
    ap.add_argument("--model", default="",
                    help="serve this model text instead of training one")
    ap.add_argument("--train-rows", type=int, default=20000)
    ap.add_argument("--trees", type=int, default=20)
    ap.add_argument("--num-features", type=int, default=28)
    ap.add_argument("--rows-per-request", type=int, default=8)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=400,
                    help="closed-loop total across all clients")
    ap.add_argument("--qps", type=float, default=100.0,
                    help="open-loop target request rate")
    ap.add_argument("--open-seconds", type=float, default=3.0)
    ap.add_argument("--open-pool", type=int, default=32,
                    help="open-loop connection pool size")
    ap.add_argument("--deadline-ms", type=float, default=2.0)
    ap.add_argument("--max-batch-rows", type=int, default=256)
    ap.add_argument("--max-inflight", type=int, default=64)
    ap.add_argument("--replicas", type=int, default=0,
                    help="serve through the fleet gateway with N replicas "
                         "(0 = legacy threaded server, -1 = one per local "
                         "device)")
    ap.add_argument("--protocol", choices=("auto", "binary", "pickle"),
                    default="auto",
                    help="client wire protocol (auto negotiates binary, "
                         "falls back to pickle)")
    ap.add_argument("--compare", action="store_true",
                    help="run pickle-vs-binary x 1-vs-N replica legs in one "
                         "process; the binary+N leg is the headline")
    ap.add_argument("--trace-out", default="",
                    help="also capture request spans (Chrome trace JSON)")
    ap.add_argument("--note", default="")
    args = ap.parse_args(argv)

    import jax
    from lightgbm_tpu.observability import (BENCH_SERVING_SCHEMA,
                                            validate_report)

    booster = build_booster(args)
    if args.num_features != booster.num_feature():
        args.num_features = booster.num_feature()

    def run_leg(protocol: str, replicas: int):
        """One (protocol, replicas) measurement with a fresh server."""
        leg_args = argparse.Namespace(**vars(args))
        leg_args.protocol = protocol
        server = booster.serve(
            replicas=replicas, port=0, max_batch_rows=args.max_batch_rows,
            deadline_ms=args.deadline_ms, max_inflight=args.max_inflight,
            trace_out=args.trace_out)
        try:
            closed = run_closed_loop(server.host, server.port, leg_args)
            open_ = run_open_loop(server.host, server.port, leg_args)
            # fleet servers expose the registry per replica; the legacy
            # threaded server has a single one
            reg = getattr(server, "registry", None) or server.replicas
            section = server.stats.serving_section(
                models=reg.versions(), jit_entries=reg.jit_entries())
        finally:
            server.stop()
        return closed, open_, section

    if args.compare:
        n = args.replicas if args.replicas > 0 else \
            max(len(jax.local_devices()), 2)
        specs = [("pickle", 1), ("binary", 1), ("pickle", n), ("binary", n)]
        legs = []
        for proto, nrep in specs:
            closed, open_, section = run_leg(proto, nrep)
            legs.append({"protocol": proto, "replicas": nrep,
                         "closed_loop": closed, "open_loop": open_})
            print(json.dumps({"leg": f"{proto} x{nrep}",
                              "closed_p99_ms":
                              closed["latency_ms"]["p99"],
                              "closed_qps": closed["qps"],
                              "open_p99_ms": open_["latency_ms"]["p99"],
                              "open_qps": open_["qps"]}), file=sys.stderr)
        # the final (binary, N) leg is the headline; `section` already
        # holds that leg's server stats
        headline = legs[-1]
        closed, open_ = headline["closed_loop"], headline["open_loop"]
        args.protocol, args.replicas = headline["protocol"], n
    else:
        legs = None
        closed, open_, section = run_leg(args.protocol, args.replicas)

    from lightgbm_tpu.observability import provenance_section

    report = {
        # v2: provenance carries cost_ledger_sha256 (analysis/costs.json)
        "schema_version": 2,
        "round": args.round,
        # the driver's TPU runs are the arbiter; CPU seeds are marked
        "platform": jax.devices()[0].platform,
        # who-produced-this, same block as bench.py/MULTICHIP artifacts:
        # platform, jax version, host/device counts, emulated flag
        "provenance": provenance_section(),
        **({"note": args.note} if args.note else {}),
        "workload": {
            "model": args.model or "synthetic-binary",
            "train_rows": args.train_rows, "trees": args.trees,
            "num_features": args.num_features,
            "rows_per_request": args.rows_per_request,
            "deadline_ms": args.deadline_ms,
            "max_batch_rows": args.max_batch_rows,
            "max_inflight": args.max_inflight,
            "protocol": args.protocol,
            "replicas": args.replicas,
        },
        "closed_loop": closed,
        "open_loop": open_,
        **({"legs": legs} if legs else {}),
        "server": {
            "batches": section["batches"],
            "batch_occupancy": round(section["batch_occupancy"], 4),
            "shed": section["shed"],
            "compile_cache": section["compile_cache"],
            "buckets": section["buckets"],
        },
    }
    assert "provenance" in report and \
        isinstance(report["provenance"].get("emulated"), bool), \
        "BENCH_SERVING report lost its provenance block"
    assert "cost_ledger_sha256" in report["provenance"], \
        "BENCH_SERVING provenance lost cost_ledger_sha256 (schema v2)"
    errs = validate_report(report, BENCH_SERVING_SCHEMA)
    if errs:
        print(f"BENCH_SERVING report violates schema: {errs}",
              file=sys.stderr)
        return 2
    tmp = args.out + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, args.out)
    line = {"metric": "serving p50/p99 ms + sustained QPS "
                      f"({args.rows_per_request} rows/req)",
            "closed_p50_ms": report["closed_loop"]["latency_ms"]["p50"],
            "closed_p99_ms": report["closed_loop"]["latency_ms"]["p99"],
            "closed_qps": report["closed_loop"]["qps"],
            "open_p99_ms": report["open_loop"]["latency_ms"]["p99"],
            "open_qps": report["open_loop"]["qps"],
            "shed_rate": report["open_loop"]["shed_rate"],
            "protocol": args.protocol,
            "replicas": args.replicas,
            "out": args.out}
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
