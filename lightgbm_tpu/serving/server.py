"""Threaded prediction server + client over length-prefixed pickle frames.

The serving analogue of the construction-phase ``SocketNet``
(`io/net.py`): same framing (8-byte LE length + pickle via
``send_frame``/``recv_frame``), but a request/response RPC instead of a
collective relay.  One accept loop, one handler thread per connection; all
predictions funnel through per-model ``MicroBatcher`` workers so concurrent
clients coalesce into shared device batches.

Ops (dict in, dict out; ``{"ok": False, "error": ...}`` on failure):

  * ``predict``  — ``{"op", "model", "data": ndarray, "raw_score"}`` →
    ``{"ok": True, "scores": ndarray}``
  * ``swap``     — ``{"op", "model", "model_str"}`` → load/verify/hot-swap
    a new model text; the old version serves until the swap commits
  * ``stats``    — full telemetry report (``serving`` schema section)
  * ``health``   — readiness probe, distinct from ``ping`` liveness:
    registered models + admission state (inflight/capacity/shedding);
    accurate under overload
  * ``ping`` / ``shutdown``

Overload never drops a connection: past ``max_inflight`` concurrently
admitted predicts, requests shed with a structured
``{"ok": False, "error": "overloaded", "shed": True}`` frame
(`reliability/degrade.py`), and a device-path failure degrades to the
host numpy traversal instead of erroring the batch (``fallback_fn``).

Start via ``Booster.serve()`` or ``python -m lightgbm_tpu serve
input_model=model.txt``.
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Dict, Optional

import numpy as np

from ..io.net import recv_frame, send_frame
from ..reliability.degrade import AdmissionController
from .batcher import MicroBatcher, ServingStats, bucket_ladder
from .registry import ModelRegistry


class PredictionServer:
    """Long-lived serving process state: registry + batchers + listener."""

    def __init__(self, booster=None, registry: Optional[ModelRegistry] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 max_batch_rows: int = 256, deadline_ms: float = 2.0,
                 min_bucket: int = 32, warmup: bool = True,
                 telemetry_out: str = "", request_timeout: float = 60.0,
                 max_inflight: int = 64):
        self.host = host
        self.port = int(port)
        self.max_batch_rows = int(max_batch_rows)
        self.deadline_ms = float(deadline_ms)
        self.min_bucket = int(min_bucket)
        self.telemetry_out = telemetry_out
        self.request_timeout = float(request_timeout)
        self.admission = AdmissionController(max_inflight)
        self.stats = ServingStats()
        self.buckets = bucket_ladder(min_bucket, max_batch_rows)
        self.registry = registry or ModelRegistry(
            stats=self.stats, warm_buckets=self.buckets, warmup=warmup)
        if registry is not None and not registry.warm_buckets:
            registry.warm_buckets = self.buckets
        self.registry.stats = self.stats
        if booster is not None:
            self.registry.load("default", booster=booster)
        self._batchers: Dict[str, MicroBatcher] = {}
        self._batcher_lock = threading.Lock()
        self._srv: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._stopped = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "PredictionServer":
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.host, self.port))
        srv.listen(16)
        srv.settimeout(0.25)          # poll the stop flag
        self.port = srv.getsockname()[1]
        self._srv = srv
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="lgbt-serve-accept", daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        with self._batcher_lock:
            batchers = list(self._batchers.values())
        for b in batchers:
            b.stop()
        if self.telemetry_out:
            from ..observability import write_report
            write_report(self.report(), self.telemetry_out)
        self._stopped.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._stopped.wait(timeout)

    def __enter__(self) -> "PredictionServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- report --------------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        return self.stats.report(models=self.registry.versions(),
                                 jit_entries=self.registry.jit_entries())

    # -- batching ------------------------------------------------------------

    def _batcher(self, name: str) -> MicroBatcher:
        with self._batcher_lock:
            b = self._batchers.get(name)
            if b is None:
                # resolve the model at BATCH time so a hot-swap is picked
                # up atomically at the next batch boundary
                def predict_fn(Xpad, m, _name=name):
                    return self.registry.get(_name).predict_padded(Xpad, m)

                # graceful degradation: a device-path failure re-scores
                # the batch through the host numpy traversal (counted in
                # the reliability section) instead of erroring every rider
                def fallback_fn(Xpad, m, _name=name):
                    return self.registry.get(_name).host_fallback(Xpad, m)

                b = MicroBatcher(
                    predict_fn,
                    num_features=self.registry.get(name).num_features,
                    max_batch_rows=self.max_batch_rows,
                    deadline_ms=self.deadline_ms,
                    min_bucket=self.min_bucket, stats=self.stats,
                    fallback_fn=fallback_fn).start()
                self._batchers[name] = b
            return b

    # -- connection handling -------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            # deadline before the handler thread exists: a client that
            # connects and never speaks can otherwise pin a thread forever
            conn.settimeout(self.request_timeout + 30.0)
            threading.Thread(target=self._handle, args=(conn,),
                             name="lgbt-serve-conn", daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_frame(conn)
                except (ConnectionError, socket.timeout, OSError, EOFError):
                    break
                try:
                    resp = self._dispatch(msg)
                except Exception as e:
                    # Exception, not BaseException: a SystemExit /
                    # KeyboardInterrupt must kill the handler, not become
                    # an RPC error frame
                    resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                try:
                    send_frame(conn, resp)
                except OSError:
                    break
                if isinstance(msg, dict) and msg.get("op") == "shutdown":
                    break
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, msg) -> Dict[str, Any]:
        if not isinstance(msg, dict) or "op" not in msg:
            return {"ok": False, "error": "malformed request"}
        op = msg["op"]
        if op == "ping":
            return {"ok": True}
        if op == "health":
            # readiness, distinct from liveness (`ping`): servable models
            # exist and the server is not stopping.  Stays ACCURATE under
            # overload — a saturated server is alive and ready, it is just
            # shedding; clients and balancers read that from `shedding`
            models = self.registry.versions()
            return {"ok": True,
                    "ready": bool(models) and not self._stop.is_set(),
                    "models": models,
                    **self.admission.snapshot()}
        if op == "predict":
            # bounded admission: past capacity we answer IMMEDIATELY with
            # a structured shed frame — never a queue-until-timeout that
            # looks like a dropped connection from the outside
            if not self.admission.try_acquire():
                self.stats.record_shed()
                return {"ok": False, "error": "overloaded", "shed": True,
                        "inflight": self.admission.inflight,
                        "capacity": self.admission.capacity}
            try:
                name = msg.get("model", "default")
                model = self.registry.get(name)
                X = np.atleast_2d(np.asarray(msg["data"], dtype=np.float64))
                raw = self._batcher(name).submit(
                    X, timeout=self.request_timeout)
                scores = model.convert_output(raw, bool(msg.get("raw_score")))
                return {"ok": True, "scores": np.asarray(scores)}
            finally:
                self.admission.release()
        if op == "swap":
            version = self.registry.load(
                msg.get("model", "default"), model_str=msg.get("model_str"),
                model_file=msg.get("model_file"))
            return {"ok": True, "version": version}
        if op == "stats":
            return {"ok": True, "report": self.report()}
        if op == "shutdown":
            # ack first; stop from a side thread (stop() joins batcher
            # threads and must not run on this handler)
            threading.Thread(target=self.stop, daemon=True).start()
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}


class ServingClient:
    """Tiny blocking client for ``PredictionServer`` (same framing)."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.settimeout(timeout)
        self._lock = threading.Lock()

    def _call(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            send_frame(self._sock, msg)
            resp = recv_frame(self._sock)
        if not resp.get("ok"):
            raise RuntimeError(f"server error: {resp.get('error')}")
        return resp

    def ping(self) -> bool:
        return self._call({"op": "ping"})["ok"]

    def health(self) -> Dict[str, Any]:
        """Readiness + admission state (see ``health`` op)."""
        return self._call({"op": "health"})

    def predict(self, X, model: str = "default",
                raw_score: bool = False) -> np.ndarray:
        resp = self._call({"op": "predict", "model": model,
                           "data": np.asarray(X, dtype=np.float64),
                           "raw_score": raw_score})
        return resp["scores"]

    def swap(self, model_str: str, model: str = "default") -> int:
        return self._call({"op": "swap", "model": model,
                           "model_str": model_str})["version"]

    def stats(self) -> Dict[str, Any]:
        return self._call({"op": "stats"})["report"]

    def shutdown(self) -> None:
        self._call({"op": "shutdown"})

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServingClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
