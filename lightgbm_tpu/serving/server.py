"""Threaded prediction server + client over length-prefixed pickle frames.

The serving analogue of the construction-phase ``SocketNet``
(`io/net.py`): same framing (8-byte LE length + pickle via
``send_frame``/``recv_frame``), but a request/response RPC instead of a
collective relay.  One accept loop, one handler thread per connection; all
predictions funnel through per-model ``MicroBatcher`` workers so concurrent
clients coalesce into shared device batches.

Ops (dict in, dict out; ``{"ok": False, "error": ...}`` on failure):

  * ``predict``  — ``{"op", "model", "data": ndarray, "raw_score",
    "trace_id"?}`` → ``{"ok": True, "scores": ndarray, "trace_id"?}``; the
    (client-supplied or, when tracing, server-generated) ``trace_id`` is
    echoed back and carried through the batcher so the request span, its
    micro-batch span and the batch's stage spans share one id
  * ``swap``     — ``{"op", "model", "model_str"}`` → load/verify/hot-swap
    a new model text; the old version serves until the swap commits
  * ``stats``    — full telemetry report (``serving`` schema section,
    including exact p50/p95/p99 request latency)
  * ``metrics``  — Prometheus text-format snapshot (counters, stage
    timers, reliability counters, request-latency histogram) through the
    same framed-RPC plumbing as ``health``
  * ``health``   — readiness probe, distinct from ``ping`` liveness:
    registered models + admission state (inflight/capacity/shedding);
    accurate under overload
  * ``ping`` / ``shutdown``

Overload never drops a connection: past ``max_inflight`` concurrently
admitted predicts, requests shed with a structured
``{"ok": False, "error": "overloaded", "shed": True}`` frame that echoes
the request's ``trace_id`` so clients can correlate rejections
(`reliability/degrade.py`), and a device-path failure degrades to the
host numpy traversal instead of erroring the batch (``fallback_fn``).

Operational surfaces beyond the socket: ``stats_out``/``stats_interval_s``
write periodic atomic (tmp + ``os.replace``) schema-validated stats
snapshots operators can poll without a connection, and
``trace=True``/``trace_out`` record request-scoped spans
(`observability/trace.py`) written as Chrome trace-event JSON on stop.

Start via ``Booster.serve()`` or ``python -m lightgbm_tpu serve
input_model=model.txt``.
"""

from __future__ import annotations

import contextlib
import socket
import threading
import time
from typing import Any, Dict, Optional

_NULL_CTX = contextlib.nullcontext()

import numpy as np

from ..io.net import recv_frame, send_frame
from ..lifecycle.recorder import TrafficRecorder
from ..observability.trace import TraceRecorder, new_trace_id
from ..reliability.degrade import AdmissionController
from .batcher import MicroBatcher, ServingStats, bucket_ladder
from .registry import ModelRegistry


class ServerOverloaded(RuntimeError):
    """Raised by ``ServingClient`` on a structured shed frame.  Carries
    the server's admission state and the request's echoed ``trace_id``
    so a client can correlate the rejection with its own records."""

    def __init__(self, resp: Dict[str, Any]):
        super().__init__(
            f"server overloaded (inflight "
            f"{resp.get('inflight')}/{resp.get('capacity')})")
        self.trace_id = resp.get("trace_id")
        self.inflight = resp.get("inflight")
        self.capacity = resp.get("capacity")


class ServerUnavailable(ConnectionError):
    """Raised by ``ServingClient`` when the transport retry budget is
    exhausted (connect or send/recv kept failing).  A ``ConnectionError``
    subclass, so callers that already handle transport failures keep
    working; distinct from ``ServerOverloaded``, which is a STRUCTURED
    server decision and is never retried blindly."""

    def __init__(self, attempts: int, last: BaseException):
        super().__init__(
            f"server unavailable after {attempts} attempt(s): "
            f"{type(last).__name__}: {last}")
        self.attempts = attempts
        self.last_error = last


class PredictionServer:
    """Long-lived serving process state: registry + batchers + listener."""

    def __init__(self, booster=None, registry: Optional[ModelRegistry] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 max_batch_rows: int = 256, deadline_ms: float = 2.0,
                 min_bucket: int = 32, warmup: bool = True,
                 telemetry_out: str = "", request_timeout: float = 60.0,
                 max_inflight: int = 64, trace: bool = False,
                 trace_out: str = "", trace_capacity: int = 65536,
                 stats_out: str = "", stats_interval_s: float = 10.0,
                 record_rows: int = 0, slo_p99_ms: float = 50.0,
                 slo_target: float = 0.99):
        self.host = host
        self.port = int(port)
        self.max_batch_rows = int(max_batch_rows)
        self.deadline_ms = float(deadline_ms)
        self.min_bucket = int(min_bucket)
        self.telemetry_out = telemetry_out
        self.request_timeout = float(request_timeout)
        self.admission = AdmissionController(max_inflight)
        self.stats = ServingStats(slo_p99_ms=slo_p99_ms,
                                  slo_target=slo_target)
        # request-scoped tracing: host-side spans only, written as Chrome
        # trace-event JSON on stop (open in Perfetto)
        self.trace_out = trace_out
        self.tracer: Optional[TraceRecorder] = None
        if trace or trace_out:
            self.tracer = TraceRecorder(True, capacity=trace_capacity)
            self.stats.attach_tracer(self.tracer)
        # periodic atomic schema-validated stats snapshots (poll the file
        # instead of the socket op)
        self.stats_out = stats_out
        self.stats_interval_s = float(stats_interval_s)
        self._stats_thread: Optional[threading.Thread] = None
        # bounded traffic ring for the lifecycle shadow loop; capacity 0
        # (the default) keeps the request path a single attribute check
        self.recorder = TrafficRecorder(record_rows)
        # set by LifecycleController when one is bound to this server;
        # report() then carries the "lifecycle" section
        self.lifecycle = None
        self.buckets = bucket_ladder(min_bucket, max_batch_rows)
        self.registry = registry or ModelRegistry(
            stats=self.stats, warm_buckets=self.buckets, warmup=warmup)
        if registry is not None and not registry.warm_buckets:
            registry.warm_buckets = self.buckets
        self.registry.stats = self.stats
        if booster is not None:
            self.registry.load("default", booster=booster)
        self._batchers: Dict[str, MicroBatcher] = {}
        self._batcher_lock = threading.Lock()
        self._srv: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._stopped = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "PredictionServer":
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((self.host, self.port))
            srv.listen(16)
            srv.settimeout(0.25)          # poll the stop flag
        except OSError:
            # close-on-error-path: a failed bind (port in use) must not
            # leak the listener fd
            srv.close()
            raise
        self.port = srv.getsockname()[1]
        self._srv = srv
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="lgbt-serve-accept", daemon=True)
        self._accept_thread.start()
        if self.stats_out:
            self._stats_thread = threading.Thread(
                target=self._stats_loop, name="lgbt-serve-stats", daemon=True)
            self._stats_thread.start()
        return self

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        with self._batcher_lock:
            batchers = list(self._batchers.values())
        for b in batchers:
            b.stop()
        # join-on-stop: the accept loop exits on the closed listener and
        # the stats loop wakes on the stop event — wait for both so no
        # daemon thread outlives stop() and races the final snapshot
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        if self._stats_thread is not None:
            self._stats_thread.join(timeout=5.0)
        if self.telemetry_out:
            from ..observability import write_report
            write_report(self.report(), self.telemetry_out)
        if self.stats_out:
            self._write_stats_snapshot()     # final snapshot at shutdown
        if self.trace_out and self.tracer is not None:
            self.tracer.save(self.trace_out)
        self._stopped.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._stopped.wait(timeout)

    def __enter__(self) -> "PredictionServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- report --------------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        rep = self.stats.report(models=self.registry.versions(),
                                jit_entries=self.registry.jit_entries())
        if self.lifecycle is not None:
            rep["lifecycle"] = self.lifecycle.section()
        return rep

    def trace(self) -> Optional[Dict[str, Any]]:
        """The captured Chrome trace-event JSON object (``None`` when
        tracing is off)."""
        return self.tracer.export() if self.tracer is not None else None

    def _write_stats_snapshot(self) -> None:
        from ..observability import write_report
        try:
            write_report(self.report(), self.stats_out)
        except Exception as e:
            # a full disk or transient schema problem must not kill the
            # snapshot loop (or serving); the failure is counted so it
            # still surfaces in the reliability section
            from ..reliability.metrics import rel_inc
            rel_inc("serve.stats_snapshot_errors")
            print(f"[LightGBM-TPU] [Warning] stats snapshot failed: {e}",
                  flush=True)

    def _stats_loop(self) -> None:
        """Periodic operator-pollable snapshots: atomic (tmp +
        ``os.replace`` inside ``write_report``) and schema-validated, so
        a reader never observes a torn or malformed file."""
        while not self._stop.wait(self.stats_interval_s):
            self._write_stats_snapshot()

    # -- batching ------------------------------------------------------------

    def _batcher(self, name: str) -> MicroBatcher:
        with self._batcher_lock:
            b = self._batchers.get(name)
            if b is None:
                # resolve the model at BATCH time so a hot-swap is picked
                # up atomically at the next batch boundary
                def predict_fn(Xpad, m, _name=name):
                    return self.registry.get(_name).predict_padded(Xpad, m)

                # graceful degradation: a device-path failure re-scores
                # the batch through the host numpy traversal (counted in
                # the reliability section) instead of erroring every rider
                def fallback_fn(Xpad, m, _name=name):
                    return self.registry.get(_name).host_fallback(Xpad, m)

                b = MicroBatcher(
                    predict_fn,
                    num_features=self.registry.get(name).num_features,
                    max_batch_rows=self.max_batch_rows,
                    deadline_ms=self.deadline_ms,
                    min_bucket=self.min_bucket, stats=self.stats,
                    fallback_fn=fallback_fn).start()
                self._batchers[name] = b
            return b

    # -- connection handling -------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            # deadline before the handler thread exists: a client that
            # connects and never speaks can otherwise pin a thread forever
            conn.settimeout(self.request_timeout + 30.0)
            threading.Thread(target=self._handle, args=(conn,),
                             name="lgbt-serve-conn", daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_frame(conn)
                except (ConnectionError, socket.timeout, OSError, EOFError):
                    break
                try:
                    resp = self._dispatch(msg)
                except Exception as e:
                    # Exception, not BaseException: a SystemExit /
                    # KeyboardInterrupt must kill the handler, not become
                    # an RPC error frame
                    resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                try:
                    send_frame(conn, resp)
                except OSError:
                    break
                if isinstance(msg, dict) and msg.get("op") == "shutdown":
                    break
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, msg) -> Dict[str, Any]:
        if not isinstance(msg, dict) or "op" not in msg:
            return {"ok": False, "error": "malformed request"}
        op = msg["op"]
        if op == "ping":
            return {"ok": True}
        if op == "health":
            # readiness, distinct from liveness (`ping`): servable models
            # exist and the server is not stopping.  Stays ACCURATE under
            # overload — a saturated server is alive and ready, it is just
            # shedding; clients and balancers read that from `shedding`
            models = self.registry.versions()
            return {"ok": True,
                    "ready": bool(models) and not self._stop.is_set(),
                    "models": models,
                    # serving + retained-previous version per model, so an
                    # operator sees what is live and what a rollback
                    # would restore
                    "versions": self.registry.versions_detail(),
                    **self.admission.snapshot()}
        if op == "predict":
            name = str(msg.get("model", "default"))
            # the request's causal id: client-supplied, or minted here
            # when tracing so every request is attributable in the trace
            trace_id = msg.get("trace_id") or \
                (new_trace_id() if self.tracer is not None else None)
            # bounded admission: past capacity we answer IMMEDIATELY with
            # a structured shed frame — never a queue-until-timeout that
            # looks like a dropped connection from the outside.  The shed
            # frame echoes trace_id so the client can correlate the
            # rejection with its own request records
            if not self.admission.try_acquire():
                self.stats.record_shed()
                self.stats.record_tenant_shed(name)
                resp = {"ok": False, "error": "overloaded", "shed": True,
                        "inflight": self.admission.inflight,
                        "capacity": self.admission.capacity}
                if trace_id is not None:
                    resp["trace_id"] = trace_id
                return resp
            t0 = time.perf_counter()
            failed = False
            try:
                model = self.registry.get(name)
                X = np.atleast_2d(np.asarray(msg["data"], dtype=np.float64))
                # lifecycle traffic capture: the shadow loop replays
                # candidates against what the server actually answered
                self.recorder.record(X)
                span = self.tracer.span(
                    "serve.request", cat="serving", trace_id=trace_id,
                    args={"model": name, "rows": int(X.shape[0])}) \
                    if self.tracer is not None else _NULL_CTX
                with span:
                    raw = self._batcher(name).submit(
                        X, timeout=self.request_timeout, trace_id=trace_id)
                    scores = model.convert_output(raw,
                                                  bool(msg.get("raw_score")))
                resp = {"ok": True, "scores": np.asarray(scores)}
                if trace_id is not None:
                    resp["trace_id"] = trace_id
                return resp
            except Exception:
                # an admitted request answering with an error frame — the
                # rate the lifecycle rollback watchdog judges a fresh
                # promotion by
                failed = True
                self.stats.record_error()
                raise
            finally:
                self.admission.release()
                # admission→response latency, errors included — the p99
                # an external client actually observes server-side
                ms = (time.perf_counter() - t0) * 1e3
                self.stats.record_request_latency(ms)
                self.stats.record_tenant_request(name, ms, error=failed)
        if op == "swap":
            version = self.registry.load(
                msg.get("model", "default"), model_str=msg.get("model_str"),
                model_file=msg.get("model_file"))
            return {"ok": True, "version": version}
        if op == "stats":
            return {"ok": True, "report": self.report()}
        if op == "metrics":
            # Prometheus text exposition over the same framed-RPC plumbing
            # as `health` — scrape with `ServingClient.metrics()` or the
            # CLI; le buckets in seconds, counters monotone
            from ..observability.metrics_export import prometheus_snapshot
            return {"ok": True,
                    "text": prometheus_snapshot(
                        self.stats, registry=self.registry,
                        admission=self.admission,
                        tenants=self.stats.tenants_section()),
                    "content_type": "text/plain; version=0.0.4"}
        if op == "shutdown":
            # ack first; stop from a side thread (stop() joins batcher
            # threads and must not run on this handler)
            threading.Thread(target=self.stop, daemon=True).start()
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}


class ServingClient:
    """Tiny blocking client for ``PredictionServer`` and ``FleetServer``.

    Protocol: ``protocol="auto"`` (the default) probes the server ONCE
    with a binary ``ping`` frame (`serving/fleet/wire.py`) on the first
    connection — a fleet gateway answers in kind and the client speaks
    compact typed binary frames from then on; a legacy pickle server
    rejects the probe's magic as a protocol mismatch and closes, and the
    client reconnects speaking pickle (without burning the transport
    retry budget — negotiation is not a failure).  ``protocol="binary"``
    / ``"pickle"`` pin the framing explicitly.

    Transport failures — refused/dropped connections, recv timeouts,
    torn frames — retry with bounded exponential backoff (the SocketNet
    reconnect pattern, `io/net.py`), reconnecting between attempts;
    after ``retries`` failed attempts a typed ``ServerUnavailable``
    raises.  Structured SERVER decisions are never retried blindly: a
    shed/overload frame raises ``ServerOverloaded`` immediately (the
    server is alive and explicitly refusing — hammering it back is how
    retry storms start) and error frames raise ``RuntimeError`` — the
    same semantics under both framings.
    """

    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 retries: int = 3, backoff_s: float = 0.05,
                 protocol: str = "auto"):
        if protocol not in ("auto", "binary", "pickle"):
            raise ValueError(f"unknown protocol {protocol!r} "
                             f"(auto, binary or pickle)")
        self._host = host
        self._port = int(port)
        self._timeout = float(timeout)
        self._retries = max(int(retries), 0)
        self._backoff_s = float(backoff_s)
        self._protocol = protocol
        # the negotiated framing, sticky after the first connection
        self._wire: Optional[str] = \
            "pickle" if protocol == "pickle" else None
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        with self._lock:
            self._connect_locked()

    @property
    def protocol(self) -> Optional[str]:
        """The negotiated framing ("binary" or "pickle")."""
        return self._wire

    def _negotiate(self, s: socket.socket) -> bool:
        """One-shot probe on a fresh socket: binary ping → True when the
        server answers in wire framing.  A pickle server sees the magic
        as a giant/mismatched length prefix and closes; that surfaces
        here as a transport error → False (fall back), unless the caller
        pinned ``protocol="binary"``."""
        from .fleet import wire
        try:
            wire.send_wire_frame(s, wire.OP_PING)
            opcode, _flags, _tid, payload = wire.recv_wire_frame(s)
            wire.response_to_dict(opcode, _flags, _tid, payload)
            return True
        except (ConnectionError, socket.timeout, OSError, EOFError) as e:
            if self._protocol == "binary":
                raise ServerUnavailable(1, e) from e
            return False

    def _connect_locked(self) -> None:
        """(Re)connect under ``self._lock`` with the bounded
        backoff-retry loop; transient connect errors count into the
        reliability table.  Protocol negotiation runs once, on the first
        successful connection."""
        from ..reliability.metrics import rel_inc
        self._close_locked()
        backoff = self._backoff_s
        last: Optional[BaseException] = None
        for attempt in range(self._retries + 1):
            s: Optional[socket.socket] = None
            try:
                s = socket.create_connection((self._host, self._port),
                                             timeout=self._timeout)
                s.settimeout(self._timeout)
                if self._wire is None:
                    if self._negotiate(s):
                        self._wire = "binary"
                    else:
                        # the probe's rejection closed the socket; the
                        # pickle reconnect is part of negotiation, not a
                        # transport failure
                        self._wire = "pickle"
                        try:
                            s.close()
                        except OSError:
                            pass
                        s = None
                        s = socket.create_connection(
                            (self._host, self._port),
                            timeout=self._timeout)
                        s.settimeout(self._timeout)
                self._sock = s
                return
            except ServerUnavailable:
                # pinned protocol="binary" against a non-binary server:
                # a definitive answer, not a transient to retry — but
                # the probe socket must still close on the way out
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                raise
            except OSError as e:
                # close-on-error-path: a socket that connected but then
                # failed (probe timeout, reset mid-negotiation) would
                # otherwise leak an fd per retry
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                last = e
                rel_inc("serve.client_connect_retries")
                if attempt >= self._retries:
                    break
                time.sleep(backoff)
                backoff = min(backoff * 2, 1.0)
        raise ServerUnavailable(self._retries + 1, last)

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _roundtrip_locked(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """One request/response exchange in the negotiated framing.
        Binary responses are normalized into the pickle protocol's dict
        shape so every caller above this line is protocol-blind."""
        if self._wire != "binary":
            send_frame(self._sock, msg)
            return recv_frame(self._sock)
        from .fleet import wire
        op = msg["op"]
        tid = msg.get("trace_id") or ""
        if op == "predict":
            payload = wire.encode_predict_request(
                np.asarray(msg["data"]), msg.get("model", "default"))
            flags = wire.FLAG_RAW_SCORE if msg.get("raw_score") else 0
            wire.send_wire_frame(self._sock, wire.OP_PREDICT, payload,
                                 flags, tid)
        else:
            opcode = {"ping": wire.OP_PING, "health": wire.OP_HEALTH,
                      "metrics": wire.OP_METRICS, "stats": wire.OP_STATS,
                      "swap": wire.OP_SWAP,
                      "shutdown": wire.OP_SHUTDOWN}.get(op)
            if opcode is None:
                raise ValueError(f"op {op!r} has no binary encoding")
            body = {k: v for k, v in msg.items()
                    if k not in ("op", "trace_id")}
            wire.send_wire_frame(self._sock, opcode,
                                 wire.encode_json(body) if body else b"",
                                 0, tid)
        return wire.response_to_dict(
            *wire.recv_wire_frame(self._sock))

    def _call(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        from ..reliability.metrics import rel_inc
        with self._lock:
            backoff = self._backoff_s
            last: Optional[BaseException] = None
            resp = None
            for attempt in range(self._retries + 1):
                try:
                    if self._sock is None:
                        self._connect_locked()
                    resp = self._roundtrip_locked(msg)
                    break
                except ServerUnavailable:
                    raise
                except (ConnectionError, socket.timeout, OSError,
                        EOFError) as e:
                    # transient transport failure: drop the socket and
                    # retry the whole send/recv on a fresh connection
                    last = e
                    self._close_locked()
                    rel_inc("serve.client_call_retries")
                    if attempt >= self._retries:
                        raise ServerUnavailable(attempt + 1, last) from e
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 1.0)
        if not resp.get("ok"):
            if resp.get("shed"):
                # structured overload: typed, with the echoed trace_id —
                # an explicit server decision, NOT retried
                raise ServerOverloaded(resp)
            raise RuntimeError(f"server error: {resp.get('error')}")
        return resp

    def ping(self) -> bool:
        return self._call({"op": "ping"})["ok"]

    def health(self) -> Dict[str, Any]:
        """Readiness + admission state (see ``health`` op)."""
        return self._call({"op": "health"})

    def predict(self, X, model: str = "default", raw_score: bool = False,
                trace_id: Optional[str] = None) -> np.ndarray:
        """Blocking predict.  ``trace_id`` (any opaque string, e.g.
        ``observability.new_trace_id()``) is carried through the server's
        request/batch/stage spans and echoed in the response — including
        shed responses, where it lands on ``ServerOverloaded.trace_id``.
        Under the binary framing the row block ships as float32 (the
        bandwidth win); scores come back float64."""
        msg = {"op": "predict", "model": model,
               "data": np.asarray(X, dtype=np.float64),
               "raw_score": raw_score}
        if trace_id is not None:
            msg["trace_id"] = trace_id
        return self._call(msg)["scores"]

    def swap(self, model_str: str, model: str = "default") -> int:
        return self._call({"op": "swap", "model": model,
                           "model_str": model_str})["version"]

    def stats(self) -> Dict[str, Any]:
        """Full telemetry report (``serving`` section with exact
        p50/p95/p99 request latency under ``latency_ms``)."""
        return self._call({"op": "stats"})["report"]

    def metrics(self) -> str:
        """Prometheus text-format metrics snapshot (see ``metrics`` op)."""
        return self._call({"op": "metrics"})["text"]

    def shutdown(self) -> None:
        self._call({"op": "shutdown"})

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def __enter__(self) -> "ServingClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
