"""Replica fleet: one registry+batcher stack per device, least-loaded dispatch.

A ``Replica`` is the unit the fleet scales and fails by — its own
``ModelRegistry`` pinned to one local device (`registry.ServingModel`
``device=``), its own per-model ``MicroBatcher`` worker threads, and its
own health state.  ``ReplicaSet`` owns N of them (default: one per
``jax.local_devices()`` entry) and routes each admitted request to the
healthy replica with the fewest requests in flight.

Health/ejection: a device-path failure inside a replica's predict
function — an organic device error or the ``serving.replica_fault``
injection point (`reliability/faults.py`, matched by ``rank`` = replica
index) — degrades THAT BATCH to the host fallback (no rider fails) and
ejects the replica for ``recovery_s`` seconds: the dispatcher skips it,
traffic redistributes to the survivors, and the ejection is counted.
After the cooldown the next dispatch re-admits it (recovery probe); a
still-faulty replica just re-ejects.  When every replica is ejected the
set dispatches least-loaded anyway — serving degraded beats refusing.

Fleet lifecycle (the PR 8 prepare/commit/rollback story across N
registries): ``prepare_all`` builds+warms+verifies a candidate on every
replica off to the side; ``commit_rolling`` then swaps one replica at a
time (each registry's commit is atomic and each batcher resolves its
model at batch time, so requests in flight during the roll are served by
whichever version their replica holds — never dropped); ``rollback_all``
re-swaps the retained incumbents.  The shadow-validation gate in front
of the roll lives in `gateway.FleetServer.promote_rolling`.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ...observability.metrics_export import LatencyHistogram
from ...reliability import faults
from ...reliability.metrics import rel_inc
from ..batcher import MicroBatcher, ServingStats, bucket_ladder
from ..registry import ModelRegistry, ServingModel


class _AggRequest:
    """Aggregate handle for an oversize async request chunked across
    several batcher submissions — quacks like ``batcher._Request`` for
    the dispatch callback (``result``/``error``/``trace_id``)."""

    __slots__ = ("result", "error", "trace_id", "_parts", "_left", "_lock")

    def __init__(self, n_parts: int, trace_id: Optional[str]):
        self.result = None
        self.error: Optional[BaseException] = None
        self.trace_id = trace_id
        self._parts: List[Optional[np.ndarray]] = [None] * n_parts
        self._left = n_parts
        self._lock = threading.Lock()

    def part_done(self, i: int, req) -> bool:
        """Record chunk ``i``; True once every chunk has reported."""
        with self._lock:
            if req.error is not None and self.error is None:
                self.error = req.error
            self._parts[i] = req.result
            self._left -= 1
            if self._left:
                return False
            if self.error is None:
                self.result = np.concatenate(self._parts, axis=0)
            return True


class Replica:
    """One servable device stack with health state and load accounting."""

    def __init__(self, index: int, device, stats: ServingStats,
                 warm_buckets: Sequence[int], warmup: bool = True,
                 max_batch_rows: int = 256, deadline_ms: float = 2.0,
                 min_bucket: int = 32, recovery_s: float = 1.0):
        self.index = int(index)
        self.device = device
        self.stats = stats
        self.max_batch_rows = int(max_batch_rows)
        self.deadline_ms = float(deadline_ms)
        self.min_bucket = int(min_bucket)
        self.recovery_s = float(recovery_s)
        self.registry = ModelRegistry(stats=stats,
                                      warm_buckets=list(warm_buckets),
                                      warmup=warmup, device=device)
        # per-replica dispatch→response latency (the fleet view; the
        # shared ServingStats request_hist stays the aggregate).  Lock-leaf
        self.hist = LatencyHistogram()
        self._batchers: Dict[str, MicroBatcher] = {}
        self._batcher_lock = threading.Lock()
        self._lock = threading.Lock()
        self._inflight = 0
        self._dispatched = 0
        self._completed = 0
        self._errors = 0
        self._device_failures = 0
        self._ejections = 0
        self._healthy_flag = True
        self._eject_until = 0.0

    # -- health --------------------------------------------------------------

    def healthy(self) -> bool:
        """Current dispatchability; an elapsed cooldown re-admits the
        replica right here (the recovery probe is the next dispatch)."""
        with self._lock:
            if not self._healthy_flag and \
                    time.monotonic() >= self._eject_until:
                self._healthy_flag = True
                rel_inc("serve.replica_recoveries")
            return self._healthy_flag

    def _record_device_failure(self) -> None:
        with self._lock:
            self._device_failures += 1
            if self._healthy_flag:
                self._healthy_flag = False
                self._ejections += 1
                self._eject_until = time.monotonic() + self.recovery_s
                ejected = True
            else:
                self._eject_until = time.monotonic() + self.recovery_s
                ejected = False
        if ejected:
            rel_inc("serve.replica_ejections")

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    # -- batching ------------------------------------------------------------

    def _batcher(self, name: str) -> MicroBatcher:
        with self._batcher_lock:
            b = self._batchers.get(name)
            if b is None:
                # resolve the model at BATCH time so a rolling commit is
                # picked up atomically at the next batch boundary
                def predict_fn(Xpad, m, _name=name):
                    f = faults.fire("serving.replica_fault", rank=self.index)
                    if f is not None:
                        self._record_device_failure()
                        raise faults.InjectedFault(
                            f"injected serving.replica_fault on replica "
                            f"{self.index}")
                    try:
                        return self.registry.get(_name).predict_padded(
                            Xpad, m)
                    except BaseException:
                        self._record_device_failure()
                        raise

                def fallback_fn(Xpad, m, _name=name):
                    return self.registry.get(_name).host_fallback(Xpad, m)

                b = MicroBatcher(
                    predict_fn,
                    num_features=self.registry.get(name).num_features,
                    max_batch_rows=self.max_batch_rows,
                    deadline_ms=self.deadline_ms,
                    min_bucket=self.min_bucket, stats=self.stats,
                    fallback_fn=fallback_fn).start()
                self._batchers[name] = b
            return b

    def submit_async(self, X: np.ndarray, name: str,
                     callback: Callable[[Any], None],
                     trace_id: Optional[str] = None) -> None:
        """Dispatch one request to this replica's batcher without
        blocking; ``callback(handle)`` runs on the batch worker once
        ``handle.result``/``handle.error`` is set.  Oversize requests are
        chunked to the batch budget and re-aggregated here (the async
        analogue of ``MicroBatcher.submit``'s chunk chain)."""
        X = np.ascontiguousarray(np.atleast_2d(np.asarray(X, np.float64)))
        b = self._batcher(name)
        with self._lock:
            self._inflight += 1
            self._dispatched += 1
        t0 = time.perf_counter()

        def _finish(handle) -> None:
            with self._lock:
                self._inflight -= 1
                self._completed += 1
                if handle.error is not None:
                    self._errors += 1
            self.hist.record((time.perf_counter() - t0) * 1e3)
            callback(handle)

        if X.shape[0] <= b.max_rows:
            b.submit_async(X, _finish, trace_id=trace_id)
            return
        chunks = [X[i:i + b.max_rows] for i in range(0, X.shape[0],
                                                     b.max_rows)]
        agg = _AggRequest(len(chunks), trace_id)

        def _chunk_cb(i):
            def cb(req):
                if agg.part_done(i, req):
                    _finish(agg)
            return cb

        for i, c in enumerate(chunks):
            b.submit_async(c, _chunk_cb(i), trace_id=trace_id)

    def stop(self) -> None:
        with self._batcher_lock:
            batchers = list(self._batchers.values())
        for b in batchers:
            b.stop()

    # -- observability -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        # histogram + registry locks are taken BEFORE self._lock so no
        # lock nests inside another (races.py lock-order discipline)
        latency = self.hist.snapshot()
        models = self.registry.versions()
        healthy = self.healthy()
        with self._lock:
            return {"index": self.index,
                    "device": str(self.device),
                    "healthy": healthy,
                    "in_flight": self._inflight,
                    "dispatched": self._dispatched,
                    "completed": self._completed,
                    "errors": self._errors,
                    "device_failures": self._device_failures,
                    "ejections": self._ejections,
                    "models": models,
                    "latency_ms": latency}


class ReplicaSet:
    """N replicas + least-loaded dispatch + fleet-wide lifecycle."""

    def __init__(self, stats: Optional[ServingStats] = None,
                 replicas: int = 0, devices: Optional[Sequence] = None,
                 max_batch_rows: int = 256, deadline_ms: float = 2.0,
                 min_bucket: int = 32, warmup: bool = True,
                 recovery_s: float = 1.0):
        import jax
        self.stats = stats or ServingStats()
        devs = list(devices) if devices is not None else jax.local_devices()
        n = int(replicas) if int(replicas) > 0 else len(devs)
        self.buckets = bucket_ladder(min_bucket, max_batch_rows)
        # replicas round-robin over devices when n > device count (CPU
        # tests run 8 virtual devices; real fleets usually match 1:1)
        self.replicas: List[Replica] = [
            Replica(i, devs[i % len(devs)], self.stats, self.buckets,
                    warmup=warmup, max_batch_rows=max_batch_rows,
                    deadline_ms=deadline_ms, min_bucket=min_bucket,
                    recovery_s=recovery_s)
            for i in range(n)]

    def __len__(self) -> int:
        return len(self.replicas)

    # -- dispatch ------------------------------------------------------------

    def pick(self) -> Replica:
        """Least-loaded healthy replica (lowest in-flight count, index
        breaking ties).  With the whole fleet ejected, dispatch
        least-loaded over everyone — degraded service beats refusal,
        and the batcher's host fallback still answers."""
        healthy = [r for r in self.replicas if r.healthy()]
        pool = healthy or self.replicas
        if not healthy:
            rel_inc("serve.dispatch_no_healthy_replica")
        return min(pool, key=lambda r: (r.inflight, r.index))

    def dispatch(self, X: np.ndarray, name: str,
                 callback: Callable[[Any], None],
                 trace_id: Optional[str] = None) -> Replica:
        r = self.pick()
        r.submit_async(X, name, callback, trace_id=trace_id)
        return r

    # -- fleet lifecycle -----------------------------------------------------

    def load(self, name: str = "default", booster=None,
             model_str: Optional[str] = None,
             model_file: Optional[str] = None) -> Dict[int, int]:
        """Initial (non-rolling) load on every replica."""
        return {r.index: r.registry.load(name, booster=booster,
                                         model_str=model_str,
                                         model_file=model_file)
                for r in self.replicas}

    def prepare_all(self, name: str = "default", booster=None,
                    model_str: Optional[str] = None,
                    model_file: Optional[str] = None) -> List[ServingModel]:
        """Build+warm+verify a candidate on EVERY replica, off to the
        side — serving never sees any of them until ``commit_rolling``.
        A failure on any replica propagates with nothing swapped."""
        return [r.registry.prepare(name, booster=booster,
                                   model_str=model_str,
                                   model_file=model_file)
                for r in self.replicas]

    def commit_rolling(self, prepared: Sequence[ServingModel],
                       settle_s: float = 0.0) -> Dict[int, int]:
        """Swap the prepared candidates in one replica at a time.  Each
        registry commit is atomic and batchers resolve their model at
        batch time, so during the roll a request is served by whichever
        version its replica currently holds — old or new, never neither:
        zero requests are dropped (the hammer test pins this).
        ``settle_s`` optionally pauses between replicas so a canary
        failure surfaces before the roll finishes."""
        versions: Dict[int, int] = {}
        for r, model in zip(self.replicas, prepared):
            versions[r.index] = r.registry.commit(model)
            rel_inc("serve.fleet_rolling_commits")
            if settle_s > 0 and r is not self.replicas[-1]:
                time.sleep(settle_s)
        return versions

    def commit_rolling_gated(self, prepared: Sequence[ServingModel],
                             gate: Callable[[int, ServingModel],
                                            Any],
                             settle_s: float = 0.0,
                             name: str = "default") -> Dict[str, Any]:
        """``commit_rolling`` with an admission gate in front of EVERY
        replica's commit (not just replica 0's): ``gate(index, model)``
        returns ``(passed, report)`` and runs immediately before that
        replica would swap.  The first failing gate aborts the roll and
        reverse-rolls the replicas already committed (each registry's
        retained incumbent swaps back, newest-committed first), leaving
        the fleet homogeneous on the old version.  Requests in flight
        during an abort ride whichever version their replica holds at
        batch-resolve time — old or new, never neither."""
        versions: Dict[int, int] = {}
        gates: List[Dict[str, Any]] = []
        committed: List[Replica] = []
        for r, model in zip(self.replicas, prepared):
            passed, report = gate(r.index, model)
            gates.append({"replica": r.index, "passed": bool(passed),
                          "report": report})
            if not passed:
                restored: Dict[int, int] = {}
                for rc in reversed(committed):
                    restored[rc.index] = rc.registry.rollback(name)
                rel_inc("serve.fleet_roll_aborts")
                return {"committed": False, "aborted_replica": r.index,
                        "versions": versions, "gates": gates,
                        "restored": restored}
            versions[r.index] = r.registry.commit(model)
            committed.append(r)
            rel_inc("serve.fleet_rolling_commits")
            if settle_s > 0 and r is not self.replicas[-1]:
                time.sleep(settle_s)
        return {"committed": True, "aborted_replica": None,
                "versions": versions, "gates": gates, "restored": {}}

    def rollback_all(self, name: str = "default") -> Dict[int, int]:
        """Re-swap every replica's retained incumbent (reverse rolling
        order, matching how far a partial roll got)."""
        restored: Dict[int, int] = {}
        for r in reversed(self.replicas):
            restored[r.index] = r.registry.rollback(name)
        return restored

    # -- aggregate views -----------------------------------------------------

    def versions(self) -> Dict[str, int]:
        """Fleet-wide model versions (replica 0's view — the roll makes
        them momentarily heterogeneous; ``section()`` has the per-replica
        truth)."""
        return self.replicas[0].registry.versions()

    def versions_detail(self) -> Dict[str, Dict[str, Optional[int]]]:
        return self.replicas[0].registry.versions_detail()

    def jit_entries(self) -> Optional[int]:
        return self.replicas[0].registry.jit_entries()

    def get(self, name: str = "default") -> ServingModel:
        return self.replicas[0].registry.get(name)

    def section(self) -> List[Dict[str, Any]]:
        """``serving.replicas[]`` for the stats report / metrics op."""
        return [r.snapshot() for r in self.replicas]

    def stop(self) -> None:
        for r in self.replicas:
            r.stop()
