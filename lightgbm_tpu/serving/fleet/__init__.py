"""Serving fleet: binary wire protocol, async gateway, replica dispatch.

``FleetServer`` is the production front end (`gateway.py`): a selector
event loop speaking both the binary wire protocol (`wire.py`) and the
legacy pickle framing on one port, dispatching least-loaded across one
``Replica`` per local device (`replicas.py`) with per-replica health
ejection and zero-drop rolling promotion."""

from .gateway import FleetServer
from .replicas import Replica, ReplicaSet
from .wire import (WIRE_VERSION, WireError, recv_wire_frame,
                   send_wire_frame)

__all__ = ["FleetServer", "Replica", "ReplicaSet", "WIRE_VERSION",
           "WireError", "recv_wire_frame", "send_wire_frame"]
