"""Selector-based async gateway fronting the replica fleet.

One event-loop thread owns EVERY client socket — thousands of
connections cost buffers, not threads (the thread-per-connection
`serving/server.py` model tops out at the OS thread budget long before
the device does).  The loop accepts, reads, incrementally parses frames,
applies admission, and hands complete predict requests to
`replicas.ReplicaSet.dispatch`; the per-replica ``MicroBatcher`` device
workers stay threaded and respond through a cross-thread outbuf +
socketpair wakeup, so the loop never blocks on device work and device
work never touches a socket.

Three protocols on one port: the first 4 bytes of a connection decide —
``LGBT`` means binary wire frames (`wire.py`), ``GET `` (or ``HEAD``)
means a plain-HTTP Prometheus scrape of ``/metrics`` (one HTTP/1.0
response assembled from the fleet-aggregated snapshot, then close), and
anything else is the legacy 8-byte-length + pickle framing, so old
``ServingClient``s keep working unmodified and a stock Prometheus
scrapes the gateway with zero custom tooling.  Corrupt binary headers
follow wire.py's defined
resync-or-close behavior: an oversize length on a well-formed header
gets a structured error frame then close; a bad magic/version closes
immediately (no trustable frame boundary remains).

Threading map (the races.py lock discipline):

  * loop thread ONLY: ``_conns``, every ``_Conn.inbuf``/parser field
  * ``_Conn.out_lock`` (leaf): ``outbuf``/``closing`` — loop + worker
    threads
  * ``_pending`` under ``self._pending_lock`` (leaf): conns with fresh
    output awaiting a selector interest update, drained by the loop
  * replica/batcher/stats state: their own locks (never held while a
    gateway lock is)

Fleet lifecycle: ``promote_rolling`` prepares a candidate on every
replica, gates it with the PR 8 shadow validator over recorded traffic,
then commits one replica at a time — in-flight requests ride whichever
version their replica holds, so zero requests drop during a roll or a
``rollback_fleet`` (the hammer test in tests/test_fleet.py pins this).
"""

from __future__ import annotations

import contextlib
import pickle
import selectors
import socket
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

_NULL_CTX = contextlib.nullcontext()

from ...io.net import DEFAULT_MAX_FRAME_BYTES, _LEN
from ...lifecycle.recorder import TrafficRecorder
from ...lifecycle.shadow import shadow_validate
from ...observability.drift import DriftMonitor
from ...observability.trace import TraceRecorder, new_trace_id
from ...reliability.degrade import AdmissionController, TenantAdmission
from ...reliability.metrics import rel_inc
from ..batcher import ServingStats
from . import wire
from .replicas import ReplicaSet

_RECV_CHUNK = 1 << 16


class _Conn:
    """Per-connection state.  Parser fields (``inbuf``, ``protocol``)
    are loop-thread-only; ``outbuf``/``closing`` are shared with worker
    threads under ``out_lock`` (a leaf lock)."""

    __slots__ = ("sock", "inbuf", "outbuf", "out_lock", "protocol",
                 "closing")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.inbuf = bytearray()
        self.outbuf = bytearray()
        self.out_lock = threading.Lock()
        self.protocol: Optional[str] = None     # None until sniffed
        self.closing = False                    # flush outbuf, then close


class FleetServer:
    """Async front end + replica fleet; drop-in surface for
    ``PredictionServer`` (start/stop/wait/report/port) plus the fleet
    ops (``promote_rolling``/``rollback_fleet``, per-replica stats)."""

    def __init__(self, booster=None, replicas: int = 0,
                 host: str = "127.0.0.1", port: int = 0,
                 max_batch_rows: int = 256, deadline_ms: float = 2.0,
                 min_bucket: int = 32, warmup: bool = True,
                 telemetry_out: str = "", request_timeout: float = 60.0,
                 max_inflight: int = 64, trace: bool = False,
                 trace_out: str = "", trace_capacity: int = 65536,
                 stats_out: str = "", stats_interval_s: float = 10.0,
                 record_rows: int = 0, recovery_s: float = 1.0,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 slo_p99_ms: float = 50.0, slo_target: float = 0.99,
                 drift_psi_threshold: float = 0.2,
                 drift_ks_threshold: float = 0.15,
                 drift_min_rows: int = 32,
                 tenant_max_inflight: int = 0,
                 drift_baseline_path: str = ""):
        self.host = host
        self.port = int(port)
        self.request_timeout = float(request_timeout)
        self.max_frame_bytes = int(max_frame_bytes)
        self.telemetry_out = telemetry_out
        self.admission = AdmissionController(max_inflight)
        # per-tenant caps (0 = derive from the global cap: a single
        # tenant may use the whole capacity; set lower to isolate)
        self.tenant_admission = TenantAdmission(
            tenant_max_inflight if tenant_max_inflight > 0
            else max_inflight)
        self.stats = ServingStats(slo_p99_ms=slo_p99_ms,
                                  slo_target=slo_target)
        self.tracer: Optional[TraceRecorder] = None
        if trace or trace_out:
            self.tracer = TraceRecorder(True, capacity=trace_capacity)
            self.stats.attach_tracer(self.tracer)
        self.trace_out = trace_out
        self.stats_out = stats_out
        self.stats_interval_s = float(stats_interval_s)
        self.recorder = TrafficRecorder(record_rows)
        # drift detection over the recorder window (observability/
        # drift.py): a no-op until a baseline is captured, which only
        # happens when the recorder is enabled — telemetry off keeps the
        # request path free of any drift work
        self.drift = DriftMonitor(psi_threshold=drift_psi_threshold,
                                  ks_threshold=drift_ks_threshold,
                                  min_rows=drift_min_rows,
                                  tracer=self.tracer)
        # baselines persisted alongside the model artifact survive a
        # gateway restart — without this, a restart silently disables
        # drift detection until the next promotion recaptures
        self.drift_baseline_path = drift_baseline_path
        if drift_baseline_path and self.recorder.enabled:
            try:
                self.drift.restore(drift_baseline_path)
            except Exception as e:
                rel_inc("drift.baseline_restore_errors")
                print(f"[LightGBM-TPU] [Warning] drift baseline restore "
                      f"failed: {e}", flush=True)
        self.lifecycle = None
        self.autopilot = None
        self.replicas = ReplicaSet(
            stats=self.stats, replicas=replicas,
            max_batch_rows=max_batch_rows, deadline_ms=deadline_ms,
            min_bucket=min_bucket, warmup=warmup, recovery_s=recovery_s)
        self.buckets = self.replicas.buckets
        if booster is not None:
            self.replicas.load("default", booster=booster)
        self._sel: Optional[selectors.BaseSelector] = None
        self._srv: Optional[socket.socket] = None
        self._wake_r: Optional[socket.socket] = None
        self._wake_w: Optional[socket.socket] = None
        self._conns: Dict[socket.socket, _Conn] = {}
        self._pending_lock = threading.Lock()
        self._pending: List[_Conn] = []
        self._thread: Optional[threading.Thread] = None
        self._stats_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._stopped = threading.Event()
        self._promote_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetServer":
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((self.host, self.port))
            srv.listen(128)
            # the selector loop IS the timeout discipline: non-blocking
            # sockets can never park a thread in recv/accept
            srv.setblocking(False)
            self.port = srv.getsockname()[1]
            self._srv = srv
            self._wake_r, self._wake_w = socket.socketpair()
            self._wake_r.setblocking(False)
            self._wake_w.setblocking(False)
            self._sel = selectors.DefaultSelector()
            self._sel.register(srv, selectors.EVENT_READ, "accept")
            self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        except Exception:
            # close-on-error-path: a failed bind/register must not leak
            # the listener, the wakeup pair or the selector — the loop's
            # finally never runs because the loop never starts
            self._close_io()
            raise
        self._thread = threading.Thread(
            target=self._loop, name="lgbt-fleet-gateway", daemon=True)
        self._thread.start()
        if self.stats_out:
            self._stats_thread = threading.Thread(
                target=self._stats_loop, name="lgbt-fleet-stats",
                daemon=True)
            self._stats_thread.start()
        return self

    def _close_io(self) -> None:
        """Best-effort close of the loop-owned io objects — the error
        path of ``start()`` (the loop's ``finally`` owns the happy
        path)."""
        if self._sel is not None:
            try:
                self._sel.close()
            except OSError:
                pass
            self._sel = None
        for attr in ("_srv", "_wake_r", "_wake_w"):
            s = getattr(self, attr)
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
                setattr(self, attr, None)

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        if self.autopilot is not None:
            self.autopilot.stop()
        if self.lifecycle is not None:
            self.lifecycle.stop()
        self._wake()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._stats_thread is not None:
            # the snapshot loop wakes on the same stop event; joining it
            # here means no snapshot write can race the final one below
            self._stats_thread.join(timeout=5.0)
        self.replicas.stop()
        if self.telemetry_out:
            from ...observability import write_report
            write_report(self.report(), self.telemetry_out)
        if self.stats_out:
            self._write_stats_snapshot()
        if self.trace_out and self.tracer is not None:
            self.tracer.save(self.trace_out)
        self._stopped.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._stopped.wait(timeout)

    def __enter__(self) -> "FleetServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- report / snapshots --------------------------------------------------

    def report(self) -> Dict[str, Any]:
        rep = self.stats.report(models=self.replicas.versions(),
                                jit_entries=self.replicas.jit_entries())
        rep["serving"]["replicas"] = self.replicas.section()
        if self.lifecycle is not None:
            rep["lifecycle"] = self.lifecycle.section()
        if self.autopilot is not None:
            rep["autopilot"] = self.autopilot.section()
        drift = self.check_drift()
        if drift is not None:
            rep["drift"] = drift
        return rep

    @property
    def registry(self):
        """Replica 0's registry — the fleet's canonical view, letting
        ``LifecycleController`` (built for the single-registry server)
        bind to a fleet for refit/shadow; promotion goes through
        ``promote_rolling``, never through this registry alone."""
        return self.replicas.replicas[0].registry

    # -- drift monitoring ----------------------------------------------------

    def capture_drift_baseline(self, name: str = "default") -> bool:
        """Snapshot the current recorder window as the drift baseline
        for one model — called after every committed promotion, and
        callable by operators/tests directly.  False (nothing captured)
        when recording is off, the window is under the monitor's
        ``min_rows`` or no model by that name is live."""
        if not self.recorder.enabled:
            return False
        try:
            model = self.replicas.get(name)
        except KeyError:
            return False
        captured = self.drift.capture(model, self.recorder.snapshot())
        if captured:
            self._persist_drift_baselines()
        return captured

    def _persist_drift_baselines(self) -> None:
        """Atomic save (tmp + ``os.replace``) of every captured baseline
        so a restarted gateway resumes drift detection immediately."""
        if not self.drift_baseline_path:
            return
        try:
            self.drift.save(self.drift_baseline_path)
        except Exception as e:
            rel_inc("drift.baseline_persist_errors")
            print(f"[LightGBM-TPU] [Warning] drift baseline save "
                  f"failed: {e}", flush=True)

    def check_drift(self, name: str = "default",
                    drain: bool = False) -> Optional[Dict[str, Any]]:
        """Compare the recorder window against the captured baseline →
        the ``drift`` report section (None when recording is off or no
        baseline exists — the proven telemetry-off no-op).  ``drain``
        empties the ring so consecutive checks judge disjoint windows;
        the default non-destructive snapshot keeps the window available
        for the lifecycle shadow replay."""
        if not self.recorder.enabled or not self.drift.has_baseline(name):
            return None
        try:
            model = self.replicas.get(name)
        except KeyError:
            return None
        X = self.recorder.drain() if drain else self.recorder.snapshot()
        if X.size == 0:
            return self.drift.section(name)
        return self.drift.check(model, X) or self.drift.section(name)

    def trace(self) -> Optional[Dict[str, Any]]:
        return self.tracer.export() if self.tracer is not None else None

    def _write_stats_snapshot(self) -> None:
        from ...observability import write_report
        try:
            write_report(self.report(), self.stats_out)
        except Exception as e:
            rel_inc("serve.stats_snapshot_errors")
            print(f"[LightGBM-TPU] [Warning] stats snapshot failed: {e}",
                  flush=True)

    def _stats_loop(self) -> None:
        while not self._stop.wait(self.stats_interval_s):
            self._write_stats_snapshot()

    # -- fleet promotion -----------------------------------------------------

    def promote_rolling(self, name: str = "default", booster=None,
                        model_str: Optional[str] = None,
                        model_file: Optional[str] = None,
                        settle_s: float = 0.0,
                        divergence_max: float = 0.25,
                        latency_max_ratio: float = 8.0,
                        shadow_min_rows: int = 1) -> Dict[str, Any]:
        """Fleet-wide promotion with a PER-REPLICA shadow gate: prepare
        (build+warm+verify) the candidate on EVERY replica off to the
        side, then commit one replica at a time, re-running the shadow
        validator on THAT replica's prepared copy against its own
        incumbent immediately before its swap.  A gate failure at
        replica 0 commits nothing; a failure mid-roll aborts and
        reverse-rolls the already-committed replicas, leaving the fleet
        homogeneous on the incumbent.  Serving is never interrupted:
        each commit (and each rollback) is an atomic registry swap and
        batchers resolve their model per batch.  Returns the structured
        outcome with every gate's report."""
        with self._promote_lock:
            prepared = self.replicas.prepare_all(
                name, booster=booster, model_str=model_str,
                model_file=model_file)
            out: Dict[str, Any] = {"model": name,
                                   "replicas": len(self.replicas)}
            X = self.recorder.snapshot()
            rows = int(X.shape[0]) if X.size else 0
            incumbents: Dict[int, Any] = {}
            for r in self.replicas.replicas:
                try:
                    incumbents[r.index] = r.registry.get(name)
                except KeyError:
                    pass
            gate_active = X.size and rows >= shadow_min_rows

            def _gate(index, model):
                inc = incumbents.get(index)
                if inc is None or not gate_active:
                    return True, {"skipped": True, "rows": rows}
                rep = shadow_validate(
                    model, inc, X, divergence_max=divergence_max,
                    latency_max_ratio=latency_max_ratio,
                    min_rows=shadow_min_rows, buckets=self.buckets)
                return bool(rep["passed"]), rep

            roll = self.replicas.commit_rolling_gated(
                prepared, _gate, settle_s=settle_s, name=name)
            out["gates"] = [{"replica": g["replica"],
                             "passed": g["passed"]}
                            for g in roll["gates"]]
            out["shadow"] = (roll["gates"][0]["report"] if roll["gates"]
                             else {"skipped": True, "rows": rows})
            out["versions"] = roll["versions"]
            out["committed"] = roll["committed"]
            if not roll["committed"]:
                out["aborted_replica"] = roll["aborted_replica"]
                out["restored"] = roll["restored"]
                # mid-roll abort (something already committed, now
                # reverse-rolled) vs a clean replica-0 rejection
                rel_inc("serve.fleet_promotions_aborted"
                        if roll["restored"]
                        else "serve.fleet_promotions_rejected")
                if self.tracer is not None:
                    self.tracer.instant(
                        "fleet.roll_abort",
                        args={"model": name,
                              "replica": str(roll["aborted_replica"])})
                return out
            rel_inc("serve.fleet_promotions")
            # the traffic the new version was judged on becomes its
            # drift baseline: later windows are compared against the
            # distribution that was live at promote time
            if self.recorder.enabled and X.size:
                out["drift_baseline"] = self.drift.capture(prepared[0], X)
                self._persist_drift_baselines()
            return out

    def rollback_fleet(self, name: str = "default") -> Dict[str, Any]:
        """Re-swap every replica's retained incumbent (zero-drop for the
        same reason the roll is)."""
        with self._promote_lock:
            restored = self.replicas.rollback_all(name)
        return {"model": name, "restored": restored}

    # -- event loop ----------------------------------------------------------

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except (OSError, AttributeError, BlockingIOError):
            pass                      # full pipe still wakes the selector

    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                events = self._sel.select(timeout=0.25)
                for key, mask in events:
                    if key.data == "accept":
                        self._accept_ready()
                    elif key.data == "wake":
                        self._drain_wake()
                    else:
                        conn: _Conn = key.data
                        if mask & selectors.EVENT_READ:
                            self._read_ready(conn)
                        if mask & selectors.EVENT_WRITE and \
                                conn.sock in self._conns:
                            self._write_ready(conn)
                self._apply_pending()
        finally:
            for conn in list(self._conns.values()):
                self._close_conn(conn)
            for s in (self._srv, self._wake_r, self._wake_w):
                if s is not None:
                    try:
                        self._sel.unregister(s)
                    except (KeyError, ValueError):
                        pass
                    try:
                        s.close()
                    except OSError:
                        pass
            self._sel.close()

    def _accept_ready(self) -> None:
        while True:
            try:
                sock, _addr = self._srv.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            sock.setblocking(False)
            conn = _Conn(sock)
            self._conns[sock] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)
            rel_inc("serve.fleet_connections")

    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            pass

    def _apply_pending(self) -> None:
        """Loop-thread: pick up conns whose outbuf gained data from a
        worker thread and add EVENT_WRITE to their interest."""
        with self._pending_lock:
            pending, self._pending = self._pending, []
        for conn in pending:
            if conn.sock not in self._conns:
                continue
            self._write_ready(conn)      # try inline; registers WRITE if short

    def _read_ready(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(_RECV_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            self._close_conn(conn)
            return
        conn.inbuf.extend(data)
        try:
            self._parse(conn)
        except wire.WireError as e:
            # bad magic / version / unparseable frame: no trustable
            # frame boundary remains — close (wire.py's defined
            # resync-or-close contract)
            rel_inc("serve.fleet_wire_errors")
            self._send_bytes(conn, wire.error_frame(str(e)), close=True)
            if conn.protocol != "binary":
                self._close_conn(conn)

    def _write_ready(self, conn: _Conn) -> None:
        if conn.sock not in self._conns:
            return
        with conn.out_lock:
            buf = conn.outbuf
            while buf:
                try:
                    sent = conn.sock.send(bytes(buf[:_RECV_CHUNK]))
                except (BlockingIOError, InterruptedError):
                    break
                except OSError:
                    self._close_conn(conn)
                    return
                del buf[:sent]
            drained = not buf
            closing = conn.closing
        want = selectors.EVENT_READ if drained else \
            selectors.EVENT_READ | selectors.EVENT_WRITE
        try:
            self._sel.modify(conn.sock, want, conn)
        except (KeyError, ValueError):
            return
        if drained and closing:
            self._close_conn(conn)

    def _close_conn(self, conn: _Conn) -> None:
        if self._conns.pop(conn.sock, None) is None:
            return
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    # -- response path (any thread) ------------------------------------------

    def _send_bytes(self, conn: _Conn, data: bytes,
                    close: bool = False) -> None:
        """Queue response bytes and (cross-thread) wake the selector.
        Safe from worker threads: only touches outbuf under its leaf
        lock and the pending list under its own."""
        with conn.out_lock:
            conn.outbuf.extend(data)
            if close:
                conn.closing = True
        on_loop = threading.current_thread() is self._thread
        if on_loop:
            self._write_ready(conn)
        else:
            with self._pending_lock:
                self._pending.append(conn)
            self._wake()

    def _encode_resp(self, conn: _Conn, resp: Dict[str, Any],
                     opcode: int, trace_id: str = "") -> bytes:
        """One response dict → this connection's framing."""
        if conn.protocol == "pickle":
            blob = pickle.dumps(resp, protocol=pickle.HIGHEST_PROTOCOL)
            return _LEN.pack(len(blob)) + blob
        if opcode == wire.OP_PREDICT and resp.get("ok"):
            return wire.pack_frame(
                wire.OP_PREDICT,
                wire.encode_predict_response(resp["scores"]),
                wire.FLAG_RESP, trace_id)
        if resp.get("shed"):
            return wire.shed_frame(resp.get("inflight", 0),
                                   resp.get("capacity", 0), trace_id,
                                   model=resp.get("model", ""),
                                   scope=resp.get("scope", ""))
        if not resp.get("ok", True):
            return wire.error_frame(str(resp.get("error")), trace_id)
        body = {k: v for k, v in resp.items() if k != "ok"}
        return wire.pack_frame(opcode, wire.encode_json(body),
                               wire.FLAG_RESP, trace_id)

    # -- request parsing (loop thread only) ----------------------------------

    def _parse(self, conn: _Conn) -> None:
        if conn.protocol is None:
            if len(conn.inbuf) < len(wire.MAGIC):
                return
            # three protocols, one port, one 4-byte sniff: the wire
            # magic means binary frames, an HTTP method means a plain
            # Prometheus scrape, anything else is legacy pickle framing
            head = bytes(conn.inbuf[:4])
            if head == wire.MAGIC:
                conn.protocol = "binary"
            elif head in (b"GET ", b"HEAD"):
                conn.protocol = "http"
            else:
                conn.protocol = "pickle"
        if conn.protocol == "binary":
            self._parse_binary(conn)
        elif conn.protocol == "http":
            self._parse_http(conn)
        else:
            self._parse_pickle(conn)

    def _parse_binary(self, conn: _Conn) -> None:
        while len(conn.inbuf) >= wire.HEADER_SIZE:
            opcode, flags, tid, length = wire.unpack_header(
                bytes(conn.inbuf[:wire.HEADER_SIZE]), self.max_frame_bytes)
            if len(conn.inbuf) < wire.HEADER_SIZE + length:
                return
            payload = bytes(conn.inbuf[wire.HEADER_SIZE:
                                       wire.HEADER_SIZE + length])
            del conn.inbuf[:wire.HEADER_SIZE + length]
            self._handle_binary(conn, opcode, flags, tid, payload)
            if conn.sock not in self._conns:
                return

    # upper bound on an HTTP request head: a scrape request is a few
    # hundred bytes; anything bigger is not a scraper
    _HTTP_MAX_HEAD = 16384

    def _parse_http(self, conn: _Conn) -> None:
        """The Prometheus scrape protocol: wait for one complete request
        head, answer one HTTP/1.0 response assembled from the
        fleet-aggregated snapshot, close.  Loop thread only — the page
        render is host-side string work, never a device call."""
        end = conn.inbuf.find(b"\r\n\r\n")
        if end < 0:
            if len(conn.inbuf) > self._HTTP_MAX_HEAD:
                self._close_conn(conn)
            return
        head = bytes(conn.inbuf[:end]).decode("latin-1", "replace")
        del conn.inbuf[:]
        parts = head.split("\r\n", 1)[0].split()
        method = parts[0].upper() if parts else "GET"
        path = parts[1].split("?", 1)[0] if len(parts) >= 2 else ""
        if path == "/metrics":
            status, ctype = "200 OK", "text/plain; version=0.0.4; " \
                                      "charset=utf-8"
            body = self._prometheus_page()
        else:
            status, ctype = "404 Not Found", "text/plain; charset=utf-8"
            body = "not found (scrape /metrics)\n"
        rel_inc("serve.fleet_http_scrapes")
        payload = body.encode("utf-8")
        resp = (f"HTTP/1.0 {status}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: close\r\n\r\n").encode("ascii")
        if method != "HEAD":
            resp += payload
        self._send_bytes(conn, resp, close=True)

    def _prometheus_page(self) -> str:
        """The fleet-aggregated Prometheus exposition: gateway counters
        + admission + every replica + per-tenant SLO series + drift
        gauges — the same text the binary/pickle ``metrics`` op returns."""
        from ...observability.metrics_export import prometheus_snapshot
        return prometheus_snapshot(
            self.stats, registry=self.replicas, admission=self.admission,
            replicas=self.replicas.section(),
            tenants=self.stats.tenants_section(), drift=self.drift)

    def _parse_pickle(self, conn: _Conn) -> None:
        while len(conn.inbuf) >= _LEN.size:
            (ln,) = _LEN.unpack(bytes(conn.inbuf[:_LEN.size]))
            if self.max_frame_bytes > 0 and ln > self.max_frame_bytes:
                rel_inc("net.frames_rejected_oversize")
                self._close_conn(conn)
                return
            if len(conn.inbuf) < _LEN.size + ln:
                return
            blob = bytes(conn.inbuf[_LEN.size:_LEN.size + ln])
            del conn.inbuf[:_LEN.size + ln]
            try:
                msg = pickle.loads(blob)
            except Exception:
                self._close_conn(conn)
                return
            self._handle_pickle(conn, msg)
            if conn.sock not in self._conns:
                return

    # -- op dispatch ---------------------------------------------------------

    def _handle_pickle(self, conn: _Conn, msg) -> None:
        if not isinstance(msg, dict) or "op" not in msg:
            self._send_bytes(conn, self._encode_resp(
                conn, {"ok": False, "error": "malformed request"}, 0))
            return
        op = str(msg.get("op"))
        if op == "predict":
            X = msg.get("data")
            self._predict(conn, wire.OP_PREDICT, np.asarray(X, np.float64),
                          str(msg.get("model", "default")),
                          bool(msg.get("raw_score")),
                          msg.get("trace_id") or "")
            return
        self._control(conn, op, dict(msg), opcode=0)

    def _handle_binary(self, conn: _Conn, opcode: int, flags: int,
                       tid: str, payload: bytes) -> None:
        if opcode == wire.OP_PREDICT:
            X, name = wire.decode_predict_request(payload)
            self._predict(conn, opcode, X, name,
                          bool(flags & wire.FLAG_RAW_SCORE), tid)
            return
        msg = wire.decode_json(payload) if payload else {}
        msg["op"] = wire.OP_NAMES.get(opcode, "?")
        self._control(conn, msg["op"], msg, opcode=opcode, trace_id=tid)

    def _control(self, conn: _Conn, op: str, msg: Dict[str, Any],
                 opcode: int, trace_id: str = "") -> None:
        """Non-predict ops.  Cheap ones answer inline on the loop
        thread; slow ones (swap = prepare+warm on every replica,
        shutdown = join worker threads) run on a side thread and respond
        through the cross-thread outbuf."""
        if op == "ping":
            resp = {"ok": True, "version": wire.WIRE_VERSION}
        elif op == "health":
            models = self.replicas.versions()
            healthy = sum(1 for r in self.replicas.replicas if r.healthy())
            resp = {"ok": True,
                    "ready": bool(models) and not self._stop.is_set(),
                    "models": models,
                    "versions": self.replicas.versions_detail(),
                    "replicas": len(self.replicas),
                    "replicas_healthy": healthy,
                    **self.admission.snapshot()}
        elif op == "stats":
            resp = {"ok": True, "report": self.report()}
        elif op == "metrics":
            # refresh the drift verdict so a scrape-by-op sees the same
            # data the stats report carries, then render the one page
            # the HTTP endpoint also serves
            self.check_drift()
            resp = {"ok": True,
                    "text": self._prometheus_page(),
                    "content_type": "text/plain; version=0.0.4"}
        elif op == "swap":
            def _swap():
                try:
                    out = self.promote_rolling(
                        str(msg.get("model", "default")),
                        model_str=msg.get("model_str"),
                        model_file=msg.get("model_file"))
                    if out.get("committed"):
                        r = {"ok": True, "fleet": out,
                             "version": max(out["versions"].values())}
                    else:
                        r = {"ok": False, "fleet": out,
                             "error": "candidate rejected by shadow gate"}
                except Exception as e:
                    r = {"ok": False,
                         "error": f"{type(e).__name__}: {e}"}
                if not r.get("ok"):
                    # control-plane failure: burn the tenant's error
                    # budget too, so the rollback watchdog's error-rate
                    # deltas see failed swaps, not just predict errors
                    self.stats.record_error()
                    self.stats.record_tenant_error(
                        str(msg.get("model", "default")))
                self._send_bytes(conn, self._encode_resp(
                    conn, r, opcode or wire.OP_SWAP, trace_id))
            threading.Thread(target=_swap, name="lgbt-fleet-swap",
                             daemon=True).start()
            return
        elif op == "shutdown":
            resp = {"ok": True}
            self._send_bytes(conn, self._encode_resp(
                conn, resp, opcode or wire.OP_SHUTDOWN, trace_id),
                close=True)
            threading.Thread(target=self.stop, daemon=True).start()
            return
        else:
            resp = {"ok": False, "error": f"unknown op {op!r}"}
            self.stats.record_error()
            self.stats.record_tenant_error(str(msg.get("model",
                                                       "default")))
        self._send_bytes(conn, self._encode_resp(conn, resp,
                                                 opcode, trace_id))

    def _predict(self, conn: _Conn, opcode: int, X: np.ndarray, name: str,
                 raw_score: bool, trace_id: str) -> None:
        tid = trace_id or (new_trace_id() if self.tracer is not None
                           else "")
        if not self.admission.try_acquire():
            self.stats.record_shed()
            self.stats.record_tenant_shed(name)
            resp = {"ok": False, "error": "overloaded", "shed": True,
                    "model": name,
                    "inflight": self.admission.inflight,
                    "capacity": self.admission.capacity}
            if tid:
                resp["trace_id"] = tid
            self._send_bytes(conn, self._encode_resp(conn, resp, opcode,
                                                     tid))
            return
        if not self.tenant_admission.try_acquire(name):
            # over THIS tenant's cap while the gateway still has global
            # headroom: shed the hot tenant, the rest keep admitting
            self.admission.release()
            self.stats.record_shed()
            self.stats.record_tenant_shed(name)
            self.stats.record_tenant_cap_shed(name)
            resp = {"ok": False, "error": "overloaded", "shed": True,
                    "model": name, "scope": "tenant",
                    "inflight": self.tenant_admission.inflight(name),
                    "capacity": self.tenant_admission.capacity}
            if tid:
                resp["trace_id"] = tid
            self._send_bytes(conn, self._encode_resp(conn, resp, opcode,
                                                     tid))
            return
        t0 = time.perf_counter()
        try:
            X = np.atleast_2d(X)
            self.recorder.record(X)
            replica = self.replicas.pick()
            model = replica.registry.get(name)
            span = self.tracer.span(
                "serve.request", cat="serving", trace_id=tid or None,
                args={"model": name, "rows": int(X.shape[0]),
                      "replica": replica.index}) \
                if self.tracer is not None else _NULL_CTX

            def _done(handle) -> None:
                try:
                    if handle.error is not None:
                        self.stats.record_error()
                        resp = {"ok": False,
                                "error": f"{type(handle.error).__name__}: "
                                         f"{handle.error}"}
                    else:
                        scores = model.convert_output(handle.result,
                                                      raw_score)
                        resp = {"ok": True, "scores": np.asarray(scores)}
                    if tid:
                        resp["trace_id"] = tid
                    self._send_bytes(conn, self._encode_resp(
                        conn, resp, opcode, tid))
                finally:
                    self.tenant_admission.release(name)
                    self.admission.release()
                    ms = (time.perf_counter() - t0) * 1e3
                    self.stats.record_request_latency(ms)
                    self.stats.record_tenant_request(
                        name, ms, error=handle.error is not None)

            with span:
                replica.submit_async(X, name, _done, trace_id=tid or None)
        except Exception as e:
            # dispatch-time failure (unknown model, bad shape): the
            # admission slots release HERE because no callback will
            self.stats.record_error()
            self.tenant_admission.release(name)
            self.admission.release()
            ms = (time.perf_counter() - t0) * 1e3
            self.stats.record_request_latency(ms)
            self.stats.record_tenant_request(name, ms, error=True)
            resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            if tid:
                resp["trace_id"] = tid
            self._send_bytes(conn, self._encode_resp(conn, resp, opcode,
                                                     tid))
