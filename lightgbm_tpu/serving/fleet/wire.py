"""Binary wire protocol for the serving fleet — no pickle on the wire.

The original serving RPC (`serving/server.py` over `io/net.py`) frames
every message as 8-byte little-endian length + pickle.  Pickle is a
safety liability for untrusted clients (``pickle.loads`` executes
arbitrary reduce callables) and a bandwidth one (a float64 row matrix
pickles at ~2.2x its raw size).  This module defines the typed
fixed-header framing the fleet gateway speaks instead:

Frame header (32 bytes, little-endian)::

    magic      4s   b"LGBT"
    version    u8   protocol version (1)
    opcode     u8   OP_* below
    flags      u16  FLAG_* bits
    trace_id   16s  NUL-padded ASCII request id ("" = none)
    length     u64  payload byte count

Payloads:

  * ``OP_PREDICT`` request — ``<IIH`` (n_rows, n_features, name_len) +
    UTF-8 model name + raw little-endian **float32** row block
    (n_rows x n_features, C order).  ``FLAG_RAW_SCORE`` asks for raw
    scores.
  * ``OP_PREDICT`` response (``FLAG_RESP``) — ``<II`` (n_rows, k) + raw
    little-endian **float64** scores (exact: the response is tiny next
    to the request, so it keeps full precision).
  * ``OP_SHED`` / ``OP_ERROR`` responses and every other op — a UTF-8
    JSON object.  Typed data only; nothing on this path ever unpickles.

Version negotiation: a new client opens with a binary ``OP_PING``.  A
fleet gateway answers in kind (``{"version": 1}``); a legacy pickle
server reads the header as a giant length prefix, trips its
``max_frame_bytes`` guard and closes — the client reconnects and falls
back to pickle framing (`server.ServingClient`).  A legacy client
against the gateway simply never sends the magic, and the gateway
serves that connection as pickle (`gateway.AsyncGateway` sniffs the
first 4 bytes).

Corrupt input: the header is UNTRUSTED.  A bad magic/version or a
length past ``max_bytes`` raises ``WireError`` BEFORE any payload
allocation; because a byte stream with a corrupt header has no reliable
resync point, the defined behavior is **close the connection** (the
reader cannot know where the next frame starts).  `tests/test_fleet.py`
pins both halves: no over-allocation, no desync-into-garbage.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ...io.net import DEFAULT_MAX_FRAME_BYTES, _recv_exact

MAGIC = b"LGBT"
WIRE_VERSION = 1

_HDR = struct.Struct("<4sBBH16sQ")          # magic, ver, op, flags, tid, len
_PREDICT_REQ = struct.Struct("<IIH")        # n_rows, n_features, name_len
_PREDICT_RESP = struct.Struct("<II")        # n_rows, k

HEADER_SIZE = _HDR.size                     # 32

# opcodes (request and response share the opcode; FLAG_RESP marks the
# direction, OP_SHED/OP_ERROR are response-only)
OP_PREDICT = 1
OP_PING = 2
OP_HEALTH = 3
OP_METRICS = 4
OP_STATS = 5
OP_SWAP = 6
OP_SHUTDOWN = 7
OP_SHED = 8
OP_ERROR = 9

FLAG_RESP = 1 << 0
FLAG_RAW_SCORE = 1 << 1

OP_NAMES = {OP_PREDICT: "predict", OP_PING: "ping", OP_HEALTH: "health",
            OP_METRICS: "metrics", OP_STATS: "stats", OP_SWAP: "swap",
            OP_SHUTDOWN: "shutdown", OP_SHED: "shed", OP_ERROR: "error"}


class WireError(ConnectionError):
    """Corrupt or oversize binary frame.  A ``ConnectionError`` subclass
    because the only safe reaction is dropping the connection: after a
    bad fixed-size header there is no way to find the next frame
    boundary in the stream."""


def _json_default(obj):
    # reports carry numpy scalars (latency percentiles etc.)
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError(f"not JSON serializable: {type(obj).__name__}")


def pack_frame(opcode: int, payload: bytes = b"", flags: int = 0,
               trace_id: str = "") -> bytes:
    tid = (trace_id or "").encode("ascii", "replace")[:16]
    return _HDR.pack(MAGIC, WIRE_VERSION, opcode, flags, tid,
                     len(payload)) + payload


def unpack_header(header: bytes,
                  max_bytes: int = DEFAULT_MAX_FRAME_BYTES
                  ) -> Tuple[int, int, str, int]:
    """Validate a 32-byte header → (opcode, flags, trace_id, length).

    Every check runs BEFORE the payload exists: a corrupt or malicious
    header can never drive an allocation (`io/net.py` gives the pickle
    path the same guarantee)."""
    magic, ver, opcode, flags, tid, length = _HDR.unpack(header)
    if magic != MAGIC:
        raise WireError(f"bad frame magic {magic!r} — not a wire frame "
                        f"(close and resynchronize by reconnecting)")
    if ver != WIRE_VERSION:
        raise WireError(f"unsupported wire version {ver} "
                        f"(this side speaks {WIRE_VERSION})")
    if opcode not in OP_NAMES:
        raise WireError(f"unknown opcode {opcode}")
    if max_bytes > 0 and length > max_bytes:
        raise WireError(
            f"frame length {length} exceeds max_frame_bytes {max_bytes} — "
            f"corrupt header or protocol mismatch")
    return opcode, flags, tid.rstrip(b"\x00").decode("ascii", "replace"), \
        int(length)


# -- JSON payloads (every non-predict op) ------------------------------------

def encode_json(obj: Dict[str, Any]) -> bytes:
    return json.dumps(obj, default=_json_default,
                      separators=(",", ":")).encode("utf-8")


def decode_json(payload: bytes) -> Dict[str, Any]:
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"malformed JSON payload: {e}") from None
    if not isinstance(obj, dict):
        raise WireError("JSON payload is not an object")
    return obj


# -- predict payloads --------------------------------------------------------

def encode_predict_request(X: np.ndarray, model: str = "default") -> bytes:
    """Raw float32 row block: ``<IIH`` + name + C-order rows."""
    X = np.ascontiguousarray(np.atleast_2d(X), dtype=np.float32)
    name = model.encode("utf-8")
    return _PREDICT_REQ.pack(X.shape[0], X.shape[1], len(name)) + name + \
        X.tobytes()


def decode_predict_request(payload: bytes) -> Tuple[np.ndarray, str]:
    if len(payload) < _PREDICT_REQ.size:
        raise WireError("truncated predict request payload")
    n, f, nlen = _PREDICT_REQ.unpack_from(payload)
    ofs = _PREDICT_REQ.size
    want = ofs + nlen + n * f * 4
    if len(payload) != want:
        raise WireError(f"predict payload size mismatch: header promises "
                        f"{want} bytes, frame carries {len(payload)}")
    name = payload[ofs:ofs + nlen].decode("utf-8", "replace") or "default"
    X = np.frombuffer(payload, dtype="<f4", count=n * f,
                      offset=ofs + nlen).reshape(n, f)
    return X.astype(np.float64), name


def encode_predict_response(scores: np.ndarray) -> bytes:
    """``<II`` (n_rows, k) + float64 scores (k=1 → flat vector)."""
    s = np.asarray(scores, dtype="<f8")
    if s.ndim == 1:
        n, k = s.shape[0], 1
    else:
        n, k = s.shape
    return _PREDICT_RESP.pack(n, k) + np.ascontiguousarray(s).tobytes()


def decode_predict_response(payload: bytes) -> np.ndarray:
    if len(payload) < _PREDICT_RESP.size:
        raise WireError("truncated predict response payload")
    n, k = _PREDICT_RESP.unpack_from(payload)
    want = _PREDICT_RESP.size + n * k * 8
    if len(payload) != want:
        raise WireError(f"predict response size mismatch: header promises "
                        f"{want} bytes, frame carries {len(payload)}")
    s = np.frombuffer(payload, dtype="<f8", count=n * k,
                      offset=_PREDICT_RESP.size)
    return s.copy() if k == 1 else s.reshape(n, k).copy()


# -- blocking socket helpers (client side + tests) ---------------------------

def send_wire_frame(sock, opcode: int, payload: bytes = b"",
                    flags: int = 0, trace_id: str = "") -> None:
    sock.sendall(pack_frame(opcode, payload, flags, trace_id))


def recv_wire_frame(sock, max_bytes: int = DEFAULT_MAX_FRAME_BYTES
                    ) -> Tuple[int, int, str, bytes]:
    """Blocking receive of one frame → (opcode, flags, trace_id, payload).
    The header is validated (magic/version/length guard) before the
    payload is read, so ``max_bytes`` bounds every allocation."""
    opcode, flags, tid, length = unpack_header(
        _recv_exact(sock, HEADER_SIZE), max_bytes)
    payload = _recv_exact(sock, length) if length else b""
    return opcode, flags, tid, payload


def error_frame(message: str, trace_id: str = "") -> bytes:
    return pack_frame(OP_ERROR, encode_json({"error": message}),
                      FLAG_RESP, trace_id)


def shed_frame(inflight: int, capacity: int, trace_id: str = "",
               model: str = "", scope: str = "") -> bytes:
    """Structured overload answer.  ``model`` names the shed tenant and
    ``scope`` distinguishes a per-tenant-cap shed (``"tenant"``) from a
    global-capacity one (empty), so clients and log scrapers can tell
    WHOSE budget burned."""
    body = {"error": "overloaded", "shed": True,
            "inflight": int(inflight), "capacity": int(capacity)}
    if model:
        body["model"] = model
    if scope:
        body["scope"] = scope
    return pack_frame(OP_SHED, encode_json(body), FLAG_RESP, trace_id)


def response_to_dict(opcode: int, flags: int, trace_id: str,
                     payload: bytes) -> Dict[str, Any]:
    """Normalize a binary RESPONSE frame into the dict shape the pickle
    protocol uses, so ``ServingClient`` shares one result path (shed →
    ``ServerOverloaded``, error → ``RuntimeError``) across protocols."""
    if opcode == OP_SHED:
        resp = decode_json(payload)
        resp.setdefault("ok", False)
    elif opcode == OP_ERROR:
        resp = {"ok": False, "error": decode_json(payload).get("error")}
    elif opcode == OP_PREDICT:
        resp = {"ok": True, "scores": decode_predict_response(payload)}
    else:
        resp = decode_json(payload) if payload else {}
        resp.setdefault("ok", True)
    if trace_id:
        resp.setdefault("trace_id", trace_id)
    return resp
