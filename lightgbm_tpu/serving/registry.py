"""Versioned model registry with atomic hot-swap.

A ``ServingModel`` binds one booster to the device pipeline: the padded-
array binner (`binner.py`), the packed tree traversal
(`predictor.DevicePredictor`) and the compile-cache bookkeeping.  Boosters
WITH training data serve in their training bin space; text-loaded boosters
serve through the reconstructed schema (`predictor.reconstruct_bin_schema`)
— the loaded-model host-path caveat is gone.

``ModelRegistry.prepare`` builds, warms and VERIFIES a candidate (device
scores vs the host reference traversal on a fuzz sample) entirely off to
the side — the lifecycle loop's shadow validation replays exactly this
prepared-but-never-swapped object.  ``commit`` performs the atomic swap
under the registry lock while RETAINING the displaced incumbent, so
``rollback`` can re-swap it back (the lifecycle watchdog's automatic
recovery); ``load`` is prepare+commit.  A failed prepare raises and
changes nothing — rejection is the absence of the swap.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_NULL_CTX = contextlib.nullcontext()

from .batcher import ServingStats, bucket_ladder, next_pow2
from .binner import BinnerArrays


class ServingModel:
    """One immutable servable model version (swap = replace the object)."""

    def __init__(self, booster, stats: Optional[ServingStats] = None,
                 name: str = "default", version: int = 1, device=None):
        from ..predictor import DevicePredictor, reconstruct_bin_schema

        self.booster = booster
        self.name = name
        self.version = int(version)
        self.stats = stats or ServingStats()
        # pin this model's compute to one local device (the fleet gives
        # each replica its own); None = jax default placement
        self.device = device
        gbdt = booster.gbdt
        if not gbdt.models:
            raise ValueError("model has no trees to serve")
        data = gbdt.train_data
        if data is None:
            data = reconstruct_bin_schema(gbdt)
        self.predictor = DevicePredictor(gbdt, data)
        self.arrays = BinnerArrays.for_data(data)
        self.num_features = int(gbdt.max_feature_idx) + 1
        self.K = self.predictor.K
        self.objective = gbdt.objective
        self._warmed: set = set()

    # -- the batch path (batcher worker thread only) -------------------------

    def predict_padded(self, Xpad: np.ndarray, m: int) -> np.ndarray:
        """Raw scores of the first ``m`` rows of a padded
        ``(bucket, num_features)`` matrix; stages timed into ``stats``."""
        from ..reliability import faults
        f = faults.fire("serve.predict.delay")
        if f is not None:
            import time as _time
            _time.sleep(float(f.get("seconds", 0.1)))
        if faults.fire("serve.predict.fail") is not None:
            raise RuntimeError("injected fault serve.predict.fail "
                               "(device predict path)")
        bucket = Xpad.shape[0]
        self.stats.record_compile_cache(hit=bucket in self._warmed)
        self._warmed.add(bucket)
        with self.stats.stage("bin"):
            xu = self.arrays.select_used(Xpad)
            # device_put of the committed input pulls the whole jitted
            # bin+traverse program onto the replica's device; the cached
            # uncommitted binner/pack constants follow placement
            xb = jnp.asarray(xu) if self.device is None else \
                jax.device_put(xu, self.device)
            bins = self.arrays.bin_device(xb)
            bins.block_until_ready()
        with self.stats.stage("traverse"):
            score = self.predictor.predict_binned(bins)
            score.block_until_ready()
        with self.stats.stage("unpad"):
            s = np.asarray(score)[:, :m].astype(np.float64)
            return s[0] if self.K == 1 else s.T

    def convert_output(self, raw: np.ndarray,
                       raw_score: bool = False) -> np.ndarray:
        if raw_score or self.objective is None:
            return raw
        return self.objective.convert_output(raw)

    def warm(self, buckets: Sequence[int]) -> List[int]:
        """Compile the jitted bin+traverse pipeline for every bucket shape
        up front — after this, requests inside the ladder never compile."""
        warmed = []
        for b in buckets:
            self.predict_padded(np.zeros((int(b), self.num_features)), 1)
            warmed.append(int(b))
        return warmed

    def jit_entries(self) -> Optional[int]:
        """Underlying jit cache entry count (bin + traverse), when the jax
        version exposes it — the honest recompile gauge the zero-recompile
        test asserts on."""
        try:
            from ..predictor import _predict_all
            from .binner import _bin_device
            return int(_bin_device._cache_size()) + \
                int(_predict_all._cache_size())
        except Exception:
            return None

    def host_fallback(self, Xpad: np.ndarray, m: int) -> np.ndarray:
        """Degraded-mode scoring for a padded batch: the host numpy
        traversal over the real rows, same output convention as
        ``predict_padded`` — the batcher swaps to this when the device
        path raises (`batcher.MicroBatcher` ``fallback_fn``)."""
        return self.host_raw(Xpad[:m])

    def host_raw(self, X: np.ndarray) -> np.ndarray:
        """Reference host traversal (per-tree numpy), the verify oracle."""
        gbdt = self.booster.gbdt
        X = np.ascontiguousarray(X, dtype=np.float64)
        k = max(gbdt.num_tree_per_iteration, 1)
        out = np.zeros((X.shape[0], k))
        for i, t in enumerate(gbdt.models):
            out[:, i % k] += t.predict(X)
        return out[:, 0] if k == 1 else out


class ModelRegistry:
    """Name → current ``ServingModel``; swaps are atomic and verified."""

    def __init__(self, stats: Optional[ServingStats] = None,
                 warm_buckets: Sequence[int] = (), warmup: bool = True,
                 verify_rows: int = 64, verify_tol: float = 1e-5,
                 device=None):
        self.stats = stats or ServingStats()
        self.warm_buckets = [int(b) for b in warm_buckets]
        self.warmup = bool(warmup)
        self.verify_rows = int(verify_rows)
        self.verify_tol = float(verify_tol)
        # every model prepared by this registry is pinned here (one
        # registry per fleet replica); None = jax default placement
        self.device = device
        self._lock = threading.Lock()
        self._models: Dict[str, ServingModel] = {}
        # the version each commit displaced, retained per name so
        # rollback() can re-swap it (lifecycle auto-rollback)
        self._previous: Dict[str, ServingModel] = {}

    # -- prepare / commit (load = both) --------------------------------------

    def prepare(self, name: str = "default", booster=None,
                model_str: Optional[str] = None,
                model_file: Optional[str] = None) -> ServingModel:
        """Build, warm and verify a candidate WITHOUT swapping it in —
        the serving path never sees it.  The lifecycle shadow loop
        replays this object; ``commit`` makes it live.  On any failure
        the exception propagates and nothing changed."""
        if booster is None:
            from ..engine import Booster
            booster = Booster(model_str=model_str) if model_str is not None \
                else Booster(model_file=model_file)
        with self._lock:
            version = self._models[name].version + 1 \
                if name in self._models else 1
        tr = self.stats.tracer
        model = ServingModel(booster, self.stats, name, version,
                             device=self.device)
        if self.warmup and self.warm_buckets:
            with (tr.span("serve.warm", cat="serving",
                          args={"buckets": list(self.warm_buckets)})
                  if tr is not None else _NULL_CTX):
                model.warm(self.warm_buckets)
        with (tr.span("serve.verify", cat="serving")
              if tr is not None else _NULL_CTX):
            self._verify(model)
        return model

    def commit(self, model: ServingModel) -> int:
        """Atomically swap a prepared candidate in, retaining the
        displaced incumbent for ``rollback``."""
        tr = self.stats.tracer
        with (tr.span("serve.swap", cat="serving",
                      args={"model": model.name, "version": model.version})
              if tr is not None else _NULL_CTX):
            with self._lock:
                old = self._models.get(model.name)
                # re-number against the live version (another commit may
                # have landed since prepare)
                model.version = old.version + 1 if old is not None else \
                    max(model.version, 1)
                if old is not None:
                    self._previous[model.name] = old
                self._models[model.name] = model
        return model.version

    def load(self, name: str = "default", booster=None,
             model_str: Optional[str] = None,
             model_file: Optional[str] = None) -> int:
        """Build, warm and verify a candidate, then atomically swap it in.
        On any failure the exception propagates and the previous version
        keeps serving untouched."""
        return self.commit(self.prepare(name, booster=booster,
                                        model_str=model_str,
                                        model_file=model_file))

    def rollback(self, name: str = "default") -> int:
        """Re-swap the retained previous version in (the displaced
        current version becomes the new retained one, so a mistaken
        rollback is itself reversible).  Raises ``KeyError`` when no
        previous version is retained."""
        from ..reliability.metrics import rel_inc
        tr = self.stats.tracer
        with self._lock:
            prev = self._previous.get(name)
            if prev is None:
                raise KeyError(f"no previous version retained for "
                               f"model {name!r}")
            cur = self._models[name]
            self._models[name] = prev
            self._previous[name] = cur
            restored = prev.version
        rel_inc("serve.rollbacks")
        if tr is not None:
            tr.instant("serve.rollback", cat="serving",
                       args={"model": name, "restored": restored,
                             "displaced": cur.version})
        return restored

    def _verify(self, model: ServingModel) -> None:
        """Device scores vs the host reference traversal on a fuzz sample
        (NaNs and negative/unseen categorical codes included)."""
        rng = np.random.RandomState(7)
        rows = self.verify_rows
        X = rng.randn(rows, model.num_features) * 3.0
        X[::7] = np.abs(np.floor(X[::7] * 4))   # int-ish rows for cat LUTs
        X[::11, :] = np.where(rng.rand(model.num_features) < 0.3,
                              np.nan, X[::11, :])
        bucket = next_pow2(rows)
        if self.warm_buckets:
            fits = [b for b in self.warm_buckets if b >= rows]
            bucket = min(fits) if fits else max(self.warm_buckets)
        Xpad = np.zeros((bucket, model.num_features))
        m = min(rows, bucket)
        Xpad[:m] = X[:m]
        got = model.predict_padded(Xpad, m)
        want = model.host_raw(X[:m])
        if not np.allclose(got, want, rtol=self.verify_tol,
                           atol=self.verify_tol):
            worst = float(np.max(np.abs(np.asarray(got) - want)))
            raise ValueError(
                f"model verification failed: device scores diverge from the "
                f"host traversal (max abs err {worst:g}); swap aborted")

    # -- lookup --------------------------------------------------------------

    def get(self, name: str = "default") -> ServingModel:
        with self._lock:
            if name not in self._models:
                raise KeyError(f"no model named {name!r} is registered")
            return self._models[name]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def versions(self) -> Dict[str, int]:
        with self._lock:
            return {n: m.version for n, m in self._models.items()}

    def versions_detail(self) -> Dict[str, Dict[str, Optional[int]]]:
        """Per-name serving + retained-previous versions — the operator
        view the ``health`` op exposes, so "what is serving and what
        would a rollback restore" is answerable without logs."""
        with self._lock:
            return {n: {"version": m.version,
                        "previous": (self._previous[n].version
                                     if n in self._previous else None)}
                    for n, m in self._models.items()}

    def jit_entries(self) -> Optional[int]:
        with self._lock:
            models = list(self._models.values())
        return models[0].jit_entries() if models else None
