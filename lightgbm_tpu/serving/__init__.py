"""TPU-resident serving subsystem.

Everything past training used to be a one-shot path: ``predict_raw`` binned
each request host-side in a per-feature Python loop and recompiled whenever
the row count changed.  This package is the long-lived serving layer the
ROADMAP north star ("serves heavy traffic from millions of users") needs:

  * ``binner`` — the value→bin quantization of ``BinMapper`` re-expressed as
    padded per-feature arrays (boundary rows for a vectorized
    ``searchsorted``, category LUT rows) with one jitted device kernel and
    one vectorized host variant.  Bit-parity with
    ``BinMapper.values_to_bins_predict`` (OOV categoricals, NaN bins,
    zero-as-missing) is the contract ``tests/test_serving.py`` pins.
  * ``batcher`` — a deadline-based micro-batching queue: concurrent
    requests coalesce into padded power-of-two row buckets so every shape
    hits a warm jit cache; the request path never compiles (buckets are
    compiled once, at warmup).
  * ``registry`` — a versioned multi-model registry with atomic hot-swap:
    a new model text is loaded, warmed and verified against the host
    traversal while the old version keeps serving; failure rolls back by
    simply never swapping.
  * ``server`` — a threaded socket server + client over the
    length-prefixed-pickle framing of ``io/net.py``, exposed as
    ``python -m lightgbm_tpu serve`` and ``Booster.serve()``.
  * ``fleet`` — the multi-replica production front end: a typed binary
    wire protocol (no pickle on the untrusted path), a selector-based
    async gateway owning every client socket, least-loaded dispatch
    across one replica per local device with health ejection, and
    zero-drop rolling promotion (``serve_replicas`` in the CLI).

Serving telemetry (QPS, queue/bin/traverse/unpad stage latency, batch
occupancy, compile-cache hits) reports through ``observability/`` under the
``serving`` section of ``schema.json``.
"""

from .binner import OOV_BIN, BinnerArrays

_LAZY = {
    "MicroBatcher": "batcher", "ServingStats": "batcher",
    "ModelRegistry": "registry", "ServingModel": "registry",
    "PredictionServer": "server", "ServingClient": "server",
    "ServerOverloaded": "server", "ServerUnavailable": "server",
    "FleetServer": "fleet", "ReplicaSet": "fleet", "Replica": "fleet",
    "WireError": "fleet",
}

__all__ = ["OOV_BIN", "BinnerArrays", "MicroBatcher", "ServingStats",
           "ModelRegistry", "ServingModel", "PredictionServer",
           "ServingClient", "ServerOverloaded", "ServerUnavailable",
           "FleetServer", "ReplicaSet", "Replica", "WireError"]


def __getattr__(name):
    # registry/server pull in the Booster facade — import lazily so that
    # `import lightgbm_tpu.serving.binner` from the predictor stays light
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
