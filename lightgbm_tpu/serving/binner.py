"""Device-side predict binning over padded per-feature arrays.

``BinMapper.values_to_bins_predict`` (`lightgbm_tpu/binning.py`) is exact but
per-feature: a Python loop building a fresh LUT per call, host-only.  This
module re-expresses the whole mapper fleet as a handful of padded arrays so
one vectorized pass bins every feature of a request matrix at once:

  * ``bounds``   (F, B) float64 — each row is the feature's searchable upper
    bounds (``bin_upper_bound[:r]`` — the exact slice ``values_to_bins``
    searches), padded with ``+inf``.  ``searchsorted(side="left")`` returns
    the count of bounds ``< v``, and ``+inf`` padding never counts, so the
    padded search is bit-identical to the per-feature truncated search.
  * ``cat_lut``  (F, C) int32 — category value → bin, padded/filled with the
    OOV sentinel; ``cat_max`` carries each feature's ``lut_max`` so the
    clip-and-mask replicates the mapper's unseen/negative handling.
  * ``missing`` / ``nan_bin`` / ``default_bin`` / ``is_cat`` — per-feature
    metadata driving the NaN rules.

Two consumers share the arrays: ``bin_host`` (vectorized numpy, the
``DevicePredictor.predict_raw`` fallback — golden-parity-tested against the
old loop) and ``bin_device`` (jitted, the serving path — bins land on device
already laid out as the ``(F_pad, N)`` matrix the packed traversal reads).
Jit is keyed on array SHAPES, so serving's power-of-two row buckets each
compile exactly once.

Semantics (`tree.h:250-268` raw-prediction traversal): unseen or negative
categories map to ``OOV_BIN`` — beyond every split bitset, always-right;
NaN maps to the NaN bin (numerical, missing_type NaN), to ``OOV_BIN``
(categorical, missing_type NaN), or probes as 0.0 otherwise.
"""

from __future__ import annotations

import functools
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..binning import BIN_CATEGORICAL, MISSING_NAN, BinMapper

# categories unseen at train time probe past every split bitset → right
# child, matching raw-value traversal (`tree.h:250-268`)
OOV_BIN = 1 << 20

# row-chunk budget for the host broadcast-count (bool bytes per chunk)
_HOST_CHUNK_BYTES = 16 << 20


class BinnerArrays:
    """Padded per-feature binning arrays for one mapper fleet (see module
    docstring).  Numpy-resident; device mirrors are created lazily and
    cached so repeated jit calls see identical buffers."""

    def __init__(self, bin_mappers: Sequence[BinMapper],
                 used_feature_map, f_pad: int):
        fu = len(bin_mappers)
        self.used_feature_map = np.asarray(used_feature_map, dtype=np.int64)
        self.f_pad = int(f_pad)
        self.num_used = fu

        r_list: List[int] = []
        cat_sz: List[int] = []
        for m in bin_mappers:
            if m.bin_type == BIN_CATEGORICAL:
                r_list.append(0)
                # mapper LUT size: lut_max + 2 (`values_to_bins_predict`)
                lut_max = max(m.categorical_2_bin.keys(), default=0)
                cat_sz.append(lut_max + 2)
            else:
                r = m.num_bin - 1
                if m.missing_type == MISSING_NAN:
                    r -= 1
                r_list.append(max(r, 0))
                cat_sz.append(0)
        B = max(max(r_list, default=0), 1)
        C = max(max(cat_sz, default=0), 1)

        self.bounds = np.full((max(fu, 1), B), np.inf, dtype=np.float64)
        self.missing = np.zeros(max(fu, 1), dtype=np.int32)
        self.nan_bin = np.zeros(max(fu, 1), dtype=np.int32)
        self.default_bin = np.zeros(max(fu, 1), dtype=np.int32)
        self.is_cat = np.zeros(max(fu, 1), dtype=bool)
        self.cat_lut = np.full((max(fu, 1), C), OOV_BIN, dtype=np.int32)
        self.cat_max = np.zeros(max(fu, 1), dtype=np.int32)
        for k, m in enumerate(bin_mappers):
            self.missing[k] = m.missing_type
            self.nan_bin[k] = m.num_bin - 1
            self.default_bin[k] = m.default_bin
            if m.bin_type == BIN_CATEGORICAL:
                self.is_cat[k] = True
                lut_max = max(m.categorical_2_bin.keys(), default=0)
                self.cat_max[k] = lut_max
                for cat, b in m.categorical_2_bin.items():
                    if cat >= 0:
                        self.cat_lut[k, cat] = b
            else:
                r = r_list[k]
                self.bounds[k, :r] = m.bin_upper_bound[:r]
        self._dev = None

    @classmethod
    def for_data(cls, data) -> "BinnerArrays":
        """Arrays for a dataset-like object (``_ConstructedDataset`` or
        ``PredictionBinSchema``), cached on the object."""
        arrs = getattr(data, "_binner_arrays", None)
        if arrs is None:
            arrs = cls(data.bin_mappers, data.used_feature_map,
                       data.bins.shape[0])
            data._binner_arrays = arrs
        return arrs

    # -- host variant (vectorized numpy; parity-pinned) ----------------------

    def bin_host(self, X: np.ndarray) -> np.ndarray:
        """(f_pad, n) int32 predict-bins of an (n, num_total_features) raw
        matrix — bit-identical to calling ``values_to_bins_predict`` per
        used feature (`tests/test_serving.py` golden parity)."""
        X = np.asarray(X, dtype=np.float64)
        n = X.shape[0]
        fu = self.num_used
        out = np.zeros((self.f_pad, n), dtype=np.int32)
        if fu == 0 or n == 0:
            return out
        v = np.ascontiguousarray(X[:, self.used_feature_map].T)  # (fu, n)
        nan = np.isnan(v)
        v0 = np.where(nan, 0.0, v)

        # numerical: count bounds < v (== searchsorted side="left") in row
        # chunks bounded by _HOST_CHUNK_BYTES of comparison intermediates
        B = self.bounds.shape[1]
        cnt = np.empty((fu, n), dtype=np.int32)
        chunk = max(128, _HOST_CHUNK_BYTES // max(fu * B, 1))
        for c0 in range(0, n, chunk):
            c1 = min(c0 + chunk, n)
            cnt[:, c0:c1] = (
                self.bounds[:, :, None] < v0[:, None, c0:c1]
            ).sum(axis=1, dtype=np.int32)
        num = np.where(nan & (self.missing[:, None] == MISSING_NAN),
                       self.nan_bin[:, None], cnt)

        # categorical: LUT probe with the mapper's exact clip-and-mask
        iv = v0.astype(np.int64)
        cm = self.cat_max[:, None].astype(np.int64)
        oov_mask = (iv < 0) | (iv > cm)
        gathered = np.take_along_axis(
            self.cat_lut, np.clip(iv, 0, cm).astype(np.int64), axis=1)
        cat = np.where(oov_mask, OOV_BIN, gathered)
        # raw categorical prediction always sends NaN right under
        # missing_type NaN (`tree.h:255-258`)
        cat = np.where(nan & (self.missing[:, None] == MISSING_NAN),
                       OOV_BIN, cat)

        out[:fu] = np.where(self.is_cat[:, None], cat, num)
        return out

    # -- device variant (jitted; serving + bucketed buckets) -----------------

    def device_arrays(self):
        if self._dev is None:
            self._dev = (jnp.asarray(self.bounds), jnp.asarray(self.missing),
                         jnp.asarray(self.nan_bin), jnp.asarray(self.is_cat),
                         jnp.asarray(self.cat_lut), jnp.asarray(self.cat_max))
        return self._dev

    def bin_device(self, Xu):
        """(f_pad, N) int32 device bins of an (N, num_used) device/host
        matrix of USED-feature columns (caller selects ``used_feature_map``
        columns; rows may be padding).  Jit-cached per (N, fu) shape."""
        bounds, missing, nan_bin, is_cat, cat_lut, cat_max = \
            self.device_arrays()
        return _bin_device(Xu, bounds, missing, nan_bin, is_cat, cat_lut,
                           cat_max, f_pad=self.f_pad)

    def select_used(self, X: np.ndarray) -> np.ndarray:
        """Host helper: (n, num_total_features) → contiguous (n, num_used)
        float matrix of the used columns (the ``bin_device`` input)."""
        X = np.asarray(X, dtype=np.float64)
        return np.ascontiguousarray(X[:, self.used_feature_map])


@functools.partial(jax.jit, static_argnames=("f_pad",))
def _bin_device(xu, bounds, missing, nan_bin, is_cat, cat_lut, cat_max, *,
                f_pad: int):
    v = xu.T.astype(bounds.dtype)                       # (fu, n)
    nan = jnp.isnan(v)
    v0 = jnp.where(nan, 0.0, v)
    nan_missing = nan & (missing[:, None] == MISSING_NAN)

    # numerical: per-feature binary search over the +inf-padded bounds rows
    cnt = jax.vmap(lambda b, col: jnp.searchsorted(b, col, side="left"))(
        bounds, v0).astype(jnp.int32)
    num = jnp.where(nan_missing, nan_bin[:, None], cnt)

    # categorical: LUT probe; unseen/negative/NaN(missing-NaN) → OOV
    iv = v0.astype(jnp.int32)
    cm = cat_max[:, None]
    oov_mask = (iv < 0) | (iv > cm)
    gathered = jnp.take_along_axis(cat_lut, jnp.clip(iv, 0, cm), axis=1)
    cat = jnp.where(oov_mask | nan_missing, OOV_BIN, gathered)

    bins = jnp.where(is_cat[:, None], cat, num)
    return jnp.pad(bins, ((0, f_pad - bins.shape[0]), (0, 0)))
