"""Deadline-based micro-batching queue + serving statistics.

Concurrent prediction requests coalesce into one device dispatch: the worker
collects requests until either the batch deadline elapses or the row budget
fills, concatenates them, pads the row axis up to the nearest power-of-two
bucket and runs the model's jitted bin+traverse pipeline.  Because every
bucket shape was compiled at warmup, the request path NEVER compiles — the
serving analogue of the training loop's static padded shapes
(`dataset.py` row padding).

Stage accounting (queue → bin → traverse → unpad) flows through a
``ServingStats`` wrapping the same ``Telemetry`` accumulator training uses,
and surfaces in the JSON report's ``serving`` section
(``observability/schema.json``).
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..observability import LatencyHistogram, Telemetry

_NULL_CTX = contextlib.nullcontext()


def next_pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def bucket_ladder(min_bucket: int, max_rows: int) -> List[int]:
    """The power-of-two row buckets between ``min_bucket`` and
    ``max_rows`` inclusive — the shapes warmed at startup."""
    lo, hi = next_pow2(min_bucket), next_pow2(max_rows)
    out = []
    b = lo
    while b <= hi:
        out.append(b)
        b *= 2
    return out


class TenantStats:
    """Per-model-name ("tenant") serving metrics: an admission→response
    ``LatencyHistogram`` plus request/error/shed counters and the SLO
    view (attainment against a latency target, error-budget burn).

    Lock-leaf like the histogram it wraps: its one lock guards the
    counters only and nothing is called while holding it."""

    __slots__ = ("name", "hist", "_lock", "requests", "errors", "shed",
                 "tenant_shed", "within_slo")

    def __init__(self, name: str):
        self.name = name
        self.hist = LatencyHistogram()
        self._lock = threading.Lock()
        self.requests = 0
        self.errors = 0
        self.shed = 0
        self.tenant_shed = 0    # shed by THIS tenant's own cap
        self.within_slo = 0

    def record(self, ms: float, slo_p99_ms: float,
               error: bool = False) -> None:
        self.hist.record(ms)
        with self._lock:
            self.requests += 1
            if error:
                self.errors += 1
            if ms <= slo_p99_ms:
                self.within_slo += 1

    def record_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def record_cap_shed(self) -> None:
        """A shed caused by this tenant's OWN admission cap (the global
        gate still had headroom) — the isolation signal per-tenant
        admission control exists to surface."""
        with self._lock:
            self.tenant_shed += 1

    def record_error(self) -> None:
        """An error WITHOUT a latency sample — the control-plane path
        (failed swap, unknown op), so the tenant's error rate sees every
        failure, not just predict errors."""
        with self._lock:
            self.errors += 1

    def section(self, slo_p99_ms: float, slo_target: float
                ) -> Dict[str, Any]:
        # histogram snapshot first: its lock stays leaf beside ours
        latency = self.hist.snapshot()
        with self._lock:
            requests, errors = self.requests, self.errors
            shed, within = self.shed, self.within_slo
            tenant_shed = self.tenant_shed
        # a request this tenant's own cap refused was offered work that
        # never met the SLO: tenant-local sheds burn the error budget
        offered = requests + tenant_shed
        attainment = within / offered if offered else 1.0
        budget = max(1.0 - float(slo_target), 1e-9)
        return {"model": self.name,
                "requests": requests,
                "errors": errors,
                "shed": shed,
                "tenant_shed": tenant_shed,
                "latency_ms": latency,
                "slo": {"p99_target_ms": float(slo_p99_ms),
                        "target": float(slo_target),
                        "attainment": attainment,
                        "error_budget_burn": (1.0 - attainment) / budget}}


class ServingStats:
    """Thread-safe serving counters + stage phase timers.

    Stage timers reuse ``Telemetry`` phases (named ``serve_<stage>``), so
    they show up both in the standard ``phases`` section and, summarized,
    under ``serving.stage_ms``.  Per-model-name ``TenantStats`` hang off
    the same object (the fleet gateway and the threaded server both
    record into them at dispatch completion), surfacing as the schema-v8
    ``serving.tenants[]`` section and the ``lgbt_serving_tenant_*``
    Prometheus series.
    """

    STAGES = ("queue", "pad", "bin", "traverse", "unpad", "fallback")

    def __init__(self, slo_p99_ms: float = 50.0, slo_target: float = 0.99):
        self.tel = Telemetry(True)
        # per-request end-to-end latency (admission → response), backing
        # the serving section's exact p50/p95/p99 and the Prometheus
        # histogram of the `metrics` op.  Lock-leaf: recorded OUTSIDE
        # self._lock (metrics_export.LatencyHistogram has its own)
        self.request_hist = LatencyHistogram()
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.batched_rows = 0
        self.bucket_rows = 0
        self.bucket_batches: Dict[int, int] = {}
        self.cache_hits = 0
        self.cache_misses = 0
        self.shed = 0
        self.errors = 0
        self.fallback_batches = 0
        self.fallback_rows = 0
        # per-tenant metrics under their own leaf lock (the request path
        # must never take self._lock just to find its tenant)
        self.slo_p99_ms = float(slo_p99_ms)
        self.slo_target = float(slo_target)
        self._tenants: Dict[str, TenantStats] = {}
        self._tenants_lock = threading.Lock()

    @property
    def tracer(self):
        """The attached span recorder (``None`` when tracing is off)."""
        return self.tel.tracer

    def attach_tracer(self, tracer) -> None:
        """Attach a ``TraceRecorder``: stage timers double as spans and
        the batcher emits per-batch / per-request-queue spans."""
        self.tel.tracer = tracer

    def stage(self, name: str):
        return self.tel.phase(f"serve_{name}")

    def record_request(self, rows: int) -> None:
        with self._lock:
            self.requests += 1
            self.rows += int(rows)

    def record_request_latency(self, ms: float) -> None:
        """End-to-end server-side request latency (admission→response)."""
        self.request_hist.record(ms)

    def record_queue_wait(self, seconds: float,
                          t0: Optional[float] = None) -> None:
        self.tel.add_phase_time("serve_queue", seconds, t0=t0)

    def record_batch(self, bucket: int, rows: int) -> None:
        with self._lock:
            self.batches += 1
            self.batched_rows += int(rows)
            self.bucket_rows += int(bucket)
            self.bucket_batches[int(bucket)] = \
                self.bucket_batches.get(int(bucket), 0) + 1

    def record_compile_cache(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.cache_hits += 1
            else:
                self.cache_misses += 1

    def record_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def record_error(self) -> None:
        """An admitted predict request that answered with an error frame
        — the signal the lifecycle rollback watchdog rates promotions
        by (`lifecycle/controller.py`)."""
        from ..reliability.metrics import rel_inc
        with self._lock:
            self.errors += 1
        rel_inc("serve.request_errors")

    def configure_slo(self, p99_ms: float, target: float) -> None:
        """Set the latency SLO every tenant is judged against
        (``serve_slo_p99_ms`` / ``serve_slo_target`` config keys)."""
        self.slo_p99_ms = float(p99_ms)
        self.slo_target = float(target)

    def tenant(self, name: str) -> TenantStats:
        """The (lazily created) per-model-name metrics bundle."""
        with self._tenants_lock:
            t = self._tenants.get(name)
            if t is None:
                t = self._tenants[name] = TenantStats(name)
            return t

    def record_tenant_request(self, name: str, ms: float,
                              error: bool = False) -> None:
        """One completed (admission→response) request for a tenant —
        recorded in the dispatch ``finally`` beside the global
        ``record_request_latency``."""
        self.tenant(name).record(ms, self.slo_p99_ms, error=error)

    def record_tenant_shed(self, name: str) -> None:
        self.tenant(name).record_shed()

    def record_tenant_cap_shed(self, name: str) -> None:
        """A shed by the tenant's own admission cap, not the global one
        (`reliability.degrade.TenantAdmission`)."""
        self.tenant(name).record_cap_shed()

    def record_tenant_error(self, name: str) -> None:
        """Control-plane failure attributed to a tenant (no latency
        sample): failed swaps and malformed ops burn the same error
        budget the rollback watchdog reads."""
        self.tenant(name).record_error()

    def tenants_section(self) -> List[Dict[str, Any]]:
        """``serving.tenants[]``: one section per model name, sorted."""
        with self._tenants_lock:
            tenants = sorted(self._tenants.values(), key=lambda t: t.name)
        return [t.section(self.slo_p99_ms, self.slo_target)
                for t in tenants]

    def record_fallback(self, rows: int) -> None:
        from ..reliability.metrics import rel_inc
        with self._lock:
            self.fallback_batches += 1
            self.fallback_rows += int(rows)
        rel_inc("serve.host_fallback_batches")
        rel_inc("serve.host_fallback_rows", int(rows))

    def serving_section(self, models: Optional[Dict[str, int]] = None,
                        jit_entries: Optional[int] = None) -> Dict[str, Any]:
        # histogram/tenant snapshots BEFORE self._lock: their locks stay
        # leaf (no nested acquisition for the race detector to chew)
        latency = self.request_hist.snapshot()
        tenants = self.tenants_section()
        with self._lock:
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            stage_ms = {}
            for s in self.STAGES:
                st = self.tel._phases.get(f"serve_{s}")
                if st is not None:
                    stage_ms[s] = {"total_ms": st[0] * 1e3, "count": st[1],
                                   "max_ms": st[2] * 1e3}
            return {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "qps": self.requests / elapsed,
                "rows_per_s": self.rows / elapsed,
                "batch_occupancy": (self.batched_rows / self.bucket_rows
                                    if self.bucket_rows else 0.0),
                "compile_cache": {"hits": self.cache_hits,
                                  "misses": self.cache_misses,
                                  "jit_entries": jit_entries},
                "stage_ms": stage_ms,
                "buckets": {str(b): c
                            for b, c in sorted(self.bucket_batches.items())},
                "models": dict(models or {}),
                "shed": self.shed,
                "errors": self.errors,
                "fallback_batches": self.fallback_batches,
                "fallback_rows": self.fallback_rows,
                "latency_ms": latency,
                "tenants": tenants,
            }

    def report(self, models: Optional[Dict[str, int]] = None,
               jit_entries: Optional[int] = None) -> Dict[str, Any]:
        """Full telemetry report with the ``serving`` section attached —
        validates against the extended ``observability/schema.json``."""
        rep = self.tel.report()
        rep["serving"] = self.serving_section(models, jit_entries)
        return rep


class _Request:
    __slots__ = ("X", "n", "done", "result", "error", "t_enq", "trace_id",
                 "callback")

    def __init__(self, X: np.ndarray, trace_id: Optional[str] = None,
                 callback: Optional[Callable[["_Request"], None]] = None):
        self.X = X
        self.n = X.shape[0]
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        # perf_counter: the clock the trace recorder's epoch is on, so
        # the queue-wait span aligns with the stage spans
        self.t_enq = time.perf_counter()
        self.trace_id = trace_id
        self.callback = callback


class MicroBatcher:
    """Coalesces concurrent requests into padded power-of-two batches.

    ``predict_fn(Xpad, m)`` receives an ``(bucket, num_features)`` float64
    matrix whose first ``m`` rows are real and returns host scores for
    those rows (``(m,)`` or ``(m, K)``).  It runs ONLY on the worker
    thread, so the device is never entered concurrently.

    ``fallback_fn`` (same signature) is the graceful-degradation path:
    when ``predict_fn`` raises — a device fault, an OOM, an injected
    ``serve.predict.fail`` — the batch is re-scored through it (the host
    numpy traversal in practice) instead of failing every rider, and the
    fallback is counted (`reliability/metrics.py`).
    """

    def __init__(self, predict_fn: Callable[[np.ndarray, int], np.ndarray],
                 num_features: int, max_batch_rows: int = 1024,
                 deadline_ms: float = 2.0, min_bucket: int = 16,
                 stats: Optional[ServingStats] = None,
                 fallback_fn: Optional[Callable[[np.ndarray, int],
                                                np.ndarray]] = None):
        self.predict_fn = predict_fn
        self.fallback_fn = fallback_fn
        self.num_features = int(num_features)
        self.max_rows = next_pow2(max_batch_rows)
        self.min_bucket = min(next_pow2(min_bucket), self.max_rows)
        self.deadline_s = float(deadline_ms) / 1e3
        self.stats = stats or ServingStats()
        self._q: "queue.Queue[_Request]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._worker, name="lgbt-serve-batcher", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- request side (any thread) ------------------------------------------

    def submit(self, X: np.ndarray, timeout: Optional[float] = None,
               trace_id: Optional[str] = None) -> np.ndarray:
        """Blocking predict; rows of oversized requests are chunked to the
        batch budget and re-concatenated.  ``trace_id`` rides the request
        into the batch worker so its queue-wait and micro-batch spans
        link back to the originating request."""
        X = np.ascontiguousarray(np.atleast_2d(np.asarray(X, np.float64)))
        if X.shape[1] != self.num_features:
            raise ValueError(f"request has {X.shape[1]} features, model "
                             f"expects {self.num_features}")
        if X.shape[0] > self.max_rows:
            parts = [self.submit(X[i:i + self.max_rows], timeout, trace_id)
                     for i in range(0, X.shape[0], self.max_rows)]
            return np.concatenate(parts, axis=0)
        self.stats.record_request(X.shape[0])
        req = _Request(X, trace_id=trace_id)
        self._q.put(req)
        if not req.done.wait(timeout):
            raise TimeoutError("prediction request timed out in the "
                               "serving queue")
        if req.error is not None:
            raise req.error
        return req.result

    def submit_async(self, X: np.ndarray, callback: Callable[[_Request], None],
                     trace_id: Optional[str] = None) -> _Request:
        """Non-blocking predict: enqueue and return immediately; the batch
        worker invokes ``callback(request)`` once ``result``/``error`` is
        set.  This is the seam the fleet gateway's event loop rides — it
        must never block on device work (`serving/fleet/gateway.py`), so
        callbacks run on the batcher worker thread and must themselves be
        non-blocking (the gateway just enqueues the response and wakes the
        selector).  Oversize requests are the dispatcher's problem: rows
        beyond ``max_rows`` raise here rather than silently blocking on a
        chunk chain."""
        X = np.ascontiguousarray(np.atleast_2d(np.asarray(X, np.float64)))
        if X.shape[1] != self.num_features:
            raise ValueError(f"request has {X.shape[1]} features, model "
                             f"expects {self.num_features}")
        if X.shape[0] > self.max_rows:
            raise ValueError(f"async request of {X.shape[0]} rows exceeds "
                             f"the {self.max_rows}-row batch budget; chunk "
                             f"at the dispatch layer")
        self.stats.record_request(X.shape[0])
        req = _Request(X, trace_id=trace_id, callback=callback)
        self._q.put(req)
        return req

    # -- worker side ---------------------------------------------------------

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            batch = [first]
            rows = first.n
            deadline = time.monotonic() + self.deadline_s
            while rows < self.max_rows:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    break
                try:
                    r = self._q.get(timeout=rem)
                except queue.Empty:
                    break
                batch.append(r)
                rows += r.n
            # preserve request boundaries while keeping every dispatch
            # inside the row budget
            group: List[_Request] = []
            grows = 0
            for r in batch:
                if group and grows + r.n > self.max_rows:
                    self._run_batch(group)
                    group, grows = [], 0
                group.append(r)
                grows += r.n
            if group:
                self._run_batch(group)

    def _run_batch(self, reqs: List[_Request]) -> None:
        t_start = time.perf_counter()
        tracer = self.stats.tracer
        for r in reqs:
            # one queue-wait span per rider, carrying ITS trace_id
            with (tracer.bind(r.trace_id) if tracer is not None
                  else _NULL_CTX):
                self.stats.record_queue_wait(t_start - r.t_enq, t0=r.t_enq)
        m = sum(r.n for r in reqs)
        bucket = max(self.min_bucket, next_pow2(m))
        # the micro-batch span carries EVERY rider's trace_id, and the
        # bind makes the stage spans recorded inside (pad here,
        # bin/traverse/unpad in ServingModel.predict_padded) inherit the
        # same ids — the request→batch→stage causal link
        ids = [r.trace_id for r in reqs if r.trace_id]
        span = bind = _NULL_CTX
        if tracer is not None:
            span = tracer.span("serve.batch", cat="serving",
                               trace_id=ids or None,
                               args={"bucket": int(bucket), "rows": int(m),
                                     "requests": len(reqs)})
            bind = tracer.bind(ids or None)
        try:
            with span, bind:
                with self.stats.stage("pad"):
                    Xpad = np.zeros((bucket, self.num_features), np.float64)
                    ofs = 0
                    for r in reqs:
                        Xpad[ofs:ofs + r.n] = r.X
                        ofs += r.n
                try:
                    scores = self.predict_fn(Xpad, m)
                except BaseException:
                    if self.fallback_fn is None:
                        raise
                    with self.stats.stage("fallback"):
                        scores = self.fallback_fn(Xpad, m)
                    self.stats.record_fallback(m)
            ofs = 0
            for r in reqs:
                r.result = scores[ofs:ofs + r.n]
                ofs += r.n
                r.done.set()
                if r.callback is not None:
                    self._fire_callback(r)
            self.stats.record_batch(bucket, m)
        except BaseException as e:
            for r in reqs:
                r.error = e
                r.done.set()
                if r.callback is not None:
                    self._fire_callback(r)

    @staticmethod
    def _fire_callback(r: _Request) -> None:
        # a broken callback must not take down the batch worker (or the
        # other riders' callbacks)
        try:
            r.callback(r)
        except BaseException:
            pass
