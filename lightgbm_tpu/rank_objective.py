"""Lambdarank NDCG objective — padded-query vectorization.

TPU-native re-design of ``LambdarankNDCG``
(`src/objective/rank_objective.hpp:19-228`).  The reference runs a per-query
O(n²) pairwise scalar loop under OpenMP; here queries are padded to a common
length and the pairwise lambda matrix is computed densely per query and
reduced — vmapped over query batches so the work is (batch, Q, Q) element-wise
ops, which the VPU eats.  The sigmoid lookup table
(`rank_objective.hpp:180-193`) is replaced by the exact expression
``2 / (1 + exp(2·σ·Δ))`` — same function the table approximates.

Semantics preserved: rank discounts 1/log2(2+pos) over a stable sort by score
(`rank_objective.hpp:100-104`), per-pair ΔNDCG with the max-DCG@k
normalization (``CalMaxDCGAtK``, `src/metric/dcg_calculator.cpp`), the
``(0.01+|Δscore|)`` regularization when scores are not all equal, and the
``p_hessian = p_lambda·(2-p_lambda)`` curvature.
"""

from __future__ import annotations

import functools
import math
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .objectives import ObjectiveFunction


def default_label_gain(max_label: int = 31) -> np.ndarray:
    """2^i - 1 (`DCGCalculator::DefaultLabelGain`)."""
    return (2.0 ** np.arange(max_label + 1)) - 1.0


def max_dcg_at_k(k: int, labels: np.ndarray, label_gain: np.ndarray) -> float:
    """``DCGCalculator::CalMaxDCGAtK`` (`src/metric/dcg_calculator.cpp`)."""
    srt = np.sort(labels)[::-1][:k]
    disc = 1.0 / np.log2(np.arange(len(srt)) + 2.0)
    return float((label_gain[srt.astype(np.int64)] * disc).sum())


class LambdarankNDCG(ObjectiveFunction):
    name = "lambdarank"
    need_group = True

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        if cfg.sigmoid <= 0:
            raise ValueError("Sigmoid param should be greater than zero")
        self.sigmoid = float(cfg.sigmoid)
        lg = cfg.label_gain
        self.label_gain = np.asarray(lg, dtype=np.float64) if lg \
            else default_label_gain()
        self.optimize_pos_at = cfg.max_position

    def init(self, metadata, num_data, num_data_padded):
        super().init(metadata, num_data, num_data_padded)
        qb = metadata.query_boundaries
        if qb is None:
            raise ValueError("Lambdarank tasks require query information")
        self.query_boundaries = qb
        sizes = np.diff(qb)
        self.num_queries = len(sizes)
        qmax = int(sizes.max())
        # pad to the next power of two for shape reuse across datasets
        self.q_pad = max(8, 1 << (qmax - 1).bit_length())
        nq = self.num_queries
        n = num_data
        qid = np.repeat(np.arange(nq, dtype=np.int64), sizes)
        within = np.arange(n, dtype=np.int64) - qb[qid]
        # (nq, Q) doc index matrix into the padded row axis (-1 = padding)
        doc_idx = np.full((nq, self.q_pad), -1, dtype=np.int32)
        doc_idx[qid, within] = np.arange(n, dtype=np.int32)
        self.doc_idx = jnp.asarray(doc_idx)
        self.doc_valid = jnp.asarray(doc_idx >= 0)
        labels = np.where(doc_idx >= 0, self._pad_gather(metadata.label, doc_idx), -1)
        self.q_labels = jnp.asarray(labels.astype(np.int32))
        # max DCG@k per query, vectorized: one stable (qid, -label) sort
        lab_int = metadata.label.astype(np.int64)
        if lab_int.size and int(lab_int.max()) >= len(self.label_gain):
            raise ValueError(
                f"Label {int(lab_int.max())} exceeds label_gain size "
                f"{len(self.label_gain)}; set label_gain explicitly")
        lab_int = np.clip(lab_int, 0, None)
        ideal = np.lexsort((-lab_int, qid))
        disc = 1.0 / np.log2(within + 2.0)
        k = self.optimize_pos_at
        gains = self.label_gain[lab_int[ideal]] * disc * (within < k)
        maxdcg = np.bincount(qid, weights=gains, minlength=nq)
        inv = np.where(maxdcg > 0, 1.0 / np.where(maxdcg > 0, maxdcg, 1.0),
                       0.0)
        self.inverse_max_dcgs = jnp.asarray(inv.astype(np.float32))
        self.gains_lut = jnp.asarray(self.label_gain.astype(np.float32))
        # batch queries so the (qb, Q, Q) intermediate stays bounded (~256MB f32)
        self.q_batch = max(1, min(nq, int(2 ** 26 // max(self.q_pad ** 2, 1)) or 1))
        self._jit_grads = jax.jit(self._grads_impl)

    @staticmethod
    def _pad_gather(arr, idx):
        safe = np.clip(idx, 0, len(arr) - 1)
        return np.asarray(arr)[safe]

    # -- device computation --------------------------------------------------

    def _one_query(self, scores_q, labels_q, valid_q, inv_max_dcg):
        """Pairwise lambdas for one padded query
        (`rank_objective.hpp:79-164` GetGradientsForOneQuery)."""
        Q = scores_q.shape[0]
        neg_inf = jnp.float32(-np.inf)
        s = jnp.where(valid_q, scores_q, neg_inf)
        # rank position of each doc (stable sort by descending score)
        order = jnp.argsort(-s, stable=True)                  # pos -> doc
        pos = jnp.argsort(order, stable=True)                 # doc -> pos
        discount = 1.0 / jnp.log2(pos.astype(jnp.float32) + 2.0)
        gains = self.gains_lut[jnp.clip(labels_q, 0, len(self.label_gain) - 1)]
        valid_f = valid_q.astype(jnp.float32)
        best = jnp.max(jnp.where(valid_q, s, neg_inf))
        worst = jnp.min(jnp.where(valid_q, s, jnp.inf))
        norm = best != worst

        ds = s[:, None] - s[None, :]                          # Δscore high-low
        dgap = gains[:, None] - gains[None, :]
        pdisc = jnp.abs(discount[:, None] - discount[None, :])
        delta = dgap * pdisc * inv_max_dcg
        delta = jnp.where(norm, delta / (0.01 + jnp.abs(ds)), delta)
        pair = (labels_q[:, None] > labels_q[None, :]) & \
               valid_q[:, None] & valid_q[None, :]
        pf = pair.astype(jnp.float32)
        sig = 2.0 / (1.0 + jnp.exp(2.0 * self.sigmoid * ds))
        p_lambda = -delta * sig * pf
        p_hessian = sig * (2.0 - sig) * 2.0 * delta * pf
        lam = p_lambda.sum(axis=1) - p_lambda.sum(axis=0)
        hes = p_hessian.sum(axis=1) + p_hessian.sum(axis=0)
        return lam * valid_f, hes * valid_f

    def _grads_impl(self, score):
        n_pad = score.shape[0]

        def batch(carry, args):
            g, h = carry
            didx, lab, val, inv = args
            safe = jnp.clip(didx, 0, n_pad - 1)
            s = score[safe]
            lam, hes = jax.vmap(self._one_query)(s, lab, val, inv)
            didx_flat = jnp.where(val, didx, n_pad).reshape(-1)
            g = g.at[didx_flat].add(lam.reshape(-1), mode="drop")
            h = h.at[didx_flat].add(hes.reshape(-1), mode="drop")
            return (g, h), None

        nq = self.num_queries
        qb = self.q_batch
        nb = (nq + qb - 1) // qb
        pad_q = nb * qb
        pad = lambda a, fill: jnp.concatenate(
            [a, jnp.full((pad_q - nq,) + a.shape[1:], fill, a.dtype)]) \
            if pad_q > nq else a
        didx = pad(self.doc_idx, -1).reshape(nb, qb, -1)
        lab = pad(self.q_labels, -1).reshape(nb, qb, -1)
        val = pad(self.doc_valid, False).reshape(nb, qb, -1)
        inv = pad(self.inverse_max_dcgs, 0.0).reshape(nb, qb)
        init = (jnp.zeros(n_pad, jnp.float32), jnp.zeros(n_pad, jnp.float32))
        (g, h), _ = jax.lax.scan(batch, init, (didx, lab, val, inv))
        if self.weights is not None:
            g, h = g * self.weights, h * self.weights
        return g, h

    def get_gradients(self, score, class_id=0):
        return self._jit_grads(score)
