"""Training engine: ``train`` / ``cv`` and the ``Booster`` facade.

Mirrors the reference python package (`python-package/lightgbm/engine.py:19-447`
``train``/``cv`` and `basic.py:1577+` ``Booster``): same signatures, callback
protocol (``CallbackEnv``), early stopping and evaluation-history semantics,
so user code written against the reference's ``lgb.train`` runs unchanged.
"""

from __future__ import annotations

import collections
import copy
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from . import callback as callback_mod
from .boosting import create_boosting
from .boosting.gbdt import GBDT
from .config import Config
from .dataset import Dataset
from .metrics import create_metric
from .objectives import create_objective


class Booster:
    """User-facing booster handle (`python-package/lightgbm/basic.py:1577`)."""

    def __init__(self, params: Optional[Dict] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        params = dict(params or {})
        self.params = params
        self.cfg = Config.from_params(params)
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._train_set = train_set
        self.gbdt: Optional[GBDT] = None
        # multi-host pod: join the jax.distributed cluster BEFORE the first
        # device touch (dataset construct uploads arrays); the per-iteration
        # liveness heartbeat rides the same coordinator (parallel/multihost)
        self._mh_net = None
        self._last_step_s: Optional[float] = None
        from .parallel import multihost
        if multihost.initialize_from_config(self.cfg) and train_set is not None:
            self._mh_net = multihost.net_for_run(self.cfg)
        if train_set is not None:
            import time as _time
            _t0 = _time.perf_counter()
            train_set.construct()
            _bin_s = _time.perf_counter() - _t0
            objective = create_objective(self.cfg)
            self.gbdt = create_boosting(self.cfg)
            train_metrics = []
            if self.cfg.is_provide_training_metric:
                train_metrics = self._make_metrics(train_set)
            self.gbdt.init(train_set, objective, train_metrics)
            # binning happened before the GBDT (and its Telemetry) existed
            # — credit it to the report's "binning" phase after the fact
            self.gbdt.telemetry.add_phase_time("binning", _bin_s)
            if self._mh_net is not None:
                self.gbdt.telemetry.set_distributed(
                    process_count=int(self._mh_net.num_machines),
                    process_index=int(self._mh_net.rank))
                if self.cfg.elastic:
                    self.gbdt.telemetry.set_elastic(
                        epoch=int(self.cfg.elastic_epoch),
                        members=int(self._mh_net.num_machines))
        elif model_file is not None:
            with open(model_file) as fh:
                self._load_from_string(fh.read())
        elif model_str is not None:
            self._load_from_string(model_str)
        else:
            raise ValueError("At least one of params/train_set, model_file "
                             "or model_str should be provided")

    def _load_from_string(self, s: str) -> None:
        self.gbdt = GBDT(self.cfg)
        self.gbdt.load_model_from_string(s)

    def _make_metrics(self, dataset: Dataset):
        metrics = []
        for name in self.cfg.metric:
            m = create_metric(name, self.cfg)
            if m is not None:
                m.init(dataset.constructed.metadata, dataset.constructed.num_data)
                metrics.append(m)
        return metrics

    # -- training-side API ---------------------------------------------------

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        data.construct()
        self.gbdt.add_valid_data(data, name, self._make_metrics(data))
        return self

    def update(self, train_set: Optional[Dataset] = None,
               fobj: Optional[Callable] = None) -> bool:
        """One boosting iteration (`basic.py:1842`); returns True if training
        should stop."""
        tel = self.gbdt.telemetry
        if self._mh_net is not None:
            # pre-step liveness agreement: a host that died since the last
            # iteration surfaces HERE as a ConnectionError naming the dead
            # rank (within the collective deadline) instead of a hang
            # inside the next XLA collective.  With telemetry on, the LAST
            # step's host duration rides the same allgather — straggler
            # detection without an extra collective
            payload = self._last_step_s if tel.enabled else None
            with tel.phase("heartbeat"):
                from .parallel.multihost import RankDeathError
                try:
                    peers = self._mh_net.heartbeat(self.gbdt.iter_,
                                                   payload=payload)
                except RankDeathError as e:
                    # the engine's abort verdict: which iteration of which
                    # membership epoch died — the elastic controller keys
                    # its recovery on exactly this (epoch, dead_ranks) pair
                    raise RankDeathError(
                        f"training aborted before iteration "
                        f"{self.gbdt.iter_ + 1} (membership epoch "
                        f"{e.epoch}): {e}", dead_ranks=e.dead_ranks,
                        epoch=e.epoch) from None
            if tel.enabled:
                self._note_rank_skew(peers)
        if not tel.enabled:
            if fobj is None:
                return self.gbdt.train_one_iter()
            grad, hess = fobj(self._curr_preds(), self._train_set)
            return self.__boost(grad, hess)
        import time as _time
        _t0 = _time.perf_counter()
        if fobj is None:
            ret = self.gbdt.train_one_iter()
        else:
            grad, hess = fobj(self._curr_preds(), self._train_set)
            ret = self.__boost(grad, hess)
        self._last_step_s = _time.perf_counter() - _t0
        return ret

    def _note_rank_skew(self, peers) -> None:
        """Land rank-skew gauges from the heartbeat's gathered step
        timings; past ``telemetry_skew_warn_ratio`` emit a warning NAMING
        the slowest rank."""
        tel = self.gbdt.telemetry
        times: Dict[int, Optional[float]] = {}
        for p in peers or ():
            if isinstance(p, tuple) and len(p) >= 4 and p[0] == "hb":
                times[int(p[1])] = None if p[3] is None else float(p[3])
        vals = sorted(s for s in times.values() if s is not None)
        if not vals:
            return
        tel.set_distributed(rank_step_s={str(r): s for r, s
                                         in sorted(times.items())})
        if len(vals) < 2:
            return
        m = len(vals)
        med = vals[m // 2] if m % 2 else \
            0.5 * (vals[m // 2 - 1] + vals[m // 2])
        slow_s, slow_rank = max(
            (s, r) for r, s in times.items() if s is not None)
        ratio = (slow_s / med) if med > 0 else 0.0
        warn_ratio = float(getattr(self.cfg,
                                   "telemetry_skew_warn_ratio", 0.0))
        tel.set_distributed(skew_ratio=ratio, slowest_rank=int(slow_rank),
                            skew_warn_ratio=warn_ratio)
        if warn_ratio > 0 and ratio > warn_ratio:
            tel.inc("straggler_warnings")
            warnings.warn(
                f"straggler: rank {slow_rank} last step "
                f"{slow_s * 1e3:.1f} ms is {ratio:.2f}x the pod median "
                f"({med * 1e3:.1f} ms)")

    def __boost(self, grad: np.ndarray, hess: np.ndarray) -> bool:
        return self.gbdt.train_one_iter(grad, hess)

    def _curr_preds(self) -> np.ndarray:
        return self.gbdt.train_score.np_score()

    def rollback_one_iter(self) -> "Booster":
        self.gbdt.rollback_one_iter()
        return self

    @property
    def current_iteration(self) -> int:
        return self.gbdt.iter_

    def num_trees(self) -> int:
        return len(self.gbdt.models)

    # -- evaluation ----------------------------------------------------------

    def eval_train(self, feval=None) -> List[Tuple]:
        return self._eval_set("training", self.gbdt.train_score,
                              self.gbdt.training_metrics, feval,
                              self._train_set)

    def eval_valid(self, feval=None) -> List[Tuple]:
        out = []
        for i, name in enumerate(self.gbdt.valid_names):
            out.extend(self._eval_set(name, self.gbdt.valid_scores[i],
                                      self.gbdt.valid_metrics[i], feval, None))
        return out

    def _eval_set(self, name, updater, metrics, feval, dataset) -> List[Tuple]:
        results = []
        score = updater.np_score()
        for m in metrics:
            for mname, val in m.eval(score, self.gbdt.objective):
                results.append((name, mname, val, m.is_higher_better))
        if feval is not None:
            ds = dataset if dataset is not None else None
            fname, fval, higher_better = feval(score, ds)
            results.append((name, fname, fval, higher_better))
        # keep the per-iteration history that cv()/sklearn evals_result_ read
        for dname, mname, val, _ in results:
            self.gbdt.eval_history.setdefault(dname, {}).setdefault(
                mname, []).append(val)
        return results

    # -- prediction / persistence -------------------------------------------

    def predict(self, data, num_iteration: int = -1, raw_score: bool = False,
                pred_leaf: bool = False, pred_contrib: bool = False,
                **kwargs) -> np.ndarray:
        if hasattr(data, "dtypes") and hasattr(data, "columns") \
                and not isinstance(data, np.ndarray):
            data = self._predict_data_from_pandas(data)
        elif hasattr(data, "values") and not isinstance(data, np.ndarray):
            data = data.values
        data = np.asarray(data, dtype=np.float64)
        if pred_contrib:
            from .contrib import predict_contrib
            return predict_contrib(self.gbdt, data, num_iteration)
        return self.gbdt.predict(data, num_iteration, raw_score, pred_leaf)

    def save_model(self, filename: str, num_iteration: int = -1,
                   start_iteration: int = 0) -> "Booster":
        if num_iteration < 0:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        self.gbdt.save_model_to_file(filename, start_iteration, num_iteration)
        return self

    def model_to_string(self, num_iteration: int = -1,
                        start_iteration: int = 0) -> str:
        if num_iteration < 0:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        return self.gbdt.save_model_to_string(start_iteration, num_iteration)

    def dump_model(self, num_iteration: int = -1, start_iteration: int = 0
                   ) -> Dict:
        """Model as a JSON-able dict (`basic.py:2102` / ``DumpModel``,
        `gbdt_model_text.cpp:15`)."""
        if num_iteration < 0:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        ret = self.gbdt.dump_model(start_iteration, num_iteration)
        # the python layer appends pandas category mappings (`basic.py:2233`);
        # None for non-pandas-categorical training data
        ret["pandas_categorical"] = self.gbdt.pandas_categorical
        return ret

    def _predict_data_from_pandas(self, df) -> np.ndarray:
        """Predict-time DataFrame conversion: re-apply the category lists
        recorded at training (`basic.py:262-304` — the stored order defines
        the code space; unseen values → NaN)."""
        stored = self.gbdt.pandas_categorical
        cat_cols = [j for j, c in enumerate(df.columns)
                    if str(df.dtypes.iloc[j]) == "category"]
        if not cat_cols:
            return np.asarray(df.values, dtype=np.float64)
        if stored is None or len(stored) != len(cat_cols):
            raise ValueError(
                "train and predict dataset categorical_feature do not "
                f"match ({0 if stored is None else len(stored)} recorded "
                f"category columns vs {len(cat_cols)} in this DataFrame)")
        from .dataset import recode_pandas
        return recode_pandas(df, cat_cols, stored)

    def refit(self, data, label, decay_rate: float = 0.9,
              **kwargs) -> "Booster":
        """Refit the existing model's leaf values on new data
        (`basic.py:2284` Booster.refit → ``GBDT::RefitTree``,
        `gbdt.cpp:262-286`)."""
        leaf_preds = self.predict(data, pred_leaf=True, **kwargs)
        leaf_preds = np.atleast_2d(np.asarray(leaf_preds))
        new_train = Dataset(data, label=label, params=dict(self.params))
        new_booster = Booster(params=dict(self.params), train_set=new_train)
        import copy as _copy
        new_booster.gbdt.models = [_copy.deepcopy(t) for t in self.gbdt.models]
        new_booster.gbdt.iter_ = len(new_booster.gbdt.models) // max(
            new_booster.gbdt.num_tree_per_iteration, 1)
        for tree in new_booster.gbdt.models:
            # inner bin-space fields refer to the OLD dataset; rebuild lazily
            # if this booster continues training (`_continue_training`)
            tree.needs_rebind = True
        new_booster.gbdt.refit_leaf_preds(leaf_preds, decay_rate)
        return new_booster

    def refit_file(self, data_path: str, decay_rate: float = 0.9) -> "Booster":
        """CLI ``task=refit``: refit in place from a data file."""
        from .io.parser import load_data_file
        mat, label, _, _ = load_data_file(data_path, self.params)
        refitted = self.refit(mat, label, decay_rate)
        self.gbdt = refitted.gbdt
        return self

    def feature_importance(self, importance_type: str = "split",
                           iteration: int = -1) -> np.ndarray:
        return self.gbdt.feature_importance(importance_type, iteration)

    def get_telemetry(self, light: bool = False) -> Dict:
        """Training telemetry report (``telemetry=True`` in params; see
        README "Telemetry & profiling" and observability/schema.json)."""
        return self.gbdt.get_telemetry(light=light)

    # -- serving (lightgbm_tpu/serving/) -------------------------------------

    def to_server(self, replicas: int = 0, **kwargs) -> "Any":
        """An UNSTARTED server with this booster registered as the
        ``default`` model (see README "Serving").  ``replicas=0`` (the
        default) builds the single-replica threaded ``PredictionServer``;
        any other value builds the async binary-protocol ``FleetServer``
        (``-1`` = one replica per local device, N>0 = exactly N).
        Keyword args are forwarded (host/port/max_batch_rows/deadline_ms/
        min_bucket/warmup/max_inflight/telemetry_out, the observability
        knobs trace/trace_out/trace_capacity/stats_out/stats_interval_s,
        and the lifecycle traffic-ring capacity record_rows)."""
        if replicas:
            from .serving import FleetServer

            return FleetServer(booster=self,
                               replicas=max(int(replicas), 0), **kwargs)
        from .serving import PredictionServer

        return PredictionServer(booster=self, **kwargs)

    def serve(self, **kwargs) -> "Any":
        """Start serving this booster over a socket; returns the running
        server (``.host``/``.port``/``.stop()``)."""
        return self.to_server(**kwargs).start()

    def feature_name(self) -> List[str]:
        return list(self.gbdt.feature_names)

    def num_feature(self) -> int:
        return self.gbdt.max_feature_idx + 1

    def __getstate__(self):
        state = {"model_str": self.model_to_string(num_iteration=-1),
                 "params": self.params,
                 "best_iteration": self.best_iteration,
                 "best_score": self.best_score}
        return state

    def __setstate__(self, state):
        self.params = state["params"]
        self.cfg = Config.from_params(self.params)
        self.best_iteration = state["best_iteration"]
        self.best_score = state["best_score"]
        self._train_set = None
        self._load_from_string(state["model_str"])


def train(params: Dict, train_set: Dataset, num_boost_round: int = 100,
          valid_sets: Optional[Sequence[Dataset]] = None,
          valid_names: Optional[Sequence[str]] = None,
          fobj: Optional[Callable] = None, feval: Optional[Callable] = None,
          init_model: Optional[Union[str, Booster]] = None,
          feature_name: str = "auto", categorical_feature: str = "auto",
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[Dict] = None, verbose_eval=True,
          learning_rates=None, keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None,
          resume: Optional[bool] = None) -> Booster:
    """`python-package/lightgbm/engine.py:19-245` semantics.

    Beyond the reference: ``snapshot_freq > 0`` checkpoints the model text
    every K iterations (atomic write + config-fingerprint sidecar +
    retention — `reliability/resume.py`), and ``resume=True`` (or config
    ``resume``/CLI ``--resume``) continues a killed run from the newest
    valid snapshot, training only the REMAINING iterations so the result
    is identical to an uninterrupted run.  Resume composes with
    ``init_model`` continued training (the lifecycle refit path): a
    snapshot NEWER than the incumbent wins — it already embeds the
    incumbent's trees — and the run still targets the original total of
    incumbent iterations + ``num_boost_round``; with no (or an older)
    snapshot the incumbent warm-starts as usual."""
    params = dict(params or {})
    cfg_probe = Config.from_params(params)
    if cfg_probe.trace_out and not cfg_probe.telemetry:
        # spans ride the phase timers, so asking for a trace opts into
        # telemetry (same implication the CLI applies for --telemetry-out)
        params["telemetry"] = True
        cfg_probe = Config.from_params(params)
    if "num_iterations" not in params and num_boost_round is not None:
        params["num_iterations"] = num_boost_round
    num_boost_round = Config.from_params(params).num_iterations
    if fobj is not None:
        params["objective"] = "none"
    if cfg_probe.fault_spec:
        from .reliability import faults
        faults.arm(cfg_probe.fault_spec)

    # warm start: an init_model (continued training / refit) seeds the
    # incumbent's trees and replayed scores before boosting continues on
    # the fresh data.  Loaded up front so the crash-safe resume decision
    # below can compare snapshot iterations against the incumbent's.
    init_booster: Optional[Booster] = None
    resume_base_iter = 0
    if init_model is not None:
        init_booster = init_model if isinstance(init_model, Booster) else \
            Booster(model_file=init_model, params=params)
        resume_base_iter = init_booster.current_iteration

    # crash-safe resume: the newest valid snapshot becomes the init model.
    # Composes with init_model (a refit killed mid-run): the snapshot
    # already EMBEDS the incumbent's trees, so it wins whenever it is
    # newer than the incumbent, and the round target stays the original
    # refit's total (incumbent iterations + num_boost_round)
    resumed_iter: Optional[int] = None
    snapshot_state_path: Optional[str] = None
    if (resume if resume is not None else cfg_probe.resume):
        from .reliability.metrics import rel_inc
        from .reliability.resume import find_resume_snapshot
        found = find_resume_snapshot(cfg_probe.output_model, cfg_probe)
        if found is not None and found[0] > resume_base_iter:
            resumed_iter, snapshot_state_path = found
            init_booster = Booster(model_file=snapshot_state_path,
                                   params=params)
            rel_inc("resume_runs")

    train_set.params = {**params, **(train_set.params or {})}
    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature

    # structured span recorder (observability/trace.py): host-side only —
    # attaching it cannot change a traced program, and with trace_out
    # unset nothing is allocated.  Created BEFORE the Booster (and
    # registered process-wide) so the streaming loader's ingestion-chunk
    # spans — recorded during dataset construction, before the GBDT's
    # Telemetry exists — land in the same flight recorder
    _tracer = None
    if cfg_probe.trace_out:
        from .observability.trace import TraceRecorder, set_global_tracer
        _tracer = TraceRecorder(True, capacity=cfg_probe.trace_capacity)
        set_global_tracer(_tracer)
    booster = Booster(params=params, train_set=train_set)
    if _tracer is not None:
        booster.gbdt.telemetry.tracer = _tracer
    if init_booster is not None:
        _continue_training(booster, init_booster)
        if snapshot_state_path is not None:
            # exact continuation: the state sidecar restores the LIVE
            # float32 score array and RNG streams, making the resumed
            # run bit-identical to an uninterrupted one (the traversal
            # replay above is a ulp-level approximation of it)
            from .reliability.resume import (load_snapshot_state,
                                             restore_training_state)
            state = load_snapshot_state(snapshot_state_path)
            if state is not None:
                restore_training_state(booster.gbdt, state)

    valid_sets = list(valid_sets or [])
    names = []
    for i, vs in enumerate(valid_sets):
        if vs is train_set:
            continue
        name = (valid_names[i] if valid_names and i < len(valid_names)
                else f"valid_{i}")
        booster.add_valid(vs, name)
        names.append(name)

    callbacks = list(callbacks or [])
    if verbose_eval is True:
        callbacks.append(callback_mod.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval >= 1:
        callbacks.append(callback_mod.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        callbacks.append(callback_mod.early_stopping(
            early_stopping_rounds, verbose=bool(verbose_eval)))
    if evals_result is not None:
        callbacks.append(callback_mod.record_evaluation(evals_result))
    if learning_rates is not None:
        callbacks.append(callback_mod.reset_parameter(
            learning_rate=learning_rates))
    callbacks_before = [cb for cb in callbacks
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks
                       if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    init_iter = booster.current_iteration
    # resumed runs train to the ORIGINAL target — the incumbent's
    # iterations (0 for a from-scratch run) plus the requested rounds —
    # while init_model continuation keeps the reference's "N more
    # rounds" semantics
    end_iter = init_iter + num_boost_round if resumed_iter is None \
        else max(resume_base_iter + num_boost_round, init_iter)
    snapshot_freq = cfg_probe.snapshot_freq
    evaluation_result_list: List[Tuple] = []
    # opt-in jax.profiler device trace around the training loop — real
    # per-op timings (works over the remote tunnel, profiling/PROFILE.md)
    _tracing = False
    if cfg_probe.profile_trace_dir:
        try:
            import jax as _jax
            _jax.profiler.start_trace(cfg_probe.profile_trace_dir)
            _tracing = True
        except Exception as e:
            warnings.warn(f"profile_trace_dir set but the profiler trace "
                          f"could not start: {e}")
    for i in range(init_iter, end_iter):
        env = callback_mod.CallbackEnv(
            model=booster, params=params, iteration=i,
            begin_iteration=init_iter,
            end_iteration=end_iter,
            evaluation_result_list=None)
        for cb in callbacks_before:
            cb(env)
        finished = booster.update(fobj=fobj)
        if snapshot_freq > 0 and cfg_probe.output_model \
                and (i + 1) % snapshot_freq == 0:
            from .reliability.resume import save_snapshot
            save_snapshot(booster.gbdt, cfg_probe.output_model, i + 1,
                          cfg_probe)
        # chaos seam: `train.crash[:nth=K]` kills the run after its K-th
        # completed iteration (snapshot, if due, already written) so the
        # lifecycle tests exercise the REAL kill-mid-refit → resume path
        from .reliability import faults as _faults
        if _faults.fire("train.crash") is not None:
            raise RuntimeError(
                f"injected fault train.crash at iteration {i + 1}")
        evaluation_result_list = []
        if booster.gbdt.valid_metrics or booster.gbdt.training_metrics or feval:
            if booster.gbdt.training_metrics or (
                    feval and cfg_probe.is_provide_training_metric):
                evaluation_result_list.extend(booster.eval_train(feval))
            evaluation_result_list.extend(booster.eval_valid(feval))
        env = env._replace(evaluation_result_list=evaluation_result_list)
        try:
            for cb in callbacks_after:
                cb(env)
        except callback_mod.EarlyStopException as es:
            booster.best_iteration = es.best_iteration + 1
            for name, mname, val, _ in es.best_score:
                booster.best_score.setdefault(name, {})[mname] = val
            break
        if finished:
            break
    if _tracing:
        try:
            import jax as _jax
            _jax.profiler.stop_trace()
        except Exception as e:
            warnings.warn(f"profiler trace did not stop cleanly: {e}")
    if booster.best_iteration <= 0:
        for name, mname, val, _ in (evaluation_result_list or []):
            booster.best_score.setdefault(name, {})[mname] = val
    if _tracing and cfg_probe.telemetry:
        # automated capture-and-parse: map the profiler's device events
        # back to the named legs and the ledger's collective sites; lands
        # in the report's distributed.profile (None when the backend
        # wrote no Chrome-format trace — xplane-only captures)
        from .observability.attribution import attribute_profile
        prof = attribute_profile(
            cfg_probe.profile_trace_dir,
            getattr(booster.gbdt.learner, "_ledger", None))
        if prof is not None:
            booster.gbdt.telemetry.set_distributed(profile=prof)
    if booster._mh_net is not None and cfg_probe.telemetry \
            and (cfg_probe.telemetry_out or cfg_probe.trace_out):
        # one clock-offset handshake serves both the report's
        # distributed.clock and the per-rank trace metadata below
        from .observability import podtrace as _podtrace
        _clk = _podtrace.estimate_clock_offset(booster._mh_net)
        booster.gbdt.telemetry.set_distributed(clock={
            "offset_us": _clk["offset_s"] * 1e6,
            "rtt_us": _clk["rtt_s"] * 1e6,
            "rounds": _clk["rounds"], "method": _clk["method"]})
    else:
        _clk = None
    if cfg_probe.telemetry and cfg_probe.telemetry_out:
        from .observability import write_report
        write_report(booster.get_telemetry(), cfg_probe.telemetry_out)
    if cfg_probe.telemetry and cfg_probe.telemetry_prom_out:
        from .observability.metrics_export import training_prometheus
        import os
        _prom_tmp = cfg_probe.telemetry_prom_out + ".tmp"
        with open(_prom_tmp, "w") as _fh:
            _fh.write(training_prometheus(booster.get_telemetry()))
        os.replace(_prom_tmp, cfg_probe.telemetry_prom_out)
    if _tracer is not None:
        # annotate the span timeline with the collective ledger's static
        # sites (op/phase/cadence/bytes), then write the Chrome JSON —
        # per-rank (`<trace_out>.rank<r>`) on a pod, with the clock
        # handshake stamped into otherData for podtrace.merge_pod_trace
        ledger = getattr(booster.gbdt.learner, "_ledger", None)
        if ledger is not None:
            for site in ledger.sites():
                _tracer.instant(f"collective:{site['op']}",
                                cat="collective", args=dict(site))
        from .observability import podtrace as _podtrace
        from .observability.trace import set_global_tracer
        _podtrace.export_rank_trace(_tracer, cfg_probe.trace_out,
                                    net=booster._mh_net, clock=_clk)
        set_global_tracer(None)
    return booster


def _continue_training(booster: Booster, init_booster: Booster) -> None:
    """Continue-training: seed models and replay their scores
    (`boosting.cpp:43-62`, `application.cpp:88-93` init-score threading)."""
    from .boosting.gbdt import _traverse_tree_binned, rebind_tree_to_dataset
    gbdt = booster.gbdt
    src = init_booster.gbdt
    gbdt.models = [copy.deepcopy(t) for t in src.models]
    gbdt.num_tree_per_iteration = src.num_tree_per_iteration
    gbdt.iter_ = len(gbdt.models) // max(gbdt.num_tree_per_iteration, 1)
    for tree in gbdt.models:
        # the copied inner fields (split_feature_inner / threshold_in_bin)
        # are in the SOURCE dataset's bin space — always rebind against the
        # new training data's bins (rebind also drops the traversal cache)
        tree.needs_rebind = True
        rebind_tree_to_dataset(tree, gbdt.train_data)
    for idx, tree in enumerate(gbdt.models):
        k = idx % gbdt.num_tree_per_iteration
        if tree.num_leaves > 1:
            delta = _traverse_tree_binned(gbdt.train_data, tree)
            gbdt.train_score.score = gbdt.train_score.score.at[k].add(delta)
            for vs in gbdt.valid_scores:
                vs.add_by_tree(tree, k)
        else:
            gbdt.train_score.add_constant(float(tree.leaf_value[0]), k)
            for vs in gbdt.valid_scores:
                vs.add_constant(float(tree.leaf_value[0]), k)
    gbdt.train_score.has_init_score = True


class CVBooster:
    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def _append(self, booster):
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler


def cv(params: Dict, train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, fobj=None, feval=None, init_model=None,
       feature_name="auto", categorical_feature="auto",
       early_stopping_rounds=None, fpreproc=None, verbose_eval=None,
       show_stdv: bool = True, seed: int = 0, callbacks=None,
       eval_train_metric: bool = False) -> Dict[str, List[float]]:
    """K-fold cross-validation (`engine.py:334-447`)."""
    params = dict(params or {})
    if metrics is not None:
        params["metric"] = metrics
    # params-carried round counts (num_iterations/n_estimators/...) win,
    # like train()
    if "num_iterations" not in params and num_boost_round is not None:
        params["num_iterations"] = num_boost_round
    num_boost_round = Config.from_params(params).num_iterations
    train_set.construct()
    full = train_set
    n = full.num_data()
    label = np.asarray(full.get_label())
    rng = np.random.RandomState(seed)
    if folds is None:
        idx = np.arange(n)
        if stratified and Config.from_params(params).objective in (
                "binary", "multiclass", "multiclassova"):
            folds = _stratified_folds(label, nfold, rng, shuffle)
        else:
            if shuffle:
                rng.shuffle(idx)
            folds = [(np.setdiff1d(idx, idx[f::nfold], assume_unique=False),
                      idx[f::nfold]) for f in range(nfold)]

    results = collections.defaultdict(list)
    cvbooster = CVBooster()
    raw = full._load_raw(full._raw_data)
    weights = full.get_weight()
    for train_idx, test_idx in folds:
        dtrain = Dataset(raw[train_idx], label=label[train_idx],
                         weight=None if weights is None else weights[train_idx],
                         params=params,
                         categorical_feature=full.categorical_feature)
        dtest = Dataset(raw[test_idx], label=label[test_idx],
                        weight=None if weights is None else weights[test_idx],
                        reference=dtrain, params=params)
        if fpreproc is not None:
            dtrain, dtest, params = fpreproc(dtrain, dtest, dict(params))
        params_fold = dict(params)
        params_fold.pop("early_stopping_round", None)
        bst = Booster(params=params_fold, train_set=dtrain)
        bst.add_valid(dtest, "valid")
        cvbooster._append(bst)

    # lockstep boosting: one round across ALL folds, then aggregate and run
    # the early-stopping logic (and user callbacks) on the AGGREGATED means
    # — the reference's cv structure (`engine.py:334-447` +
    # ``_agg_cv_result``), not a post-hoc truncation of independent folds
    callbacks = list(callbacks or [])
    if early_stopping_rounds:
        callbacks.append(callback_mod.early_stopping(
            early_stopping_rounds, verbose=bool(verbose_eval)))
    if isinstance(verbose_eval, int) and not isinstance(verbose_eval, bool) \
            and verbose_eval > 0:
        callbacks.append(callback_mod.print_evaluation(verbose_eval,
                                                       show_stdv))
    elif verbose_eval is True:
        callbacks.append(callback_mod.print_evaluation(show_stdv=show_stdv))
    cbs_before = sorted((cb for cb in callbacks
                         if getattr(cb, "before_iteration", False)),
                        key=lambda cb: getattr(cb, "order", 0))
    cbs_after = sorted((cb for cb in callbacks
                        if not getattr(cb, "before_iteration", False)),
                       key=lambda cb: getattr(cb, "order", 0))
    stopped_at = -1
    for it in range(num_boost_round):
        env = callback_mod.CallbackEnv(
            model=cvbooster, params=params, iteration=it,
            begin_iteration=0, end_iteration=num_boost_round,
            evaluation_result_list=None)
        for cb in cbs_before:
            cb(env)
        finished = False
        agg: Dict[str, List[float]] = collections.defaultdict(list)
        hb_map: Dict[str, bool] = {}
        for bst in cvbooster.boosters:
            if bst.update(fobj=fobj):
                finished = True
            for dname, mname, val, hb in bst.eval_valid(feval):
                agg[mname].append(val)
                hb_map[mname] = hb
        agg_list = []
        for mname, vals in agg.items():
            results[f"{mname}-mean"].append(float(np.mean(vals)))
            results[f"{mname}-stdv"].append(float(np.std(vals)))
            agg_list.append(("cv_agg", mname, float(np.mean(vals)),
                             hb_map[mname], float(np.std(vals))))
        try:
            env = env._replace(evaluation_result_list=agg_list)
            for cb in cbs_after:
                cb(env)
        except callback_mod.EarlyStopException as e:
            stopped_at = getattr(e, "best_iteration", it)
            break
        if finished:
            break
    if stopped_at >= 0:
        for key in list(results):
            results[key] = results[key][:stopped_at + 1]
    return dict(results)


def _stratified_folds(label, nfold, rng, shuffle):
    classes = np.unique(label)
    test_folds = [[] for _ in range(nfold)]
    for c in classes:
        idx = np.where(label == c)[0]
        if shuffle:
            rng.shuffle(idx)
        for f in range(nfold):
            test_folds[f].extend(idx[f::nfold])
    n = len(label)
    out = []
    for f in range(nfold):
        test = np.asarray(sorted(test_folds[f]))
        train_idx = np.setdiff1d(np.arange(n), test)
        out.append((train_idx, test))
    return out
