"""Data-parallel compact learner: shard_map + psum_scatter over a mesh.

TPU-native re-design of ``DataParallelTreeLearner``
(`src/treelearner/data_parallel_tree_learner.cpp:49-254`): every device owns
a row shard and keeps the compact learner's leaf-contiguous layout over its
LOCAL rows (partition sorts are local); the two cross-device exchanges per
split mirror the reference's wire protocol exactly:

  * histograms: local windowed histogram → ``lax.psum_scatter`` over the
    (padded) feature axis, so each device sums and then SCANS a feature
    slice — the reference's ``ReduceScatter`` +
    ``HistogramBinEntry::SumReducer`` (`data_parallel_tree_learner.cpp:
    146-161`), riding ICI instead of sockets.
  * best split: each device packs its feature-slice winner into a tiny
    fixed-width record, ``lax.all_gather`` + argmax replaces
    ``SyncUpGlobalBestSplit`` (`parallel_tree_learner.h:186-209`); ties
    break toward the lowest global feature index because shard slices are
    contiguous and ascending in the axis index.

Leaf sums/counts are ``psum``-ed; the tiny replicated record stream drives
identical host tree assembly on every process.  The whole tree builds
inside ONE ``shard_map``-ped jit, so XLA schedules collectives alongside
local compute; under a multi-host mesh the same program spans DCN.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..dataset import _ConstructedDataset
from ..learner import NUM_REC_FIELDS
from ..learner_compact import (CF_GAIN, CF_LCNT, CF_LOUT, CF_LSG, CF_LSH,
                               CF_RCNT, CF_ROUT, CF_RSG, CF_RSH, CI_FEAT,
                               CI_FLAGS, CI_THR, LF_CNT, LF_DEPTH, LF_MAX_C,
                               LF_MIN_C, LF_OUT, LF_SUM_G, LF_SUM_H, NUM_CF,
                               NUM_CI, NUM_LF, CompactState,
                               CompactTPUTreeLearner)
from ..ops.split import find_best_splits
from ..tree import Tree

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


class ShardedCompactLearner(CompactTPUTreeLearner):
    """`tree_learner=data` (and the data half of voting) on the compact
    learner.  One row shard per device; histograms reduce-scattered over
    features."""

    _supports_bundle = False
    _placement_mode = "data"     # rules_for_mode table this learner rides

    def __init__(self, cfg: Config, data: _ConstructedDataset, mesh: Mesh,
                 hist_backend: str = "auto"):
        from .sharding import row_axis
        self.mesh = mesh
        self.axis = row_axis(mesh)
        self.D = int(np.prod(mesh.devices.shape))
        super().__init__(cfg, data, hist_backend)
        if self.n_pad % self.D:
            raise ValueError(f"padded rows {self.n_pad} not divisible by "
                             f"mesh size {self.D}")
        self.n_local = self.n_pad // self.D
        f_pad = data.bins.shape[0]
        if f_pad % self.D:
            raise ValueError(f"padded features {f_pad} not divisible by "
                             f"mesh size {self.D}")
        self.f_pad = f_pad
        self.fs = f_pad // self.D            # features per shard (padded)
        # local window buckets (windows live in the local row axis)
        self._init_local_windows(cfg, self.n_local)
        self._use_pallas = False  # local XLA one-hot path under shard_map
        self._pad_feature_meta(data, f_pad)
        self._sharded_bins = None
        self._jit_tree_c = None  # built lazily (needs the sharded bins)

    def _init_local_windows(self, cfg: Config, n_local: int) -> None:
        """Window-bucket ladder over the local row axis (shared by every
        sharded learner; feature-parallel passes the FULL row count)."""
        mw = max(int(cfg.tpu_min_window), 1024)
        mw = 1 << (mw - 1).bit_length()
        sizes = []
        s0 = mw
        while s0 < n_local:
            sizes.append(s0)
            s0 *= 2
        sizes.append(n_local)
        self._win_sizes = sizes
        self._win_sizes_arr = jnp.asarray(sizes, dtype=jnp.int32)

    def _pad_feature_meta(self, data: _ConstructedDataset,
                          f_pad: int) -> None:
        """Feature metadata padded to f_pad so shard slices are uniform;
        padding slots are trivial features (num_bin=0 → -inf gain)."""
        num_bin, missing, default_bin, is_cat = data.feature_meta_arrays()
        pad = f_pad - len(num_bin)
        zp = lambda a, fill=0: np.concatenate(
            [a, np.full(pad, fill, a.dtype)]) if pad else a
        self.fp_num_bin = jnp.asarray(zp(num_bin))
        self.fp_missing = jnp.asarray(zp(missing))
        self.fp_default_bin = jnp.asarray(zp(default_bin))
        self.fp_is_cat = jnp.asarray(zp(is_cat.astype(np.int32)) > 0)
        mono = np.zeros(f_pad, np.int8)
        if self.has_monotone:
            mono[:self.num_features] = np.asarray(self.f_monotone)
        self.fp_monotone = jnp.asarray(mono) if self.has_monotone else None
        pen = np.ones(f_pad, np.float32)
        if self.has_penalty:
            pen[:self.num_features] = np.asarray(self.f_penalty)
        self.fp_penalty = jnp.asarray(pen) if self.has_penalty else None
        # the inherited partition branch and shared split step read
        # per-feature metadata with a padded feature index — rebind to the
        # padded arrays
        self.f_num_bin = self.fp_num_bin
        self.f_missing = self.fp_missing
        self.f_default_bin = self.fp_default_bin
        if self.has_monotone:
            self.f_monotone = self.fp_monotone

    def _rows_len(self) -> int:
        return self.n_local

    # -- forced splits (`serial_tree_learner.cpp:543-663`) -------------------
    # The reference's parallel learners inherit ForceSplits from the serial
    # learner (`data_parallel_tree_learner.cpp:257-258` templates over it);
    # here the shared `_forced_phase_compact` runs inside the shard_map
    # program — only the histogram-row fetch differs (the pool is feature-
    # scattered, so the owning device broadcasts the row via a tiny psum).

    def set_forced_splits(self, forced) -> None:
        self._forced = list(forced) if forced else None
        self._jit_tree_c = None              # rebuilt lazily with the phase

    def _fix_hrow(self, hrow, fi: int, sum_g, sum_h, cnt):
        """Single-feature ``Dataset::FixHistogram`` (the sliced pools make
        the full-width `_fix_histogram` inapplicable)."""
        db = int(self.np_default_bin[fi])
        if db <= 0:
            return hrow
        totals = jnp.stack([sum_g, sum_h, cnt]).astype(hrow.dtype)
        others = jnp.sum(hrow, axis=0) - hrow[db]
        return hrow.at[db].set(totals - others)

    def _forced_hrow(self, state, fs, sum_g, sum_h, cnt):
        fi = fs.feature_inner
        owner, loc = divmod(fi, self.fs)
        row = state.hist_pool[fs.leaf, loc]              # (B, 3) slice row
        d = lax.axis_index(self.axis)
        hrow = lax.psum(jnp.where(d == owner, row, jnp.zeros_like(row)),
                        self.axis)
        return self._fix_hrow(hrow, fi, sum_g, sum_h, cnt)

    # -- sharded data placement (rule-driven, `parallel/sharding.py`) --------

    def _rules(self):
        from .sharding import rules_for_mode
        return rules_for_mode(self._placement_mode, self.mesh)

    def sharded_bins(self) -> jax.Array:
        if self._sharded_bins is None:
            self._sharded_bins = self._rules().place("bins",
                                                     self.bins_packed())
        return self._sharded_bins

    def _row_sharded(self, arr):
        return self._rules().place("rows", arr)

    def _reduce_hist(self, local_hist):
        """Histogram exchange: reduce-scatter over the feature axis so each
        device sums (and later scans) a feature slice
        (`data_parallel_tree_learner.cpp:146-161`)."""
        return self._exchange(local_hist, 0)

    def _reduce_hist_batch(self, local_hists):
        """Batched (K, F, B, 3) member histograms exchanged in ONE
        collective (scatter over the feature axis), mirroring the wave
        body's single psum_scatter per wave — K per-member exchanges
        would pay K collective latencies per stall event."""
        return self._exchange(local_hists, 1)

    def _sync_counts(self, lc_bag, c_bag):
        """Global bagged counts from the local partition's sums."""
        self._rec_coll("psum", lc_bag)
        self._rec_coll("psum", c_bag)
        return (lax.psum(lc_bag, self.axis), lax.psum(c_bag, self.axis))

    def _global_scalar(self, v):
        self._rec_coll("psum", v)
        return lax.psum(v, self.axis)

    def _global_max(self, v):
        self._rec_coll("pmax", v)
        return lax.pmax(v, self.axis)

    def _global_row_offset(self):
        # rows are shard-contiguous in axis order, so shard d quantizes
        # rows [d·n_local, (d+1)·n_local) exactly as the serial learner
        # would (the stochastic-rounding hash keys on the global index)
        return lax.axis_index(self.axis) * jnp.int32(self.n_local)

    # -- int16 histogram wire format (quantized mode, ops/quant.py) ----------

    def _wire_int16(self) -> bool:
        """Quantized histograms ride the exchange as int16 integer units
        when every reduced channel provably fits (GLOBAL row bound)."""
        from ..ops.quant import exchange_tier
        return bool(getattr(self, "_quant", False)) \
            and exchange_tier(self.n_pad) == "int16"

    def _exchange(self, h, dim: int):
        """One histogram reduce-scatter over the data axis.  In quantized
        mode with the int16 tier active, channels are divided back to
        integer units and shipped as int16 — HALF the f32 payload — then
        rescaled after the integer reduction (exact: sums are bounded by
        the tier gate).  The ledger records the PACKED operand so traced
        collective payload bytes reflect the wire format."""
        if self._wire_int16():
            from ..ops.quant import pack_hist_int16, unpack_hist_int16
            inv_sg, inv_sh = self._q_inv
            h16 = pack_hist_int16(h, inv_sg, inv_sh, self._q_mbar)
            self._rec_coll("psum_scatter", h16)
            h16 = lax.psum_scatter(h16, self.axis, scatter_dimension=dim,
                                   tiled=True)
            return unpack_hist_int16(h16, *self._q_scales,
                                     1.0 / self._q_mbar)
        self._rec_coll("psum_scatter", h)
        return lax.psum_scatter(h, self.axis, scatter_dimension=dim,
                                tiled=True)

    def _child_best_rows(self, hist_left, hist_right, crow_f, fmask_pad,
                         depth_ok, constraints):
        hist2 = jnp.stack([hist_left, hist_right])
        sums = (jnp.stack([crow_f[CF_LSG], crow_f[CF_RSG]]),
                jnp.stack([crow_f[CF_LSH], crow_f[CF_RSH]]),
                jnp.stack([crow_f[CF_LCNT], crow_f[CF_RCNT]]))
        return self._best_rows_global(hist2, sums, fmask_pad, depth_ok,
                                      constraints)

    # -- per-shard split finding --------------------------------------------

    def _shard_slice(self, full):
        d = lax.axis_index(self.axis)
        return lax.dynamic_slice_in_dim(full, d * self.fs, self.fs)

    def _feature_cands_shard(self, hist, sum_g, sum_h, cnt, fmask_pad,
                             min_c=None, max_c=None):
        """The merged numerical+categorical finder over THIS device's
        feature slice of the reduce-scattered histogram."""
        return self._feature_cands_meta(
            hist, sum_g, sum_h, cnt,
            self._shard_slice(self.fp_num_bin),
            self._shard_slice(self.fp_missing),
            self._shard_slice(self.fp_default_bin),
            self._shard_slice(self.fp_is_cat),
            self._shard_slice(fmask_pad),
            self._shard_slice(self.fp_monotone) if self.has_monotone else None,
            self._shard_slice(self.fp_penalty) if self.has_penalty else None,
            min_c, max_c)

    def _feature_cands_meta(self, hist, sum_g, sum_h, cnt, num_bin, missing,
                            default_bin, is_cat, fmask_sel, mono, pen,
                            min_c=None, max_c=None):
        """Merged finder over an arbitrary feature subset described by the
        given metadata arrays (a contiguous shard slice, or a gathered
        voting selection)."""
        # ``Dataset::FixHistogram`` on the subset, mirroring the serial
        # scan (`learner.py:_feature_cands`): rebuild each default-bin
        # entry as leaf totals minus the other bins.  An exact no-op on
        # consistent paths, but FORCED-SPLIT chains carry the reference's
        # GatherInfo-vs-partition sum inconsistency whose delta lands in
        # the default bin — without this the sharded scans see different
        # histograms than serial on forced descendants (round-5 bug).
        dt = hist.dtype
        dbm = (jnp.arange(hist.shape[1])[None, :] == default_bin[:, None]) \
            & (default_bin[:, None] > 0)
        totals = jnp.stack([sum_g, sum_h, cnt]).astype(dt)
        others = jnp.sum(jnp.where(dbm[..., None], 0.0, hist), axis=1)
        hist = jnp.where(dbm[..., None],
                         (totals[None, :] - others)[:, None, :], hist)
        fsel = hist.shape[0]
        fmask = fmask_sel & ~is_cat
        if not self.has_monotone:
            min_c = max_c = None
        elif min_c is None:
            min_c = jnp.asarray(-jnp.inf, hist.dtype)
            max_c = jnp.asarray(jnp.inf, hist.dtype)
        num = find_best_splits(
            hist, sum_g, sum_h, cnt, num_bin, missing, default_bin, fmask,
            mono, min_c, max_c, **self._split_kwargs)
        if self.has_penalty:
            num = num._replace(gain=jnp.where(
                jnp.isneginf(num.gain), num.gain, num.gain * pen))
        gain, thr, dl = num.gain, num.threshold, num.default_left
        if self.has_categorical:
            from ..ops.split_cat import find_best_splits_categorical
            cmask = fmask_sel & is_cat
            cat = find_best_splits_categorical(
                hist, sum_g, sum_h, cnt, num_bin, missing, cmask,
                min_c, max_c, **self._cat_split_kwargs)
            if self.has_penalty:
                cat = cat._replace(gain=jnp.where(
                    jnp.isneginf(cat.gain), cat.gain, cat.gain * pen))
            pickc = lambda c, n_: jnp.where(is_cat, c, n_)
            gain = pickc(cat.gain, num.gain)
            thr = jnp.where(is_cat, 0, num.threshold)
            dl = jnp.where(is_cat, False, num.default_left)
            lsg = pickc(cat.left_sum_g, num.left_sum_g)
            lsh = pickc(cat.left_sum_h, num.left_sum_h)
            lcn = pickc(cat.left_cnt, num.left_cnt)
            rsg = pickc(cat.right_sum_g, num.right_sum_g)
            rsh = pickc(cat.right_sum_h, num.right_sum_h)
            rcn = pickc(cat.right_cnt, num.right_cnt)
            lo = pickc(cat.left_output, num.left_output)
            ro = pickc(cat.right_output, num.right_output)
            bits = jnp.where(is_cat[:, None], cat.bits,
                             jnp.zeros((fsel, self.cat_W), jnp.uint32))
        else:
            lsg, lsh, lcn = num.left_sum_g, num.left_sum_h, num.left_cnt
            rsg, rsh, rcn = num.right_sum_g, num.right_sum_h, num.right_cnt
            lo, ro = num.left_output, num.right_output
            bits = jnp.zeros((fsel, self.cat_W), jnp.uint32)
            is_cat = jnp.zeros(fsel, bool)
        return gain, thr, dl, is_cat, bits, lsg, lsh, lcn, rsg, rsh, rcn, \
            lo, ro

    def _best_rows_global(self, hist2, crow_sums, fmask_pad, depth_ok,
                          constraints):
        """Per-child best split over ALL features: local slice scan →
        all_gather of one packed row per device → global argmax
        (``SyncUpGlobalBestSplit``)."""
        K = hist2.shape[0]
        d = lax.axis_index(self.axis)

        def one(hist, sg, sh, cn, mn, mx):
            g, thr, dl, ic, bits, lsg, lsh, lcn, rsg, rsh, rcn, lo, ro = \
                self._feature_cands_shard(hist, sg, sh, cn, fmask_pad, mn, mx)
            bf = jnp.argmax(g).astype(jnp.int32)
            pick = lambda a: a[bf]
            cf = jnp.stack([pick(g).astype(self._acc), pick(lsg), pick(lsh),
                            pick(lcn), pick(rsg), pick(rsh), pick(rcn),
                            pick(lo), pick(ro)]).astype(self._acc)
            flags = pick(dl).astype(jnp.int32) + 2 * pick(ic).astype(jnp.int32)
            ci = jnp.stack([bf + d * self.fs, pick(thr), flags])
            return cf, ci.astype(jnp.int32), bits[bf]

        sg2, sh2, cn2 = crow_sums
        if constraints is not None:
            mins, maxs = constraints
            cf, ci, cb = jax.vmap(one)(hist2, sg2, sh2, cn2, mins, maxs)
        else:
            cf, ci, cb = jax.vmap(
                lambda h, g, hh, c: one(h, g, hh, c, None, None)
            )(hist2, sg2, sh2, cn2)
        # global winner per child (tiny allgather)
        for x in (cf, ci, cb):
            self._rec_coll("all_gather", x)
        cf_all = lax.all_gather(cf, self.axis)     # (D, K, NUM_CF)
        ci_all = lax.all_gather(ci, self.axis)
        cb_all = lax.all_gather(cb, self.axis)
        win = jnp.argmax(cf_all[:, :, CF_GAIN], axis=0)   # (K,) device idx
        cf_g = jnp.take_along_axis(
            cf_all, win[None, :, None], axis=0)[0]
        ci_g = jnp.take_along_axis(
            ci_all, win[None, :, None], axis=0)[0]
        cb_g = jnp.take_along_axis(
            cb_all, win[None, :, None], axis=0)[0]
        cf_g = cf_g.at[:, CF_GAIN].set(
            jnp.where(depth_ok, cf_g[:, CF_GAIN], -jnp.inf))
        return cf_g, ci_g, cb_g

    # -- the sharded tree ----------------------------------------------------

    def _train_tree_sharded(self, bins_p, grad, hess, bag, fmask_pad):
        """Body under shard_map: all row-axis arrays are LOCAL shards."""
        self._ledger.begin_trace()
        self._coll_ctx = ("root", "tree")
        axis = self.axis
        n, L = self.n_local, self.num_leaves
        b = self.num_bins_padded
        acc = self._acc
        self._hist_branches = [self._make_hist_branch_shard(S)
                               for S in self._win_sizes]
        self._partition_branches = [
            self._make_partition_branch(S, sort_mode=S > self._sort_cutoff)
            for S in self._win_sizes]

        w = jnp.stack([grad * bag, hess * bag, bag], axis=0)
        lid0 = jnp.zeros(n, jnp.int32)
        local_root = self._hist_branches[-1](bins_p, w, lid0, jnp.int32(0),
                                             jnp.int32(n), jnp.int32(0))
        root_hist = self._reduce_hist(local_root)   # (fs, B, 3) scattered
        sum_g = self._global_scalar(jnp.sum((grad * bag).astype(acc)))
        sum_h = self._global_scalar(jnp.sum((hess * bag).astype(acc)))
        cnt = self._global_scalar(jnp.sum(bag.astype(acc)))

        md = int(self.cfg.max_depth)
        depth_ok = jnp.asarray([True if md <= 0 else md > 0])
        cf_root, ci_root, cb_root = self._best_rows_global(
            root_hist[None], (sum_g[None], sum_h[None], cnt[None]),
            fmask_pad, depth_ok, None)

        root_lf = jnp.zeros(NUM_LF, acc) \
            .at[LF_SUM_G].set(sum_g).at[LF_SUM_H].set(sum_h) \
            .at[LF_CNT].set(cnt).at[LF_MIN_C].set(-jnp.inf) \
            .at[LF_MAX_C].set(jnp.inf)
        state = CompactState(
            bins_p=bins_p,
            w_p=w,
            rid_p=jnp.arange(n, dtype=jnp.int32),
            lid_p=jnp.zeros(n, jnp.int32),
            leaf_i=jnp.zeros((L, 2), jnp.int32).at[0, 1].set(n),
            leaf_f=jnp.zeros((L, NUM_LF), acc)
                      .at[:, LF_MIN_C].set(-jnp.inf)
                      .at[:, LF_MAX_C].set(jnp.inf)
                      .at[0].set(root_lf),
            hist_pool=jnp.zeros((L,) + root_hist.shape, root_hist.dtype)
                         .at[0].set(root_hist),
            cand_f=jnp.zeros((L, NUM_CF), acc)
                      .at[:, CF_GAIN].set(-jnp.inf)
                      .at[0].set(cf_root[0]),
            cand_i=jnp.zeros((L, NUM_CI), jnp.int32).at[0].set(ci_root[0]),
            cand_b=jnp.zeros((L, self.cat_W), jnp.uint32)
                      .at[0].set(cb_root[0]),
            num_leaves=jnp.asarray(1, jnp.int32),
            rec_f=jnp.zeros((L - 1, NUM_REC_FIELDS), jnp.float32),
            rec_i=jnp.zeros((L - 1, 2), jnp.int32),
            rec_cat=jnp.zeros((L - 1, self.cat_W), jnp.uint32))

        state = self._forced_phase_compact(state, fmask_pad)

        def body(i, st):
            # records land at cursor num_leaves-1 (like the serial learner)
            # so the forced phase and best-gain growth share one stream;
            # iterations past the leaf budget are exact no-ops
            return self._split_step_compact(st, fmask_pad,
                                            st.num_leaves - 1)

        state = jax.lax.fori_loop(0, L - 1, body, state)
        leaf_id = lax.sort([state.rid_p, state.lid_p], num_keys=1)[1]
        leaf_output = state.leaf_f[:, LF_OUT].astype(jnp.float32)
        return (state.rec_f, state.rec_i, state.rec_cat, leaf_id,
                leaf_output)

    def _make_hist_branch_shard(self, S: int):
        """Local windowed histogram over the FULL padded feature axis (the
        scatter happens outside the bucket switch — collectives must not
        live under data-dependent branches)."""
        fw, b = self.fw, self.num_bins_padded
        n = self.n_local
        from ..ops.hist_pallas import unpack_bin_words
        from ..ops.histogram import build_histogram_onehot

        def branch(bins_p, w_p, lid_p, start, cnt, leaf):
            sa = jnp.clip(start, 0, n - S).astype(jnp.int32)
            off = (start - sa).astype(jnp.int32)
            bw = lax.dynamic_slice(bins_p, (jnp.int32(0), sa), (fw, S))
            ww = lax.dynamic_slice(w_p, (jnp.int32(0), sa), (3, S))
            lid = lax.dynamic_slice(lid_p, (sa,), (S,))
            pos = jnp.arange(S, dtype=jnp.int32)
            m = (pos >= off) & (pos < off + cnt) & (lid == leaf)
            wm = ww * m[None, :].astype(ww.dtype)
            bu = unpack_bin_words(bw, fw * 4)     # keep padded features
            if self._quant:
                # quantized lanes (mirrors the serial branch): two
                # channels ride the contraction, the count channel is the
                # normalized Σhq/m̄ effective row count — identical
                # channels to the serial quant learner keep the records
                # stream exact
                h2 = build_histogram_onehot(bu, wm[:2], num_bins=b)
                h = jnp.concatenate([h2, h2[:, :, 1:2]], axis=2)
                return h * jnp.stack([jnp.float32(1.0), jnp.float32(1.0),
                                      self._q_cnt])
            return build_histogram_onehot(bu, wm, num_bins=b,
                                          dp=self.hist_dp)

        return branch

    # -- host orchestration --------------------------------------------------

    def _build_jit(self):
        if self._jit_tree_c is None:
            ax = self.axis
            kw = dict(mesh=self.mesh,
                      in_specs=(P(None, ax), P(ax), P(ax), P(ax), P()),
                      out_specs=(P(), P(), P(), P(ax), P()))
            try:  # replication checking kwarg was renamed in jax 0.8
                fn = shard_map(self._train_tree_sharded, check_vma=False,
                               **kw)
            except TypeError:
                fn = shard_map(self._train_tree_sharded, check_rep=False,
                               **kw)
            self._jit_tree_c = jax.jit(fn)
        return self._jit_tree_c

    def train_async(self, grad: jax.Array, hess: jax.Array, bag: jax.Array,
                    feature_mask: Optional[jax.Array] = None):
        if feature_mask is None:
            feature_mask = jnp.ones(self.num_features, dtype=bool)
        fmask_pad = jnp.zeros(self.f_pad, bool).at[:self.num_features].set(
            feature_mask)
        return self._build_jit()(self.sharded_bins(), grad, hess, bag,
                                 fmask_pad)

    def lowered_hlo_text(self) -> str:
        """Compiled HLO of the sharded tree step (for collective asserts)."""
        n = self.n_pad
        z = jnp.zeros(n, jnp.float32)
        fmask_pad = jnp.ones(self.f_pad, bool)
        return self._build_jit().lower(
            self.sharded_bins(), z, z, z, fmask_pad).compile().as_text()

    # -- attribution probe (observability/attribution.py) --------------------

    def _probe_program(self, body, in_specs, out_specs, args):
        """Build + cache a standalone jitted shard_map probe over this
        learner's real exchange seam.  The ledger is muted while the
        probe traces, so ``collectives.sites`` and the analysis-gate
        budgets never see the probe's sites; the probe jit itself is
        outside the gate's traced-program set."""
        ledger = self._ledger
        kw = dict(mesh=self.mesh, in_specs=in_specs, out_specs=out_specs)
        try:
            fn = shard_map(body, check_vma=False, **kw)
        except TypeError:
            fn = shard_map(body, check_rep=False, **kw)
        jfn = jax.jit(fn)

        def run(*a):
            with ledger.muted():
                return jfn(*a)

        self._probe_fn, self._probe_args = run, tuple(args)
        return self._probe_fn, self._probe_args

    def exchange_probe(self):
        """The REAL root-histogram exchange (`_exchange` dim 0: the
        reduce-scatter over the feature axis) over a representative zero
        buffer."""
        if getattr(self, "_probe_fn", None) is None:
            return self._probe_program(
                lambda h: self._exchange(h, 0), P(), P(self.axis),
                (jnp.zeros((self.f_pad, self.num_bins_padded, 3),
                           jnp.float32),))
        return self._probe_fn, self._probe_args


def make_sharded_learner(cfg: Config, data: _ConstructedDataset,
                         mesh: Mesh) -> ShardedCompactLearner:
    return ShardedCompactLearner(cfg, data, mesh)


class ShardedVotingLearner(ShardedCompactLearner):
    """``tree_learner=voting`` — PV-Tree feature voting to cut histogram
    communication (`voting_parallel_tree_learner.cpp:166-345`).

    Per child: every device ranks features on its LOCAL (unreduced)
    histograms and proposes its top-``top_k`` (``LocalVoting``); one tiny
    all_gather of vote indices elects the global top-2k by vote count with
    low-index tie-break (``GlobalVoting`` / ``ArgMaxK``); only the ELECTED
    features' histograms are reduce-scattered (``CopyLocalHistogram``) and
    scanned.  The histogram pool stays local-unreduced so parent
    subtraction needs no extra wire traffic — communicated volume per split
    drops from (F, B, 3) to (2k, B, 3)."""

    _placement_mode = "voting"

    def __init__(self, cfg: Config, data: _ConstructedDataset, mesh: Mesh,
                 hist_backend: str = "auto"):
        super().__init__(cfg, data, mesh, hist_backend)
        self._init_voting_sizing(cfg)

    def _init_voting_sizing(self, cfg: Config) -> None:
        """2k elected features, rounded to a mesh multiple for the scatter
        (f_pad is itself a mesh multiple, so min() preserves divisibility).
        Shared with the voting-wave learner — keep the rounding rules in
        one place."""
        k2 = max(2 * int(cfg.top_k), self.D)
        k2 = min(((k2 + self.D - 1) // self.D) * self.D, self.f_pad)
        self.k_vote = min(int(cfg.top_k), self.f_pad)
        self.k2 = k2
        self.k2s = k2 // self.D              # elected features per device

    def _reduce_hist(self, local_hist):
        # the pool stays LOCAL; reduction happens per elected feature set
        return local_hist

    def _reduce_hist_batch(self, local_hists):
        # likewise: the batched stall-correction histograms stay local
        return local_hists

    def _forced_hrow(self, state, fs, sum_g, sum_h, cnt):
        # the voting pool is full-width LOCAL-unreduced: reduce the one
        # forced feature's row across devices, then fix it
        hrow = lax.psum(state.hist_pool[fs.leaf, fs.feature_inner],
                        self.axis)
        return self._fix_hrow(hrow, fs.feature_inner, sum_g, sum_h, cnt)

    def exchange_probe(self):
        """Voting's real wire payload is the ELECTED feature set (2k wide,
        not f_pad) — probe the elected-width reduce-scatter."""
        if getattr(self, "_probe_fn", None) is None:
            return self._probe_program(
                lambda h: self._exchange(h, 0), P(), P(self.axis),
                (jnp.zeros((self.k2, self.num_bins_padded, 3),
                           jnp.float32),))
        return self._probe_fn, self._probe_args

    def _best_rows_global(self, hist2, crow_sums, fmask_pad, depth_ok,
                          constraints):
        """hist2 here is (K, f_pad, B, 3) LOCAL-unreduced."""
        K = hist2.shape[0]
        d = lax.axis_index(self.axis)
        sg2, sh2, cn2 = crow_sums

        def one(hist, sg, sh, cn, mn, mx):
            # ---- LocalVoting: rank features on this device's local rows
            lsg = jnp.sum(hist[0, :, 0])
            lsh = jnp.sum(hist[0, :, 1])
            lcn = jnp.sum(hist[0, :, 2])
            g_loc, *_ = self._feature_cands_meta(
                hist, lsg, lsh, lcn, self.fp_num_bin, self.fp_missing,
                self.fp_default_bin, self.fp_is_cat, fmask_pad,
                self.fp_monotone, self.fp_penalty)
            vals, votes = lax.top_k(g_loc, self.k_vote)       # (k,)
            self._rec_coll("all_gather", votes)
            self._rec_coll("all_gather", vals)
            all_votes = lax.all_gather(votes, self.axis).reshape(-1)
            all_valid = ~jnp.isneginf(
                lax.all_gather(vals, self.axis).reshape(-1))
            counts = jnp.zeros(self.f_pad, jnp.int32).at[all_votes].add(
                all_valid.astype(jnp.int32), mode="drop")
            # GlobalVoting: top-2k by count, low feature index breaks ties
            score = counts.astype(jnp.float32) * self.f_pad \
                - jnp.arange(self.f_pad, dtype=jnp.float32)
            sel = jnp.sort(lax.top_k(score, self.k2)[1]).astype(jnp.int32)
            # ---- CopyLocalHistogram: exchange only elected features
            sel_hist = self._exchange(hist[sel], 0)           # (k2s, B, 3)
            my_sel = lax.dynamic_slice_in_dim(sel, d * self.k2s, self.k2s)
            gidx = lambda a: a[my_sel]
            g, thr, dl, ic, bits, lsg2, lsh2, lcn2, rsg, rsh, rcn, lo, ro = \
                self._feature_cands_meta(
                    sel_hist, sg, sh, cn,
                    gidx(self.fp_num_bin), gidx(self.fp_missing),
                    gidx(self.fp_default_bin), gidx(self.fp_is_cat),
                    gidx(fmask_pad),
                    gidx(self.fp_monotone) if self.has_monotone else None,
                    gidx(self.fp_penalty) if self.has_penalty else None,
                    mn, mx)
            bf = jnp.argmax(g).astype(jnp.int32)
            pick = lambda a: a[bf]
            cf = jnp.stack([pick(g).astype(self._acc), pick(lsg2),
                            pick(lsh2), pick(lcn2), pick(rsg), pick(rsh),
                            pick(rcn), pick(lo), pick(ro)]).astype(self._acc)
            flags = pick(dl).astype(jnp.int32) + 2 * pick(ic).astype(jnp.int32)
            ci = jnp.stack([my_sel[bf], pick(thr), flags])
            return cf, ci.astype(jnp.int32), bits[bf]

        if constraints is not None:
            mins, maxs = constraints
            cf, ci, cb = jax.vmap(one)(hist2, sg2, sh2, cn2, mins, maxs)
        else:
            cf, ci, cb = jax.vmap(
                lambda h, g, hh, c: one(h, g, hh, c, None, None)
            )(hist2, sg2, sh2, cn2)
        cf_all = lax.all_gather(cf, self.axis)
        ci_all = lax.all_gather(ci, self.axis)
        cb_all = lax.all_gather(cb, self.axis)
        # global winner; exact tie-break toward the LOWEST feature index —
        # unlike the sharded scan, the election's device slices are not
        # contiguous feature ranges, so the argmax alone is not enough
        gains = cf_all[:, :, CF_GAIN]
        max_gain = jnp.max(gains, axis=0)
        at_max = gains == max_gain[None, :]
        feat_masked = jnp.where(at_max, ci_all[:, :, CI_FEAT],
                                jnp.int32(1 << 30))
        win = jnp.argmin(feat_masked, axis=0)
        cf_g = jnp.take_along_axis(cf_all, win[None, :, None], axis=0)[0]
        ci_g = jnp.take_along_axis(ci_all, win[None, :, None], axis=0)[0]
        cb_g = jnp.take_along_axis(cb_all, win[None, :, None], axis=0)[0]
        cf_g = cf_g.at[:, CF_GAIN].set(
            jnp.where(depth_ok, cf_g[:, CF_GAIN], -jnp.inf))
        return cf_g, ci_g, cb_g
