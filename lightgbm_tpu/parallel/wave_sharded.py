"""Data-parallel frontier-wave learner: wave growth over row shards.

Round-3's ``ShardedCompactLearner`` wraps the SEQUENTIAL compact learner —
254 dependent split steps per tree, each paying the collective + bookkeeping
floor.  This subclass ports the frontier-wave growth
(`lightgbm_tpu/learner_wave.py`) into the shard_map program, mirroring the
reference's template of parallelizing its fastest serial learner
(`src/treelearner/data_parallel_tree_learner.cpp:257-258` instantiates over
the serial learner):

  * every device runs the wave partition over its LOCAL rows (the one
    stable sort per wave sorts the local shard);
  * the W smaller-child histograms of a wave are ``psum_scatter``-ed over
    the feature axis in ONE batched collective per wave — W× fewer
    exchanges than the sequential sharded learner
    (`data_parallel_tree_learner.cpp:146-161` reduce-scatters per split);
  * the 2W children's best splits come from per-device feature-slice scans
    merged by a tiny all_gather (``SyncUpGlobalBestSplit``,
    `parallel_tree_learner.h:186-209`);
  * node/candidate state stays replicated, so the exact greedy replay (and
    its leaf numbering) is pure replicated bookkeeping — no communication.

Exactness: the records stream is identical to the serial wave learner's
(`tests/test_parallel.py::test_wave_sharded_records_match_serial`).

Round 6: the Pallas stable-partition kernel composes here PER SHARD —
``_wave_body`` (shared with the serial learner) computes destinations
from LOCAL window geometry and local prefix sums and permutes only the
local rows, so ``tpu_wave_pallas_partition`` changes ZERO collective
sites (`analysis/budgets.json` pins them); ``_init_wave_dims`` re-runs
with the shard-local row count, so the 2^24-row eligibility gate applies
per shard.  The fused split-scan does NOT apply here: the sharded
candidate scans go through ``_best_rows_global`` (feature-slice scans +
all_gather), which overrides ``_cand_rows_batch`` entirely.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..config import Config
from ..dataset import _ConstructedDataset
from ..learner_wave import WaveState, WaveTPUTreeLearner, \
    wave_budget_reason
from .compact_sharded import ShardedCompactLearner, shard_map


class ShardedWaveLearner(ShardedCompactLearner, WaveTPUTreeLearner):
    """`tree_learner=data` on the frontier-wave learner (see module
    docstring).  MRO: sharded seams (_reduce_hist/_sync_counts/
    _best_rows_global) override the serial ones; wave growth/replay comes
    from WaveTPUTreeLearner."""

    def __init__(self, cfg: Config, data: _ConstructedDataset, mesh: Mesh,
                 hist_backend: str = "auto"):
        ShardedCompactLearner.__init__(self, cfg, data, mesh, hist_backend)
        # wave bookkeeping over the PADDED feature axis (no EFB bundles in
        # the sharded path; metadata was padded by the sharded __init__)
        self._init_wave_dims(cfg)
        # the sharded program keeps the round-4 per-wave flow (one
        # collective per wave); the serial opening's multi-slot kernel has
        # no exchange seam yet — growth starts at wave 1 as before
        self.open_levels = 0
        self.fw_col = jnp.arange(self.f_pad, dtype=jnp.int32)
        self.fw_goff = jnp.zeros(self.f_pad, jnp.int32)
        self.fw_bnd = jnp.zeros(self.f_pad, jnp.int32)
        self._jit_tree_w = None

    # -- sharded seams used by the wave body ---------------------------------

    def _sync_counts3(self, cnt3):
        # row 0 (left ROW count) is local window geometry; rows 1-2 are
        # the global bagged counts every device must agree on
        self._rec_coll("psum", cnt3[1:])
        bagged = lax.psum(cnt3[1:], self.axis)
        return jnp.concatenate([cnt3[:1], bagged], axis=0)

    def _replicated_spans(self, spans):
        # phys_i spans are LOCAL row-window geometry here — replicate the
        # batched-stall gate with the cross-device max so bv (and the
        # whole replay bookkeeping) stays identical on every shard
        self._rec_coll("pmax", spans)
        return lax.pmax(spans, self.axis)

    def _cand_rows_batch(self, hists, sg, sh, cn, feature_mask, depth_ok,
                         constraints):
        """(K, fs, B, 3) scattered child histograms -> replicated best
        rows via feature-slice scans + all_gather."""
        return self._best_rows_global(hists, (sg, sh, cn), feature_mask,
                                      depth_ok, constraints)

    def _wave_member_hists(self, st: WaveState, sm_slot, sm_start, sm_cnt,
                           valid, ph, lh_w, rh_w, left_small):
        """Local per-member histograms over the full padded feature axis,
        ONE batched psum_scatter over features per wave, then subtraction
        against the (scattered) parent pool slices."""
        def hist_member(_, xs):
            slot, start, cnt, vk = xs

            def compute(_):
                hidx = self._bucket_idx(jnp.maximum(cnt, 1))
                return lax.switch(hidx, self._hist_branches, st.bins_p,
                                  st.w_p, st.lid_p, start, cnt, slot)

            def skip(_):
                b = self.num_bins_padded
                return jnp.zeros((self.f_pad, b, 3), self._hist_dtype())

            return 0, lax.cond(vk, compute, skip, 0)

        _, h_local = lax.scan(hist_member, 0,
                              (sm_slot, sm_start, sm_cnt, valid))
        # (W, f_pad, B, 3) -> (W, fs, B, 3): one collective per wave,
        # int16-packed in quantized mode (_exchange)
        h_small = self._exchange(h_local, 1)
        h_par = st.hist_pool[ph]                       # (W, fs, B, 3)
        h_large = h_par - h_small
        lsm = left_small[:, None, None, None]
        hl = jnp.where(lsm, h_small, h_large)
        hr = jnp.where(lsm, h_large, h_small)
        pool = st.hist_pool.at[lh_w].set(hl).at[rh_w].set(hr)
        return pool, hl, hr

    def _hist_dtype(self):
        import jax.numpy as jnp
        return jnp.float64 if self.hist_dp else jnp.float32

    # -- the sharded wave tree ----------------------------------------------

    def _train_tree_wave_sharded(self, bins_p, grad, hess, bag, fmask_pad):
        self._ledger.begin_trace()
        self._hist_branches = [self._make_hist_branch_shard(S)
                               for S in self._win_sizes]
        self._stall_branches = [
            self._make_stall_branch(S, sort_mode=S > self._stall_cutoff)
            for S in self._win_sizes]
        st = self._init_root_wave(bins_p, grad, hess, bag, fmask_pad)

        def gcond(s):
            return (s.num_splits < self.grow_budget) & \
                (jnp.max(self._pool_gains(s)) > 0.0)

        st = lax.while_loop(gcond,
                            lambda s: self._wave_step(s, fmask_pad), st)
        if self._defer_sorts and self._stall_batch == 1:
            # batched (K>1) replay corrections mask through phys_i spans
            # and skip the pre-replay materialization (see learner_wave)
            st = lax.cond(st.pending, self._materialize_sort,
                          lambda s: s, st)
        return self._emit_tree_wave(st, fmask_pad)

    def train_async(self, grad: jax.Array, hess: jax.Array, bag: jax.Array,
                    feature_mask: Optional[jax.Array] = None):
        if feature_mask is None:
            feature_mask = jnp.ones(self.num_features, dtype=bool)
        fmask_pad = jnp.zeros(self.f_pad, bool).at[:self.num_features].set(
            feature_mask)
        if self._jit_tree_w is None:
            ax = self.axis
            out_specs = (P(), P(), P(), P(ax), P())
            if self._telemetry:  # the counter lane is replicated bookkeeping
                out_specs = out_specs + (P(),)
            kw = dict(mesh=self.mesh,
                      in_specs=(P(None, ax), P(ax), P(ax), P(ax), P()),
                      out_specs=out_specs)
            try:
                fn = shard_map(self._train_tree_wave_sharded,
                               check_vma=False, **kw)
            except TypeError:
                fn = shard_map(self._train_tree_wave_sharded,
                               check_rep=False, **kw)
            self._jit_tree_w = jax.jit(fn, donate_argnums=(1, 2)) \
                if self._donate else jax.jit(fn)
        return self._pop_telem(self._jit_tree_w(
            self.sharded_bins(), grad, hess, bag, fmask_pad))

    def lowered_hlo_text(self) -> str:
        # grad/hess are donate_argnums under _donate: each position gets
        # its OWN buffer so the donated args never alias bag (LGB009)
        n = self.n_pad
        g, h, b = (jnp.zeros(n, jnp.float32) for _ in range(3))
        self.train_async(g, h, b)  # build the jit
        g, h, b = (jnp.zeros(n, jnp.float32) for _ in range(3))
        fmask_pad = jnp.ones(self.f_pad, bool)
        return self._jit_tree_w.lower(
            self.sharded_bins(), g, h, b, fmask_pad).compile().as_text()

    def exchange_probe(self):
        """The wave learner's real per-wave exchange: ONE batched
        psum_scatter over the (W, f_pad, B, 3) member histograms,
        scattered over the feature axis (`_wave_member_hists`)."""
        if getattr(self, "_probe_fn", None) is None:
            return self._probe_program(
                lambda h: self._exchange(h, 1), P(),
                P(None, self.axis),
                (jnp.zeros((self.W, self.f_pad, self.num_bins_padded, 3),
                           self._hist_dtype()),))
        return self._probe_fn, self._probe_args


class ShardedVotingWaveLearner(ShardedWaveLearner):
    """``tree_learner=voting`` on the frontier-wave learner: the histogram
    pool stays LOCAL-unreduced (exactly like the sequential
    ``ShardedVotingLearner``) and every wave's 2W children each run the
    PV-Tree election — local top-k votes, global top-2k election, elected
    features' histograms reduce-scattered and scanned
    (`voting_parallel_tree_learner.cpp:166-345`) — inside the one batched
    candidate scan, so the election happens once per wave instead of once
    per split."""

    def __init__(self, cfg: Config, data: _ConstructedDataset, mesh: Mesh,
                 hist_backend: str = "auto"):
        super().__init__(cfg, data, mesh, hist_backend)
        from .compact_sharded import ShardedVotingLearner
        ShardedVotingLearner._init_voting_sizing(self, cfg)

    def _reduce_hist(self, local_hist):
        # the pool stays LOCAL; reduction happens per elected feature set
        return local_hist

    def _reduce_hist_batch(self, local_hists):
        # batched stall-correction histograms stay local too (the voting
        # protocol reduces only elected features inside the candidate scan)
        return local_hists

    def _wave_member_hists(self, st, sm_slot, sm_start, sm_cnt, valid, ph,
                           lh_w, rh_w, left_small):
        # local full-width member histograms, NO exchange — subtraction
        # against the local pool (the voting protocol reduces only the
        # elected features inside the candidate scan)
        return WaveTPUTreeLearner._wave_member_hists(
            self, st, sm_slot, sm_start, sm_cnt, valid, ph, lh_w, rh_w,
            left_small)

    def _cand_rows_batch(self, hists, sg, sh, cn, feature_mask, depth_ok,
                         constraints):
        from .compact_sharded import ShardedVotingLearner
        return ShardedVotingLearner._best_rows_global(
            self, hists, (sg, sh, cn), feature_mask, depth_ok, constraints)

    def exchange_probe(self):
        # voting's wire payload is the elected (2k-wide) feature set —
        # probe that seam, not the full-width wave exchange
        from .compact_sharded import ShardedVotingLearner
        return ShardedVotingLearner.exchange_probe(self)


def wave_sharded_eligible(cfg: Config, data: _ConstructedDataset,
                          mesh_size: int) -> bool:
    """The sharded wave learner reuses the serial wave shape/byte gates
    with the PER-DEVICE shard length (no EFB condition — the sharded path
    never bundles).  NOTE: ``wave_budget_reason`` sizes the histogram pool
    at the FULL feature width — exact for the voting learner's
    local-unreduced pool, conservative for data-parallel's scattered one;
    keep it that way if the formula is ever tightened."""
    if cfg.tpu_learner not in ("auto", "wave"):
        return False       # explicit compact/masked request is honored
    if data.max_num_bin > 256:
        return False
    if data.num_data_padded % max(mesh_size, 1):
        return False
    if data.bins.shape[0] % max(mesh_size, 1):
        return False
    return wave_budget_reason(
        cfg, int(data.num_data_padded) // max(mesh_size, 1),
        data.bins.shape[0], int(data.max_num_bin)) is None
