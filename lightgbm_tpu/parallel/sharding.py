"""Declarative mesh/sharding layer: N-D meshes + regex placement rules.

The reference wires three DISJOINT parallel modes over a hand-written
Allreduce/ReduceScatter layer (`src/treelearner/*_parallel_tree_learner.cpp`
+ `src/network/network.cpp:64-330`); our earlier rounds mirrored that split
with per-mode hand-placed ``device_put`` calls scattered through
`parallel/learners.py` and the sharded learners.  This module replaces the
hand placement with the GSPMD idiom (the mesh-helper / partition-rules
pattern of SNIPPETS.md [2]/[3]):

  * :func:`make_mesh` builds 1-D *or* N-D meshes over named axes
    (``("data", "feature")``) — the analogue of the reference's
    ``num_machines``/``machine_list`` config grown to two dimensions;
  * :class:`PlacementRules` maps array NAMES to ``PartitionSpec``s via an
    ordered regex table (first match wins), so "bins shard
    features×rows, row vectors shard rows, metadata replicates" is ONE
    declarative table per mode instead of a dozen call sites;
  * :func:`rules_for_mode` holds those per-mode tables, including the 2-D
    hybrid ``data_feature`` mode (bins ``P("feature", "data")``).

Axes:
  * ``data``    — row shards (`tree_learner=data|voting`, and the row axis
    of ``data_feature``)
  * ``feature`` — feature shards (`tree_learner=feature`, and the feature
    axis of ``data_feature``)
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DATA = "data"
AXIS_FEATURE = "feature"


# -- mesh construction --------------------------------------------------------

def make_mesh(num_devices: Optional[int] = None, axis_name: str = AXIS_DATA,
              devices: Optional[Sequence] = None,
              shape: Optional[Sequence[int]] = None,
              axis_names: Optional[Sequence[str]] = None) -> Mesh:
    """Mesh over the available devices.

    1-D (the round-3 signature, unchanged): ``make_mesh(4)`` → 4 devices
    on axis ``data``.  N-D: ``make_mesh(shape=(2, 4),
    axis_names=("data", "feature"))`` → a 2×4 grid, the analogue of the
    reference's ``num_machines`` config grown to a second dimension.
    """
    if devices is None:
        devices = jax.devices()
        if shape is not None:
            need = int(np.prod(shape))
            if len(devices) < need:
                raise ValueError(
                    f"mesh shape {tuple(shape)} needs {need} devices, "
                    f"platform has {len(devices)}")
            devices = devices[:need]
        elif num_devices is not None:
            devices = devices[:num_devices]
    if shape is None:
        return Mesh(np.asarray(devices), (axis_name,))
    if axis_names is None:
        axis_names = (AXIS_DATA, AXIS_FEATURE)[:len(shape)]
    if len(axis_names) != len(shape):
        raise ValueError(f"axis_names {tuple(axis_names)} does not match "
                         f"mesh shape {tuple(shape)}")
    return Mesh(np.asarray(devices).reshape(tuple(shape)),
                tuple(axis_names))


def parse_mesh_shape(spec: str) -> Optional[Tuple[int, ...]]:
    """``"2x4"`` → ``(2, 4)``; ``"8"`` → ``(8,)``; ``""``/``"auto"`` →
    None (let the mode pick).  The ``parallel_mesh`` config grammar —
    for ``data_feature`` the order is data×feature."""
    s = str(spec or "").strip().lower()
    if s in ("", "auto"):
        return None
    parts = [p for p in re.split(r"[x*,]", s) if p]
    try:
        dims = tuple(int(p) for p in parts)
    except ValueError:
        raise ValueError(f"parallel_mesh={spec!r} is not of the form "
                         f"'D' or 'DxF'")
    if not dims or any(d <= 0 for d in dims) or len(dims) > 2:
        raise ValueError(f"parallel_mesh={spec!r} must be 1 or 2 positive "
                         f"dims")
    return dims


def default_mesh_shape_2d(n_devices: int) -> Tuple[int, int]:
    """Auto (data, feature) factorization: the feature axis gets the
    smaller balanced factor (rows usually dominate, and the per-device
    split-scan slice shrinks by the FULL device count either way)."""
    n = max(int(n_devices), 1)
    df = 1
    for f in range(int(np.sqrt(n)), 0, -1):
        if n % f == 0:
            df = f
            break
    return n // df, df


def mesh_for_config(cfg, devices: Optional[Sequence] = None) -> Mesh:
    """The mesh a Config asks for: ``parallel_mesh`` ("2x4" = data×feature)
    when set, else all local devices — 2-D for ``tree_learner=
    data_feature``, 1-D otherwise."""
    mode = getattr(cfg, "tree_learner", "serial")
    shape = parse_mesh_shape(getattr(cfg, "parallel_mesh", ""))
    ndev = len(devices) if devices is not None else len(jax.devices())
    if mode == "data_feature":
        if shape is None:
            shape = default_mesh_shape_2d(ndev)
        elif len(shape) == 1:
            shape = default_mesh_shape_2d(shape[0])
        return make_mesh(shape=shape, devices=devices,
                         axis_names=(AXIS_DATA, AXIS_FEATURE))
    if shape is not None:
        return make_mesh(num_devices=int(np.prod(shape)), devices=devices)
    return make_mesh(devices=devices)


# -- axis resolution (the N-D fix for the old axis_names[0] assumption) ------

def row_axis(mesh: Mesh) -> str:
    """The row-shard axis of a mesh: ``data`` when present, else the first
    axis (1-D meshes built with a custom axis name)."""
    return AXIS_DATA if AXIS_DATA in mesh.axis_names else mesh.axis_names[0]


def feature_axis(mesh: Mesh) -> str:
    return AXIS_FEATURE if AXIS_FEATURE in mesh.axis_names \
        else mesh.axis_names[0]


# -- regex -> PartitionSpec rules (SNIPPETS.md [3] match_partition_rules) ----

class PlacementRules:
    """Ordered (regex, PartitionSpec) table bound to a mesh; first match
    wins, no match replicates.  Names are '/'-joined pytree paths."""

    def __init__(self, mesh: Mesh,
                 rules: Sequence[Tuple[str, P]]) -> None:
        self.mesh = mesh
        self.rules: List[Tuple[Any, P]] = [
            (re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, name: str) -> P:
        for pat, spec in self.rules:
            if pat.search(name):
                return spec
        return P()

    def sharding_for(self, name: str) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec_for(name))

    def place(self, name: str, arr):
        """device_put one named array per its matched rule."""
        return jax.device_put(arr, self.sharding_for(name))

    def place_tree(self, tree):
        """Place every leaf of a pytree; leaf names are '/'-joined key
        paths (dict keys / attr names / sequence indices)."""
        from jax.tree_util import tree_flatten_with_path, tree_unflatten

        def _key(k) -> str:
            for attr in ("key", "name", "idx"):
                if hasattr(k, attr):
                    return str(getattr(k, attr))
            return str(k)

        leaves, treedef = tree_flatten_with_path(tree)
        placed = [self.place("/".join(_key(k) for k in path), leaf)
                  for path, leaf in leaves]
        return tree_unflatten(treedef, placed)


#: row-aligned 1-D vector names used across the boosting loop / objectives
_ROW_VECTORS = (r"(^|/)(valid_rows|bag_mask|grad|hess|bag|rows|label|"
                r"weights|trans_label|label_sign|label_w|label_weight)$")
#: (K, N) row-aligned matrices (score table, one-hot labels)
_ROW_MATRICES = r"(^|/)(score|label_onehot)$"


def rules_for_mode(mode: str, mesh: Mesh) -> PlacementRules:
    """The per-mode placement tables (the declarative replacement for the
    hand-written device_put ladders of rounds 3-6)."""
    d, f = row_axis(mesh), feature_axis(mesh)
    if mode in ("data", "voting"):
        table = [
            (r"(^|/)bins$", P(None, d)),       # (F, N): shard rows
            (_ROW_MATRICES, P(None, d)),
            (_ROW_VECTORS, P(d)),
        ]
    elif mode == "feature":
        # the reference feature-parallel data model: every worker holds all
        # rows AND features (the shard_map body slices its word range by
        # axis_index) — everything replicates, including bins
        table = [
            (r"(^|/)bins$", P(None, None)),
        ]
    elif mode == "data_feature":
        table = [
            (r"(^|/)bins$", P(f, d)),          # (F, N) tile per device
            (_ROW_MATRICES, P(None, d)),
            (_ROW_VECTORS, P(d)),
        ]
    else:
        raise ValueError(f"unknown parallel mode {mode!r}")
    # histograms / split state / feature metadata replicate (the sharded
    # learners' shard_map programs own their internal scatter)
    return PlacementRules(mesh, table)
