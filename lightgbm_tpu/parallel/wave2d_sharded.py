"""2-D hybrid data×feature frontier-wave learner (`tree_learner=
data_feature`).

The reference treats data- and feature-parallel as DISJOINT modes
(`src/treelearner/data_parallel_tree_learner.cpp` vs
`feature_parallel_tree_learner.cpp`); on a TPU slice the mesh makes them one
program: each device owns a (feature-word-slice × row-shard) TILE of the
packed bin matrix (``P("feature", "data")`` under
`parallel/sharding.py`'s rules), so at D = Dd×Df devices

  * member histograms cover only ``fs_col = f_pad/Df`` features over
    ``n_pad/Dd`` local rows, and the per-wave ``psum_scatter`` runs along
    the ``data`` axis ONLY — Dd participants moving (W, fs_col, B, 3)
    instead of the 1-D data mode's D participants moving (W, f_pad, B, 3):
    a Df× smaller payload over a Dd-wide group;
  * split scans cover the device's ``fs = fs_col/Dd`` slice of the
    scattered histogram, and the winner merge is ONE joint all_gather over
    BOTH axes of a tiny packed record (``SyncUpGlobalBestSplit``,
    `parallel_tree_learner.h:186-209`) — same wire volume as either 1-D
    mode's merge;
  * the only new exchanges are two tiny per-row word broadcasts along
    ``feature`` (the split feature's packed bin word lives on one feature
    column — the decide pass and the stall partition each psum an
    (rows,)-int32 lane), the price of never replicating bins.

Double-buffered waves (``tpu_wave_hist_buffers``): the W member histograms
of a wave accumulate in B independent half-wave groups, each followed by
its own reduce-scatter.  Group g+1's accumulation has no data dependence
on group g's collective, so XLA's async collectives (TPU: ICI DMA; the
guide's "overlap of collective communication with compute") run the wire
transfer of one group under the VPU/MXU accumulation of the next.  TRUE
cross-wave overlap is impossible by construction — wave k+1's membership
depends on wave k's reduced scans — so the half-wave split is the whole
legal overlap window.

Exactness: same records stream as the serial wave learner
(`tests/test_parallel2d.py`), via the same replicated-bookkeeping argument
as the 1-D modes plus a lowest-feature-index tie-break at the 2-D merge
(tile offsets are not monotone in gathered device order).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..config import Config
from ..dataset import _ConstructedDataset
from ..learner_compact import CF_GAIN, CI_FEAT, CompactTPUTreeLearner
from ..learner_wave import WaveState, wave_budget_reason
from .compact_sharded import shard_map
from .sharding import AXIS_DATA, AXIS_FEATURE
from .wave_sharded import ShardedWaveLearner


def _mesh_dims(mesh: Mesh):
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    return int(shape.get(AXIS_DATA, 1)), int(shape.get(AXIS_FEATURE, 1))


class ShardedWave2DLearner(ShardedWaveLearner):
    """One shard_map tree step over a ``("data", "feature")`` mesh (see
    module docstring).  Inheriting from the 1-D data learner with
    ``self.axis = "data"`` keeps every row collective (leaf counts, global
    scalars, span replication, histogram reduce-scatter) on the data axis
    untouched; rebinding ``self.fw`` to the LOCAL tile word count makes the
    inherited sort/partition/histogram machinery tile-local for free."""

    _placement_mode = "data_feature"

    def __init__(self, cfg: Config, data: _ConstructedDataset, mesh: Mesh,
                 hist_backend: str = "auto"):
        self.mesh = mesh
        self.axis = AXIS_DATA          # inherited row seams ride this
        self.faxis = AXIS_FEATURE
        self.Dd, self.Df = _mesh_dims(mesh)
        self.D = self.Dd * self.Df
        CompactTPUTreeLearner.__init__(self, cfg, data, hist_backend)
        if self.n_pad % self.Dd:
            raise ValueError(f"padded rows {self.n_pad} not divisible by "
                             f"data axis {self.Dd}")
        self.n_local = self.n_pad // self.Dd
        f_pad = data.bins.shape[0]
        self.f_pad = f_pad
        fw_global = self.fw            # packed words over ALL features
        if fw_global % self.Df:
            raise ValueError(f"packed words {fw_global} not divisible by "
                             f"feature axis {self.Df} (word-aligned tiles)")
        self.fw_global = fw_global
        self.fws = fw_global // self.Df     # packed words per tile
        self.fs_col = self.fws * 4          # features per feature column
        if self.fs_col % self.Dd:
            raise ValueError(f"feature column {self.fs_col} not divisible "
                             f"by data axis {self.Dd}")
        self.fs = self.fs_col // self.Dd    # scan slice per device
        # rebind to the LOCAL tile: the inherited histogram branches,
        # partition sorts and materialization all read self.fw
        self.fw = self.fws
        self._init_local_windows(cfg, self.n_local)
        self._use_pallas = False
        self._pad_feature_meta(data, f_pad)
        self._sharded_bins = None
        self._jit_tree_c = None
        # wave dims over the local shard (same as the 1-D wave __init__)
        self._init_wave_dims(cfg)
        self.open_levels = 0
        self.fw_col = jnp.arange(self.f_pad, dtype=jnp.int32)
        self.fw_goff = jnp.zeros(self.f_pad, jnp.int32)
        self.fw_bnd = jnp.zeros(self.f_pad, jnp.int32)
        self._jit_tree_w = None
        self._hist_buffers = max(
            int(getattr(cfg, "tpu_wave_hist_buffers", 2)), 1)

    # -- tile geometry --------------------------------------------------------

    def _shard_slice(self, full):
        """This device's scan slice of a global (f_pad,) array: feature
        column j covers [j·fs_col, (j+1)·fs_col); the data-axis scatter
        hands row i the i-th fs-slice of that column."""
        i = lax.axis_index(self.axis)
        j = lax.axis_index(self.faxis)
        return lax.dynamic_slice_in_dim(full, j * self.fs_col + i * self.fs,
                                        self.fs)

    # -- split-word broadcast along the feature axis --------------------------

    def _word_select(self, bins_c, widx_r):
        """Decide-pass word extraction: ``widx_r`` carries GLOBAL packed
        word indices, this device's (fws, rows) chunk holds words
        [j·fws, (j+1)·fws) — masked local sum, then one (rows,)-int32 psum
        along ``feature`` broadcasts the owning column's words."""
        j = lax.axis_index(self.faxis)
        loc = widx_r - j * self.fws
        word = jnp.zeros(widx_r.shape[0], jnp.int32)
        for wdi in range(self.fws):
            word = word + jnp.where(loc == wdi, bins_c[wdi], 0)
        self._rec_coll("psum", word)
        return lax.psum(word, self.faxis)

    def _window_word(self, bw, col):
        """Stall-partition word extraction over a sliced (fws, S) window;
        ``col`` is the replicated global packed column, so every device in
        a feature group takes the same branch and the psum pairs up."""
        j = lax.axis_index(self.faxis)
        w = col // 4 - j * self.fws
        S = bw.shape[1]
        safe = jnp.clip(w, 0, self.fws - 1)
        word = lax.dynamic_slice(bw, (safe, jnp.int32(0)), (1, S))[0]
        word = jnp.where((w >= 0) & (w < self.fws), word, 0)
        self._rec_coll("psum", word)
        return lax.psum(word, self.faxis)

    # -- best-split merge over BOTH axes --------------------------------------

    def _best_rows_global(self, hist2, crow_sums, fmask_pad, depth_ok,
                          constraints):
        """Local fs-slice scans → ONE joint all_gather over (data, feature)
        → global argmax with an explicit lowest-feature-index tie-break
        (tile offsets are NOT monotone in gathered device order, so the
        1-D learner's positional tie-break does not reproduce the serial
        argmax)."""
        i = lax.axis_index(self.axis)
        j = lax.axis_index(self.faxis)
        goff = j * self.fs_col + i * self.fs

        def one(hist, sg, sh, cn, mn, mx):
            g, thr, dl, ic, bits, lsg, lsh, lcn, rsg, rsh, rcn, lo, ro = \
                self._feature_cands_shard(hist, sg, sh, cn, fmask_pad, mn,
                                          mx)
            bf = jnp.argmax(g).astype(jnp.int32)
            pick = lambda a: a[bf]
            cf = jnp.stack([pick(g).astype(self._acc), pick(lsg), pick(lsh),
                            pick(lcn), pick(rsg), pick(rsh), pick(rcn),
                            pick(lo), pick(ro)]).astype(self._acc)
            flags = pick(dl).astype(jnp.int32) + \
                2 * pick(ic).astype(jnp.int32)
            ci = jnp.stack([bf + goff, pick(thr), flags])
            return cf, ci.astype(jnp.int32), bits[bf]

        sg2, sh2, cn2 = crow_sums
        if constraints is not None:
            mins, maxs = constraints
            cf, ci, cb = jax.vmap(one)(hist2, sg2, sh2, cn2, mins, maxs)
        else:
            cf, ci, cb = jax.vmap(
                lambda h, g, hh, c: one(h, g, hh, c, None, None)
            )(hist2, sg2, sh2, cn2)
        axes = (self.axis, self.faxis)
        for x in (cf, ci, cb):
            self._rec_coll("all_gather", x)
        cf_all = lax.all_gather(cf, axes)      # (Dd*Df, K, NUM_CF)
        ci_all = lax.all_gather(ci, axes)
        cb_all = lax.all_gather(cb, axes)
        gains = cf_all[:, :, CF_GAIN]
        max_gain = jnp.max(gains, axis=0)
        at_max = gains == max_gain[None, :]
        feat_masked = jnp.where(at_max, ci_all[:, :, CI_FEAT],
                                jnp.int32(1 << 30))
        win = jnp.argmin(feat_masked, axis=0)
        cf_g = jnp.take_along_axis(cf_all, win[None, :, None], axis=0)[0]
        ci_g = jnp.take_along_axis(ci_all, win[None, :, None], axis=0)[0]
        cb_g = jnp.take_along_axis(cb_all, win[None, :, None], axis=0)[0]
        cf_g = cf_g.at[:, CF_GAIN].set(
            jnp.where(depth_ok, cf_g[:, CF_GAIN], -jnp.inf))
        return cf_g, ci_g, cb_g

    # -- double-buffered wave histograms --------------------------------------

    def _wave_member_hists(self, st: WaveState, sm_slot, sm_start, sm_cnt,
                           valid, ph, lh_w, rh_w, left_small):
        """The W member histograms split into ``tpu_wave_hist_buffers``
        independent groups, each with its own data-axis reduce-scatter:
        group g+1's local accumulation has no dependence on group g's
        collective, so async collectives overlap the wire with compute
        (half-wave double buffering — see module docstring)."""
        def hist_member(_, xs):
            slot, start, cnt, vk = xs

            def compute(_):
                hidx = self._bucket_idx(jnp.maximum(cnt, 1))
                return lax.switch(hidx, self._hist_branches, st.bins_p,
                                  st.w_p, st.lid_p, start, cnt, slot)

            def skip(_):
                b = self.num_bins_padded
                return jnp.zeros((self.fs_col, b, 3), self._hist_dtype())

            return 0, lax.cond(vk, compute, skip, 0)

        W = int(sm_slot.shape[0])
        nb = min(self._hist_buffers, W)
        bounds = [round(g * W / nb) for g in range(nb + 1)]
        parts = []
        for g in range(nb):
            lo, hi = bounds[g], bounds[g + 1]
            if lo == hi:
                continue
            _, h_loc = lax.scan(hist_member, 0,
                                (sm_slot[lo:hi], sm_start[lo:hi],
                                 sm_cnt[lo:hi], valid[lo:hi]))
            parts.append(self._exchange(h_loc, 1))
        h_small = parts[0] if len(parts) == 1 else \
            jnp.concatenate(parts, axis=0)      # (W, fs, B, 3)
        h_par = st.hist_pool[ph]
        h_large = h_par - h_small
        lsm = left_small[:, None, None, None]
        hl = jnp.where(lsm, h_small, h_large)
        hr = jnp.where(lsm, h_large, h_small)
        pool = st.hist_pool.at[lh_w].set(hl).at[rh_w].set(hr)
        return pool, hl, hr

    # -- host orchestration ---------------------------------------------------

    def train_async(self, grad: jax.Array, hess: jax.Array, bag: jax.Array,
                    feature_mask: Optional[jax.Array] = None):
        if feature_mask is None:
            feature_mask = jnp.ones(self.num_features, dtype=bool)
        fmask_pad = jnp.zeros(self.f_pad, bool).at[:self.num_features].set(
            feature_mask)
        if self._jit_tree_w is None:
            ax, fx = self.axis, self.faxis
            out_specs = (P(), P(), P(), P(ax), P())
            if self._telemetry:
                out_specs = out_specs + (P(),)
            kw = dict(mesh=self.mesh,
                      in_specs=(P(fx, ax), P(ax), P(ax), P(ax), P()),
                      out_specs=out_specs)
            try:
                fn = shard_map(self._train_tree_wave_sharded,
                               check_vma=False, **kw)
            except TypeError:
                fn = shard_map(self._train_tree_wave_sharded,
                               check_rep=False, **kw)
            self._jit_tree_w = jax.jit(fn, donate_argnums=(1, 2)) \
                if self._donate else jax.jit(fn)
        return self._pop_telem(self._jit_tree_w(
            self.sharded_bins(), grad, hess, bag, fmask_pad))

    def exchange_probe(self):
        """The 2D learner's dominant wire: the per-wave data-axis
        reduce-scatter at the LOCAL feature-column shape, entered over
        the full 2D mesh (the feature axis rides along replicated, as in
        the real program)."""
        if getattr(self, "_probe_fn", None) is None:
            return self._probe_program(
                lambda h: self._exchange(h, 1), P(),
                P(None, self.axis),
                (jnp.zeros((self.W, self.fs_col, self.num_bins_padded, 3),
                           self._hist_dtype()),))
        return self._probe_fn, self._probe_args


def wave2d_ineligible_reason(cfg: Config, data: _ConstructedDataset,
                             mesh: Mesh) -> Optional[str]:
    """Why ``tree_learner=data_feature`` cannot run on this mesh/dataset
    (None = eligible).  Divisibility mirrors the tile geometry above; the
    byte gate reuses the serial wave budget at the LOCAL tile shape."""
    if cfg.tpu_learner not in ("auto", "wave"):
        return f"tpu_learner={cfg.tpu_learner} (2D mode is wave-only)"
    if data.max_num_bin > 256:
        return f"max_num_bin {data.max_num_bin} > 256"
    dd, df = _mesh_dims(mesh)
    n_pad = int(data.num_data_padded)
    f_pad = int(data.bins.shape[0])
    if f_pad % 4:
        return f"padded features {f_pad} not word-aligned"
    if n_pad % max(dd, 1):
        return f"padded rows {n_pad} % data axis {dd} != 0"
    fw = f_pad // 4
    if fw % max(df, 1):
        return f"packed words {fw} % feature axis {df} != 0"
    fs_col = (fw // max(df, 1)) * 4
    if fs_col % max(dd, 1):
        return f"feature column {fs_col} % data axis {dd} != 0"
    return wave_budget_reason(cfg, n_pad // max(dd, 1), fs_col,
                              int(data.max_num_bin))


def wave2d_eligible(cfg: Config, data: _ConstructedDataset,
                    mesh: Mesh) -> bool:
    return wave2d_ineligible_reason(cfg, data, mesh) is None
