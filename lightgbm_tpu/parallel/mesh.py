"""Device mesh construction and dataset sharding (compatibility shims).

TPU-native replacement for the reference's process-level distribution setup
(`Network::Init`, `src/network/linkers_socket.cpp:20-218`: machine-list
parsing + all-pairs TCP mesh).  Here "machines" are devices in a
`jax.sharding.Mesh`; placement is declarative shardings and every collective
is inserted by XLA over ICI/DCN — there is no hand-written Bruck allgather or
recursive-halving reduce-scatter to port (`src/network/network.cpp:64-330`),
because the compiler owns the schedule.

Round 7: the mode-specific helpers here (``shard_dataset``,
``row_sharding``) are DEPRECATED in favor of the rule-driven layer in
`parallel/sharding.py` (:func:`rules_for_mode` /
:class:`~.sharding.PlacementRules`), which also fixes the old helpers'
hardcoded ``mesh.axis_names[0]`` row-axis assumption on N-D meshes.  They
remain as thin aliases so round-3-era callers and tests don't churn.
``make_mesh`` IS the supported entry point — it now lives in
`parallel/sharding.py` and grows N-D ``("data", "feature")`` support; the
re-export keeps the old import path working.
"""

from __future__ import annotations

import warnings

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import (  # noqa: F401  (re-exported API)
    AXIS_DATA, AXIS_FEATURE, feature_axis, make_mesh, mesh_for_config,
    parse_mesh_shape, row_axis, rules_for_mode)


def _deprecated(old: str, new: str) -> None:
    warnings.warn(f"lightgbm_tpu.parallel.mesh.{old} is deprecated; use "
                  f"{new} (parallel/sharding.py)", DeprecationWarning,
                  stacklevel=3)


def shard_dataset(data, mesh: Mesh, mode: str = "data"):
    """DEPRECATED: use ``rules_for_mode(mode, mesh).place("bins", ...)``.

    Places a constructed dataset's bins for a parallel mode and returns the
    sharded array; now rule-driven, so it resolves the row/feature axes by
    NAME and works on N-D meshes (the old version assumed
    ``mesh.axis_names[0]`` was the row axis)."""
    _deprecated("shard_dataset", "rules_for_mode(mode, mesh).place")
    if mode == "feature":
        # legacy behavior: the round-3 helper sharded the raw bins over
        # features (the modern feature-sharded learners replicate bins and
        # slice by axis_index — see rules_for_mode)
        return jax.device_put(data.device_bins(),
                              NamedSharding(mesh, P(feature_axis(mesh),
                                                    None)))
    if mode not in ("data", "voting", "data_feature"):
        raise ValueError(f"unknown parallel mode {mode}")
    return rules_for_mode(mode, mesh).place("bins", data.device_bins())


def row_sharding(mesh: Mesh) -> NamedSharding:
    """DEPRECATED: use ``rules_for_mode(...).sharding_for("rows")`` or
    ``NamedSharding(mesh, P(row_axis(mesh)))``."""
    _deprecated("row_sharding", "row_axis")
    return NamedSharding(mesh, P(row_axis(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
