"""Device mesh construction and dataset sharding.

TPU-native replacement for the reference's process-level distribution setup
(`Network::Init`, `src/network/linkers_socket.cpp:20-218`: machine-list
parsing + all-pairs TCP mesh).  Here "machines" are devices in a
`jax.sharding.Mesh`; placement is declarative shardings and every collective
is inserted by XLA over ICI/DCN — there is no hand-written Bruck allgather or
recursive-halving reduce-scatter to port (`src/network/network.cpp:64-330`),
because the compiler owns the schedule.

Axes:
  * ``data``    — row shards (data-parallel learner, `tree_learner=data`)
  * ``feature`` — feature shards (feature-parallel, `tree_learner=feature`)
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(num_devices: Optional[int] = None, axis_name: str = "data",
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the available devices (the analogue of the reference's
    ``num_machines``/``machine_list`` config, `config.h:690-717`)."""
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def shard_dataset(data, mesh: Mesh, mode: str = "data"):
    """Place a constructed dataset's device arrays for a parallel mode.

    data-parallel: rows sharded (`data_parallel_tree_learner.cpp:49` —
    each machine owns a row shard); feature-parallel: features sharded
    (`feature_parallel_tree_learner.cpp:29` — each machine owns features).
    Returns the sharded bins array; row-aligned vectors must use
    ``row_sharding(mesh)``.
    """
    axis = mesh.axis_names[0]
    if mode == "data":
        spec = P(None, axis)    # bins (F, N): shard rows
    elif mode == "feature":
        spec = P(axis, None)    # shard features
    else:
        raise ValueError(f"unknown parallel mode {mode}")
    sharding = NamedSharding(mesh, spec)
    return jax.device_put(data.device_bins(), sharding)


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
