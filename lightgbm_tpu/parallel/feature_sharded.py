"""Feature-parallel learners on the fast (compact/wave) tree learners.

TPU-native ``tree_learner=feature``
(`src/treelearner/feature_parallel_tree_learner.cpp:29-73`): every machine
holds ALL rows, histograms + split scans cover only its FEATURE shard, and
the winning split is agreed with a tiny allgather (``SyncUpGlobalBestSplit``,
`parallel_tree_learner.h:186-209`); the row partition is then performed
identically everywhere (the reference's workers also keep full data — the
mode trades replicated partitioning for an F/D scan load, its win on wide
dense datasets like Epsilon 400K×2000).

Round 3 draped feature-parallel over the slow masked learner; these
subclasses put it on the compact and frontier-wave learners instead:
row-axis seams revert to the serial behavior (rows are NOT sharded), while
the histogram branches compute only the local word slice and the split
scans ride the same slice machinery as the data-parallel learner.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..config import Config
from ..dataset import _ConstructedDataset
from ..learner_wave import WaveTPUTreeLearner
from .compact_sharded import ShardedCompactLearner, shard_map


class FeatureShardedCompactLearner(ShardedCompactLearner):
    """`tree_learner=feature` on the compact learner: replicated rows,
    feature-sliced histograms + scans, allgathered best splits."""

    _placement_mode = "feature"

    def __init__(self, cfg: Config, data: _ConstructedDataset, mesh: Mesh,
                 hist_backend: str = "auto"):
        super().__init__(cfg, data, mesh, hist_backend)
        # rows are replicated: window buckets span the FULL row axis
        self.n_local = self.n_pad
        self._init_local_windows(cfg, self.n_pad)
        # pad the packed-word axis to a mesh multiple (padding words carry
        # num_bin=0 features -> -inf gains, never selected)
        self.fw2 = ((self.fw + self.D - 1) // self.D) * self.D
        self.fws = self.fw2 // self.D       # words per device
        f_pad2 = self.fw2 * 4
        if f_pad2 != self.f_pad:
            pad = f_pad2 - self.f_pad
            zp = lambda a, fill=0: jnp.concatenate(
                [a, jnp.full((pad,), fill, a.dtype)])
            self.fp_num_bin = zp(self.fp_num_bin)
            self.fp_missing = zp(self.fp_missing)
            self.fp_default_bin = zp(self.fp_default_bin)
            self.fp_is_cat = zp(self.fp_is_cat.astype(jnp.int32)) > 0
            if self.has_monotone:
                self.fp_monotone = zp(self.fp_monotone)
            if self.has_penalty:
                self.fp_penalty = zp(self.fp_penalty, 1.0)
            self.f_num_bin = self.fp_num_bin
            self.f_missing = self.fp_missing
            self.f_default_bin = self.fp_default_bin
            if self.has_monotone:
                self.f_monotone = self.fp_monotone
            self.f_pad = f_pad2
            self.fw = self.fw2
        self.fs = self.f_pad // self.D      # features per device

    # rows replicated -> the serial row seams
    def _rows_len(self) -> int:
        return self.n_pad

    def _sync_counts(self, lc_bag, c_bag):
        return lc_bag, c_bag

    def _sync_counts3(self, cnt3):
        return cnt3

    def _global_scalar(self, v):
        return v                            # rows are replicated

    def _global_max(self, v):
        return v                            # rows are replicated

    def _global_row_offset(self):
        return jnp.int32(0)                 # every device holds all rows

    def _reduce_hist(self, local_hist):
        return local_hist                   # hist IS the local slice

    def _reduce_hist_batch(self, local_hists):
        return local_hists                  # feature slices need no exchange

    def _make_hist_branch_shard(self, S: int):
        """Windowed histogram over THIS device's feature-word slice of the
        replicated packed bins."""
        fws, b = self.fws, self.num_bins_padded
        n = self.n_pad
        from ..ops.hist_pallas import unpack_bin_words
        from ..ops.histogram import build_histogram_onehot

        def branch(bins_p, w_p, lid_p, start, cnt, leaf):
            d = lax.axis_index(self.axis)
            bw_f = lax.dynamic_slice_in_dim(bins_p, d * fws, fws, axis=0)
            sa = jnp.clip(start, 0, n - S).astype(jnp.int32)
            off = (start - sa).astype(jnp.int32)
            bw = lax.dynamic_slice(bw_f, (jnp.int32(0), sa), (fws, S))
            ww = lax.dynamic_slice(w_p, (jnp.int32(0), sa), (3, S))
            lid = lax.dynamic_slice(lid_p, (sa,), (S,))
            pos = jnp.arange(S, dtype=jnp.int32)
            m = (pos >= off) & (pos < off + cnt) & (lid == leaf)
            wm = ww * m[None, :].astype(ww.dtype)
            bu = unpack_bin_words(bw, fws * 4)
            if self._quant:
                # quantized lanes over the feature slice (no exchange —
                # same channel contract as the serial quant branch)
                h2 = build_histogram_onehot(bu, wm[:2], num_bins=b)
                h = jnp.concatenate([h2, h2[:, :, 1:2]], axis=2)
                return h * jnp.stack([jnp.float32(1.0), jnp.float32(1.0),
                                      self._q_cnt])
            return build_histogram_onehot(bu, wm, num_bins=b,
                                          dp=self.hist_dp)

        return branch

    def _train_tree_feature_sharded(self, bins_p, grad, hess, bag,
                                    fmask_pad):
        # identical body to the data-parallel tree, but with replicated
        # rows the collectives reduce to the best-split allgather only
        return self._train_tree_sharded(bins_p, grad, hess, bag, fmask_pad)

    def _build_jit(self):
        if self._jit_tree_c is None:
            ax = self.axis
            kw = dict(mesh=self.mesh,
                      in_specs=(P(None, None), P(), P(), P(), P()),
                      out_specs=(P(), P(), P(), P(), P()))
            try:
                fn = shard_map(self._train_tree_feature_sharded,
                               check_vma=False, **kw)
            except TypeError:
                fn = shard_map(self._train_tree_feature_sharded,
                               check_rep=False, **kw)
            self._jit_tree_c = jax.jit(fn)
        return self._jit_tree_c

    def sharded_bins(self) -> jax.Array:
        # replicated bins: every worker holds all rows and features, the
        # reference feature-parallel data model
        if self._sharded_bins is None:
            packed = self.bins_packed()
            if packed.shape[0] != self.fw2:
                packed = jnp.concatenate(
                    [packed, jnp.zeros((self.fw2 - packed.shape[0],
                                        packed.shape[1]), packed.dtype)])
            self._sharded_bins = self._rules().place("bins", packed)
        return self._sharded_bins

    def exchange_probe(self):
        """Feature-parallel's only per-split wire traffic is the tiny
        best-split allgather (``SyncUpGlobalBestSplit``,
        `_best_rows_global`) — probe exactly those three rows."""
        if getattr(self, "_probe_fn", None) is None:
            from ..learner_compact import NUM_CF, NUM_CI
            ax = self.axis

            def body(cf, ci, cb):
                return (lax.all_gather(cf, ax), lax.all_gather(ci, ax),
                        lax.all_gather(cb, ax))

            return self._probe_program(
                body, (P(), P(), P()), (P(), P(), P()),
                (jnp.zeros((1, NUM_CF), self._acc),
                 jnp.zeros((1, NUM_CI), jnp.int32),
                 jnp.zeros((1, self.cat_W), jnp.uint32)))
        return self._probe_fn, self._probe_args


class FeatureShardedWaveLearner(FeatureShardedCompactLearner,
                                WaveTPUTreeLearner):
    """`tree_learner=feature` on the frontier-wave learner: the wave's
    member histograms each cover the local feature slice (no exchange at
    all — subtraction and the pool stay slice-local); only the 2W best
    child splits are allgathered per wave."""

    def __init__(self, cfg: Config, data: _ConstructedDataset, mesh: Mesh,
                 hist_backend: str = "auto"):
        FeatureShardedCompactLearner.__init__(self, cfg, data, mesh,
                                              hist_backend)
        self._init_wave_dims(cfg)
        self.fw_col = jnp.arange(self.f_pad, dtype=jnp.int32)
        self.fw_goff = jnp.zeros(self.f_pad, jnp.int32)
        self.fw_bnd = jnp.zeros(self.f_pad, jnp.int32)
        self._jit_tree_w = None

    def _cand_rows_batch(self, hists, sg, sh, cn, feature_mask, depth_ok,
                         constraints):
        return self._best_rows_global(hists, (sg, sh, cn), feature_mask,
                                      depth_ok, constraints)

    # _wave_member_hists: the inherited WaveTPUTreeLearner scan branch is
    # already slice-local (sharded learners run with _use_pallas=False and
    # the hist branches compute this device's feature slice) — no override

    def _train_tree_feature_wave(self, bins_p, grad, hess, bag, fmask_pad):
        self._ledger.begin_trace()
        self._hist_branches = [self._make_hist_branch_shard(S)
                               for S in self._win_sizes]
        self._stall_branches = [
            self._make_stall_branch(S, sort_mode=S > self._stall_cutoff)
            for S in self._win_sizes]
        st = self._init_root_wave(bins_p, grad, hess, bag, fmask_pad)

        def gcond(s):
            return (s.num_splits < self.grow_budget) & \
                (jnp.max(self._pool_gains(s)) > 0.0)

        st = lax.while_loop(gcond,
                            lambda s: self._wave_step(s, fmask_pad), st)
        if self._defer_sorts and self._stall_batch == 1:
            # batched (K>1) replay corrections mask through phys_i spans
            # and skip the pre-replay materialization (see learner_wave)
            st = lax.cond(st.pending, self._materialize_sort,
                          lambda s: s, st)
        return self._emit_tree_wave(st, fmask_pad)

    def train_async(self, grad: jax.Array, hess: jax.Array, bag: jax.Array,
                    feature_mask: Optional[jax.Array] = None):
        if feature_mask is None:
            feature_mask = jnp.ones(self.num_features, dtype=bool)
        fmask_pad = jnp.zeros(self.f_pad, bool).at[:self.num_features].set(
            feature_mask)
        if self._jit_tree_w is None:
            ax = self.axis
            out_specs = (P(), P(), P(), P(), P())
            if self._telemetry:
                out_specs = out_specs + (P(),)
            kw = dict(mesh=self.mesh,
                      in_specs=(P(None, None), P(), P(), P(), P()),
                      out_specs=out_specs)
            try:
                fn = shard_map(self._train_tree_feature_wave,
                               check_vma=False, **kw)
            except TypeError:
                fn = shard_map(self._train_tree_feature_wave,
                               check_rep=False, **kw)
            self._jit_tree_w = jax.jit(fn, donate_argnums=(1, 2)) \
                if self._donate else jax.jit(fn)
        return self._pop_telem(self._jit_tree_w(
            self.sharded_bins(), grad, hess, bag, fmask_pad))

    def lowered_hlo_text(self) -> str:
        # grad/hess are donate_argnums under _donate: each position gets
        # its OWN buffer so the donated args never alias bag (LGB009)
        g, h, b = (jnp.zeros(self.n_pad, jnp.float32) for _ in range(3))
        self.train_async(g, h, b)
        g, h, b = (jnp.zeros(self.n_pad, jnp.float32) for _ in range(3))
        fmask_pad = jnp.ones(self.f_pad, bool)
        return self._jit_tree_w.lower(
            self.sharded_bins(), g, h, b, fmask_pad).compile().as_text()


def feature_sharded_eligible(cfg: Config, data: _ConstructedDataset,
                             mesh_size: int) -> bool:
    if data.max_num_bin > 256:
        return False
    # the word axis pads itself to a mesh multiple; the base
    # compact-sharded scaffolding still asserts f_pad and n_pad
    # divisibility in its __init__, so gate on both here
    if data.bins.shape[0] % max(mesh_size, 1):
        return False
    if data.num_data_padded % max(mesh_size, 1):
        return False
    return True
