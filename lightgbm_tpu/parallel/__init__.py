from .sharding import (AXIS_DATA, AXIS_FEATURE, PlacementRules, make_mesh,
                       mesh_for_config, parse_mesh_shape, row_axis,
                       rules_for_mode)
from .mesh import shard_dataset
from .learners import (make_data_parallel, make_feature_parallel,
                       make_hybrid_parallel, apply_parallel_sharding)
from . import multihost
