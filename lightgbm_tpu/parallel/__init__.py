from .mesh import make_mesh, shard_dataset
from .learners import (make_data_parallel, make_feature_parallel,
                       apply_parallel_sharding)
