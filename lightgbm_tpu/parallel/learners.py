"""Parallel tree learning via declarative sharding.

TPU-native re-design of the reference's three parallel learners:

  * data-parallel (`src/treelearner/data_parallel_tree_learner.cpp:49-254`):
    each machine owns a row shard, builds local histograms for all features,
    and the histograms are summed with ``ReduceScatter`` +
    ``HistogramBinEntry::SumReducer`` (`include/LightGBM/bin.h:40-56`), then
    the best split is agreed with an Allreduce of max-gain SplitInfos
    (`parallel_tree_learner.h:186-209`).
  * feature-parallel (`feature_parallel_tree_learner.cpp:29-73`): each
    machine owns a feature shard and all the data; only the tiny best-split
    message crosses the wire.
  * voting-parallel (`voting_parallel_tree_learner.cpp:166-345`): data
    parallel with top-k feature voting to cut communication.

Here none of those collectives are written by hand.  The binned matrix and
row-aligned vectors carry `jax.sharding.NamedSharding` annotations and the
SAME jitted tree-build step compiles under GSPMD: the one-hot histogram
contraction over a row-sharded axis lowers to partial sums plus an
all-reduce over ICI (the exact rewiring SURVEY §2.6 calls for at the
``Network::Init`` external-function seam, `network.h:96`), the per-feature
argmax over a feature-sharded axis lowers to an all-gather of per-shard
bests.  ``Network`` as a class does not exist — the mesh is the network.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import (AXIS_DATA, AXIS_FEATURE, default_mesh_shape_2d,
                       feature_axis, make_mesh, row_axis, rules_for_mode)


def _route_log(cfg, msg: str) -> None:
    """Learner-selection telemetry, mirroring the serial factory's
    (`learner_compact.py` create_tree_learner): a user with 8 chips and an
    off-by-one row count must be TOLD they got the slow masked path."""
    if int(getattr(cfg, "verbosity", 1)) >= 1:
        print(f"[lightgbm_tpu] {msg}")


def _fast_gate_reason(data, mesh_size: int) -> Optional[str]:
    """Why the sharded compact/wave path cannot run (None = eligible)."""
    if data.max_num_bin > 256:
        return f"max_num_bin={data.max_num_bin} > 256"
    if data.num_data_padded % mesh_size:
        return (f"padded row count {data.num_data_padded} not divisible by "
                f"mesh size {mesh_size}")
    if data.bins.shape[0] % mesh_size:
        return (f"padded feature count {data.bins.shape[0]} not divisible "
                f"by mesh size {mesh_size}")
    return None


def apply_parallel_sharding(gbdt, mesh: Mesh, mode: str) -> None:
    """Re-place a GBDT's device arrays for a parallel mode.  Subsequent jitted
    steps compile under GSPMD with collectives over the mesh."""
    from ..learner import TPUTreeLearner

    # pipelined iterations queued before the swap hold compact-format records
    # — materialize them with the learner that produced them
    if hasattr(gbdt, "_flush_pending"):
        gbdt._flush_pending()
    learner = gbdt.learner
    # forced splits ride the sharded COMPACT learners (the wave learners
    # don't carry the forced phase, mirroring the serial factory's routing)
    forced = getattr(learner, "_forced", None)
    mesh_size = max(int(np.prod(mesh.devices.shape)), 1)
    if mode == "data_feature":
        from .wave2d_sharded import (ShardedWave2DLearner,
                                     wave2d_ineligible_reason)
        if len(mesh.axis_names) < 2:
            # a flat mesh was passed: factor it into (data, feature)
            mesh = make_mesh(shape=default_mesh_shape_2d(mesh_size),
                             devices=list(mesh.devices.reshape(-1)),
                             axis_names=(AXIS_DATA, AXIS_FEATURE))
        reason = ("forced splits ride the sequential sharded learner"
                  if forced else
                  wave2d_ineligible_reason(learner.cfg, learner.data, mesh))
        if reason is None:
            shp = dict(zip(mesh.axis_names, mesh.devices.shape))
            _route_log(learner.cfg,
                       f"tree_learner=data_feature: using "
                       f"ShardedWave2DLearner over a "
                       f"{shp[AXIS_DATA]}x{shp[AXIS_FEATURE]} "
                       f"(data x feature) mesh")
            gbdt.learner = ShardedWave2DLearner(learner.cfg, learner.data,
                                                mesh)
            _place_row_arrays(gbdt, mesh, mode)
            gbdt._mesh = mesh
            gbdt._parallel_mode = mode
            return
        _route_log(learner.cfg,
                   f"tree_learner=data_feature: 2D hybrid ineligible "
                   f"({reason}); falling back to tree_learner=data over a "
                   f"flat {mesh_size}-device mesh")
        apply_parallel_sharding(
            gbdt, make_mesh(devices=list(mesh.devices.reshape(-1))), "data")
        return
    fast_reason = _fast_gate_reason(learner.data, mesh_size) \
        if mode in ("data", "voting") else None
    if mode in ("data", "voting") and fast_reason is None:
        # the real distributed path: per-shard compact learner with
        # reduce-scattered histograms; voting adds PV-Tree feature election
        # (`compact_sharded.py`)
        from .compact_sharded import (ShardedCompactLearner,
                                      ShardedVotingLearner)
        from .wave_sharded import wave_sharded_eligible
        wave_ok = not forced and wave_sharded_eligible(
            learner.cfg, learner.data, mesh_size)
        if mode == "voting":
            from .wave_sharded import ShardedVotingWaveLearner
            cls = ShardedVotingWaveLearner if wave_ok \
                else ShardedVotingLearner
        else:
            # data-parallel rides the frontier-wave learner where eligible
            # (the reference templates its parallel learners over its
            # fastest serial learner, `data_parallel_tree_learner.cpp:257`)
            from .wave_sharded import ShardedWaveLearner
            cls = ShardedWaveLearner if wave_ok else ShardedCompactLearner
        if not wave_ok:
            why = "forced splits ride the sequential sharded learner" \
                if forced else "shape/byte gates, see wave_sharded_eligible"
            _route_log(learner.cfg,
                       f"tree_learner={mode}: wave-sharded learner "
                       f"ineligible ({why}); using the sequential "
                       f"{cls.__name__}")
        else:
            _route_log(learner.cfg,
                       f"tree_learner={mode}: using {cls.__name__} over "
                       f"{mesh_size} devices")
        gbdt.learner = cls(learner.cfg, learner.data, mesh)
        if forced:
            gbdt.learner.set_forced_splits(forced)
        _place_row_arrays(gbdt, mesh, mode)
        gbdt._mesh = mesh
        gbdt._parallel_mode = mode
        return
    if mode == "feature" and learner.data.max_num_bin <= 256:
        from ..learner_wave import wave_budget_reason
        from .feature_sharded import (FeatureShardedCompactLearner,
                                      FeatureShardedWaveLearner,
                                      feature_sharded_eligible)
        if feature_sharded_eligible(learner.cfg, learner.data, mesh_size):
            # rows are REPLICATED in feature-parallel, so the wave variant
            # must pass the serial wave gates at the FULL row count and
            # width (wide datasets use the feature-sharded compact learner
            # — its scans are feature-sliced either way)
            wave_ok = (not forced
                       and learner.cfg.tpu_learner in ("auto", "wave")
                       and wave_budget_reason(
                           learner.cfg, int(learner.data.num_data_padded),
                           learner.data.bins.shape[0],
                           int(learner.data.max_num_bin)) is None)
            cls = FeatureShardedWaveLearner if wave_ok \
                else FeatureShardedCompactLearner
            _route_log(learner.cfg,
                       f"tree_learner=feature: using {cls.__name__} over "
                       f"{mesh_size} devices")
            gbdt.learner = cls(learner.cfg, learner.data, mesh)
            if forced:
                gbdt.learner.set_forced_splits(forced)
            gbdt._mesh = mesh
            gbdt._parallel_mode = mode
            return
    # every fast path refused — name the failed gate before draping GSPMD
    # over the masked learner (round-2-era performance)
    if mode in ("data", "voting"):
        _route_log(learner.cfg,
                   f"tree_learner={mode}: sharded compact/wave path "
                   f"ineligible ({fast_reason}); falling back to the "
                   f"masked GSPMD learner")
    elif mode == "feature":
        why = (f"max_num_bin={learner.data.max_num_bin} > 256"
               if learner.data.max_num_bin > 256
               else "feature_sharded_eligible gates failed")
        _route_log(learner.cfg,
                   f"tree_learner=feature: feature-sharded path ineligible "
                   f"({why}); falling back to the masked GSPMD learner")
    if type(learner) is not TPUTreeLearner:
        # feature-parallel / >256-bin fallbacks drape GSPMD over the masked
        # learner — the compact learner's packed-bin cache and global-axis
        # sort would silently ignore the sharding mutations below
        learner = TPUTreeLearner(learner.cfg, learner.data,
                                 learner.hist_backend)
        if forced:
            learner.set_forced_splits(forced)
        gbdt.learner = learner
    ax_r, ax_f = row_axis(mesh), feature_axis(mesh)
    if mode in ("data", "voting"):
        bins_spec = P(None, ax_r)      # (F, N): shard rows
        row_spec = P(ax_r)
    elif mode == "feature":
        bins_spec = P(ax_f, None)      # shard features, replicate rows
        row_spec = P()
    else:
        raise ValueError(f"unknown parallel mode: {mode}")

    put = lambda arr, spec: jax.device_put(arr, NamedSharding(mesh, spec))
    # the Pallas kernel has no GSPMD partitioning rule; under a sharded mesh
    # the XLA one-hot path is used instead — it auto-partitions and lowers
    # the row reduction to an all-reduce over ICI.  (A shard_map-wrapped
    # pallas-per-shard + psum path is the planned upgrade.)
    learner.hist_backend = "onehot"
    learner.bins = put(learner.bins, bins_spec)
    learner.data._device_bins = learner.bins
    # per-feature metadata is replicated
    learner.f_num_bin = put(learner.f_num_bin, P())
    learner.f_missing = put(learner.f_missing, P())
    learner.f_default_bin = put(learner.f_default_bin, P())
    # row-aligned vectors
    gbdt._valid_rows = put(gbdt._valid_rows, row_spec)
    gbdt._bag_mask = put(gbdt._bag_mask, row_spec)
    score_spec = P(None, ax_r) if mode in ("data", "voting") else P()
    gbdt.train_score.score = put(gbdt.train_score.score, score_spec)
    # objective label arrays follow the rows
    obj = gbdt.objective
    if obj is not None:
        for name in ("label", "weights", "trans_label", "label_sign",
                     "label_w", "label_weight", "label_onehot"):
            arr = getattr(obj, name, None)
            if arr is not None and hasattr(arr, "shape") and arr.ndim >= 1:
                spec = row_spec if arr.ndim == 1 else P(None, ax_r) \
                    if mode in ("data", "voting") else P()
                try:
                    setattr(obj, name, put(arr, spec))
                except Exception as e:
                    import warnings
                    warnings.warn(f"could not shard objective array "
                                  f"{name!r} over the mesh: {e}")
    gbdt._mesh = mesh
    gbdt._parallel_mode = mode


def _place_row_arrays(gbdt, mesh: Mesh, mode: str) -> None:
    """Shard the boosting loop's row-aligned arrays (score, bagging mask,
    objective label arrays) over the mesh — rule-driven
    (`parallel/sharding.py`), so the same call covers 1-D and 2-D modes."""
    rules = rules_for_mode(mode, mesh)
    gbdt._valid_rows = rules.place("valid_rows", gbdt._valid_rows)
    gbdt._bag_mask = rules.place("bag_mask", gbdt._bag_mask)
    gbdt.train_score.score = rules.place("score", gbdt.train_score.score)
    obj = gbdt.objective
    if obj is not None:
        for name in ("label", "weights", "trans_label", "label_sign",
                     "label_w", "label_weight", "label_onehot"):
            arr = getattr(obj, name, None)
            if arr is not None and hasattr(arr, "shape") and arr.ndim >= 1:
                try:
                    setattr(obj, name, rules.place(name, arr))
                except Exception as e:
                    import warnings
                    warnings.warn(f"could not shard objective array "
                                  f"{name!r} over the mesh: {e}")


def make_data_parallel(gbdt, num_devices: Optional[int] = None) -> Mesh:
    """`tree_learner=data` over the local mesh."""
    mesh = make_mesh(num_devices)
    apply_parallel_sharding(gbdt, mesh, "data")
    return mesh


def make_feature_parallel(gbdt, num_devices: Optional[int] = None) -> Mesh:
    """`tree_learner=feature` over the local mesh."""
    mesh = make_mesh(num_devices)
    apply_parallel_sharding(gbdt, mesh, "feature")
    return mesh


def make_hybrid_parallel(gbdt, shape=None) -> Mesh:
    """`tree_learner=data_feature` over a 2-D (data, feature) mesh;
    ``shape=(2, 4)``-style, auto-factored over the local devices when
    omitted."""
    if shape is None:
        shape = default_mesh_shape_2d(len(jax.devices()))
    mesh = make_mesh(shape=shape, axis_names=(AXIS_DATA, AXIS_FEATURE))
    apply_parallel_sharding(gbdt, mesh, "data_feature")
    return mesh
