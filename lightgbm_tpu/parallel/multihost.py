"""Multi-host pod training over ``jax.distributed``.

Lifts the PR 9 mesh/sharding layer (`parallel/sharding.py`) from one host
to a pod: ``jax.distributed.initialize`` wiring from config keys +
environment, global device discovery, a host-alignment check for the
cross-host mesh, and a :class:`DistributedNet` that backs the
`io/distributed.py` allgather/sync_min/sync_max seam with the
jax.distributed coordinator's key-value store (SocketNet stays as the
loader-side fallback seam it was built for — `ROADMAP.md` item 2).

The crucial property, proven by `tests/test_multihost.py` on a CPU
emulation (N processes x ``--xla_force_host_platform_device_count`` local
devices against a local coordinator): because every sharded learner
already expresses its collectives through the mesh, the SAME jitted
programs run unchanged on a global mesh spanning processes — a 2-process x
4-device run trains byte-identical models to a 1-process x 8-device run
(with ``tpu_hist_dtype=float64`` accounting; f32 differs only in
summation-order ulps).  On CPU the cross-process collectives need jax's
gloo backend, enabled here before ``initialize``.

Config / environment contract (config keys win; env fills the gaps so one
launch recipe works for every rank)::

    coordinator_address = host:port     # or LGBT_COORDINATOR
    num_hosts           = N             # or LGBT_NUM_HOSTS
    process_id          = r             # or LGBT_PROCESS_ID

Launch recipe (same command on every host, only the rank differs)::

    LGBT_COORDINATOR=10.0.0.1:12421 LGBT_NUM_HOSTS=2 LGBT_PROCESS_ID=$R \\
        python -m lightgbm_tpu.cli task=train data=... tree_learner=data
"""

from __future__ import annotations

import os
import pickle
import time
from typing import List, Optional, Tuple

import numpy as np

ENV_COORDINATOR = "LGBT_COORDINATOR"
ENV_NUM_HOSTS = "LGBT_NUM_HOSTS"
ENV_PROCESS_ID = "LGBT_PROCESS_ID"

_initialized = False
_ns_counts: dict = {}


class RankDeathError(ConnectionError):
    """A collective's deadline scan NAMED dead rank(s).

    Subclasses ``ConnectionError`` so every existing caller (and the PR 4
    rank-crash drills asserting on ConnectionError text) keeps working;
    the elastic controller (`lightgbm_tpu/elastic/`) catches THIS type to
    distinguish "a peer died, shrink and continue" from "the coordinator
    itself is unreachable" (plain ConnectionError — not recoverable by
    re-forming membership, the control plane is gone).

    ``dead_ranks`` are rank ids within the CURRENT membership epoch;
    ``epoch`` is that membership epoch's generation counter (0 for
    non-elastic pods), so a verdict from epoch k can never be misread as
    naming ranks of epoch k+1's (re-numbered) membership."""

    def __init__(self, message: str, dead_ranks=(), epoch: int = 0):
        super().__init__(message)
        self.dead_ranks = list(dead_ranks)
        self.epoch = int(epoch)


def resolve_multihost(cfg=None) -> Optional[Tuple[str, int, int]]:
    """(coordinator_address, num_processes, process_id) this run asks for,
    or None for a single-host run.  Config keys win over the LGBT_*
    environment; a partial spec (hosts without coordinator, rank out of
    range) is an error, not a silent single-host fallback."""
    coord = str(getattr(cfg, "coordinator_address", "") or
                os.environ.get(ENV_COORDINATOR, "")).strip()
    nproc = int(getattr(cfg, "num_hosts", 1) or 1)
    if nproc <= 1:
        nproc = int(os.environ.get(ENV_NUM_HOSTS, "1") or 1)
    pid = int(getattr(cfg, "process_id", -1) if cfg is not None else -1)
    if pid < 0:
        pid = int(os.environ.get(ENV_PROCESS_ID, "-1") or -1)
    if nproc <= 1 and not coord:
        return None
    if nproc <= 1 or not coord or pid < 0:
        raise ValueError(
            "multi-host run under-specified: need coordinator_address "
            f"({coord!r}), num_hosts ({nproc}), process_id ({pid}) — set "
            "the config keys or LGBT_COORDINATOR/LGBT_NUM_HOSTS/"
            "LGBT_PROCESS_ID")
    if pid >= nproc:
        raise ValueError(f"process_id {pid} out of range for num_hosts "
                         f"{nproc}")
    return coord, nproc, pid


def is_initialized() -> bool:
    return _initialized


def initialize_from_config(cfg=None) -> bool:
    """Idempotent ``jax.distributed.initialize`` from config + env; returns
    True when this process is part of a multi-host pod.  Must run before
    the first device use (jax backends are configured at first touch); on
    the CPU backend the gloo cross-process collectives are enabled first —
    without them multi-process programs fail with "Multiprocess
    computations aren't implemented on the CPU backend"."""
    global _initialized
    spec = resolve_multihost(cfg)
    if spec is None:
        return False
    if _initialized:
        return True
    coord, nproc, pid = spec
    import jax
    if str(os.environ.get("JAX_PLATFORMS", "")).startswith("cpu") or \
            str(jax.config.jax_platforms or "").startswith("cpu"):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nproc, process_id=pid)
    _initialized = True
    return True


def _kv_client():
    from jax._src.distributed import global_state
    client = getattr(global_state, "client", None)
    if client is None:
        raise RuntimeError(
            "jax.distributed is not initialized — call "
            "multihost.initialize_from_config(cfg) (or set "
            "coordinator_address/num_hosts/process_id) first")
    return client


def host_layout() -> Tuple[int, int, int]:
    """(process_count, process_index, local_device_count) — the host
    layout string recorded in bench/MULTICHIP metric lines."""
    import jax
    return jax.process_count(), jax.process_index(), jax.local_device_count()


def mesh_for_config(cfg, devices=None):
    """The `parallel/sharding.py` mesh grammar laid across hosts: the
    ``parallel_mesh`` spec (e.g. ``"2x8"`` on 2 hosts x 8 local devices)
    is resolved over the GLOBAL device list, and the resulting mesh is
    checked for host alignment — each process's local devices must occupy
    contiguous blocks of the row (data) axis, so every host's row shard of
    a ``P(..., "data")``-sharded array is host-local.  jax orders
    ``jax.devices()`` process-major, so any factorization whose trailing
    axes divide the local device count is aligned."""
    from .sharding import mesh_for_config as _local_mesh_for_config
    from .sharding import row_axis
    import jax

    mesh = _local_mesh_for_config(cfg, devices=devices)
    if jax.process_count() <= 1:
        return mesh
    ax = row_axis(mesh)
    arr = mesh.devices
    # collapse every non-row axis; each row-coordinate slice should sit on
    # as few processes as possible, and process blocks must be contiguous
    # along the row axis (row shard r on host r // (rows_per_host))
    order = [mesh.axis_names.index(ax)] + [
        i for i in range(arr.ndim) if i != mesh.axis_names.index(ax)]
    by_row = np.transpose(arr, order).reshape(arr.shape[order[0]], -1)
    first_proc = [min(d.process_index for d in row) for row in by_row]
    if any(first_proc[i] > first_proc[i + 1]
           for i in range(len(first_proc) - 1)):
        import warnings
        warnings.warn(
            f"mesh {dict(zip(mesh.axis_names, arr.shape))} scatters row "
            f"shards across hosts non-contiguously (row->host "
            f"{first_proc}); cross-host transfers will dominate — prefer a "
            f"parallel_mesh whose data axis is host-major, e.g. "
            f"\"{jax.process_count()}x{jax.local_device_count()}\"")
    return mesh


class DistributedNet:
    """`io/distributed.py` net seam (allgather / sync_min / sync_max) over
    the jax.distributed coordinator's key-value store.

    Payloads are pickled to seq-numbered per-rank keys and read back with a
    deadline; a rank that never posts (crashed, partitioned) surfaces as a
    ``ConnectionError`` NAMING the missing rank(s) on every survivor within
    the deadline — the `reliability/faults.py` ``net.crash`` chaos point is
    compiled into the collective entry exactly as in SocketNet, so the PR 4
    rank-crash drills drive this path too (`tests/test_multihost.py`).

    This is the loader/heartbeat side-channel only: the histogram and
    split-vote traffic of the sharded learners rides the mesh collectives
    of their jitted programs, never this store.
    """

    def __init__(self, cfg=None, rank: Optional[int] = None,
                 num_machines: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 namespace: str = "lgbt"):
        import jax
        self.rank = int(jax.process_index() if rank is None else rank)
        self.num_machines = int(jax.process_count()
                                if num_machines is None else num_machines)
        if deadline_s is None:
            deadline_s = float(getattr(cfg, "net_collective_deadline_s", 0.0)
                               or 0.0)
            if deadline_s <= 0.0:
                deadline_s = float(getattr(cfg, "time_out", 120) or 120)
        self.deadline_s = float(deadline_s)
        # membership generation this net belongs to (elastic runs bump it
        # per shrink; 0 = the original membership).  Stamped into every
        # dead-rank verdict so recovery code can reject stale verdicts.
        self.epoch = int(getattr(cfg, "elastic_epoch", 0) or 0)
        # distinct key prefix per net instance: the lagged GC leaves each
        # net's FINAL round keys behind, and a later net restarting _seq at
        # 1 would collide with them (ALREADY_EXISTS from the coordinator).
        # Safe because every rank constructs nets in the same order — one
        # per Booster — so the counter agrees pod-wide.
        n = _ns_counts.get(namespace, 0)
        _ns_counts[namespace] = n + 1
        self._ns = f"{namespace}.{n}" if n else namespace
        self._seq = 0
        self._client = _kv_client()

    # -- the three seam calls (`io/distributed.py` LoopbackCluster parity) --

    def allgather(self, obj) -> List:
        from ..reliability import faults

        self._seq += 1
        seq = self._seq
        prefix = f"{self._ns}/ag{seq}/"
        if faults.fire("net.crash", rank=self.rank) is not None:
            # hard exit mid-collective — the PR 4 rank-death drill.  The
            # survivors' deadline scan below must name THIS rank.
            os._exit(17)
        self._client.key_value_set_bytes(prefix + f"r{self.rank}",
                                         pickle.dumps(obj))
        deadline_ms = max(int(self.deadline_s * 1000), 1)
        out: List = [None] * self.num_machines
        for r in range(self.num_machines):
            key = prefix + f"r{r}"
            try:
                out[r] = pickle.loads(
                    self._client.blocking_key_value_get_bytes(
                        key, deadline_ms))
            except Exception as e:
                from ..reliability.metrics import rel_inc
                missing, report = self._missing_report(prefix)
                rel_inc("net.multihost_collective_timeouts")
                rel_inc("net.multihost_peers_dead", max(len(missing), 1))
                msg = (f"multihost collective #{seq} timed out after "
                       f"{self.deadline_s:.1f}s on rank {self.rank} "
                       f"(membership epoch {self.epoch}): {report} "
                       f"(coordinator error: {e})")
                if missing:
                    # a NAMED dead peer is the recoverable verdict: the
                    # elastic controller re-forms membership over the
                    # survivors.  No named rank (all posted late / scan
                    # failed) means the coordinator itself is suspect —
                    # stay a plain ConnectionError.
                    raise RankDeathError(msg, dead_ranks=missing,
                                         epoch=self.epoch) from None
                raise ConnectionError(msg) from None
        # best-effort GC, lagged ONE round: rank r posting for round N proves
        # its round N-1 allgather returned, i.e. it read every N-1 key — so
        # only once ALL ranks posted round N are round N-1's keys dead.
        # Deleting round N here instead races peers still reading it.
        if self.rank == 0 and seq > 1:
            try:
                self._client.key_value_delete(f"{self._ns}/ag{seq - 1}/")
            except Exception:
                pass
        return out

    def sync_min(self, v: int) -> int:
        return min(self.allgather(int(v)))

    def sync_max(self, v: int) -> int:
        return max(self.allgather(int(v)))

    # -- liveness ----------------------------------------------------------

    def heartbeat(self, tag: int = 0, payload=None) -> List:
        """One tiny allgather: every live rank agrees everyone is still
        here, and a dead rank is NAMED within the collective deadline.  The
        boosting loop runs this before each iteration's jitted step
        (`engine.py`), so a host crash surfaces as a root-caused
        ConnectionError instead of a hang inside an XLA collective.

        ``payload`` piggybacks per-rank observability data on the SAME
        allgather (the engine passes its last step duration — straggler
        detection costs zero extra collectives); the gathered
        ``("hb", rank, tag, payload)`` tuples are returned so the caller
        can compare ranks."""
        return self.allgather(("hb", int(self.rank), int(tag), payload))

    def _missing_report(self, prefix: str):
        """(missing_ranks, message): which ranks never posted their payload
        for ``prefix`` — the named root cause on every survivor."""
        try:
            posted = set()
            for key in self._client.key_value_dir_get_bytes(prefix) or []:
                name = key[0] if isinstance(key, tuple) else key
                name = str(name).rsplit("/", 1)[-1]
                if name.startswith("r"):
                    posted.add(int(name[1:]))
            missing = sorted(set(range(self.num_machines)) - posted)
            if missing:
                return missing, (
                    "rank(s) " + ", ".join(map(str, missing)) +
                    " never posted — process(es) dead or partitioned")
            return [], "all ranks posted late (coordinator stall?)"
        except Exception as e:  # pragma: no cover — coordinator itself gone
            return [], f"missing-rank scan failed: {e}"

    def barrier(self, name: str) -> None:
        self._client.wait_at_barrier(
            f"{self._ns}/{name}", max(int(self.deadline_s * 1000), 1))

    def close(self) -> None:  # seam parity with SocketNet
        pass


def net_for_run(cfg) -> Optional[DistributedNet]:
    """The loader/heartbeat net for this run: a :class:`DistributedNet`
    when the pod is initialized, else None (SocketNet via
    `io/net.py:net_from_config` remains the socket-only fallback)."""
    if not _initialized:
        return None
    return DistributedNet(cfg)
