"""Elastic pod training — shrink-and-continue without operator action.

Composes three pieces that already exist into a supervised recovery state
machine (`ROADMAP.md` item 2c):

  * the per-iteration heartbeat that NAMES a dead rank within the
    collective deadline (`parallel/multihost.py`, PR 13) — now a typed
    :class:`~lightgbm_tpu.parallel.multihost.RankDeathError`;
  * crash-safe snapshots + bit-exact resume (`reliability/resume.py`,
    PR 4) — now with the world-shape keys split out of the config
    fingerprint so a post-shrink resume is accepted, not rejected;
  * the PlacementRules mesh layer (`parallel/sharding.py`, PR 9), which
    lays the SAME jitted programs over whatever device set the surviving
    membership exposes.

Architecture (the TorchElastic shape, forced by a measured constraint):
jax.distributed cannot shrink in place — after a rank dies, the
coordination service propagates fatal errors to the survivors and any
``device_put`` against a multi-process sharding issues a gloo collective
over the ORIGINAL world, which fails against the dead peer.  So each
**membership epoch** is a fresh jax.distributed cluster in a fresh worker
subprocess, supervised by a per-host **controller** (`controller.py`)
that never touches devices:

  1. epoch k's workers train; a death surfaces as ``RankDeathError``;
  2. the survivors negotiate epoch k+1's membership over the STILL-LIVE
     epoch-k KV store (`epoch.py` — the coordination service keeps
     serving until its host process exits), write a verdict file and
     exit with ``EXIT_RESHAPE``;
  3. each controller reads its worker's verdict, enforces the
     ``elastic_max_recoveries`` / ``elastic_min_ranks`` budget, and
     relaunches a worker for epoch k+1: new coordinator (port =
     base + epoch, hosted by the new rank 0), rows re-dealt over the
     survivors via the ``from_stream`` loader (`redeal.py`), training
     resumed from the last crash-safe snapshot to the ORIGINAL round
     target.

A zombie worker from epoch k cannot poison epoch k+1: the new epoch is a
physically separate cluster (different coordinator port), and every
verdict/KV key is generation-stamped.
"""

from .controller import (EXIT_RESHAPE, ElasticHostDead, ElasticResult,
                         ElasticTerminalError, run_host)
from .epoch import MembershipEpoch, negotiate_next_epoch

__all__ = ["run_host", "ElasticResult", "ElasticTerminalError",
           "ElasticHostDead", "EXIT_RESHAPE", "MembershipEpoch",
           "negotiate_next_epoch"]
