"""Per-host elastic agent: supervise one worker subprocess per epoch.

The controller is the only long-lived process on a host and it NEVER
touches jax devices or jax.distributed — that is what lets it outlive a
cluster whose coordination service has gone fatal.  It runs the epoch
state machine described in the package docstring: launch a worker for the
current membership, interpret its exit, enforce the recovery budget, and
relaunch for the next epoch until the worker trains to the original round
target.

Structured failures carry the full epoch history (every membership the
run agreed on, in order) so a post-mortem reads the whole shrink
trajectory from the exception alone.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .epoch import MembershipEpoch, coordinator_for_epoch

#: worker exit codes (os._exit — see worker.py)
EXIT_RESHAPE = 43
EXIT_DECLARED_DEAD = 44
EXIT_CONTROL_LOST = 45


class ElasticTerminalError(RuntimeError):
    """Recovery is over: below ``elastic_min_ranks``, past
    ``elastic_max_recoveries``, or the control plane is gone.  ``history``
    is the ordered list of membership-epoch dicts this run lived
    through."""

    def __init__(self, message: str, history: List[Dict[str, Any]]):
        super().__init__(message)
        self.history = list(history)


class ElasticHostDead(RuntimeError):
    """THIS host's worker died (or was declared dead by the survivors) —
    the local controller has nothing left to supervise."""

    def __init__(self, message: str, rc: Optional[int] = None):
        super().__init__(message)
        self.rc = rc


@dataclass
class ElasticResult:
    """A finished elastic run on this host."""

    model_path: str
    history: List[Dict[str, Any]]
    recoveries: int
    ranks_lost: int
    recovery_wall_s: float
    result: Dict[str, Any] = field(default_factory=dict)
    report: Optional[Dict[str, Any]] = None


def write_json(path: str, obj: Any) -> None:
    tmp = f"{path}.tmp"
    with open(tmp, "w") as fh:
        json.dump(obj, fh)
    os.replace(tmp, path)


def _parse_base(params: Dict[str, Any], host_id: int) -> "tuple":
    """(coordinator_host, port_base) from the params: ``elastic_port_base``
    wins, else the port in ``coordinator_address``."""
    addr = str(params.get("coordinator_address", "") or "127.0.0.1:12421")
    host, _, port = addr.rpartition(":")
    base = int(params.get("elastic_port_base", 0) or 0)
    if base <= 0:
        base = int(port)
    return (host or "127.0.0.1"), base


def run_host(params: Dict[str, Any], data: str, num_boost_round: int,
             host_id: int, num_hosts: int, workdir: str,
             worker_env: Optional[Dict[str, str]] = None,
             enable_x64: bool = False, cache_dir: Optional[str] = None,
             negotiate_deadline_s: float = 20.0,
             worker_timeout_s: float = 600.0) -> ElasticResult:
    """Supervise this host through every membership epoch until training
    reaches ``num_boost_round`` (the ORIGINAL target — epochs resume, they
    do not extend).  ``data`` must be a file path (the ``from_stream``
    loader is what makes re-dealing possible).  Raises
    :class:`ElasticTerminalError` / :class:`ElasticHostDead` with the
    epoch history on unrecoverable failure."""
    from ..observability.trace import TraceRecorder
    from ..reliability.metrics import rel_inc

    params = dict(params)
    host_id = int(host_id)
    max_recoveries = int(params.get("elastic_max_recoveries", 3))
    min_ranks = int(params.get("elastic_min_ranks", 1))
    coord_host, port_base = _parse_base(params, host_id)
    params["elastic_port_base"] = port_base

    hostdir = os.path.join(workdir, f"h{host_id}")
    os.makedirs(hostdir, exist_ok=True)
    output_model = os.path.join(hostdir, "model.txt")

    epoch = MembershipEpoch(
        epoch=0, members=list(range(int(num_hosts))),
        coordinator=coordinator_for_epoch(coord_host, port_base, 0))
    history: List[Dict[str, Any]] = [epoch.to_dict()]
    recoveries = 0
    ranks_lost = 0
    recovery_wall_s = 0.0
    tracer = TraceRecorder(True, capacity=4096)
    tracer.set_metadata(elastic_host=host_id)

    while True:
        edir = os.path.join(hostdir, f"e{epoch.epoch}")
        os.makedirs(edir, exist_ok=True)
        spec = {
            "params": params, "data": data,
            "num_boost_round": int(num_boost_round),
            "membership": epoch.to_dict(), "host_id": host_id,
            "output_model": output_model,
            "verdict_path": os.path.join(edir, "verdict.json"),
            "result_path": os.path.join(edir, "result.json"),
            "negotiate_deadline_s": float(negotiate_deadline_s),
            "enable_x64": bool(enable_x64), "cache_dir": cache_dir,
        }
        spec_path = os.path.join(edir, "spec.json")
        write_json(spec_path, spec)
        env = dict(os.environ)
        env.update(worker_env or {})
        log_path = os.path.join(edir, "worker.log")
        with tracer.span("elastic.epoch", cat="elastic",
                         args={"epoch": epoch.epoch,
                               "members": list(epoch.members)}):
            with open(log_path, "w") as log:
                proc = subprocess.Popen(
                    [sys.executable, "-m",
                     "lightgbm_tpu.elastic.worker", spec_path],
                    env=env, stdout=log, stderr=subprocess.STDOUT)
                try:
                    rc = proc.wait(timeout=float(worker_timeout_s))
                except subprocess.TimeoutExpired:
                    rc = None
                finally:
                    # reap-on-epoch-teardown: a timed-out (or any
                    # still-running) worker is killed AND waited here, so
                    # no epoch leaves a zombie behind for the next one
                    if proc.poll() is None:
                        proc.kill()
                        proc.wait()

        def _tail(n: int = 2000) -> str:
            try:
                with open(log_path) as fh:
                    return fh.read()[-n:]
            except OSError:
                return ""

        if rc == 0:
            with open(spec["result_path"]) as fh:
                result = json.load(fh)
            res = ElasticResult(
                model_path=output_model, history=history,
                recoveries=recoveries, ranks_lost=ranks_lost,
                recovery_wall_s=recovery_wall_s, result=result,
                report=result.get("report"))
            _finalize_observability(params, host_id, res, tracer)
            return res

        # the verdict file outranks the exit code: the worker makes its
        # verdict durable BEFORE releasing the epoch's anchor, and the
        # anchor's exit aborts (SIGABRT) any peer still winding down —
        # so a dirty rc with a readable verdict is a normal transition
        try:
            with open(spec["verdict_path"]) as fh:
                verdict = json.load(fh)
        except (OSError, ValueError) as e:
            verdict = None
            if rc == EXIT_RESHAPE:
                raise ElasticHostDead(
                    f"host {host_id}: epoch {epoch.epoch} worker exited "
                    f"EXIT_RESHAPE but left no readable verdict ({e}); "
                    f"log tail: {_tail()}", rc=rc)

        if verdict is not None and verdict.get("kind") == "reshape":
            t0 = time.monotonic()
            nxt = MembershipEpoch.from_dict(verdict["next"])
            nxt.coordinator = coordinator_for_epoch(coord_host, port_base,
                                                    nxt.epoch)
            lost = len(epoch.members) - len(nxt.members)
            recoveries += 1
            ranks_lost += lost
            rel_inc("elastic.recoveries")
            rel_inc("elastic.ranks_lost", max(lost, 0))
            history.append(nxt.to_dict())
            negotiate_s = float(verdict.get("negotiate_s", 0.0))
            recovery_wall_s += negotiate_s + (time.monotonic() - t0)
            tracer.add_complete(
                "elastic.recovery", time.perf_counter() - negotiate_s,
                negotiate_s + (time.monotonic() - t0), cat="elastic",
                args={"failed_epoch": epoch.epoch,
                      "dead_hosts": nxt.dead_hosts,
                      "next_members": list(nxt.members)})
            if len(nxt.members) < min_ranks:
                raise ElasticTerminalError(
                    f"host {host_id}: epoch {nxt.epoch} has "
                    f"{len(nxt.members)} rank(s), below elastic_min_ranks="
                    f"{min_ranks} — terminal. Epoch history: "
                    f"{json.dumps(history)}", history)
            if recoveries > max_recoveries:
                raise ElasticTerminalError(
                    f"host {host_id}: recovery #{recoveries} exceeds "
                    f"elastic_max_recoveries={max_recoveries} — terminal. "
                    f"Epoch history: {json.dumps(history)}", history)
            if host_id not in nxt.members:
                raise ElasticHostDead(
                    f"host {host_id} is not in epoch {nxt.epoch}'s "
                    f"membership {nxt.members} — declared dead", rc=rc)
            epoch = nxt
            continue

        if rc not in (EXIT_DECLARED_DEAD, EXIT_CONTROL_LOST, None):
            # dirty exit AFTER finishing: the coordination service lives in
            # rank 0's worker, and native teardown while peers disconnect
            # can kill the process after every byte of work is on disk.
            # The contract is "the controller reads results, not exits" —
            # a complete ok-result makes the epoch a success.
            try:
                with open(spec["result_path"]) as fh:
                    result = json.load(fh)
            except (OSError, ValueError):
                result = None
            if result and result.get("ok"):
                rel_inc("elastic.dirty_exits")
                res = ElasticResult(
                    model_path=output_model, history=history,
                    recoveries=recoveries, ranks_lost=ranks_lost,
                    recovery_wall_s=recovery_wall_s, result=result,
                    report=result.get("report"))
                _finalize_observability(params, host_id, res, tracer)
                return res

        if rc == EXIT_DECLARED_DEAD:
            raise ElasticHostDead(
                f"host {host_id} was declared dead during the epoch "
                f"{epoch.epoch} -> {epoch.epoch + 1} negotiation (stalled "
                f"past the ack deadline). Epoch history: "
                f"{json.dumps(history)}", rc=rc)
        if rc == EXIT_CONTROL_LOST or (
                verdict is not None
                and verdict.get("kind") == "control_plane_lost"):
            raise ElasticTerminalError(
                f"host {host_id}: control plane lost during epoch "
                f"{epoch.epoch} recovery (anchor or coordination service "
                f"dead). Epoch history: {json.dumps(history)}", history)
        raise ElasticHostDead(
            f"host {host_id}: epoch {epoch.epoch} worker "
            f"{'timed out' if rc is None else f'died (rc={rc})'}; "
            f"log tail: {_tail()}", rc=rc)


def _finalize_observability(params: Dict[str, Any], host_id: int,
                            res: ElasticResult, tracer) -> None:
    """Inject the ``elastic`` section into the worker's telemetry report
    and export the controller's recovery spans — both opt-in via the same
    config keys the engine honors (``telemetry_out`` / ``trace_out``)."""
    final = res.history[-1]
    section = {
        "epochs": len(res.history),
        "epoch": int(final["epoch"]),
        "members": list(final["members"]),
        "recoveries": int(res.recoveries),
        "ranks_lost": int(res.ranks_lost),
        "recovery_wall_s": float(res.recovery_wall_s),
    }
    if res.report is not None:
        counters = (res.report.get("reliability", {}) or {}) \
            .get("counters", {})
        section["redeal_rows"] = int(
            counters.get("elastic.redeal_rows", 0))
        res.report["elastic"] = section
        out = params.get("telemetry_out")
        if out:
            write_json(str(out), res.report)
    res.result["elastic"] = section
    trace_out = params.get("trace_out")
    if trace_out:
        try:
            tracer.save(f"{trace_out}.elastic_h{host_id}")
        except OSError:
            pass
