"""Membership epochs: the agreed live-host set + generation counter.

An epoch is the unit of cluster identity: ``(epoch, members)`` where
``members`` is the ordered list of STABLE host ids still alive (host ids
never renumber; a host's RANK within an epoch is its index in
``members``).  Epoch k+1 is negotiated by epoch k's survivors over the
epoch-k KV store immediately after a ``RankDeathError`` — the
coordination service lives inside epoch k's process 0 and keeps serving
until that process exits, which is exactly the window the negotiation
uses (the same window `DistributedNet._missing_report` already relies on
to name dead ranks).

Protocol (all keys generation-stamped under ``elastic/e<k+1>/``):

  1. every survivor posts ``ack/h<host>`` = its verdict (the dead-rank
     set it observed, translated to host ids);
  2. the ANCHOR — the lowest-host-id survivor — collects every proposed
     member's ack with a deadline; a proposed member that never acks is
     declared dead too (cascading failure during recovery), then the
     anchor posts the canonical ``record``;
  3. non-anchor survivors block on ``record``, make their verdict
     DURABLE (the controller's verdict file), and only then post
     ``got/h<host>`` via :func:`confirm_record`; the anchor waits for
     every got-ack before returning, so it cannot exit (taking the KV
     store — and, via the fatal-error poller, every still-running peer —
     with it) while a peer's verdict is still in flight.

If the anchor itself is among the dead — or the coordination service is
already gone — the blocking reads time out and negotiation raises
``ConnectionError``: control-plane loss is terminal by design (v1; a
production deployment would re-anchor through an external store).

The generation stamp is the zombie fence: a late-returning worker from
epoch k that believes a DIFFERENT death happened writes only under its
own proposed generation, and epoch k+1 runs on a physically separate
coordinator anyway — its collectives can never interleave with the new
epoch's.
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass, field
from typing import List, Optional, Sequence


@dataclass
class MembershipEpoch:
    """One agreed generation of the pod."""

    epoch: int
    #: ordered STABLE host ids; a host's rank is its index here
    members: List[int]
    #: host ids declared dead in the transition INTO this epoch
    dead_hosts: List[int] = field(default_factory=list)
    coordinator: str = ""

    def rank_of(self, host_id: int) -> int:
        return self.members.index(int(host_id))

    def to_dict(self) -> dict:
        return {"epoch": int(self.epoch),
                "members": [int(m) for m in self.members],
                "dead_hosts": [int(d) for d in self.dead_hosts],
                "coordinator": self.coordinator}

    @classmethod
    def from_dict(cls, d: dict) -> "MembershipEpoch":
        return cls(epoch=int(d["epoch"]),
                   members=[int(m) for m in d["members"]],
                   dead_hosts=[int(x) for x in d.get("dead_hosts", [])],
                   coordinator=str(d.get("coordinator", "")))


def coordinator_for_epoch(host: str, port_base: int, epoch: int) -> str:
    """Epoch k's fresh jax.distributed cluster address: ``port_base + k``
    on the coordinator host.  A new port per generation is what isolates
    epoch k+1 from epoch k's dying coordination service (and its
    zombies)."""
    return f"{host}:{int(port_base) + int(epoch)}"


def _kv():
    from ..parallel.multihost import _kv_client
    return _kv_client()


def negotiate_next_epoch(current: MembershipEpoch, my_host: int,
                         dead_ranks: Sequence[int],
                         deadline_s: float = 20.0,
                         client=None) -> MembershipEpoch:
    """Agree epoch k+1's membership among epoch k's survivors (see module
    docstring for the protocol).  ``dead_ranks`` are epoch-k RANKS from
    the ``RankDeathError`` verdict; returns the canonical next epoch.
    Raises ``ConnectionError`` on control-plane loss (anchor dead or
    coordination service gone)."""
    if client is None:
        client = _kv()
    nxt = int(current.epoch) + 1
    prefix = f"elastic/e{nxt}"
    dead_hosts = sorted({int(current.members[r]) for r in dead_ranks
                         if 0 <= int(r) < len(current.members)})
    proposed = [h for h in current.members if h not in dead_hosts]
    deadline_ms = max(int(deadline_s * 1000), 1)

    client.key_value_set_bytes(
        f"{prefix}/ack/h{int(my_host)}",
        pickle.dumps({"host": int(my_host), "dead_hosts": dead_hosts}))

    anchor = min(proposed)
    # the anchor is rank 0 of the proposed membership — the one
    # deliberately rank-asymmetric schedule in this module (vetted via
    # the LGB008 allowlist): exactly one process may write the canonical
    # record, and survivors cannot elect one without a store round-trip
    rank = proposed.index(int(my_host)) if int(my_host) in proposed else -1
    if rank == 0:
        # anchor: collect every proposed member's ack; a survivor that
        # cannot reach the KV store in time is dead for epoch k+1 too
        confirmed: List[int] = []
        union_dead = set(dead_hosts)
        for h in proposed:
            try:
                ack = pickle.loads(client.blocking_key_value_get_bytes(
                    f"{prefix}/ack/h{h}", deadline_ms))
                confirmed.append(h)
                union_dead.update(int(x) for x in ack.get("dead_hosts", ()))
            except Exception:
                union_dead.add(int(h))
        members = [h for h in confirmed if h not in union_dead]
        record = MembershipEpoch(
            epoch=nxt, members=members,
            dead_hosts=sorted(union_dead),
            coordinator=current.coordinator)
        client.key_value_set_bytes(f"{prefix}/record",
                                   pickle.dumps(record.to_dict()))
        # hold the KV store open until every surviving peer has read the
        # record — the anchor process exiting kills the coordination
        # service, and a peer mid-read would see control-plane loss
        for h in members:
            if h == int(my_host):
                continue
            try:
                client.blocking_key_value_get_bytes(
                    f"{prefix}/got/h{h}", deadline_ms)
            except Exception:
                pass  # peer died after acking; epoch k+1's own
                # heartbeat will name it within one iteration
        return record
    try:
        raw = client.blocking_key_value_get_bytes(f"{prefix}/record",
                                                  deadline_ms)
    except Exception as e:
        raise ConnectionError(
            f"membership negotiation for epoch {nxt} lost the control "
            f"plane (anchor host {anchor} dead or coordination service "
            f"gone): {e}") from None
    return MembershipEpoch.from_dict(pickle.loads(raw))


def confirm_record(record: MembershipEpoch, my_host: int,
                   client=None) -> None:
    """Post this host's ``got`` ack for the canonical record — called by
    the worker AFTER its verdict file is durably on disk.  The ack
    releases the anchor, whose exit aborts every peer still running
    (the coordination service dies with it), so anything that must
    survive the transition has to be written before this call."""
    if client is None:
        client = _kv()
    client.key_value_set_bytes(
        f"elastic/e{int(record.epoch)}/got/h{int(my_host)}",
        pickle.dumps(True))
