"""One membership epoch's training process (subprocess re-entry).

``python -m lightgbm_tpu.elastic.worker <spec.json>`` — launched by the
per-host controller once per epoch, because a jax.distributed cluster can
neither re-initialize nor shrink in place (the coordination service
propagates fatal errors to survivors once a peer dies).  The platform
environment (JAX_PLATFORMS / XLA_FLAGS) must be composed by the
controller into the child env: importing this module already imports jax
via the package.

The worker derives its per-epoch world from the membership record — a
fresh coordinator (``port_base + epoch``), ``num_hosts`` = survivor
count, ``process_id`` = this host's index in the member list — trains to
the ORIGINAL round target with ``resume=true`` (the snapshot dir is
per-HOST, stable across epochs), and exits:

  * 0 — trained to the target; model + result JSON written;
  * ``EXIT_RESHAPE`` — a peer died (``RankDeathError``): next epoch's
    membership was negotiated over the old KV store and written to the
    verdict file for the controller;
  * ``EXIT_DECLARED_DEAD`` — negotiation declared THIS host dead (it
    stalled past the ack deadline);
  * ``EXIT_CONTROL_LOST`` — the anchor/coordination service is gone;
    terminal.

Exits go through ``os._exit``: the normal interpreter shutdown runs
jax.distributed's atexit barrier, which aborts against a dead peer — the
same reason the chaos drills exit this way.
"""

from __future__ import annotations

import json
import os
import sys
import time

from .controller import (EXIT_CONTROL_LOST, EXIT_DECLARED_DEAD,
                         EXIT_RESHAPE, write_json)
from .epoch import MembershipEpoch, confirm_record, negotiate_next_epoch


def _quiesce(epoch: MembershipEpoch, host: int, spec: dict) -> None:
    """Leader-LAST exit ordering for the success path.  The epoch's
    coordination service lives inside rank 0's process; if rank 0 exits
    while a peer is still saving its model, the peer's error-poller
    aborts it (SIGABRT) even though training succeeded.  So rank 0
    lingers until every peer's result file is durable and is the last
    one out.  The wait reads the FILESYSTEM, not the KV store — KV reads
    against the in-process service can crash it natively while peers
    disconnect (the controller's dirty-exit tolerance exists for exactly
    that) — so on a real pod with per-host workdirs this degrades to a
    bounded grace period instead of a handshake."""
    # rank 0 hosts the coordination service — it alone must linger
    # (vetted via the LGB008 allowlist)
    if epoch.rank_of(host) != 0:
        return
    try:
        edir = os.path.dirname(os.path.abspath(spec["result_path"]))
        hosts_root = os.path.dirname(os.path.dirname(edir))
        peers = [os.path.join(hosts_root, f"h{int(h)}",
                              os.path.basename(edir), "result.json")
                 for h in epoch.members if int(h) != int(host)]
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if all(os.path.exists(p) for p in peers):
                break
            time.sleep(0.05)
    except Exception:
        pass


def _recover(spec: dict, epoch: MembershipEpoch, host: int, err) -> None:
    """Negotiate the next membership over the dying epoch's KV store,
    write the verdict for the controller, and exit."""
    t0 = time.monotonic()
    try:
        record = negotiate_next_epoch(
            epoch, host, err.dead_ranks,
            deadline_s=float(spec.get("negotiate_deadline_s", 20.0)))
    except ConnectionError as e:
        write_json(spec["verdict_path"], {
            "kind": "control_plane_lost", "failed_epoch": epoch.epoch,
            "error": str(e)})
        os._exit(EXIT_CONTROL_LOST)
    write_json(spec["verdict_path"], {
        "kind": "reshape", "failed_epoch": epoch.epoch,
        "dead_ranks": [int(r) for r in err.dead_ranks],
        "error": str(err), "next": record.to_dict(),
        "negotiate_s": time.monotonic() - t0})
    # verdict is durable — NOW release the anchor (its exit kills the
    # coordination service, and the fatal-error poller takes any process
    # still running down with it, so nothing below this line may matter)
    if epoch.rank_of(host) != 0:
        try:
            confirm_record(record, host)
        except Exception:
            pass
    if int(host) not in record.members:
        os._exit(EXIT_DECLARED_DEAD)
    os._exit(EXIT_RESHAPE)


def main(argv) -> None:
    with open(argv[1]) as fh:
        spec = json.load(fh)
    epoch = MembershipEpoch.from_dict(spec["membership"])
    host = int(spec["host_id"])
    rank = epoch.rank_of(host)

    import jax
    if spec.get("enable_x64"):
        jax.config.update("jax_enable_x64", True)
    if spec.get("cache_dir"):
        jax.config.update("jax_compilation_cache_dir", spec["cache_dir"])
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    import lightgbm_tpu as lgb
    from ..parallel.multihost import RankDeathError

    params = dict(spec["params"])
    params.update({
        "coordinator_address": epoch.coordinator,
        "num_hosts": len(epoch.members),
        "process_id": rank,
        "elastic": True,
        "elastic_epoch": int(epoch.epoch),
        "two_round": True,
        "resume": True,
        "output_model": spec["output_model"],
    })
    params.setdefault("snapshot_freq", 1)

    try:
        dtrain = lgb.Dataset(spec["data"], params=params)
        bst = lgb.train(params, dtrain,
                        num_boost_round=int(spec["num_boost_round"]))
        bst.save_model(spec["output_model"])
        result = {"ok": True, "epoch": int(epoch.epoch), "rank": rank,
                  "members": list(epoch.members),
                  "iterations": int(bst.current_iteration),
                  "model": spec["output_model"]}
        if params.get("telemetry"):
            result["report"] = bst.get_telemetry()
        write_json(spec["result_path"], result)
        _quiesce(epoch, host, spec)
    except RankDeathError as e:
        _recover(spec, epoch, host, e)  # never returns
    os._exit(0)


if __name__ == "__main__":
    main(sys.argv)
