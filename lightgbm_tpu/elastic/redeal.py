"""Shard re-deal for elastic membership changes.

When a membership epoch shrinks, the dead host's rows must land on the
survivors.  In-memory Datasets CANNOT do this — whatever rows a host
uploaded at construct time is all it will ever have.  A ``from_stream``
(``two_round=true``) source can: the file outlives every host, so each
epoch simply re-runs the two-pass loader with ``rank/num_machines``
re-derived from the CURRENT membership — mod-dealing
(``global_row % num_machines == rank``) re-deals every row, including the
dead rank's, with no per-row bookkeeping.

The pass-1 bin sample is drawn from the FULL file with the config seed,
so every rank of every epoch derives the IDENTICAL mapper table without a
single collective; the binned shards are then exchanged over the
DistributedNet KV seam and reassembled in global row order on every host
(:func:`assemble_global`).  The assembled dataset is bit-identical to a
single-host ``from_matrix``/``from_stream`` construction of the same file
— so the placed global mesh arrays, and therefore the trained trees, do
not depend on the epoch's shard layout at all.  That is what makes
"resume from epoch k's snapshot under epoch k+1's membership" exact:
only the mesh over which rows are laid changes, never the rows.

Cost model: each host streams the whole file but BINS only its 1/M of
the rows (the expensive part of pass 2), then holds the full binned
matrix (uint8 — 1/8th of the float64 matrix the in-memory path
materializes) after the exchange.  The exchange itself moves O(n·f)
bytes through the coordinator KV store — fine at emulation scale and a
documented v1 limit for real pods (a production pod would exchange over
the mesh interconnect instead).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

from ..config import Config
from ..dataset import Metadata, _ConstructedDataset


def _round_up(v: int, m: int) -> int:
    return ((int(v) + m - 1) // m) * m


def construct_elastic(path: str, params: Optional[dict], cfg: Config,
                      categorical: Sequence[int] = (),
                      feature_names: Optional[List[str]] = None,
                      info=None, net=None) -> _ConstructedDataset:
    """Elastic construction of ``path``: two-pass stream of THIS rank's
    mod-dealt shard, then global reassembly over the pod net.  rank /
    num_machines come from the live jax.distributed world (the current
    membership), never from config — config still describes the ORIGINAL
    launch."""
    from ..parallel.multihost import DistributedNet
    from ..reliability.metrics import rel_inc

    if net is None:
        net = DistributedNet(cfg, namespace="redeal")
    if cfg.pre_partition:
        raise ValueError(
            "elastic=true cannot re-deal pre_partition=true shards: each "
            "host's file holds ONLY its rows, so a dead host's rows are "
            "unreachable — use a shared data file (pre_partition=false)")
    shard = _ConstructedDataset.from_stream(
        path, params, cfg, categorical=categorical,
        feature_names=feature_names, rank=net.rank,
        num_machines=net.num_machines, info=info)
    if net.num_machines <= 1:
        return shard
    rel_inc("elastic.redeal_rows", int(shard.num_data))
    return assemble_global(shard, net)


def assemble_global(shard: _ConstructedDataset,
                    net) -> _ConstructedDataset:
    """Exchange the mod-dealt binned shards and reassemble the FULL
    dataset in global row order on every rank (mutates ``shard`` in place
    and returns it).  Row padding is sized to ``lcm(tpu_row_block,
    device_count)`` so the row axis of every placed array divides evenly
    across the global mesh whatever the survivor count is."""
    import jax

    if getattr(shard.metadata, "query_boundaries", None) is not None:
        raise ValueError(
            "elastic re-deal does not support ranking query groups yet — "
            "whole-query dealing changes per-rank row counts across "
            "epochs; train lambdarank non-elastically")
    n = int(shard.num_data_global)
    n_local = int(shard.num_data)
    weights = getattr(shard.metadata, "weights", None)
    payload = (np.asarray(shard.global_rows, dtype=np.int64),
               np.ascontiguousarray(shard.bins[:, :n_local]),
               np.asarray(shard.metadata.label, dtype=np.float64),
               None if weights is None else np.asarray(weights))
    parts = net.allgather(payload)

    cfg = shard.config
    ndev = max(jax.device_count(), 1)
    block = max(int(cfg.tpu_row_block), 128)
    # BOTH padded axes must divide by the CURRENT epoch's device count or
    # the parallel router falls back to the masked GSPMD learner — whose
    # closed-over bins cannot span a multi-process mesh.  The row block
    # keeps the wave layout; the feature tile keeps the Pallas layout.
    n_pad = _round_up(max(n, 1), math.lcm(block, ndev))
    f_pad = _round_up(int(shard.bins.shape[0]),
                      math.lcm(_ConstructedDataset.FEATURE_TILE, ndev))
    bins = np.zeros((f_pad, n_pad), dtype=shard.bins.dtype)
    labels = np.zeros(n, dtype=np.float64)
    wout = None
    covered = 0
    for rows, b, lab, w in parts:
        bins[:b.shape[0], rows] = b
        labels[rows] = lab
        if w is not None:
            if wout is None:
                wout = np.zeros(n, dtype=np.float64)
            wout[rows] = w
        covered += len(rows)
    if covered != n:
        raise ValueError(f"re-deal reassembly covered {covered} rows, "
                         f"expected {n} — shards overlap or are missing")
    shard.bins = bins
    shard.num_data = n
    shard.num_data_padded = n_pad
    shard.metadata = Metadata(n)
    shard.metadata.set_label(labels)
    if wout is not None:
        shard.metadata.set_weights(wout)
    # after reassembly this is a full-coverage dataset, not a shard
    shard.global_rows = np.arange(n, dtype=np.int64)
    shard.row_offset = 0
    shard.num_data_global = n
    # drop caches derived from the pre-exchange shard layout
    shard._device_bins = None
    shard._feature_meta = None
    shard._binner_arrays = None
    return shard
