"""Latency histograms + Prometheus text-format metrics export.

``ServingStats`` (`serving/batcher.py`) kept aggregate counters only — no
percentiles, so "p99 latency against an SLO" (ROADMAP item 3) was
unanswerable.  This module adds:

  * ``LatencyHistogram`` — log-bucketed counts (powers of two from 0.1 ms,
    the Prometheus ``le`` buckets) PLUS a bounded window of raw samples.
    Percentiles are extracted from the raw window with numpy's default
    linear interpolation, so p50/p95/p99 are EXACT over the retained
    window (``tests/test_tracing.py`` pins equality with ``np.percentile``)
    rather than bucket-upper-bound approximations; the log buckets exist
    for the Prometheus exposition, where cumulative buckets are the
    contract.
  * ``prometheus_text`` / ``prometheus_snapshot`` — the text exposition
    format (``# TYPE``, ``_bucket{le=...}``, ``_sum``/``_count``) over the
    serving counters, stage timers, reliability counters and latency
    histograms; the server's ``metrics`` op returns this snapshot through
    the same framed-RPC plumbing as ``health``.
  * ``BENCH_SERVING_SCHEMA`` — the contract ``bench_serving.py`` validates
    its ``BENCH_SERVING_r*.json`` trajectory files against (same
    dependency-free validator subset as ``schema.json``).

Monotonic clocks only; host-side only; every structure is thread-safe and
lock-leaf (nothing here acquires another subsystem's lock).
"""

from __future__ import annotations

import re
import threading
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

#: default log buckets: 0.1 ms · 2^k, k = 0..20 (0.1 ms .. ~105 s)
DEFAULT_BOUNDS_MS: Tuple[float, ...] = tuple(0.1 * (2.0 ** k)
                                             for k in range(21))

#: raw-sample window backing exact percentiles (per histogram)
DEFAULT_WINDOW = 8192

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


class LatencyHistogram:
    """Thread-safe log-bucketed histogram with an exact-percentile window.

    ``record(ms)`` is O(log buckets); ``percentiles`` computes numpy
    percentiles over the last ``window`` samples (exact for any workload
    that fits the window, and a sliding-window estimate beyond it — the
    honest trade for bounded memory in a long-lived server)."""

    def __init__(self, bounds_ms: Optional[Sequence[float]] = None,
                 window: int = DEFAULT_WINDOW):
        self.bounds = np.asarray(sorted(bounds_ms if bounds_ms is not None
                                        else DEFAULT_BOUNDS_MS), np.float64)
        self._counts = np.zeros(len(self.bounds) + 1, np.int64)  # +Inf last
        self._window: deque = deque(maxlen=max(int(window), 1))
        self._lock = threading.Lock()
        self.count = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0

    def record(self, ms: float) -> None:
        ms = float(ms)
        # first bound >= ms == the Prometheus `le` bucket the sample joins
        idx = int(np.searchsorted(self.bounds, ms, side="left"))
        with self._lock:
            self._counts[idx] += 1
            self.count += 1
            self.sum_ms += ms
            if ms > self.max_ms:
                self.max_ms = ms
            self._window.append(ms)

    # -- extraction ----------------------------------------------------------

    def percentiles(self, qs: Sequence[float] = (50, 95, 99)
                    ) -> Dict[str, float]:
        """``{"p50": ..., ...}`` over the raw sample window (numpy linear
        interpolation — exact vs ``np.percentile`` on the same samples)."""
        with self._lock:
            arr = np.asarray(self._window, np.float64)
        if arr.size == 0:
            return {f"p{g:g}": 0.0 for g in qs}
        vals = np.percentile(arr, list(qs))
        return {f"p{q:g}": float(v) for q, v in zip(qs, vals)}

    def snapshot(self) -> Dict[str, Any]:
        """The ``latency_ms`` report section (observability/schema.json)."""
        p = self.percentiles((50, 95, 99))
        with self._lock:
            count, total, mx = self.count, self.sum_ms, self.max_ms
        return {"count": int(count),
                "mean": float(total / count) if count else 0.0,
                "max": float(mx),
                "p50": p["p50"], "p95": p["p95"], "p99": p["p99"]}

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """``(le_ms, cumulative_count)`` rows, ending with ``(inf, count)``."""
        with self._lock:
            cum = np.cumsum(self._counts)
        rows = [(float(b), int(c)) for b, c in zip(self.bounds, cum[:-1])]
        rows.append((float("inf"), int(cum[-1])))
        return rows

    def prometheus_lines(self, name: str, labels: str = "") -> List[str]:
        """Text-exposition histogram block (``le`` in SECONDS, the
        Prometheus convention for latency metrics)."""
        name = sanitize_metric_name(name)
        lab = labels if not labels or labels.startswith("{") else \
            "{" + labels + "}"
        base = lab[1:-1] if lab else ""
        out = [f"# TYPE {name} histogram"]
        for le_ms, cum in self.cumulative_buckets():
            le = "+Inf" if le_ms == float("inf") else f"{le_ms / 1e3:g}"
            sep = "," if base else ""
            out.append(f'{name}_bucket{{{base}{sep}le="{le}"}} {cum}')
        with self._lock:
            out.append(f"{name}_sum{lab} {self.sum_ms / 1e3:g}")
            out.append(f"{name}_count{lab} {self.count}")
        return out


def sanitize_metric_name(name: str) -> str:
    """Prometheus metric names allow ``[a-zA-Z0-9_:]`` only."""
    return _NAME_RE.sub("_", name)


def prometheus_text(counters: Optional[Dict[str, float]] = None,
                    gauges: Optional[Dict[str, float]] = None,
                    histograms: Optional[Dict[str, LatencyHistogram]] = None,
                    prefix: str = "lgbt_") -> str:
    """Render counters/gauges/histograms as one text-format exposition."""
    lines: List[str] = []
    for name, v in sorted((counters or {}).items()):
        n = sanitize_metric_name(prefix + name)
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {float(v):g}")
    for name, v in sorted((gauges or {}).items()):
        n = sanitize_metric_name(prefix + name)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {float(v):g}")
    for name, h in sorted((histograms or {}).items()):
        lines.extend(h.prometheus_lines(prefix + name))
    return "\n".join(lines) + "\n"


def _escape_label(value: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def tenant_prometheus_lines(tenants: Iterable[Dict[str, Any]]
                            ) -> List[str]:
    """``lgbt_serving_tenant_*{model="..."}`` series from a
    ``ServingStats.tenants_section()`` list: request/error/shed
    counters, latency percentile gauges, SLO attainment and error-budget
    burn per model name."""
    metrics = [
        ("lgbt_serving_tenant_requests_total", "counter",
         lambda t: t["requests"]),
        ("lgbt_serving_tenant_errors_total", "counter",
         lambda t: t["errors"]),
        ("lgbt_serving_tenant_shed_total", "counter",
         lambda t: t["shed"]),
        ("lgbt_serving_tenant_latency_p50_ms", "gauge",
         lambda t: t["latency_ms"]["p50"]),
        ("lgbt_serving_tenant_latency_p95_ms", "gauge",
         lambda t: t["latency_ms"]["p95"]),
        ("lgbt_serving_tenant_latency_p99_ms", "gauge",
         lambda t: t["latency_ms"]["p99"]),
        ("lgbt_serving_tenant_slo_p99_target_ms", "gauge",
         lambda t: t["slo"]["p99_target_ms"]),
        ("lgbt_serving_tenant_slo_target", "gauge",
         lambda t: t["slo"]["target"]),
        ("lgbt_serving_tenant_slo_attainment", "gauge",
         lambda t: t["slo"]["attainment"]),
        ("lgbt_serving_tenant_error_budget_burn", "gauge",
         lambda t: t["slo"]["error_budget_burn"]),
    ]
    tenants = list(tenants)
    lines: List[str] = []
    for name, kind, get in metrics:
        lines.append(f"# TYPE {name} {kind}")
        for t in tenants:
            lab = _escape_label(t["model"])
            lines.append(f'{name}{{model="{lab}"}} {float(get(t)):g}')
    return lines


def drift_prometheus_lines(gauges: Dict[str, float],
                           section: Optional[Dict[str, Any]] = None
                           ) -> List[str]:
    """``lgbt_serving_drift_*`` gauges from ``DriftMonitor.gauges()``,
    plus per-feature PSI series for the last check's top drifted
    features when the full ``drift`` section is supplied."""
    lines: List[str] = []
    for name, v in sorted((gauges or {}).items()):
        n = sanitize_metric_name("lgbt_" + name)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {float(v):g}")
    feats = [f for f in (section or {}).get("features", ())
             if f["feature"] in (section or {}).get("top_features", ())]
    if feats:
        lines.append("# TYPE lgbt_serving_drift_feature_psi gauge")
        for f in feats:
            lab = _escape_label(f["feature"])
            lines.append(f'lgbt_serving_drift_feature_psi'
                         f'{{feature="{lab}"}} {float(f["psi"]):g}')
    return lines


def prometheus_snapshot(stats, registry=None, admission=None,
                        replicas=None, tenants=None, drift=None) -> str:
    """The server ``metrics`` op payload: every serving counter, stage
    timer total, reliability counter, model version and the request
    latency histogram, as one Prometheus text page.  ``replicas`` (a
    ``ReplicaSet.section()`` list) adds per-replica fleet gauges:
    health, in-flight, dispatched, ejections, p99; ``tenants`` (a
    ``ServingStats.tenants_section()`` list) adds the per-model-name
    SLO series and ``drift`` (a ``drift`` report section) the
    ``lgbt_serving_drift_*`` gauges."""
    from ..reliability.metrics import rel_counters

    section = stats.serving_section(
        models=registry.versions() if registry is not None else None,
        jit_entries=registry.jit_entries() if registry is not None else None)
    counters: Dict[str, float] = {
        "serving_requests_total": section["requests"],
        "serving_rows_total": section["rows"],
        "serving_batches_total": section["batches"],
        "serving_shed_total": section["shed"],
        "serving_fallback_batches_total": section["fallback_batches"],
        "serving_compile_cache_hits_total":
            section["compile_cache"]["hits"],
        "serving_compile_cache_misses_total":
            section["compile_cache"]["misses"],
    }
    for name, v in rel_counters().items():
        counters[f"reliability_{sanitize_metric_name(name)}_total"] = v
    gauges: Dict[str, float] = {
        "serving_qps": section["qps"],
        "serving_rows_per_s": section["rows_per_s"],
        "serving_batch_occupancy": section["batch_occupancy"],
    }
    for stage, st in section["stage_ms"].items():
        g = sanitize_metric_name(stage)
        gauges[f"serving_stage_{g}_total_seconds"] = st["total_ms"] / 1e3
        counters[f"serving_stage_{g}_count_total"] = st["count"]
    if admission is not None:
        snap = admission.snapshot()
        gauges["serving_inflight"] = snap["inflight"]
        gauges["serving_inflight_capacity"] = snap["capacity"]
        gauges["serving_shedding"] = 1.0 if snap["shedding"] else 0.0
    if registry is not None:
        for name, ver in (registry.versions() or {}).items():
            gauges[f"serving_model_version:{sanitize_metric_name(name)}"] = ver
    for snap in replicas or ():
        i = snap["index"]
        gauges[f"serving_replica_healthy:{i}"] = \
            1.0 if snap["healthy"] else 0.0
        gauges[f"serving_replica_inflight:{i}"] = snap["in_flight"]
        counters[f"serving_replica_dispatched_total:{i}"] = \
            snap["dispatched"]
        counters[f"serving_replica_ejections_total:{i}"] = \
            snap["ejections"]
        gauges[f"serving_replica_latency_p99_ms:{i}"] = \
            snap["latency_ms"]["p99"]
    text = prometheus_text(
        counters, gauges,
        histograms={"serving_request_latency_seconds": stats.request_hist})
    extra: List[str] = []
    if tenants:
        extra.extend(tenant_prometheus_lines(tenants))
    if drift:
        from .drift import DriftMonitor
        if isinstance(drift, DriftMonitor):
            extra.extend(drift_prometheus_lines(
                drift.gauges(), drift.section()))
        else:
            extra.extend(drift_prometheus_lines(drift))
    if extra:
        text += "\n".join(extra) + "\n"
    return text


def training_prometheus(report: Dict[str, Any]) -> str:
    """The TRAINING analogue of ``prometheus_snapshot``: render a
    telemetry report (``Telemetry.report()`` / ``Booster.get_telemetry``)
    as ``lgbt_training_*`` text exposition — phase totals, iteration
    timings, device counters, rank-skew gauges and memory watermarks, so
    a pod run scrapes the same way the serving fleet does."""
    counters: Dict[str, float] = {
        "training_iterations_total": report["iterations"]["count"],
    }
    for name, v in (report.get("counters") or {}).items():
        counters[f"training_{sanitize_metric_name(name)}_total"] = v
    gauges: Dict[str, float] = {
        "training_iteration_mean_ms": report["iterations"]["mean_ms"],
        "training_iteration_last_ms": report["iterations"]["last_ms"],
    }
    for phase, st in (report.get("phases") or {}).items():
        g = sanitize_metric_name(phase)
        gauges[f"training_phase_{g}_total_seconds"] = st["total_ms"] / 1e3
        counters[f"training_phase_{g}_count_total"] = st["count"]
    for name, v in (report.get("gauges") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            gauges[f"training_{sanitize_metric_name(name)}"] = v
    dist = report.get("distributed") or {}
    if dist.get("skew_ratio") is not None:
        gauges["training_rank_skew_ratio"] = dist["skew_ratio"]
    if dist.get("slowest_rank") is not None:
        gauges["training_slowest_rank"] = dist["slowest_rank"]
    for r, s in (dist.get("rank_step_s") or {}).items():
        if s is not None:
            gauges[f"training_rank_step_seconds:{sanitize_metric_name(str(r))}"] = s
    mem = dist.get("memory") or {}
    for d in mem.get("devices") or ():
        dev = sanitize_metric_name(d["device"])
        gauges[f"training_hbm_peak_bytes:{dev}"] = d["peak_bytes_in_use"]
    if mem.get("host_heap"):
        gauges["training_host_heap_peak_bytes"] = \
            mem["host_heap"]["peak_bytes"]
    table = dist.get("attribution") or {}
    for leg, ms in (table.get("legs_ms") or {}).items():
        gauges[f"training_leg_ms:{sanitize_metric_name(leg)}"] = ms
    if table.get("coverage") is not None:
        gauges["training_attribution_coverage"] = table["coverage"]
    return prometheus_text(counters, gauges)


# -- bench_serving.py contract ------------------------------------------------

_LATENCY_MS_SCHEMA = {
    "type": "object",
    "required": ["count", "mean", "max", "p50", "p95", "p99"],
    "properties": {
        "count": {"type": "integer"},
        "mean": {"type": "number"},
        "max": {"type": "number"},
        "p50": {"type": "number"},
        "p95": {"type": "number"},
        "p99": {"type": "number"},
    },
}

_LOOP_SCHEMA = {
    "type": "object",
    "required": ["requests", "ok", "shed", "errors", "duration_s", "qps",
                 "shed_rate", "latency_ms"],
    "properties": {
        "requests": {"type": "integer"},
        "ok": {"type": "integer"},
        "shed": {"type": "integer"},
        "errors": {"type": "integer"},
        "duration_s": {"type": "number"},
        "qps": {"type": "number"},
        "shed_rate": {"type": "number"},
        "latency_ms": _LATENCY_MS_SCHEMA,
        "clients": {"type": "integer"},
        "target_qps": {"type": "number"},
    },
}

#: the BENCH_SERVING_r*.json contract — the serving analogue of the
#: training BENCH_r*.json trajectory discipline (validated by
#: ``observability.report.validate_report`` with this schema)
BENCH_SERVING_SCHEMA: Dict[str, Any] = {
    "type": "object",
    "required": ["schema_version", "round", "platform", "workload",
                 "closed_loop", "open_loop", "server", "provenance"],
    "properties": {
        "schema_version": {"type": "integer"},
        "round": {"type": "integer"},
        "platform": {"type": "string"},
        "note": {"type": "string"},
        # the same who-produced-this block every telemetry report and
        # BENCH/MULTICHIP writer carries (schema v7): a CPU-emulated
        # serving number can never masquerade as a device result
        "provenance": {
            "type": "object",
            "required": ["platform", "jax_version", "num_devices",
                         "num_hosts", "emulated", "cost_ledger_sha256"],
            "properties": {
                "platform": {"type": "string"},
                "device_kind": {"type": "string"},
                "jax_version": {"type": "string"},
                "num_devices": {"type": "integer"},
                "num_hosts": {"type": "integer"},
                "process_index": {"type": "integer"},
                "emulated": {"type": "boolean"},
                "mesh_shape": {"type": ["string", "null"]},
                # sha256 of the checked-in analysis/costs.json ledger the
                # run was gated against (schema v2; null = ledger absent)
                "cost_ledger_sha256": {"type": ["string", "null"]},
            },
        },
        "workload": {
            "type": "object",
            "required": ["num_features", "rows_per_request"],
            "additionalProperties": {"type": ["number", "string"]},
        },
        "closed_loop": _LOOP_SCHEMA,
        "open_loop": _LOOP_SCHEMA,
        # protocol/replica comparison sweeps: one entry per (protocol,
        # replicas) leg, each a closed+open loop pair (bench_serving.py
        # --compare); the headline closed_loop/open_loop above is the
        # last (best-configured) leg
        "legs": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["protocol", "replicas", "closed_loop",
                             "open_loop"],
                "properties": {
                    "protocol": {"type": "string"},
                    "replicas": {"type": "integer"},
                    "closed_loop": _LOOP_SCHEMA,
                    "open_loop": _LOOP_SCHEMA,
                },
            },
        },
        "server": {
            "type": "object",
            "required": ["batches", "batch_occupancy", "shed",
                         "compile_cache"],
            "properties": {
                "batches": {"type": "integer"},
                "batch_occupancy": {"type": "number"},
                "shed": {"type": "integer"},
                "compile_cache": {
                    "type": "object",
                    "required": ["hits", "misses"],
                    "properties": {
                        "hits": {"type": "integer"},
                        "misses": {"type": "integer"},
                        "jit_entries": {"type": ["integer", "null"]},
                    },
                },
                "buckets": {
                    "type": "object",
                    "additionalProperties": {"type": "integer"},
                },
            },
        },
    },
}
