"""Statistical drift detection over the serving traffic recorder.

The lifecycle loop (ROADMAP item 4) needs a *signal* before it can act:
"is the traffic this fleet is answering still the distribution the live
model was judged on?".  This module is that signal — detection and
alerting only; what to DO about drift (automatic refit) stays item 4.

Two detectors, both over distributions the serving stack already
produces (no new quantization code):

  * **Per-feature PSI + two-sample KS over bin-index distributions.**
    `serving/binner.BinnerArrays.bin_host` maps raw rows to the exact
    train-time bin space, so each used feature's traffic reduces to a
    small integer histogram (``num_bin`` regular bins + one overflow
    slot for the categorical OOV sentinel).  PSI is the classic
    population-stability index over those bin fractions; KS is the max
    CDF gap between the binned baseline and window distributions, with
    the standard asymptotic two-sample p-value.
  * **Score-distribution PSI + KS.**  Raw margins of the baseline
    sample define decile edges; window scores are binned against those
    same edges for PSI, and exact two-sample KS runs over the bounded
    raw score samples.

``DriftMonitor`` holds one baseline per model name — captured from the
``TrafficRecorder`` window at registry commit/promote time
(`fleet/gateway.FleetServer.promote_rolling`) — and compares later
recorder windows against it, producing the schema-v8 ``drift`` report
section, the ``lgbt_serving_drift_*`` gauges and a structured
``drift.alert`` trace instant when a check trips.

Everything here is host-side numpy (zero collective sites, never
touches a device) and lock-leaf: the monitor's one lock guards only its
own dicts and is never held across binning, scoring or tracer calls.
No wall clocks — freshness is expressed in recorder row counts.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

#: smoothing floor for PSI bin fractions (empty bins would otherwise
#: send the log-ratio to infinity on any novel bin)
PSI_EPS = 1e-4

#: number of quantile bins the score-distribution PSI uses
SCORE_BINS = 10

#: adjacent equal-baseline-mass groups per-feature PSI is computed over.
#: A 255-bin histogram against a few-hundred-row window holds ~2 rows
#: per bin — pure sampling noise that the eps floor would inflate into
#: PSI — so fine bins are merged to the conventional ~10-group PSI
#: binning first (KS keeps the full-resolution CDF; it is noise-robust)
PSI_GROUPS = 10

#: bounded raw score sample retained per baseline for exact two-sample KS
SCORE_SAMPLE = 8192


def psi_from_counts(expected: np.ndarray, actual: np.ndarray,
                    eps: float = PSI_EPS) -> float:
    """Population stability index between two count histograms over the
    same bins: ``sum((q - p) * ln(q / p))`` with ``eps``-floored
    fractions.  0 = identical; > 0.2 is the conventional "shifted"
    threshold."""
    p = np.asarray(expected, np.float64)
    q = np.asarray(actual, np.float64)
    if p.sum() <= 0 or q.sum() <= 0:
        return 0.0
    p = np.maximum(p / p.sum(), eps)
    q = np.maximum(q / q.sum(), eps)
    return float(np.sum((q - p) * np.log(q / p)))


def _psi_groups(expected: np.ndarray, actual: np.ndarray,
                groups: int = PSI_GROUPS
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Merge two aligned fine-bin histograms into at most ``groups``
    adjacent groups of roughly equal BASELINE mass (bins the baseline
    never saw merge into their left neighbour, so a window burst in
    them still lands in a group)."""
    p = np.asarray(expected, np.float64)
    q = np.asarray(actual, np.float64)
    tot = p.sum()
    if p.size <= groups or tot <= 0:
        return p, q
    left = (np.cumsum(p) - p) / tot
    gid = np.minimum((left * groups).astype(np.int64), groups - 1)
    return (np.bincount(gid, weights=p, minlength=groups),
            np.bincount(gid, weights=q, minlength=groups))


def _ks_pvalue(stat: float, n1: float, n2: float) -> float:
    """Asymptotic two-sample Kolmogorov p-value (Smirnov's limiting
    distribution with the Stephens small-sample correction — the same
    approximation scipy's ``ks_2samp(mode="asymp")`` uses)."""
    if n1 <= 0 or n2 <= 0 or stat <= 0:
        return 1.0
    en = np.sqrt(n1 * n2 / (n1 + n2))
    lam = (en + 0.12 + 0.11 / en) * float(stat)
    # Q_KS(lam) = 2 * sum_{j>=1} (-1)^(j-1) exp(-2 j^2 lam^2)
    j = np.arange(1, 101, dtype=np.float64)
    terms = 2.0 * ((-1.0) ** (j - 1)) * np.exp(-2.0 * (j * lam) ** 2)
    return float(min(max(np.sum(terms), 0.0), 1.0))


def ks_from_counts(expected: np.ndarray, actual: np.ndarray
                   ) -> Tuple[float, float]:
    """Two-sample KS over two count histograms on the same bins:
    max |CDF gap| between the binned empirical distributions, p-value
    from the asymptotic Kolmogorov distribution."""
    p = np.asarray(expected, np.float64)
    q = np.asarray(actual, np.float64)
    n1, n2 = p.sum(), q.sum()
    if n1 <= 0 or n2 <= 0:
        return 0.0, 1.0
    stat = float(np.max(np.abs(np.cumsum(p) / n1 - np.cumsum(q) / n2)))
    return stat, _ks_pvalue(stat, n1, n2)


def ks_2samp(a: np.ndarray, b: np.ndarray) -> Tuple[float, float]:
    """Exact two-sample KS statistic over raw samples (max ECDF gap at
    the pooled sample points), asymptotic p-value."""
    a = np.sort(np.asarray(a, np.float64).ravel())
    b = np.sort(np.asarray(b, np.float64).ravel())
    if a.size == 0 or b.size == 0:
        return 0.0, 1.0
    both = np.concatenate([a, b])
    cdf_a = np.searchsorted(a, both, side="right") / a.size
    cdf_b = np.searchsorted(b, both, side="right") / b.size
    stat = float(np.max(np.abs(cdf_a - cdf_b)))
    return stat, _ks_pvalue(stat, a.size, b.size)


def _feature_counts(model, X: np.ndarray) -> List[np.ndarray]:
    """Per-used-feature bin-index count histograms of a raw row matrix,
    through the model's OWN serving binner (`BinnerArrays.bin_host`) —
    the train-time bin space, bit-identical to what the device path
    serves.  Each feature gets ``num_bin`` slots + 1 overflow slot that
    the categorical ``OOV_BIN`` sentinel folds into."""
    arrays = model.arrays
    bins = arrays.bin_host(np.atleast_2d(np.asarray(X, np.float64)))
    out: List[np.ndarray] = []
    for k in range(arrays.num_used):
        nbins = int(arrays.nan_bin[k]) + 1
        b = bins[k].astype(np.int64)
        b = np.where((b < 0) | (b >= nbins), nbins, b)
        out.append(np.bincount(b, minlength=nbins + 1).astype(np.int64))
    return out


def _feature_names(model) -> List[str]:
    """Original-dataset feature name per used feature (positional
    ``f<idx>`` fallback when the booster carries no names)."""
    fmap = model.arrays.used_feature_map
    try:
        names = list(model.booster.gbdt.feature_names)
    except Exception:
        names = []
    return [names[int(i)] if int(i) < len(names) else f"f{int(i)}"
            for i in fmap]


def _scores(model, X: np.ndarray) -> np.ndarray:
    """Flat raw margins of a row matrix through the host reference
    traversal (deterministic, device-free — a drift check must never
    contend for the serving device)."""
    s = np.asarray(model.host_raw(np.atleast_2d(X)), np.float64)
    return s.ravel() if s.ndim == 1 else s[:, 0]


class _Baseline:
    """One captured reference distribution (immutable after capture)."""

    __slots__ = ("model_name", "version", "rows", "feature_counts",
                 "feature_names", "score_sample", "score_edges",
                 "score_counts")

    def __init__(self, model, X: np.ndarray):
        X = np.atleast_2d(np.asarray(X, np.float64))
        self.model_name = model.name
        self.version = int(model.version)
        self.rows = int(X.shape[0])
        self.feature_counts = _feature_counts(model, X)
        self.feature_names = _feature_names(model)
        scores = _scores(model, X)
        self.score_sample = scores[-SCORE_SAMPLE:].copy()
        # decile edges of the BASELINE define the score-PSI bins; both
        # windows bin against the same fixed edges
        self.score_edges = np.unique(np.percentile(
            scores, np.linspace(0, 100, SCORE_BINS + 1)[1:-1]))
        self.score_counts = self._bin_scores(scores)

    def _bin_scores(self, scores: np.ndarray) -> np.ndarray:
        idx = np.searchsorted(self.score_edges, scores, side="right")
        return np.bincount(idx, minlength=len(self.score_edges) + 1
                           ).astype(np.int64)

    # -- persistence (baselines survive a gateway restart) ------------

    def to_dict(self) -> Dict[str, Any]:
        """JSON-serializable form — exactly the ``__slots__`` state, so
        a restored baseline is indistinguishable from the captured
        one."""
        return {
            "model_name": self.model_name,
            "version": self.version,
            "rows": self.rows,
            "feature_counts": [c.tolist() for c in self.feature_counts],
            "feature_names": list(self.feature_names),
            "score_sample": self.score_sample.tolist(),
            "score_edges": self.score_edges.tolist(),
            "score_counts": self.score_counts.tolist(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "_Baseline":
        base = cls.__new__(cls)
        base.model_name = str(d["model_name"])
        base.version = int(d["version"])
        base.rows = int(d["rows"])
        base.feature_counts = [np.asarray(c, np.int64)
                               for c in d["feature_counts"]]
        base.feature_names = [str(n) for n in d["feature_names"]]
        base.score_sample = np.asarray(d["score_sample"], np.float64)
        base.score_edges = np.asarray(d["score_edges"], np.float64)
        base.score_counts = np.asarray(d["score_counts"], np.int64)
        return base


class DriftMonitor:
    """Baseline-vs-window drift checks keyed by model name.

    ``capture(model, X)`` snapshots the reference distribution (called
    at registry commit/promote time with the recorder window);
    ``check(model, X)`` compares a later window and returns the
    ``drift`` report section.  The last check per model is retained for
    ``section()``/``gauges()`` so the metrics op and the Prometheus
    scrape read the same result the check produced."""

    def __init__(self, psi_threshold: float = 0.2,
                 ks_threshold: float = 0.15, top_k: int = 5,
                 min_rows: int = 32, tracer=None):
        self.psi_threshold = float(psi_threshold)
        self.ks_threshold = float(ks_threshold)
        self.top_k = int(top_k)
        self.min_rows = max(int(min_rows), 1)
        self.tracer = tracer
        self._lock = threading.Lock()
        self._baselines: Dict[str, _Baseline] = {}
        self._last: Dict[str, Dict[str, Any]] = {}
        self._checks = 0
        self._alerts = 0

    # -- capture -------------------------------------------------------------

    def capture(self, model, X: np.ndarray) -> bool:
        """Snapshot the baseline for ``model.name`` from a raw row
        window; False (and no state change) when the window is smaller
        than ``min_rows``."""
        X = np.atleast_2d(np.asarray(X, np.float64))
        if X.shape[0] < self.min_rows or X.size == 0:
            return False
        base = _Baseline(model, X)
        with self._lock:
            self._baselines[model.name] = base
            # a fresh baseline invalidates the previous verdict
            self._last.pop(model.name, None)
        return True

    def has_baseline(self, name: str = "default") -> bool:
        with self._lock:
            return name in self._baselines

    # -- persistence ---------------------------------------------------------

    def save(self, path: str) -> int:
        """Atomic write (tmp + ``os.replace``, rule LGB002) of every
        captured baseline so a restarted gateway resumes drift
        detection without waiting for the next promotion.  Returns the
        number of baselines written."""
        import json
        import os
        with self._lock:
            data = {n: b.to_dict() for n, b in self._baselines.items()}
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"drift_baselines": data}, fh)
        os.replace(tmp, path)
        return len(data)

    def restore(self, path: str) -> int:
        """Load baselines written by :meth:`save`.  In-memory baselines
        win (a live capture is fresher than anything on disk); each
        restored entry counts on ``drift.baseline_restored``.  Returns
        the number restored; 0 when the file does not exist."""
        import json
        import os
        from ..reliability.metrics import rel_inc
        if not os.path.exists(path):
            return 0
        with open(path) as fh:
            data = json.load(fh).get("drift_baselines", {})
        restored = 0
        with self._lock:
            for name, d in data.items():
                if name in self._baselines:
                    continue
                self._baselines[name] = _Baseline.from_dict(d)
                restored += 1
        if restored:
            rel_inc("drift.baseline_restored", restored)
        return restored

    # -- check ---------------------------------------------------------------

    def check(self, model, X: np.ndarray) -> Optional[Dict[str, Any]]:
        """Compare a window of raw rows against the captured baseline →
        the ``drift`` report section (None without a baseline or with a
        window below ``min_rows``).  Emits a ``drift.alert`` trace
        instant when the verdict is drifted."""
        with self._lock:
            base = self._baselines.get(model.name)
        X = np.atleast_2d(np.asarray(X, np.float64))
        if base is None or X.shape[0] < self.min_rows or X.size == 0:
            return None
        window_counts = _feature_counts(model, X)
        features: List[Dict[str, Any]] = []
        for k, (bc, wc) in enumerate(zip(base.feature_counts,
                                         window_counts)):
            n = max(len(bc), len(wc))
            bc = np.pad(bc, (0, n - len(bc)))
            wc = np.pad(wc, (0, n - len(wc)))
            psi = psi_from_counts(*_psi_groups(bc, wc))
            ks, ks_p = ks_from_counts(bc, wc)
            features.append({
                "feature": base.feature_names[k]
                if k < len(base.feature_names) else f"f{k}",
                "psi": psi, "ks": ks, "ks_p": ks_p,
                "drifted": bool(psi >= self.psi_threshold
                                or (ks >= self.ks_threshold
                                    and ks_p < 0.05))})
        scores = _scores(model, X)
        s_psi = psi_from_counts(base.score_counts,
                                base._bin_scores(scores))
        s_ks, s_ks_p = ks_2samp(base.score_sample,
                                scores[-SCORE_SAMPLE:])
        score = {"psi": s_psi, "ks": s_ks, "ks_p": s_ks_p,
                 "drifted": bool(s_psi >= self.psi_threshold
                                 or (s_ks >= self.ks_threshold
                                     and s_ks_p < 0.05))}
        ranked = sorted(features, key=lambda f: f["psi"], reverse=True)
        top = [f["feature"] for f in ranked[:self.top_k] if f["drifted"]]
        drifted = bool(top or score["drifted"])
        section = {
            "model": base.model_name,
            "version": base.version,
            "baseline_rows": base.rows,
            "window_rows": int(X.shape[0]),
            "psi_threshold": self.psi_threshold,
            "ks_threshold": self.ks_threshold,
            "max_psi": max((f["psi"] for f in features), default=0.0),
            "max_ks": max((f["ks"] for f in features), default=0.0),
            "features": ranked,
            "top_features": top,
            "score": score,
            "drifted": drifted,
        }
        tracer = self.tracer
        with self._lock:
            self._checks += 1
            if drifted:
                self._alerts += 1
            section["checks"] = self._checks
            section["alerts"] = self._alerts
            self._last[model.name] = section
        if drifted:
            from ..reliability.metrics import rel_inc
            rel_inc("serve.drift_alerts")
            if tracer is not None:
                tracer.instant(
                    "drift.alert", cat="serving",
                    args={"model": base.model_name,
                          "top_features": top,
                          "max_psi": section["max_psi"],
                          "max_ks": section["max_ks"],
                          "score_psi": s_psi})
        return section

    # -- export --------------------------------------------------------------

    def section(self, name: str = "default") -> Optional[Dict[str, Any]]:
        """The last check's ``drift`` report section (None before any
        check completed for this model)."""
        with self._lock:
            return self._last.get(name)

    def gauges(self) -> Dict[str, float]:
        """Flat ``serving_drift_*`` gauge map for the Prometheus page —
        the headline verdict across every checked model (max-drift
        model wins the scalar gauges)."""
        with self._lock:
            last = list(self._last.values())
        if not last:
            return {}
        worst = max(last, key=lambda s: s["max_psi"])
        return {
            "serving_drift_drifted":
                1.0 if any(s["drifted"] for s in last) else 0.0,
            "serving_drift_max_psi": float(worst["max_psi"]),
            "serving_drift_max_ks": float(worst["max_ks"]),
            "serving_drift_score_psi": float(worst["score"]["psi"]),
            "serving_drift_score_ks": float(worst["score"]["ks"]),
            "serving_drift_window_rows": float(worst["window_rows"]),
            "serving_drift_checks_total": float(worst["checks"]),
            "serving_drift_alerts_total": float(worst["alerts"]),
        }
