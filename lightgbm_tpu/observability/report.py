"""JSON telemetry report: schema loading, validation, writing.

The schema (``schema.json``, checked in next to this module) is the
contract `bench.py --telemetry-out` and the tier-1 smoke test validate
against.  The validator implements the JSON-Schema subset the schema
actually uses — ``type`` (including type lists), ``required``,
``properties``, ``additionalProperties``-as-schema and ``items`` — so no
external dependency is needed in the container.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

_SCHEMA_PATH = os.path.join(os.path.dirname(__file__), "schema.json")

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "boolean": bool,
    "null": type(None),
}


def load_schema() -> Dict[str, Any]:
    with open(_SCHEMA_PATH) as fh:
        return json.load(fh)


def _type_ok(value: Any, t: str) -> bool:
    if t == "number":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if t == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    py = _TYPES.get(t)
    return py is not None and isinstance(value, py)


def validate_report(report: Any, schema: Dict[str, Any] = None,
                    path: str = "$") -> List[str]:
    """Returns a list of violation strings (empty = valid)."""
    if schema is None:
        schema = load_schema()
    errs: List[str] = []
    t = schema.get("type")
    if t is not None:
        types = t if isinstance(t, list) else [t]
        if not any(_type_ok(report, ti) for ti in types):
            errs.append(f"{path}: expected type {t}, got "
                        f"{type(report).__name__}")
            return errs
    if isinstance(report, dict):
        for key in schema.get("required", ()):
            if key not in report:
                errs.append(f"{path}: missing required key {key!r}")
        props = schema.get("properties", {})
        addl = schema.get("additionalProperties")
        for key, value in report.items():
            if key in props:
                errs.extend(validate_report(value, props[key],
                                            f"{path}.{key}"))
            elif isinstance(addl, dict):
                errs.extend(validate_report(value, addl, f"{path}.{key}"))
    if isinstance(report, list) and "items" in schema:
        for i, item in enumerate(report):
            errs.extend(validate_report(item, schema["items"],
                                        f"{path}[{i}]"))
    return errs


def write_report(report: Dict[str, Any], path: str) -> None:
    """Validate-and-write; a schema violation raises rather than shipping
    a malformed report for a driver to choke on later.  The write is
    atomic (tmp + ``os.replace``) so a crash mid-dump never leaves a
    truncated report for that driver to trip over."""
    errs = validate_report(report)
    if errs:
        raise ValueError("telemetry report violates schema.json: "
                         + "; ".join(errs[:5]))
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
