"""Host-side telemetry accumulator (see package docstring).

Phase timers are HOST wall clocks around host-visible phases (binning,
gradient/tree dispatch, score update, pipeline flush, host tree assembly);
device-side work inside one fused program is attributed through the
per-tree counter vector (``TEL_*``) and, for real device timings, the
opt-in ``profile_trace_dir`` trace.  Everything here is designed so the
enabled path never forces a device sync: per-tree counter vectors arrive
through ``device_telem`` ALREADY ``copy_to_host_async``'d by the caller
and are only materialized in ``flush_device`` — the same cadence at which
the boosting loop materializes tree records.
"""

from __future__ import annotations

import contextlib
import time
import tracemalloc
from typing import Any, Dict, List, Optional

import numpy as np

# -- device counter vector layout (accumulated by the wave learner) ---------
# int32 slots; the vector is carried through the tree program only when
# telemetry is enabled (WaveState.telem is None otherwise).
(TEL_WAVES, TEL_WAVE_SORTS, TEL_WAVE_MEMBERS, TEL_FROZEN_MEMBERS,
 TEL_GROW_SPLITS, TEL_STALL_SPLITS, TEL_STALL_EXTRAS, TEL_STALL_SORT_MODE,
 TEL_POPS, TEL_TOTAL_SPLITS) = range(10)
TEL_NSLOTS = 12  # spare slots so adding a counter never reshapes the lane

TEL_NAMES = {
    TEL_WAVES: "waves",
    TEL_WAVE_SORTS: "wave_sorts",
    TEL_WAVE_MEMBERS: "wave_members",
    TEL_FROZEN_MEMBERS: "frozen_members",
    TEL_GROW_SPLITS: "grow_splits",
    TEL_STALL_SPLITS: "stall_splits",
    TEL_STALL_EXTRAS: "stall_extras",
    TEL_STALL_SORT_MODE: "stall_sort_mode",
    TEL_POPS: "pops",
    TEL_TOTAL_SPLITS: "total_splits",
}

# v2: optional "serving" section (QPS / stage latency / batch occupancy /
# compile-cache — `lightgbm_tpu/serving/batcher.py` ServingStats.report)
# v3: "reliability" section (process-wide failure accounting: retries,
# sheds, fallbacks, aborts, snapshots, injected faults —
# `lightgbm_tpu/reliability/metrics.py`); serving section gains
# shed/fallback counters
# v4: serving section gains "latency_ms" (exact p50/p95/p99 from the
# request latency histogram — `observability/metrics_export.py`)
# v5: optional "lifecycle" section (promotions / rollbacks / shadow
# reports / watchdog state — `lightgbm_tpu/lifecycle/controller.py`);
# serving section gains "errors" (admitted requests answered with an
# error frame)
# v6: serving section gains optional "replicas" array (per-replica fleet
# state: health, in-flight, dispatched, ejections, latency histogram —
# `lightgbm_tpu/serving/fleet/replicas.py`)
# v7: required "provenance" block (platform / jax version / device & host
# counts / emulated-vs-real flag — no more BENCH_r06-style ambiguity about
# what hardware a number came from) and optional "distributed" section
# (per-rank step timings + skew, sampled-sync attribution table, memory
# watermarks, clock-offset handshake — `observability/attribution.py` /
# `observability/podtrace.py`)
# v8: serving section gains "tenants" (per-model-name latency histogram,
# request/error/shed counters and SLO attainment / error-budget burn —
# `serving/batcher.py` TenantStats) and reports gain an optional "drift"
# section (PSI/KS baseline-vs-window verdict over the traffic recorder —
# `observability/drift.py`)
# v9: optional "elastic" section (membership epoch / survivor count set by
# the engine on elastic pods; the per-host controller merges the recovery
# totals — epochs, recoveries, ranks_lost, re-dealt row count, recovery
# wall-time — into the final report, `lightgbm_tpu/elastic/controller.py`)
# v10: optional "autopilot" section (drift-triggered refit daemon: check /
# trigger / suppress / promote / rollback counts, the RefitBudget state and
# the bounded decision history — `lightgbm_tpu/lifecycle/autopilot.py`);
# serving.tenants[] items gain "tenant_shed" (sheds by the tenant's OWN
# admission cap, `reliability/degrade.py` TenantAdmission)
# v11: provenance gains "cost_ledger_sha256" — the sha256 of the checked-in
# static cost-model ledger (`analysis/costs.json`) at report time, so any
# perf artifact can be matched to the exact pinned FLOPs/bytes/exchange
# expectations it was produced under (null when the ledger is absent)
SCHEMA_VERSION = 11


def _cost_ledger_sha256() -> Optional[str]:
    """sha256 of ``analysis/costs.json`` (the static cost-model ledger),
    or None when the ledger is not checked in."""
    import hashlib
    try:
        from ..analysis.common import COSTS_PATH
        with open(COSTS_PATH, "rb") as fh:
            return hashlib.sha256(fh.read()).hexdigest()
    except (OSError, ImportError):
        return None


def provenance_section(extra: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
    """The required schema-v7 ``provenance`` block: what hardware and
    software stack produced this report.  ``emulated`` is True whenever
    the accelerator platform is NOT a real TPU (CPU runs, forced-host
    virtual device pods) — the flag the BENCH/MULTICHIP writers assert on
    so a CPU-parity number can never masquerade as a device result."""
    out: Dict[str, Any] = {
        "platform": "unknown", "device_kind": "unknown",
        "jax_version": "unknown", "num_devices": 0, "num_hosts": 1,
        "process_index": 0, "emulated": True, "mesh_shape": None,
        "cost_ledger_sha256": _cost_ledger_sha256(),
    }
    try:
        import jax
        out["jax_version"] = str(jax.__version__)
        devs = jax.devices()
        out["platform"] = str(devs[0].platform)
        out["device_kind"] = str(getattr(devs[0], "device_kind",
                                         devs[0].platform))
        out["num_devices"] = int(jax.device_count())
        out["num_hosts"] = int(jax.process_count())
        out["process_index"] = int(jax.process_index())
        out["emulated"] = out["platform"] != "tpu"
    except Exception:
        pass
    if extra:
        out.update({k: v for k, v in extra.items() if v is not None})
    return out


def memory_watermarks() -> Dict[str, Any]:
    """Device HBM peaks (``memory_stats()``; absent on backends that
    don't expose them — CPU) and the process tracemalloc snapshot when
    the caller has tracing on.  Host-only, never forces a device sync."""
    devices = []
    try:
        import jax
        for d in jax.local_devices():
            try:
                st = d.memory_stats()
            except Exception:
                st = None
            if not st:
                continue
            devices.append({
                "device": str(d),
                "peak_bytes_in_use": int(st.get("peak_bytes_in_use", 0)),
                "bytes_in_use": int(st.get("bytes_in_use", 0)),
                "bytes_limit": int(st.get("bytes_limit", 0)),
            })
    except Exception:
        pass
    host = None
    if tracemalloc.is_tracing():
        cur, peak = tracemalloc.get_traced_memory()
        host = {"current_bytes": int(cur), "peak_bytes": int(peak)}
    return {"devices": devices, "host_heap": host}


class Telemetry:
    """Accumulates phases / counters / gauges and builds the JSON report."""

    def __init__(self, enabled: bool):
        self.enabled = bool(enabled)
        # optional span recorder (observability/trace.py): when attached,
        # every phase occurrence that carries a start stamp also lands as
        # a trace span, so the Perfetto timeline and the phase table are
        # two views of the same measurements
        self.tracer = None
        self._phases: Dict[str, List[float]] = {}  # name -> [sum_s, n, max_s]
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, Any] = {}
        self._iter_wall: List[float] = []          # bounded ring, seconds
        self._iter_total = 0.0
        self._iter_count = 0
        self._pending: List[Any] = []              # async-copied device telem
        self._device_totals = np.zeros(TEL_NSLOTS, np.int64)
        self._device_trees = 0
        self._last_tree: Optional[np.ndarray] = None
        # schema-v7 additions: provenance extras (mesh shape, learner name
        # — facts only the engine/GBDT knows), the distributed section
        # (rank skew, clock handshake) and per-phase tracemalloc peaks
        self._provenance_extra: Dict[str, Any] = {}
        self._distributed: Dict[str, Any] = {}
        self._elastic: Dict[str, Any] = {}
        self._phase_heap: Dict[str, int] = {}      # name -> peak bytes
        self._heap_stack: List[int] = []

    # -- phases --------------------------------------------------------------

    def phase(self, name: str):
        """Context manager timing one phase occurrence (no-op when
        disabled)."""
        if not self.enabled:
            return contextlib.nullcontext()
        return _PhaseCtx(self, name)

    def add_phase_time(self, name: str, seconds: float,
                       t0: Optional[float] = None) -> None:
        """Accumulate one phase occurrence.  ``t0`` (a ``perf_counter``
        stamp) additionally records the occurrence as a trace span when a
        recorder is attached; without it the time lands in the phase
        table only (some callers measure durations whose start they no
        longer hold)."""
        if not self.enabled:
            return
        st = self._phases.setdefault(name, [0.0, 0, 0.0])
        st[0] += seconds
        st[1] += 1
        st[2] = max(st[2], seconds)
        tr = self.tracer
        if tr is not None and t0 is not None:
            tr.add_complete(name, t0, seconds, cat="phase")
        if name == "iteration":
            self._iter_total += seconds
            self._iter_count += 1
            self._iter_wall.append(seconds)
            if len(self._iter_wall) > 512:
                del self._iter_wall[:256]

    # -- host-heap watermarks (per phase) ------------------------------------
    # tracemalloc's peak is global-since-start; per-phase window peaks use
    # reset_peak() with explicit propagation to the enclosing phase, so a
    # nested phase's reset never loses the parent's window high-water mark.
    # Only active when the USER already turned tracemalloc on — telemetry
    # never starts tracing itself (it costs ~2x on every allocation).

    def _heap_enter(self) -> None:
        if not tracemalloc.is_tracing():
            return
        try:
            tracemalloc.reset_peak()
        except Exception:   # pragma: no cover — <3.9 has no reset_peak
            return
        self._heap_stack.append(0)

    def _heap_exit(self, name: str) -> None:
        if not self._heap_stack or not tracemalloc.is_tracing():
            return
        try:
            wpeak = max(tracemalloc.get_traced_memory()[1],
                        self._heap_stack.pop())
            self._phase_heap[name] = max(self._phase_heap.get(name, 0),
                                         int(wpeak))
            if self._heap_stack:
                self._heap_stack[-1] = max(self._heap_stack[-1], wpeak)
            tracemalloc.reset_peak()
        except Exception:   # pragma: no cover
            pass

    # -- counters / gauges ---------------------------------------------------

    def inc(self, name: str, v: int = 1) -> None:
        if self.enabled:
            self._counters[name] = self._counters.get(name, 0) + int(v)

    def gauge(self, name: str, v: Any) -> None:
        if self.enabled:
            self._gauges[name] = v

    # -- distributed / provenance extras -------------------------------------

    def set_provenance(self, **kw: Any) -> None:
        """Merge engine/GBDT-known facts (mesh_shape, tree_learner, ...)
        into the report's ``provenance`` block."""
        if self.enabled:
            self._provenance_extra.update(kw)

    def set_distributed(self, **kw: Any) -> None:
        """Merge pod facts (rank step timings, skew, clock handshake) into
        the report's ``distributed`` section."""
        if self.enabled:
            self._distributed.update(kw)

    def set_elastic(self, **kw: Any) -> None:
        """Merge elastic-pod facts (membership epoch, survivor count,
        recovery totals) into the report's optional ``elastic`` section."""
        if self.enabled:
            self._elastic.update(kw)

    def last_iteration_s(self) -> Optional[float]:
        """Duration of the most recent "iteration" phase occurrence — the
        per-rank step timing that rides the liveness heartbeat."""
        return self._iter_wall[-1] if self._iter_wall else None

    # -- device counter lane -------------------------------------------------

    def device_telem(self, arr) -> None:
        """Queue one per-tree counter vector.  The caller must have issued
        ``copy_to_host_async`` on it alongside the tree's record arrays."""
        if self.enabled and arr is not None:
            self._pending.append(arr)

    def flush_device(self) -> None:
        """Materialize queued counter vectors (host-resident after the
        async copies — the same ~0.2 ms fetch the record flush pays)."""
        if not self._pending:
            return
        pend, self._pending = self._pending, []
        for a in pend:
            v = np.asarray(a).astype(np.int64)
            n = min(len(v), TEL_NSLOTS)
            self._device_totals[:n] += v[:n]
            self._device_trees += 1
            self._last_tree = v[:n]

    # -- report --------------------------------------------------------------

    def device_counters(self) -> Dict[str, int]:
        out = {name: int(self._device_totals[idx])
               for idx, name in TEL_NAMES.items()}
        out["trees_measured"] = self._device_trees
        # derived: every correction event splits exactly one stalled TOP,
        # the rest are speculative extras (see learner_wave._replay)
        events = out["stall_splits"] - out["stall_extras"]
        out["stall_events"] = events
        out["sim_passes"] = events + self._device_trees
        return out

    def report(self, ledger=None, extra_gauges: Optional[Dict] = None,
               light: bool = False) -> Dict[str, Any]:
        if not light:
            self.flush_device()
        dev = self.device_counters()
        counters = dict(self._counters)
        counters.update(dev)
        gauges = dict(self._gauges)
        if extra_gauges:
            gauges.update(extra_gauges)
        phases = {
            name: {"total_ms": st[0] * 1e3, "count": st[1],
                   "max_ms": st[2] * 1e3}
            for name, st in self._phases.items()}
        it = {
            "count": self._iter_count,
            "total_ms": self._iter_total * 1e3,
            "mean_ms": (self._iter_total / self._iter_count * 1e3
                        if self._iter_count else 0.0),
            "last_ms": (self._iter_wall[-1] * 1e3
                        if self._iter_wall else 0.0),
        }
        coll = self._collectives(ledger, dev)
        # failure accounting travels with every report (training AND
        # serving) — the section is process-wide by design
        from ..reliability.metrics import reliability_section
        rep = {"schema_version": SCHEMA_VERSION, "enabled": self.enabled,
               "phases": phases, "iterations": it, "counters": counters,
               "gauges": gauges, "collectives": coll,
               "provenance": provenance_section(self._provenance_extra),
               "distributed": self._distributed_section(phases),
               "reliability": reliability_section()}
        if self._elastic:
            rep["elastic"] = dict(self._elastic)
        return rep

    def _distributed_section(self, phases_ms: Dict[str, Any]
                             ) -> Dict[str, Any]:
        """Schema-v7 ``distributed`` section: rank skew + clock handshake
        (set by the engine via :meth:`set_distributed`), the sampled-sync
        attribution table derived from the ``sync.*`` phases, and memory
        watermarks."""
        out: Dict[str, Any] = dict(self._distributed)
        from .attribution import attribution_table
        table = attribution_table(phases_ms)
        if table is not None:
            out["attribution"] = table
        mem = memory_watermarks()
        if self._phase_heap:
            mem["phase_heap_peak_bytes"] = dict(self._phase_heap)
        out["memory"] = mem
        return out

    def _collectives(self, ledger, dev: Dict[str, int]) -> Dict[str, Any]:
        sites = list(ledger.sites()) if ledger is not None else []
        trees = max(dev.get("trees_measured", 0), 0)
        # per-tree execution estimates from the decoded counters; cadences
        # the counters don't cover report count/bytes as null
        per_tree = {
            "tree": 1.0,
            "wave": dev["waves"] / trees if trees else None,
            "stall_event": dev["stall_events"] / trees if trees else None,
            "split": dev["total_splits"] / trees if trees else None,
        }
        total_count = 0.0
        total_bytes = 0.0
        known = True
        for s in sites:
            mult = per_tree.get(s["cadence"])
            if mult is None:
                known = False
                continue
            total_count += mult
            total_bytes += mult * s["bytes_per_call"]
        totals = {"count": total_count if (sites and known) else
                  (total_count or None),
                  "bytes": total_bytes if (sites and known) else
                  (total_bytes or None)}
        return {"sites": sites, "per_tree_estimate": totals,
                # the batched stall correction reduces K stacked member
                # histograms in ONE collective; each extra member is one
                # collective the round-5 per-member loop would have issued
                "saved_by_stall_batching": dev["stall_extras"]}


class _PhaseCtx:
    __slots__ = ("tel", "name", "t0")

    def __init__(self, tel: Telemetry, name: str):
        self.tel = tel
        self.name = name

    def __enter__(self):
        self.tel._heap_enter()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.tel.add_phase_time(self.name, time.perf_counter() - self.t0,
                                t0=self.t0)
        self.tel._heap_exit(self.name)
        return False
