"""Runtime collective & phase attribution (schema v7, ROADMAP item 1).

The phase table (`telemetry.py`) times host-visible dispatch windows, and
the CollectiveLedger records trace-time collective SITES — neither says
where device time actually goes.  This module adds the three runtime
attribution mechanisms the BENCH rounds need:

  * **Sampled-sync timer** (``telemetry_sync_every=N``): every Nth
    iteration the boosting loop drains the dispatch queue, then brackets
    each leg of the iteration (gradients, tree build, score update) with
    a forced device sync, landing ``sync.*`` phases whose per-leg means
    sum to the synced iteration wall.  Amortized: N-1 of every N
    iterations stay fully async, so the pipeline measurements and the
    training throughput coexist in one run.  ``force_sync`` is
    ``jax.block_until_ready`` **plus a one-element fetch** — on the
    remote axon tunnel ``block_until_ready`` alone returns before the
    device queue drains (see bench.py / profiling/PROFILE.md round 10),
    so every timing in this repo syncs by fetching one scalar.
  * **Exchange-window probe**: the sharded learners expose their REAL
    exchange seam (`exchange_probe` — the per-wave psum_scatter, the
    2D word-select psum, the voting all_gather) as a standalone jitted
    program over a representative zero buffer; timing it isolates the
    collective leg the fused program hides.  The probe jits are outside
    the analysis gate's traced-program set and the ledger is muted while
    they trace, so budgets.json and ``collectives.sites`` are unchanged.
  * **jax.profiler capture-and-parse** (``parse_profiler_trace``):
    best-effort scan of a ``profile_trace_dir`` for Chrome-format
    ``*.trace.json[.gz]`` files, mapping device op names back to the
    named legs the ledger knows (hist / exchange / scan / partition /
    flush).  Returns None when only ``*.xplane.pb`` exists (no protobuf
    dependency is added for it).

Everything here is host-only and lives in ``observability/`` — never
imported into a traced function — so the LGB005 wall-clock discipline
holds: these perf_counter reads can never bake a constant into a
compiled program (allowlisted with that verdict in
``analysis/allowlist.json``).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

# legs the attribution table and profiler parse speak in (the ledger's
# phase vocabulary: histogram build, cross-device exchange, split scan,
# row partition, host record flush)
LEGS = ("hist", "exchange", "scan", "partition", "flush")

# sync.* phases that are NOT iteration legs: the iteration wall itself,
# the pre-iteration queue drain, and the standalone exchange probe
_NON_LEG_SYNC = ("sync.iteration", "sync.drain", "sync.exchange_probe")

# per-iteration host phases folded into the table so the leg sum tracks
# the full iteration wall (they run on every iteration; their global
# means estimate their share of a sampled one).  ``tree_train`` is the
# non-pipelined sync path's fully-host-synchronous tree build.
_HOST_LEGS = ("bagging", "tree_dispatch", "score_update",
              "pipeline_flush", "tree_assemble", "tree_train")

# host phases whose window is a strict prefix of a sync leg's
# [dispatch, completion] window — when that sync leg was recorded,
# counting the host phase too would double-count the dispatch time
_HOST_SHADOWED = {"tree_dispatch": "sync.tree_build",
                  "score_update": "sync.score_update",
                  "tree_train": "sync.tree_train"}


def force_sync(*arrays: Any) -> None:
    """Block until every array's value is actually available.

    ``jax.block_until_ready`` alone is NOT a sync on the remote axon
    tunnel (it returns once the dispatch is acknowledged, not executed);
    fetching one element forces the queue to drain.  The fetch costs one
    small transfer (~0.2 ms pre-copied, ~105 ms cold on the tunnel) —
    only ever paid on sampled iterations.
    """
    import jax
    last = None
    for a in arrays:
        if a is None or not hasattr(a, "shape"):
            continue
        jax.block_until_ready(a)
        last = a
    if last is not None:
        np.asarray(last.ravel()[:1] if getattr(last, "ndim", 0) else last)


def timeit(fn: Callable, *args: Any, iters: int = 5, warmup: int = 2,
           sync: Optional[Callable[[Any], None]] = None) -> float:
    """Best-of-``iters`` seconds for one synced call of ``fn(*args)`` —
    THE timing implementation (profiling/profile_phases.py,
    profile_wave_phases.py and the exchange probe all route here).

    ``sync`` overrides the default ``force_sync`` on the result (callers
    whose output pytree needs a specific leaf fetched pass their own).
    """
    do_sync = sync if sync is not None else \
        (lambda out: force_sync(*_leaves(out)))
    for _ in range(max(warmup, 0)):
        do_sync(fn(*args))
    best = float("inf")
    for _ in range(max(iters, 1)):
        t0 = time.perf_counter()
        do_sync(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _leaves(out: Any) -> List[Any]:
    if out is None:
        return []
    if isinstance(out, (tuple, list)):
        return [a for a in out if hasattr(a, "shape")]
    return [out] if hasattr(out, "shape") else []


class SampledSync:
    """The boosting loop's sampled-sync bracket (``telemetry_sync_every``).

    ``sampled(iter_)`` is True on every Nth iteration; while active the
    GBDT paths call :meth:`leg` after each dispatch to force-sync that
    leg's outputs and record a ``sync.<name>`` phase.  All ranks of a pod
    evaluate ``sampled`` on the lockstep iteration counter, so the
    exchange probe's collective program is entered pod-wide together.
    """

    def __init__(self, tel, every: int):
        self.tel = tel
        self.every = max(int(every), 0)
        self.active = False

    def sampled(self, iter_: int) -> bool:
        return self.every > 0 and self.tel.enabled \
            and (iter_ % self.every == 0)

    def leg(self, name: str, t0: float, arrays: Sequence[Any]) -> None:
        """Force-sync ``arrays`` and record ``sync.<name>`` covering
        dispatch start ``t0`` → completion."""
        if not self.active:
            return
        force_sync(*arrays)
        self.tel.add_phase_time(f"sync.{name}",
                                time.perf_counter() - t0, t0=t0)

    def drain(self, *arrays: Any) -> None:
        """Pre-iteration queue drain so the bracketed iteration measures
        only its own work (recorded as ``sync.drain``, excluded from the
        leg table)."""
        t0 = time.perf_counter()
        force_sync(*arrays)
        self.tel.add_phase_time("sync.drain", time.perf_counter() - t0,
                                t0=t0)

    def probe_exchange(self, learner) -> None:
        """Time the learner's exchange-window probe (one representative
        collective, best-of-3) and record it as ``sync.exchange_probe``
        plus an ``exchange_probe_ms`` gauge.  No-op for learners without
        an exchange seam (the serial paths)."""
        probe = getattr(learner, "exchange_probe", None)
        if probe is None:
            return
        try:
            fn_args = probe()
            if fn_args is None:
                return
            fn, args = fn_args
            t0 = time.perf_counter()
            best = timeit(fn, *args, iters=3, warmup=1)
        except Exception:
            # best-effort: a probe that fails to trace (e.g. quantized
            # scales not established yet) must never kill training
            return
        self.tel.add_phase_time("sync.exchange_probe",
                                time.perf_counter() - t0, t0=t0)
        self.tel.gauge("exchange_probe_ms", best * 1e3)


def attribution_table(phases_ms: Dict[str, Dict[str, float]]
                      ) -> Optional[Dict[str, Any]]:
    """The per-leg attribution table from the ``sync.*`` phases of a
    report's ``phases`` section (``{name: {total_ms, count, max_ms}}``).

    Legs are per-iteration means: every ``sync.<leg>`` phase divided by
    the sampled-iteration count, plus the per-iteration host phases
    (bagging, flush, assembly) at their own means.  ``coverage`` is
    leg-sum / synced iteration wall — the acceptance bar is |1 - coverage|
    <= 0.1.  Returns None when no sampled iteration ran.
    """
    it = phases_ms.get("sync.iteration")
    if not it or not it.get("count"):
        return None
    n = int(it["count"])
    wall_ms = it["total_ms"] / n
    legs: Dict[str, float] = {}
    for name, st in phases_ms.items():
        if not name.startswith("sync.") or name in _NON_LEG_SYNC:
            continue
        legs[name[len("sync."):]] = st["total_ms"] / n
    for name in _HOST_LEGS:
        if _HOST_SHADOWED.get(name) in phases_ms:
            continue
        st = phases_ms.get(name)
        if st and st.get("count"):
            legs[f"host.{name}"] = st["total_ms"] / st["count"]
    legs_sum = sum(legs.values())
    probe = phases_ms.get("sync.exchange_probe")
    return {
        "sampled_iterations": n,
        "iteration_ms": wall_ms,
        "legs_ms": legs,
        "legs_sum_ms": legs_sum,
        "coverage": (legs_sum / wall_ms) if wall_ms > 0 else 0.0,
        "unattributed_ms": wall_ms - legs_sum,
        "exchange_probe_ms": (probe["total_ms"] / probe["count"]
                              if probe and probe.get("count") else None),
    }


# -- jax.profiler capture & parse --------------------------------------------

# device-op name -> leg mapping, first match wins.  The names are XLA HLO
# op names (TPU) / thunk names (CPU) — substring regexes keep this robust
# across backend renames; unmatched ops land in "other".
_LEG_PATTERNS = [
    ("exchange", re.compile(
        r"all-reduce|reduce-scatter|all-gather|collective|all-to-all"
        r"|psum|ppermute", re.I)),
    ("hist", re.compile(r"hist|one.?hot|scatter|segment|dot|conv", re.I)),
    ("partition", re.compile(r"sort|partition|gather|dynamic-slice", re.I)),
    ("scan", re.compile(r"while|scan|reduce|select|arg.?max|cumsum", re.I)),
    ("flush", re.compile(r"copy|transfer|infeed|outfeed|donat", re.I)),
]


def _profiler_trace_files(trace_dir: str) -> List[str]:
    pats = [os.path.join(trace_dir, "**", "*.trace.json.gz"),
            os.path.join(trace_dir, "**", "*.trace.json")]
    out: List[str] = []
    for p in pats:
        out.extend(glob.glob(p, recursive=True))
    return sorted(out)


def parse_profiler_trace(trace_dir: str, top_k: int = 20
                         ) -> Optional[Dict[str, Any]]:
    """Map a ``jax.profiler`` Chrome trace's device events to the named
    legs.  Best-effort: returns None when the directory holds no
    Chrome-format trace (some backends emit only ``*.xplane.pb``, whose
    protobuf schema this repo deliberately does not depend on)."""
    files = _profiler_trace_files(trace_dir)
    if not files:
        return None
    path = files[-1]             # newest capture wins (sorted run dirs)
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt") as fh:
                data = json.load(fh)
        else:
            with open(path) as fh:
                data = json.load(fh)
    except Exception:
        return None
    events = data.get("traceEvents", [])
    legs = {leg: 0.0 for leg, _ in _LEG_PATTERNS}
    legs["other"] = 0.0
    per_op: Dict[str, float] = {}
    total_us = 0.0
    n = 0
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        name = str(ev.get("name", ""))
        dur = float(ev["dur"])
        total_us += dur
        n += 1
        per_op[name] = per_op.get(name, 0.0) + dur
        for leg, pat in _LEG_PATTERNS:
            if pat.search(name):
                legs[leg] += dur
                break
        else:
            legs["other"] += dur
    if n == 0:
        return None
    top = dict(sorted(per_op.items(), key=lambda kv: -kv[1])[:top_k])
    return {"source": path, "events": n,
            "total_ms": total_us / 1e3,
            "legs_ms": {k: v / 1e3 for k, v in legs.items()},
            "top_ops_ms": {k: v / 1e3 for k, v in top.items()}}


def attribute_profile(trace_dir: str, ledger=None
                      ) -> Optional[Dict[str, Any]]:
    """``parse_profiler_trace`` plus a cross-check of its exchange leg
    against the ledger's static collective sites: every site op name the
    profile's collective events matched is listed, so a site with zero
    runtime evidence (dead code, wrong cadence estimate) is visible."""
    prof = parse_profiler_trace(trace_dir)
    if prof is None:
        return None
    sites = list(ledger.sites()) if ledger is not None else []
    if sites:
        pat = _LEG_PATTERNS[0][1]
        matched_ops = [op for op in prof["top_ops_ms"] if pat.search(op)]
        prof["ledger_sites"] = [s["op"] for s in sites]
        prof["collective_ops_seen"] = matched_ops
    return prof
