"""Pod-wide flight recorder: per-rank trace export + cross-host merge.

Every host of a pod runs its own ``TraceRecorder`` through training
(iteration / heartbeat / ingestion-chunk / exchange-window spans), but
each recorder's timeline is relative to its own ``perf_counter`` epoch —
two hosts' traces can't be laid side by side without knowing how their
clocks relate.  This module closes that gap:

  * ``estimate_clock_offset(net)`` — a Cristian-style ping handshake
    over the DistributedNet KV store (the same coordinator channel the
    liveness heartbeat rides): each round, every rank posts its send
    stamp into one allgather and stamps the return; rank 0's send stamp
    fell inside the local [send, recv] window, so the midpoint estimates
    the local-vs-rank-0 clock delta with error bounded by RTT/2.  The
    minimum-RTT round wins (NTP's selection rule).  Rank 0's offset is 0
    by definition.
  * ``export_rank_trace(tracer, path, net)`` — stamps the handshake
    results (rank, process_count, offset, RTT, the recorder epoch
    expressed on rank 0's clock) into the trace's ``otherData`` and
    writes ``<path>.rank<r>`` (single-host runs keep the plain path).
  * ``merge_pod_trace(paths, out)`` — ONE pod-wide Chrome trace: each
    rank's events shift by a constant (its aligned epoch minus the
    merge base), which preserves B/E well-nesting exactly; pids are
    rewritten to ranks with ``process_name`` metadata so Perfetto shows
    one track group per host; the per-rank offsets land in the merged
    ``otherData`` for auditability.

Host-only, monotonic clocks only (perf_counter — the recorder's own
clock); nothing here is ever traced into an XLA program (LGB005 verdict
recorded in ``analysis/allowlist.json``).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Union

#: event-phase sort rank keeping per-(pid,tid) streams well-nested when
#: the merged list is stably re-sorted by timestamp: metadata first, at
#: equal ts an E precedes a B (trace.py's export tie-break); equal keys
#: keep each rank's already-correct original order (stable sort)
_PH_RANK = {"M": -1, "E": 0, "B": 1, "i": 2}


def estimate_clock_offset(net, rounds: int = 8) -> Dict[str, Any]:
    """Estimate this rank's perf_counter delta vs rank 0 over ``net``
    (a ``parallel.multihost.DistributedNet``).  Returns
    ``{offset_s, rtt_s, rounds, method}`` — ``offset_s`` is (this rank's
    clock) − (rank 0's clock), 0.0 exactly on rank 0."""
    best_rtt = float("inf")
    best_off = 0.0
    for _ in range(max(int(rounds), 1)):
        t_send = time.perf_counter()
        stamps = net.allgather(("clk", int(net.rank), float(t_send)))
        t_recv = time.perf_counter()
        rtt = t_recv - t_send
        if rtt >= best_rtt:
            continue
        best_rtt = rtt
        # rank 0 posted its stamp somewhere inside our [send, recv]
        # window; the midpoint correspondence bounds the error by rtt/2
        s0 = float(stamps[0][2])
        best_off = (t_send + t_recv) / 2.0 - s0
    if int(net.rank) == 0:
        best_off = 0.0          # rank 0 IS the reference clock
    return {"offset_s": best_off, "rtt_s": best_rtt,
            "rounds": int(rounds), "method": "kv-ping-midpoint"}


def rank_trace_path(base: str, rank: int, process_count: int) -> str:
    """Per-rank trace file name: ``<base>.rank<r>`` on a pod, ``base``
    unchanged single-host (so existing single-host flows keep their
    output path)."""
    return f"{base}.rank{int(rank)}" if process_count > 1 else base


def export_rank_trace(tracer, base_path: str, net=None,
                      clock: Optional[Dict[str, Any]] = None) -> str:
    """Stamp pod/clock metadata into ``tracer`` and save its trace to the
    per-rank path.  With ``net=None`` (single host) the clock metadata
    degenerates to offset 0.  ``clock`` reuses an already-run
    ``estimate_clock_offset`` result (the engine shares one handshake
    between the trace metadata and the report's ``distributed.clock``).
    Returns the path written."""
    rank = int(net.rank) if net is not None else 0
    nproc = int(net.num_machines) if net is not None else 1
    clk = clock if clock is not None else (
        estimate_clock_offset(net) if net is not None else
        {"offset_s": 0.0, "rtt_s": 0.0, "rounds": 0, "method": "local"})
    # the recorder epoch expressed on rank 0's clock: the merge aligns
    # timelines by differencing these, so no rank needs to know another's
    # epoch at export time
    aligned_epoch_us = (tracer.epoch - clk["offset_s"]) * 1e6
    tracer.set_metadata(
        rank=rank, process_count=nproc,
        clock_offset_us=clk["offset_s"] * 1e6,
        clock_rtt_us=clk["rtt_s"] * 1e6,
        clock_sync=clk["method"],
        aligned_epoch_us=aligned_epoch_us)
    path = rank_trace_path(base_path, rank, nproc)
    tracer.save(path)
    return path


def _load(obj: Union[str, Dict[str, Any]]) -> Dict[str, Any]:
    if isinstance(obj, dict):
        return obj
    with open(obj) as fh:
        return json.load(fh)


def merge_pod_trace(traces: Sequence[Union[str, Dict[str, Any]]],
                    out: Optional[str] = None) -> Dict[str, Any]:
    """Merge per-rank Chrome traces into ONE pod-wide trace.

    Each input is a path or an already-loaded export dict carrying the
    ``export_rank_trace`` metadata.  All of one rank's timestamps shift
    by the same constant (aligned epoch minus the merge base), so span
    nesting is preserved exactly; traces without metadata merge at
    offset 0 with their list index as the rank.  Writes ``out``
    atomically when given; returns the merged trace object."""
    loaded: List[Dict[str, Any]] = [_load(t) for t in traces]
    ranks_meta: List[Dict[str, Any]] = []
    for i, tr in enumerate(loaded):
        od = tr.get("otherData", {})
        ranks_meta.append({
            "rank": int(od.get("rank", i)),
            "aligned_epoch_us": float(od.get("aligned_epoch_us", 0.0)),
            "clock_offset_us": float(od.get("clock_offset_us", 0.0)),
            "clock_rtt_us": float(od.get("clock_rtt_us", 0.0)),
            "dropped_spans": int(od.get("dropped_spans", 0)),
        })
    base = min((m["aligned_epoch_us"] for m in ranks_meta), default=0.0)
    merged: List[tuple] = []     # (sort_key, seq, event)
    seq = 0
    for tr, meta in zip(loaded, ranks_meta):
        shift = meta["aligned_epoch_us"] - base
        rank = meta["rank"]
        pid_orig = None
        for ev in tr.get("traceEvents", []):
            ev = dict(ev)
            ph = ev.get("ph")
            if pid_orig is None:
                pid_orig = ev.get("pid")
            ev["pid"] = rank
            if ph == "M":
                # keep per-thread names; the process row is named below
                merged.append(((float("-inf"), _PH_RANK["M"]), seq, ev))
                seq += 1
                continue
            ts = float(ev.get("ts", 0.0)) + shift
            ev["ts"] = ts
            merged.append(((ts, _PH_RANK.get(ph, 3)), seq, ev))
            seq += 1
        name_ev = {"name": "process_name", "ph": "M", "pid": rank,
                   "args": {"name": f"rank {rank}"
                            + (f" (pid {pid_orig})"
                               if pid_orig is not None else "")}}
        merged.append(((float("-inf"), _PH_RANK["M"]), -1, name_ev))
    merged.sort(key=lambda e: (e[0], e[1]))
    result = {
        "traceEvents": [ev for _, _, ev in merged],
        "displayTimeUnit": "ms",
        "otherData": {
            "clock": "perf_counter",
            "pod_merge": True,
            "process_count": len(loaded),
            "ranks": ranks_meta,
        },
    }
    if out:
        tmp = out + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(result, fh)
            fh.write("\n")
        os.replace(tmp, out)
    return result


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``python -m lightgbm_tpu.observability.podtrace OUT
    RANK_TRACE [RANK_TRACE ...]`` — merge per-rank traces into OUT."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) < 2:
        print("usage: python -m lightgbm_tpu.observability.podtrace "
              "OUT RANK_TRACE [RANK_TRACE ...]", file=sys.stderr)
        return 2
    out, paths = argv[0], argv[1:]
    merged = merge_pod_trace(paths, out=out)
    n_ev = len(merged["traceEvents"])
    print(f"merged {len(paths)} rank trace(s), {n_ev} events -> {out}")
    return 0


if __name__ == "__main__":      # pragma: no cover — CLI shim
    raise SystemExit(main())
