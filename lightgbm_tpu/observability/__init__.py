"""Structured training telemetry.

The perf trajectory so far (BENCH_r01-r05) was driven by one-off scripts
under ``profiling/`` and hand-done ablation arithmetic; the library itself
measured nothing.  This package is the first-class observability layer the
boosting loop and tree learners report through:

  * ``Telemetry`` — host wall timers per phase, per-iteration timing, and
    the host-side decode of the per-tree device counter vector the wave
    learner accumulates on device (``learner_wave.TEL_*``).  The counter
    vector rides the SAME ``copy_to_host_async`` flush as the per-tree
    record arrays, so enabling telemetry adds zero host syncs to the hot
    path; with ``telemetry=False`` the learners trace the exact same jaxpr
    as before (the counter lane is ``None`` and never enters the program).
  * ``CollectiveLedger`` — trace-time accounting of every collective the
    sharded learners issue (op, payload bytes, phase, cadence).  Dynamic
    per-tree totals are estimated by combining the static sites with the
    decoded wave/stall counters.
  * ``report`` — the JSON report schema (``schema.json``, checked in and
    validated by the tier-1 smoke test) plus a dependency-free validator.
    Schema v2 adds the optional ``serving`` section that the prediction
    service (`lightgbm_tpu/serving/`) reports QPS, queue/bin/traverse/unpad
    stage latency, batch occupancy and compile-cache hits through.
    Schema v3 adds the ``reliability`` section — the process-wide failure
    accounting (connect retries, collective aborts, shed requests, host
    fallbacks, snapshots written/pruned, injected faults) maintained by
    `lightgbm_tpu/reliability/metrics.py`.

  * ``TraceRecorder`` (`trace.py`) — request-scoped structured spans: a
    thread-safe monotonic-clock ring buffer exporting Chrome trace-event
    JSON (open in Perfetto).  Training phase timers and the serving
    queue→pad→bin→traverse→unpad stages land as spans automatically when
    a recorder is attached (``Telemetry.tracer``); serving requests carry
    a ``trace_id`` end-to-end so one id links the request span, its
    micro-batch span and the batch's stage spans.
  * ``LatencyHistogram`` / Prometheus export (`metrics_export.py`) —
    log-bucketed latency histograms with exact p50/p95/p99 over a bounded
    raw-sample window, and the text-format snapshot behind the server's
    ``metrics`` op.  Schema v4 adds the serving ``latency_ms`` section.

Schema v7 adds the distributed-training layer (ROADMAP items 1 & 2):

  * ``attribution`` (`attribution.py`) — the sampled-sync timer
    (``telemetry_sync_every``: every Nth iteration brackets each leg of
    the jitted step with a forced sync), the exchange-window probe the
    sharded learners expose, the per-leg attribution table, and the
    best-effort ``jax.profiler`` Chrome-trace parse.  One timing
    implementation (``timeit``/``force_sync``) shared with the
    ``profiling/`` scripts.
  * ``podtrace`` (`podtrace.py`) — the pod flight recorder: per-rank
    trace export with a KV-store clock-offset handshake, and the merge
    of N per-rank traces into ONE pod-wide Chrome trace.
  * every report carries a required ``provenance`` block (platform /
    jax version / host count / emulated-vs-real) and a ``distributed``
    section (rank skew, attribution table, memory watermarks);
    ``training_prometheus`` renders it as ``lgbt_training_*`` gauges.

Schema v8 adds the fleet-serving monitoring layer (ROADMAP items 3c & 4
prerequisite):

  * ``drift`` (`drift.py`) — PSI and two-sample-KS detectors over
    per-feature bin-index distributions (through the serving binner's
    existing bins) and score distributions; baselines are captured from
    the traffic recorder at promote time and later windows are compared
    against them, emitting the optional ``drift`` report section,
    ``lgbt_serving_drift_*`` gauges and ``drift.alert`` trace instants.
  * per-tenant SLO metrics (`serving/batcher.py` ``TenantStats``) — a
    per-model-name latency histogram + request/error/shed counters with
    SLO attainment and error-budget burn, reported as
    ``serving.tenants[]`` and scraped as ``lgbt_serving_tenant_*``
    series; the fleet gateway additionally answers plain-HTTP
    ``GET /metrics`` on its serving port.

Device-side *time* attribution inside the fused tree program is out of
scope for counters — that is what the opt-in ``profile_trace_dir``
(`jax.profiler`) trace is for; see README "Telemetry & profiling" and
"Tracing & service metrics".
"""

from .attribution import (SampledSync, attribution_table, force_sync,
                          parse_profiler_trace, timeit)
from .collectives import CollectiveLedger
from .drift import DriftMonitor, ks_2samp, ks_from_counts, psi_from_counts
from .metrics_export import (BENCH_SERVING_SCHEMA, LatencyHistogram,
                             prometheus_text, training_prometheus)
from .podtrace import estimate_clock_offset, export_rank_trace, \
    merge_pod_trace
from .report import load_schema, validate_report, write_report
from .telemetry import TEL_NAMES, Telemetry, provenance_section
from .trace import (TraceRecorder, get_global_tracer, new_trace_id,
                    set_global_tracer)

__all__ = ["Telemetry", "CollectiveLedger", "TEL_NAMES",
           "load_schema", "validate_report", "write_report",
           "TraceRecorder", "new_trace_id", "LatencyHistogram",
           "prometheus_text", "BENCH_SERVING_SCHEMA",
           "SampledSync", "attribution_table", "force_sync",
           "parse_profiler_trace", "timeit", "training_prometheus",
           "estimate_clock_offset", "export_rank_trace",
           "merge_pod_trace", "provenance_section",
           "get_global_tracer", "set_global_tracer",
           "DriftMonitor", "psi_from_counts", "ks_from_counts",
           "ks_2samp"]
