"""Structured training telemetry.

The perf trajectory so far (BENCH_r01-r05) was driven by one-off scripts
under ``profiling/`` and hand-done ablation arithmetic; the library itself
measured nothing.  This package is the first-class observability layer the
boosting loop and tree learners report through:

  * ``Telemetry`` — host wall timers per phase, per-iteration timing, and
    the host-side decode of the per-tree device counter vector the wave
    learner accumulates on device (``learner_wave.TEL_*``).  The counter
    vector rides the SAME ``copy_to_host_async`` flush as the per-tree
    record arrays, so enabling telemetry adds zero host syncs to the hot
    path; with ``telemetry=False`` the learners trace the exact same jaxpr
    as before (the counter lane is ``None`` and never enters the program).
  * ``CollectiveLedger`` — trace-time accounting of every collective the
    sharded learners issue (op, payload bytes, phase, cadence).  Dynamic
    per-tree totals are estimated by combining the static sites with the
    decoded wave/stall counters.
  * ``report`` — the JSON report schema (``schema.json``, checked in and
    validated by the tier-1 smoke test) plus a dependency-free validator.
    Schema v2 adds the optional ``serving`` section that the prediction
    service (`lightgbm_tpu/serving/`) reports QPS, queue/bin/traverse/unpad
    stage latency, batch occupancy and compile-cache hits through.
    Schema v3 adds the ``reliability`` section — the process-wide failure
    accounting (connect retries, collective aborts, shed requests, host
    fallbacks, snapshots written/pruned, injected faults) maintained by
    `lightgbm_tpu/reliability/metrics.py`.

  * ``TraceRecorder`` (`trace.py`) — request-scoped structured spans: a
    thread-safe monotonic-clock ring buffer exporting Chrome trace-event
    JSON (open in Perfetto).  Training phase timers and the serving
    queue→pad→bin→traverse→unpad stages land as spans automatically when
    a recorder is attached (``Telemetry.tracer``); serving requests carry
    a ``trace_id`` end-to-end so one id links the request span, its
    micro-batch span and the batch's stage spans.
  * ``LatencyHistogram`` / Prometheus export (`metrics_export.py`) —
    log-bucketed latency histograms with exact p50/p95/p99 over a bounded
    raw-sample window, and the text-format snapshot behind the server's
    ``metrics`` op.  Schema v4 adds the serving ``latency_ms`` section.

Device-side *time* attribution inside the fused tree program is out of
scope for counters — that is what the opt-in ``profile_trace_dir``
(`jax.profiler`) trace is for; see README "Telemetry & profiling" and
"Tracing & service metrics".
"""

from .collectives import CollectiveLedger
from .metrics_export import (BENCH_SERVING_SCHEMA, LatencyHistogram,
                             prometheus_text)
from .report import load_schema, validate_report, write_report
from .telemetry import TEL_NAMES, Telemetry
from .trace import TraceRecorder, new_trace_id

__all__ = ["Telemetry", "CollectiveLedger", "TEL_NAMES",
           "load_schema", "validate_report", "write_report",
           "TraceRecorder", "new_trace_id", "LatencyHistogram",
           "prometheus_text", "BENCH_SERVING_SCHEMA"]
