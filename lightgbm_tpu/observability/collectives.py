"""Trace-time collective-communication accounting.

The sharded learners (`lightgbm_tpu/parallel/`) issue their collectives
from a handful of seams (``_reduce_hist`` / ``_reduce_hist_batch`` /
``_sync_counts*`` / ``_global_scalar`` / the best-split all_gathers).
Those seams run as plain Python during jit tracing, so each call site can
record (op, payload bytes, phase, cadence) HERE with zero runtime cost —
the ledger never touches the compiled program.

A site inside a ``lax.while_loop`` body traces once but executes once per
loop iteration; the ``cadence`` tag ("tree" / "wave" / "stall_event" /
"split") names that multiplier, and ``Telemetry`` combines it with the
decoded per-tree wave/stall counters to estimate dynamic per-tree totals.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Iterable, List


def _nbytes(x: Any) -> int:
    """Payload bytes of an array/tracer (static shapes under jit)."""
    if isinstance(x, (int, float)):
        return int(x)
    try:
        return int(x.size) * int(x.dtype.itemsize)
    except Exception:
        return 0


class CollectiveLedger:
    """Per-learner registry of collective call sites (trace-time)."""

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)
        self._sites: List[Dict[str, Any]] = []
        self._keys = set()

    def begin_trace(self) -> None:
        """Reset at the top of a traced tree program so a re-trace (new
        shape signature, rebuilt jit) doesn't double-count sites."""
        if self.enabled:
            self._sites = []
            self._keys = set()

    def record(self, op: str, payload: Any, phase: str,
               cadence: str) -> None:
        """Register one collective call site.  ``payload`` is the operand
        (bytes read from its static shape) or an explicit byte count."""
        if not self.enabled:
            return
        b = _nbytes(payload)
        key = (op, phase, cadence, b)
        if key in self._keys:
            # the same seam traced again for another window bucket /
            # cond branch — one site per distinct (op, phase, bytes)
            return
        self._keys.add(key)
        self._sites.append({"op": op, "phase": phase, "cadence": cadence,
                            "bytes_per_call": b})

    @contextlib.contextmanager
    def muted(self):
        """Suppress site recording while a SIDE program traces: the
        attribution exchange probe (`attribution.py`) jits the learner's
        real exchange seam standalone — its trace must not add sites, or
        ``collectives.sites`` and the analysis-gate budgets would drift
        from the actual tree programs."""
        prev = self.enabled
        self.enabled = False
        try:
            yield self
        finally:
            self.enabled = prev

    def sites(self) -> Iterable[Dict[str, Any]]:
        return list(self._sites)
