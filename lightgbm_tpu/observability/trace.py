"""Request-scoped structured tracing: spans → Chrome trace-event JSON.

The telemetry layer (`telemetry.py`) answers "how much time did phase X
take in total"; this module answers "where did THIS request / THIS
iteration spend its time".  A ``TraceRecorder`` is a thread-safe
monotonic-clock ring buffer of completed spans that exports the Chrome
trace-event format — load the file in Perfetto (https://ui.perfetto.dev)
or ``chrome://tracing`` and every span nests under its thread track.

Design constraints (the same ones the telemetry layer lives under):

  * **Host-only.**  Spans time host-visible phases (the existing
    ``Telemetry.phase`` sites: gradients / tree_dispatch / score_update /
    pipeline_flush on the training side, queue / pad / bin / traverse /
    unpad on the serving side).  Nothing here is ever traced into an XLA
    program, and recording a span never forces a device sync — device
    work is attributed through the per-tree counter lane and the opt-in
    ``profile_trace_dir`` profiler trace, exactly as before.
  * **Monotonic clocks only** (``time.perf_counter``); wall-clock reads
    would both misbehave under NTP steps and violate the repo's LGB005
    lint discipline.
  * **Bounded.**  Completed spans land in a ``deque(maxlen=capacity)``;
    a long-lived server overwrites its oldest spans instead of growing
    without bound (``dropped_spans`` in the export counts the loss).
  * **Zero overhead when off.**  A disabled recorder's ``span()`` returns
    a shared ``nullcontext`` and every record call returns immediately;
    attaching no recorder at all (``Telemetry.tracer is None``) costs one
    attribute read per phase exit.

Causal linkage: serving requests carry a ``trace_id`` (client-supplied or
server-generated) end-to-end — the per-request span, the micro-batch span
that coalesced it, and the batch's stage spans all carry the id in their
``args``, so one grep (or one Perfetto query) reconstructs where a slow
request's time went.  ``bind()`` is the thread-local propagation
mechanism: spans recorded while a bind is active inherit the bound id,
which is how batcher-worker stage spans pick up the ids of the requests
riding the batch without threading ids through every signature.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Union

#: shared no-op context for disabled recorders (allocation-free hot path)
_NULL_CTX = contextlib.nullcontext()

#: trace ids are opaque strings; span records may carry one id or a list
TraceId = Union[str, List[str]]


def new_trace_id() -> str:
    """A fresh opaque request id (8 random bytes, hex)."""
    return os.urandom(8).hex()


# -- process-global recorder registry ----------------------------------------
# Dataset construction (streaming ingestion chunks) happens before the
# training GBDT — and therefore its Telemetry — exists, so those early
# spans reach the flight recorder through this registration point instead
# of an attribute path.  One training run per process is the norm; the
# engine re-registers per run and clears on exit.

_global_tracer: Optional["TraceRecorder"] = None


def set_global_tracer(tracer: Optional["TraceRecorder"]) -> None:
    """Register (or clear, with ``None``) the process-wide recorder."""
    global _global_tracer
    _global_tracer = tracer


def get_global_tracer() -> Optional["TraceRecorder"]:
    """The registered recorder, or None — callers must null-check."""
    return _global_tracer


class TraceRecorder:
    """Thread-safe ring buffer of completed spans + Chrome JSON export."""

    def __init__(self, enabled: bool = True, capacity: int = 65536):
        self.enabled = bool(enabled)
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=self.capacity)
        self._total = 0                     # spans ever recorded
        self._tls = threading.local()
        # the trace epoch: every exported ts is relative to this, in µs.
        # perf_counter matches the clock Telemetry._PhaseCtx stamps t0
        # with, so phase spans and explicit spans share one timeline.
        self._epoch = time.perf_counter()
        # free-form export metadata (rank, clock offsets, ...) merged into
        # the exported ``otherData`` — the pod-trace merge reads it
        self._metadata: Dict[str, Any] = {}

    @property
    def epoch(self) -> float:
        """The perf_counter stamp every exported ts is relative to."""
        return self._epoch

    def set_metadata(self, **kw: Any) -> None:
        """Attach export metadata (lands in ``otherData``).  Used by the
        pod flight recorder: rank, process_count and the clock-offset
        handshake results ride here so ``podtrace.merge_pod_trace`` can
        put every rank's spans on one timeline."""
        self._metadata.update(kw)

    # -- thread-local trace-id binding ---------------------------------------

    def bind(self, trace_id: Optional[TraceId]):
        """Context manager: spans recorded on this thread while the bind
        is active default their ``trace_id`` to ``trace_id``.  Binds
        nest; ``None`` is a no-op bind."""
        if not self.enabled or trace_id is None:
            return _NULL_CTX
        return _BindCtx(self._tls, trace_id)

    def bound_id(self) -> Optional[TraceId]:
        return getattr(self._tls, "trace_id", None)

    # -- recording -----------------------------------------------------------

    def span(self, name: str, cat: str = "span",
             trace_id: Optional[TraceId] = None,
             args: Optional[Dict[str, Any]] = None):
        """Context manager recording one span on exit (no-op when
        disabled)."""
        if not self.enabled:
            return _NULL_CTX
        return _SpanCtx(self, name, cat, trace_id, args)

    def add_complete(self, name: str, t0: float, dur_s: float,
                     cat: str = "span", trace_id: Optional[TraceId] = None,
                     args: Optional[Dict[str, Any]] = None) -> None:
        """Record an already-timed span.  ``t0`` is a ``perf_counter``
        stamp (the clock the recorder's epoch is on); ``dur_s`` seconds."""
        if not self.enabled:
            return
        if trace_id is None:
            trace_id = self.bound_id()
        th = threading.current_thread()
        rec = (name, cat, float(t0), max(float(dur_s), 0.0),
               th.ident, th.name, trace_id, args, "span")
        with self._lock:
            self._total += 1
            self._spans.append(rec)

    def instant(self, name: str, cat: str = "instant",
                trace_id: Optional[TraceId] = None,
                args: Optional[Dict[str, Any]] = None) -> None:
        """Record a zero-duration annotation event."""
        if not self.enabled:
            return
        if trace_id is None:
            trace_id = self.bound_id()
        th = threading.current_thread()
        rec = (name, cat, time.perf_counter(), 0.0,
               th.ident, th.name, trace_id, args, "instant")
        with self._lock:
            self._total += 1
            self._spans.append(rec)

    # -- introspection -------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    @property
    def dropped(self) -> int:
        """Spans overwritten by ring wrap."""
        with self._lock:
            return self._total - len(self._spans)

    def spans(self) -> List[tuple]:
        """Snapshot of the raw span records (oldest first)."""
        with self._lock:
            return list(self._spans)

    # -- Chrome trace-event export -------------------------------------------

    def export(self) -> Dict[str, Any]:
        """The trace as a Chrome trace-event JSON object: every span
        becomes a B/E pair on its thread's track (instants become "i"
        events), timestamps in µs relative to the recorder epoch.  Loads
        directly in Perfetto / ``chrome://tracing``."""
        with self._lock:
            recs = list(self._spans)
            dropped = self._total - len(recs)
        pid = os.getpid()
        tid_map: Dict[int, int] = {}
        tid_names: Dict[int, str] = {}
        events: List[tuple] = []            # (sort_key, event_dict)
        for name, cat, t0, dur, ident, tname, trace_id, args, kind in recs:
            tid = tid_map.setdefault(ident, len(tid_map) + 1)
            tid_names.setdefault(tid, tname)
            a: Dict[str, Any] = dict(args or {})
            if trace_id is not None:
                a["trace_id"] = trace_id
            ts = (t0 - self._epoch) * 1e6
            if kind == "instant":
                events.append(((ts, 2, 0.0), {
                    "name": name, "cat": cat, "ph": "i", "s": "t",
                    "ts": ts, "pid": pid, "tid": tid,
                    **({"args": a} if a else {})}))
                continue
            te = ts + dur * 1e6
            # tie-breaks keep pairs well-nested: at equal ts a parent's B
            # (longer span) precedes its child's, a child's E (shorter)
            # precedes its parent's, and any E precedes a sibling's B
            events.append(((ts, 1, -dur), {
                "name": name, "cat": cat, "ph": "B", "ts": ts,
                "pid": pid, "tid": tid, **({"args": a} if a else {})}))
            events.append(((te, 0, dur), {
                "name": name, "cat": cat, "ph": "E", "ts": te,
                "pid": pid, "tid": tid}))
        events.sort(key=lambda e: e[0])
        meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                 "args": {"name": tname}}
                for tid, tname in sorted(tid_names.items())]
        other: Dict[str, Any] = {"dropped_spans": dropped,
                                 "clock": "perf_counter",
                                 "spans_recorded": self._total}
        other.update(self._metadata)
        return {"traceEvents": meta + [e for _, e in events],
                "displayTimeUnit": "ms",
                "otherData": other}

    def save(self, path: str) -> None:
        """Atomic (tmp + ``os.replace``) write of the exported trace."""
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self.export(), fh)
            fh.write("\n")
        os.replace(tmp, path)


class _BindCtx:
    __slots__ = ("tls", "trace_id", "prev")

    def __init__(self, tls, trace_id):
        self.tls = tls
        self.trace_id = trace_id

    def __enter__(self):
        self.prev = getattr(self.tls, "trace_id", None)
        self.tls.trace_id = self.trace_id
        return self

    def __exit__(self, *exc):
        self.tls.trace_id = self.prev
        return False


class _SpanCtx:
    __slots__ = ("rec", "name", "cat", "trace_id", "args", "t0")

    def __init__(self, rec, name, cat, trace_id, args):
        self.rec = rec
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.args = args

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.rec.add_complete(self.name, self.t0,
                              time.perf_counter() - self.t0, cat=self.cat,
                              trace_id=self.trace_id, args=self.args)
        return False
