"""Supervised train→serve lifecycle: refit → shadow → promote → watch.

``LifecycleController`` closes the loop between the trainer and the
server around ONE registered model name:

  * ``refit`` — continued training: the live incumbent seeds
    ``engine.train(init_model=...)`` on fresh data, with the PR-4
    crash-safe snapshot machinery underneath (``snapshot_freq`` +
    ``resume=True``), so a refit killed mid-run relaunches bit-identical.
  * ``shadow`` — the candidate is built/warmed/verified OFF to the side
    in the registry (``prepare`` — never swapped) and replayed against
    the traffic recording with the configured gates
    (`lifecycle/shadow.py`).  A failing candidate is rejected with the
    structured shadow report; nothing changes on the serving path.
  * ``promote`` — the ALREADY-prepared candidate commits through the
    registry's atomic swap (the incumbent is retained for rollback);
    in-flight predictions are unaffected because batchers resolve the
    model at batch time.
  * ``RollbackWatchdog`` — for ``rollback_deadline_s`` after a
    promotion, serving health (request errors, device-fallback batches,
    shed rate — all from ``ServingStats``/`reliability/metrics.py`) is
    sampled every ``watch_interval_s``; a breach triggers an automatic
    ``registry.rollback`` to the retained incumbent and is recorded in
    the lifecycle report section and the reliability counters.

Every decision lands in ``section()`` — the ``lifecycle`` section of the
serving telemetry report (``observability/schema.json``) — and, when a
tracer is attached, as ``lifecycle.*`` spans.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..reliability.metrics import rel_inc
from .shadow import shadow_validate

_NULL_CTX = contextlib.nullcontext()

_MAX_EVENTS = 256


class CandidateRejected(RuntimeError):
    """Raised by ``run_cycle`` when the shadow gates reject the refit
    candidate.  Carries the structured shadow report."""

    def __init__(self, report: Dict[str, Any]):
        super().__init__("shadow validation rejected the candidate: "
                         + "; ".join(report.get("reasons", [])))
        self.report = report


class RollbackWatchdog:
    """Post-promotion circuit breaker on a daemon thread.

    Samples serving deltas since the promotion; any breach of the error /
    fallback / shed ceilings inside the deadline rolls the registry back
    to the retained incumbent.  ``result`` is ``None`` while watching,
    then ``"healthy"`` or ``"rolled_back"``.
    """

    def __init__(self, controller: "LifecycleController", version: int,
                 deadline_s: float, interval_s: float,
                 error_rate_max: float, shed_rate_max: float,
                 min_requests: int = 1):
        self.controller = controller
        self.version = int(version)
        self.deadline_s = float(deadline_s)
        self.interval_s = max(float(interval_s), 0.01)
        self.error_rate_max = float(error_rate_max)
        self.shed_rate_max = float(shed_rate_max)
        self.min_requests = max(int(min_requests), 1)
        self.result: Optional[str] = None
        self.breach: Optional[str] = None
        self._stop = threading.Event()
        self._done = threading.Event()
        stats = controller.stats
        with stats._lock:
            self._base = {"requests": stats.requests, "errors": stats.errors,
                          "fallback_batches": stats.fallback_batches,
                          "batches": stats.batches, "shed": stats.shed}
        self._t0 = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="lgbt-lifecycle-watchdog", daemon=True)

    def start(self) -> "RollbackWatchdog":
        self._thread.start()
        return self

    def cancel(self) -> None:
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def _deltas(self) -> Dict[str, int]:
        stats = self.controller.stats
        with stats._lock:
            now = {"requests": stats.requests, "errors": stats.errors,
                   "fallback_batches": stats.fallback_batches,
                   "batches": stats.batches, "shed": stats.shed}
        return {k: now[k] - self._base[k] for k in now}

    def _check(self) -> Optional[str]:
        d = self._deltas()
        if d["requests"] + d["shed"] < self.min_requests:
            return None
        err_rate = d["errors"] / max(d["requests"], 1)
        if err_rate > self.error_rate_max:
            return (f"request error rate {err_rate:.3g} > "
                    f"{self.error_rate_max:g} ({d['errors']} errors / "
                    f"{d['requests']} requests)")
        fb_rate = d["fallback_batches"] / max(d["batches"], 1)
        if fb_rate > self.error_rate_max:
            return (f"device fallback rate {fb_rate:.3g} > "
                    f"{self.error_rate_max:g} ({d['fallback_batches']} "
                    f"fallback batches / {d['batches']} batches)")
        shed_rate = d["shed"] / max(d["requests"] + d["shed"], 1)
        if shed_rate > self.shed_rate_max:
            return (f"shed rate {shed_rate:.3g} > {self.shed_rate_max:g} "
                    f"({d['shed']} shed / {d['requests'] + d['shed']} "
                    f"offered)")
        return None

    def _run(self) -> None:
        try:
            deadline = self._t0 + self.deadline_s
            while not self._stop.wait(self.interval_s):
                breach = self._check()
                if breach is not None:
                    self.breach = breach
                    self.result = "rolled_back"
                    self.controller._auto_rollback(self, breach)
                    return
                if time.monotonic() >= deadline:
                    self.result = "healthy"
                    self.controller._watch_healthy(self)
                    return
            self.result = self.result or "cancelled"
        finally:
            self._done.set()

    def section(self) -> Dict[str, Any]:
        return {"version": self.version,
                "result": self.result or "watching",
                "breach": self.breach,
                "elapsed_s": time.monotonic() - self._t0,
                "deadline_s": self.deadline_s}


class LifecycleController:
    """Drives the continuous train→serve loop for one served model."""

    def __init__(self, server, name: str = "default", *,
                 metric: str = "", metric_floor: float = float("nan"),
                 divergence_max: float = 0.25,
                 latency_max_ratio: float = 4.0, min_shadow_rows: int = 1,
                 rollback_deadline_s: float = 30.0,
                 watch_interval_s: float = 0.5,
                 error_rate_max: float = 0.05, shed_rate_max: float = 0.5,
                 watch_min_requests: int = 1):
        self.server = server
        self.registry = server.registry
        self.stats = server.stats
        self.recorder = server.recorder
        self.name = name
        self.metric = metric
        self.metric_floor = float(metric_floor)
        self.divergence_max = float(divergence_max)
        self.latency_max_ratio = float(latency_max_ratio)
        self.min_shadow_rows = int(min_shadow_rows)
        self.rollback_deadline_s = float(rollback_deadline_s)
        self.watch_interval_s = float(watch_interval_s)
        self.error_rate_max = float(error_rate_max)
        self.shed_rate_max = float(shed_rate_max)
        self.watch_min_requests = int(watch_min_requests)
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._t0 = time.monotonic()
        self._promotions = 0
        self._rollbacks = 0
        self._auto_rollbacks = 0
        self._shadow_last: Optional[Dict[str, Any]] = None
        self.watchdog: Optional[RollbackWatchdog] = None
        # the server's report() attaches section() once a controller is
        # bound (PredictionServer.lifecycle)
        server.lifecycle = self

    @classmethod
    def from_config(cls, server, cfg, name: str = "default"
                    ) -> "LifecycleController":
        """Build from the ``lifecycle_*`` config keys (`config.py`)."""
        return cls(
            server, name,
            metric=cfg.lifecycle_metric,
            metric_floor=cfg.lifecycle_metric_floor,
            divergence_max=cfg.lifecycle_divergence_max,
            latency_max_ratio=cfg.lifecycle_latency_max_ratio,
            min_shadow_rows=cfg.lifecycle_min_shadow_rows,
            rollback_deadline_s=cfg.lifecycle_rollback_deadline_s,
            watch_interval_s=cfg.lifecycle_watch_interval_s,
            error_rate_max=cfg.lifecycle_error_rate_max,
            shed_rate_max=cfg.lifecycle_shed_rate_max)

    # -- bookkeeping ---------------------------------------------------------

    def _event(self, kind: str, **info: Any) -> None:
        ev = {"event": kind,
              "t_ms": (time.monotonic() - self._t0) * 1e3, **info}
        with self._lock:
            self._events.append(ev)
            if len(self._events) > _MAX_EVENTS:
                del self._events[:_MAX_EVENTS // 2]
        tr = self.stats.tracer
        if tr is not None:
            tr.instant(f"lifecycle.{kind}", cat="lifecycle",
                       args={k: v for k, v in info.items()
                             if isinstance(v, (int, float, str, bool))})

    def _span(self, name: str, **args: Any):
        tr = self.stats.tracer
        return tr.span(f"lifecycle.{name}", cat="lifecycle", args=args) \
            if tr is not None else _NULL_CTX

    # -- continued training --------------------------------------------------

    def refit(self, train_set, num_boost_round: int = 10,
              params: Optional[Dict[str, Any]] = None,
              output_model: str = "", snapshot_freq: int = -1,
              resume: bool = False, **train_kw):
        """Continued training off the LIVE incumbent: warm-start
        ``engine.train`` on ``train_set`` for ``num_boost_round`` more
        rounds.  ``output_model`` + ``snapshot_freq`` arm the crash-safe
        snapshots; ``resume=True`` relaunches a killed refit from the
        newest valid snapshot (which wins over the incumbent when newer —
        `engine.train`)."""
        from .. import engine

        incumbent = self.registry.get(self.name).booster
        p = dict(params or {})
        if output_model:
            p.setdefault("output_model", output_model)
        if snapshot_freq > 0:
            p.setdefault("snapshot_freq", snapshot_freq)
        with self._span("refit", rounds=int(num_boost_round)):
            booster = engine.train(p, train_set, num_boost_round,
                                   init_model=incumbent, resume=resume,
                                   verbose_eval=False, **train_kw)
        self._event("refit", rounds=int(num_boost_round),
                    trees=booster.num_trees())
        rel_inc("lifecycle.refits")
        return booster

    # -- shadow validation ---------------------------------------------------

    def shadow(self, candidate, labels: Optional[np.ndarray] = None,
               X: Optional[np.ndarray] = None):
        """Prepare the candidate in the registry (warm + verify, never
        swapped) and run the shadow gates over the traffic recording (or
        an explicit ``X``).  Returns ``(prepared_model_or_None,
        report)`` — the model is ``None`` when any gate failed."""
        if X is None:
            X = self.recorder.snapshot()
        # serve the DEPLOYMENT ARTIFACT, not the trainer handle: a
        # continued-training booster's live bin space is the fresh
        # data's quantization of the incumbent's thresholds (lossy), so
        # its device path would diverge from the exact float-threshold
        # traversal and fail registry verification.  The model text
        # carries the exact thresholds, which the registry reconstructs
        # into an exact bin schema — and it is what a remote `swap`
        # would serve anyway.
        cand_text = candidate if isinstance(candidate, str) \
            else candidate.model_to_string()
        with self._span("shadow", rows=int(np.atleast_2d(X).shape[0])):
            try:
                prepared = self.registry.prepare(self.name,
                                                 model_str=cand_text)
            except Exception as e:
                # a candidate that cannot even build/verify is rejected
                # with the same structured shape as a gate failure
                report = {"rows": 0, "gates": {"verify": {"passed": False}},
                          "reasons": [f"candidate failed registry "
                                      f"verification: {e}"],
                          "passed": False}
                rel_inc("lifecycle.shadow_runs")
                rel_inc("lifecycle.shadow_rejections")
                self._record_shadow(report)
                return None, report
            report = shadow_validate(
                prepared, self.registry.get(self.name), X, labels=labels,
                metric=self.metric, metric_floor=self.metric_floor,
                divergence_max=self.divergence_max,
                latency_max_ratio=self.latency_max_ratio,
                min_rows=self.min_shadow_rows,
                buckets=self.registry.warm_buckets)
        self._record_shadow(report)
        return (prepared if report["passed"] else None), report

    def _record_shadow(self, report: Dict[str, Any]) -> None:
        with self._lock:
            self._shadow_last = report
        self._event("shadow", passed=bool(report["passed"]),
                    reasons="; ".join(report.get("reasons", [])))

    # -- promotion / rollback ------------------------------------------------

    def promote(self, prepared, watch: bool = True) -> int:
        """Commit an already-prepared candidate through the registry's
        atomic swap (incumbent retained) and start the rollback
        watchdog."""
        with self._span("promote"):
            version = self.registry.commit(prepared)
        with self._lock:
            self._promotions += 1
        rel_inc("lifecycle.promotions")
        self._event("promote", version=int(version))
        if watch:
            stale = self.watchdog
            if stale is not None:
                # Back-to-back promotions: the previous watchdog's
                # ServingStats base belongs to the OLD candidate's
                # window — left running it would judge the new candidate
                # against stale error/shed deltas and could roll it back
                # spuriously.  The replacement watchdog re-baselines in
                # its own __init__.
                stale.cancel()
                stale.join(timeout=5.0)
            self.watchdog = RollbackWatchdog(
                self, version, self.rollback_deadline_s,
                self.watch_interval_s, self.error_rate_max,
                self.shed_rate_max, self.watch_min_requests).start()
        return version

    def rollback(self, reason: str = "operator") -> int:
        """Manual rollback to the retained previous version."""
        version = self.registry.rollback(self.name)
        with self._lock:
            self._rollbacks += 1
        rel_inc("lifecycle.rollbacks")
        self._event("rollback", version=int(version), reason=reason)
        return version

    def _auto_rollback(self, watchdog: RollbackWatchdog,
                       breach: str) -> None:
        with self._span("rollback", breach=breach):
            try:
                version = self.registry.rollback(self.name)
            except KeyError:
                # no retained incumbent (first-ever load): record the
                # breach, there is nothing to roll back to
                self._event("rollback_failed", reason=breach)
                return
        with self._lock:
            self._rollbacks += 1
            self._auto_rollbacks += 1
        rel_inc("lifecycle.rollbacks")
        rel_inc("lifecycle.auto_rollbacks")
        self._event("auto_rollback", version=int(version), reason=breach,
                    promoted_version=watchdog.version,
                    elapsed_s=time.monotonic() - watchdog._t0)

    def _watch_healthy(self, watchdog: RollbackWatchdog) -> None:
        self._event("promotion_healthy", version=watchdog.version,
                    elapsed_s=time.monotonic() - watchdog._t0)
        rel_inc("lifecycle.promotions_healthy")

    # -- the whole loop ------------------------------------------------------

    def run_cycle(self, train_set, num_boost_round: int = 10,
                  params: Optional[Dict[str, Any]] = None,
                  labels: Optional[np.ndarray] = None,
                  output_model: str = "", snapshot_freq: int = -1,
                  resume: bool = False, watch: bool = True,
                  **train_kw) -> Dict[str, Any]:
        """record → refit → shadow → promote in one call.  Raises
        ``CandidateRejected`` (carrying the shadow report) when the gates
        fail; otherwise returns ``{"version", "shadow", "booster"}``."""
        booster = self.refit(train_set, num_boost_round, params,
                             output_model=output_model,
                             snapshot_freq=snapshot_freq, resume=resume,
                             **train_kw)
        prepared, report = self.shadow(booster, labels=labels)
        if prepared is None:
            raise CandidateRejected(report)
        version = self.promote(prepared, watch=watch)
        return {"version": version, "shadow": report, "booster": booster}

    def stop(self) -> None:
        if self.watchdog is not None:
            self.watchdog.cancel()
            self.watchdog.join(timeout=5.0)

    # -- report --------------------------------------------------------------

    def section(self) -> Dict[str, Any]:
        """The ``lifecycle`` section of the serving telemetry report."""
        with self._lock:
            events = list(self._events)
            out = {"promotions": self._promotions,
                   "rollbacks": self._rollbacks,
                   "auto_rollbacks": self._auto_rollbacks,
                   "shadow": self._shadow_last,
                   "events": events}
        out["recorder"] = self.recorder.section() \
            if self.recorder is not None else None
        out["watchdog"] = self.watchdog.section() \
            if self.watchdog is not None else None
        out["versions"] = self.registry.versions_detail()
        return out
