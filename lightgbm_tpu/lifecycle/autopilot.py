"""Autopilot: drift-triggered refit with budget caps and gated rollout.

Closes the loop the rest of ``lifecycle/`` left open: the
``TrafficRecorder`` keeps the live window, ``DriftMonitor`` judges it
against the promote-time baseline, ``LifecycleController`` knows how to
refit/shadow/promote — but a human still had to call ``run_cycle``.
The ``Autopilot`` is the daemon that composes them:

  1. **detect** — poll the fleet's drift verdict over the recorder
     window.  A single drifted window is noise; only ``N`` *consecutive*
     drifted verdicts, each over fresh traffic (both the monitor's check
     counter and the recorder's total-row counter must have advanced),
     arm a refit.  Never promote on drift alone.
  2. **budget** — every armed refit passes :class:`~.budget.RefitBudget`
     (window cap, min spacing, cooldown-after-rollback, one-at-a-time);
     a veto records a ``suppressed`` decision with the reason, it never
     queues.
  3. **refit** — continued training from the incumbent over the original
     train source plus the recorded window (labelled by ``label_fn``
     when the deployment can recover labels), through
     ``LifecycleController.refit`` so snapshot/resume crash-safety
     applies.
  4. **validate** — the candidate is round-tripped through model text
     and shadow-validated against the incumbent on the recorded window.
     Never promote without shadow validation.
  5. **roll** — fleet servers upgrade replica-by-replica through
     ``promote_rolling``, where every replica's commit re-runs the
     shadow gate on that replica's prepared copy; a mid-roll gate
     failure reverse-rolls the already-committed replicas.  Non-fleet
     servers fall back to the controller's single-registry promote.

Every decision lands in a bounded ring (reported as the schema-v10
``autopilot`` section), as ``lifecycle.autopilot.*`` counters and as
trace instants.  Host-only: no JAX, no collectives, and the daemon
thread never runs on the gateway's event loop.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..reliability.metrics import rel_inc
from .budget import RefitBudget
from .controller import CandidateRejected, LifecycleController

__all__ = ["Autopilot"]

_MAX_DECISIONS = 256


class Autopilot:
    """Drift→refit→shadow→roll daemon (see module doc).

    ``train_source`` is a zero-argument callable returning the original
    training data as ``(X, y)`` arrays — called once per refit cycle so
    the source can be re-read from disk.  ``label_fn`` (optional) maps
    recorded request rows to labels; when present, the recorded window
    joins the refit training set and labels the shadow metric gate.
    """

    def __init__(self, server: Any, controller: LifecycleController,
                 train_source: Callable[[], Any], *,
                 label_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
                 name: str = "default",
                 interval_s: float = 30.0,
                 consecutive_checks: int = 3,
                 budget: Optional[RefitBudget] = None,
                 num_boost_round: int = 10,
                 params: Optional[Dict[str, Any]] = None,
                 output_model: str = "",
                 snapshot_freq: int = -1,
                 settle_s: float = 0.0):
        self.server = server
        self.controller = controller
        self.train_source = train_source
        self.label_fn = label_fn
        self.name = name
        self.interval_s = float(interval_s)
        self.consecutive_checks = max(int(consecutive_checks), 1)
        self.budget = budget if budget is not None else RefitBudget()
        self.num_boost_round = int(num_boost_round)
        self.params = dict(params or {})
        self.output_model = output_model
        self.snapshot_freq = int(snapshot_freq)
        self.settle_s = float(settle_s)
        self.stats = server.stats
        self._lock = threading.Lock()
        self._decisions: List[Dict[str, Any]] = []
        self._counts = {"checks": 0, "triggered": 0, "suppressed": 0,
                        "rejected": 0, "promoted": 0, "rolled_back": 0,
                        "errors": 0}
        self._consecutive = 0
        self._seen_checks = -1
        self._seen_rows = -1
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        server.autopilot = self

    # -- daemon --------------------------------------------------------

    def start(self) -> "Autopilot":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="lgbt-autopilot")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as exc:  # daemon must survive anything
                self._decide("error", reason=repr(exc))

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)

    # -- one detect→refit→roll step (synchronous; tests call directly) -

    def tick(self) -> Optional[Dict[str, Any]]:
        """Run one check; returns the decision recorded (None when the
        window produced no fresh verdict or drift is still clear)."""
        with self._lock:
            self._counts["checks"] += 1
        verdict = self._fresh_verdict()
        if verdict is None:
            return None
        if not verdict.get("drifted"):
            with self._lock:
                self._consecutive = 0
            return None
        with self._lock:
            self._consecutive += 1
            consecutive = self._consecutive
        if consecutive < self.consecutive_checks:
            return self._decide("drift_pending", consecutive=consecutive,
                                required=self.consecutive_checks,
                                max_psi=verdict.get("max_psi"),
                                max_ks=verdict.get("max_ks"))
        admitted, reason = self.budget.try_begin()
        if not admitted:
            rel_inc("lifecycle.autopilot.suppressed")
            rel_inc(f"lifecycle.autopilot.suppressed.{reason}")
            return self._decide("suppressed", reason=reason,
                                consecutive=consecutive)
        rel_inc("lifecycle.autopilot.triggered")
        decision = self._decide("triggered", consecutive=consecutive,
                                max_psi=verdict.get("max_psi"),
                                max_ks=verdict.get("max_ks"))
        rolled_back = False
        try:
            outcome = self._refit_cycle()
        except CandidateRejected as exc:
            rel_inc("lifecycle.autopilot.rejected")
            report = getattr(exc, "report", {}) or {}
            return self._decide("rejected",
                                reason=";".join(report.get("reasons", []))
                                or "shadow_gate",
                                shadow=report.get("gates"))
        except Exception as exc:
            rel_inc("lifecycle.autopilot.errors")
            return self._decide("error", reason=repr(exc))
        else:
            rolled_back = bool(outcome.get("rolled_back"))
            if rolled_back:
                rel_inc("lifecycle.autopilot.rolled_back")
                return self._decide(
                    "rolled_back",
                    reason=outcome.get("reason", "gate_failed_mid_roll"),
                    aborted_replica=outcome.get("aborted_replica"))
            rel_inc("lifecycle.autopilot.promoted")
            with self._lock:
                self._consecutive = 0
            return self._decide("promoted",
                                versions=outcome.get("versions"),
                                replicas=outcome.get("replicas"))
        finally:
            self.budget.end(rolled_back=rolled_back)
            _ = decision

    # -- detection -----------------------------------------------------

    def _fresh_verdict(self) -> Optional[Dict[str, Any]]:
        """The fleet's current drift section, only when it reflects a
        check the autopilot has not counted yet over new traffic."""
        check = getattr(self.server, "check_drift", None)
        recorder = getattr(self.server, "recorder", None)
        if check is None or recorder is None or not recorder.enabled:
            return None
        rows = recorder.total_rows
        section = check(self.name)
        if not section or "drifted" not in section:
            return None
        checks = int(section.get("checks", 0))
        with self._lock:
            if checks <= self._seen_checks or rows <= self._seen_rows:
                return None   # stale: no new comparison or no new traffic
            self._seen_checks = checks
            self._seen_rows = rows
        return section

    # -- the refit cycle ----------------------------------------------

    def _refit_cycle(self) -> Dict[str, Any]:
        """Refit → round-trip → shadow → gated roll.  Raises
        ``CandidateRejected`` when the candidate fails shadow; returns
        an outcome dict otherwise."""
        from ..dataset import Dataset

        ctl = self.controller
        window = self.server.recorder.snapshot()
        if window.size == 0:
            raise CandidateRejected({"passed": False,
                                     "reasons": ["empty_window"]})
        X0, y0 = self.train_source()
        X0 = np.asarray(X0, dtype=np.float64)
        y0 = np.asarray(y0, dtype=np.float64).reshape(-1)
        labels = None
        if self.label_fn is not None:
            labels = np.asarray(self.label_fn(window),
                                dtype=np.float64).reshape(-1)
            Xt = np.vstack([X0, np.asarray(window, dtype=np.float64)])
            yt = np.concatenate([y0, labels])
        else:
            Xt, yt = X0, y0
        train_set = Dataset(Xt, label=yt, params=dict(self.params))
        booster = ctl.refit(
            train_set, num_boost_round=self.num_boost_round,
            params=dict(self.params), output_model=self.output_model,
            snapshot_freq=self.snapshot_freq,
            resume=bool(self.output_model))
        cand_text = booster.model_to_string()  # promote what serializes
        prepared, report = ctl.shadow(cand_text, labels=labels, X=window)
        if prepared is None:
            raise CandidateRejected(report)
        promote_rolling = getattr(self.server, "promote_rolling", None)
        if promote_rolling is None:
            version = ctl.promote(prepared, watch=True)
            return {"versions": {self.name: version}, "replicas": 1}
        out = promote_rolling(
            self.name, model_str=cand_text, settle_s=self.settle_s,
            divergence_max=ctl.divergence_max,
            latency_max_ratio=ctl.latency_max_ratio,
            shadow_min_rows=ctl.min_shadow_rows)
        if not out.get("committed"):
            return {"rolled_back": True,
                    "aborted_replica": out.get("aborted_replica"),
                    "reason": "replica_gate_failed",
                    "gates": out.get("gates")}
        return {"versions": out.get("versions"),
                "replicas": out.get("replicas")}

    # -- bookkeeping ---------------------------------------------------

    def _decide(self, decision: str, **info: Any) -> Dict[str, Any]:
        ev: Dict[str, Any] = {
            "decision": decision,
            "t_ms": round((time.monotonic() - self._t0) * 1e3, 3)}
        ev.update({k: v for k, v in info.items() if v is not None})
        key = "errors" if decision == "error" else decision
        with self._lock:
            if key in self._counts:
                self._counts[key] += 1
            self._decisions.append(ev)
            if len(self._decisions) > _MAX_DECISIONS:
                del self._decisions[:_MAX_DECISIONS // 2]
        tr = self.stats.tracer
        if tr is not None:
            tr.instant(f"autopilot.{decision}",
                       args={k: str(v) for k, v in ev.items()})
        return ev

    def section(self) -> Dict[str, Any]:
        """The schema-v10 ``autopilot`` report section."""
        with self._lock:
            counts = dict(self._counts)
            decisions = list(self._decisions)
            consecutive = self._consecutive
        return {
            "enabled": True,
            "model": self.name,
            "interval_s": self.interval_s,
            "consecutive_required": self.consecutive_checks,
            "drift_consecutive": consecutive,
            "checks": counts["checks"],
            "triggered": counts["triggered"],
            "suppressed": counts["suppressed"],
            "rejected": counts["rejected"],
            "promoted": counts["promoted"],
            "rolled_back": counts["rolled_back"],
            "errors": counts["errors"],
            "budget": self.budget.section(),
            "decisions": decisions,
        }

    @classmethod
    def from_config(cls, server: Any, controller: LifecycleController,
                    train_source: Callable[[], Any], cfg: Any,
                    **kw: Any) -> "Autopilot":
        """Map ``autopilot_*`` config keys (see ``config.py``)."""
        budget = RefitBudget(
            max_refits_per_window=cfg.autopilot_max_refits,
            window_s=cfg.autopilot_window_s,
            min_spacing_s=cfg.autopilot_min_spacing_s,
            cooldown_s=cfg.autopilot_cooldown_s)
        return cls(server, controller, train_source,
                   interval_s=cfg.autopilot_interval_s,
                   consecutive_checks=cfg.autopilot_consecutive_checks,
                   num_boost_round=cfg.autopilot_num_boost_round,
                   budget=budget, **kw)
