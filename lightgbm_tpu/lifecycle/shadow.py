"""Shadow validation: replay recorded traffic, gate the candidate.

A refreshed model is never promoted on faith.  The candidate is built and
warmed OFF to the side (`serving/registry.py` ``prepare`` — the live
model keeps serving untouched), then both candidate and incumbent are
replayed over the traffic recording through the exact padded-bucket
device path production requests take, and the candidate must clear every
configured gate:

  * **divergence ceiling** — mean |candidate − incumbent| over the
    replayed predictions (output space, after ``convert_output``) must
    stay under ``divergence_max``: a candidate that silently disagrees
    with the incumbent on live traffic is a deployment risk even when
    its offline metric looks fine.
  * **metric floor** — when labels are supplied, the candidate's metric
    ("auc" or "l2") must clear ``metric_floor``.
  * **latency ceiling** — the candidate's per-batch p50, measured with
    the same ``LatencyHistogram`` machinery the serving layer reports
    through (`observability/metrics_export.py`), must stay within
    ``latency_max_ratio`` × the incumbent's p50 from the same replay.

The outcome is a structured report (``gates`` / ``passed`` / ``reasons``)
that lands in the lifecycle telemetry section — a rejected candidate is a
recorded decision, not a log line.
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability.metrics_export import LatencyHistogram
from ..reliability.metrics import rel_inc

# metrics the floor gate understands; (higher_better, fn(preds, labels))
_LOWER_BETTER = {"l2", "mse", "binary_logloss"}


def _metric_value(name: str, preds: np.ndarray,
                  labels: np.ndarray) -> Tuple[float, bool]:
    """(value, higher_better) of a shadow metric over 1-D predictions."""
    preds = np.asarray(preds, np.float64).reshape(-1)
    labels = np.asarray(labels, np.float64).reshape(-1)[:preds.size]
    preds = preds[:labels.size]
    if name == "auc":
        pos = labels > 0
        npos, nneg = int(pos.sum()), int((~pos).sum())
        if npos == 0 or nneg == 0:
            return 0.5, True
        # rank-sum AUC with midrank ties (matches metrics.AUCMetric)
        order = np.argsort(preds, kind="mergesort")
        ranks = np.empty(preds.size, np.float64)
        sorted_p = preds[order]
        i = 0
        while i < sorted_p.size:
            j = i
            while j + 1 < sorted_p.size and sorted_p[j + 1] == sorted_p[i]:
                j += 1
            ranks[order[i:j + 1]] = (i + j) / 2.0 + 1.0
            i = j + 1
        auc = (ranks[pos].sum() - npos * (npos + 1) / 2.0) / (npos * nneg)
        return float(auc), True
    if name in ("l2", "mse"):
        return float(np.mean((preds - labels) ** 2)), False
    if name == "binary_logloss":
        p = np.clip(preds, 1e-15, 1 - 1e-15)
        return float(-np.mean(labels * np.log(p)
                              + (1 - labels) * np.log(1 - p))), False
    raise ValueError(f"unsupported shadow metric {name!r} "
                     f"(supported: auc, l2, binary_logloss)")


def _replay(model, X: np.ndarray,
            buckets: Sequence[int]) -> Tuple[np.ndarray, LatencyHistogram]:
    """Score ``X`` through the model's padded device path in warm-bucket
    chunks, timing each dispatch.  Returns (output-space predictions,
    per-batch latency histogram)."""
    hist = LatencyHistogram()
    ladder = sorted(int(b) for b in buckets) or [
        1 << max(int(X.shape[0]) - 1, 0).bit_length()]
    chunk = max(ladder)
    outs: List[np.ndarray] = []
    for ofs in range(0, X.shape[0], chunk):
        part = X[ofs:ofs + chunk]
        m = part.shape[0]
        fits = [b for b in ladder if b >= m]
        bucket = min(fits) if fits else chunk
        Xpad = np.zeros((bucket, X.shape[1]), np.float64)
        Xpad[:m] = part
        t0 = time.perf_counter()
        raw = model.predict_padded(Xpad, m)
        hist.record((time.perf_counter() - t0) * 1e3)
        outs.append(np.asarray(model.convert_output(raw), np.float64))
    return np.concatenate(outs, axis=0), hist


def shadow_validate(candidate, incumbent, X: np.ndarray, *,
                    labels: Optional[np.ndarray] = None,
                    metric: str = "",
                    metric_floor: float = float("nan"),
                    divergence_max: float = 0.25,
                    latency_max_ratio: float = 4.0,
                    min_rows: int = 1,
                    buckets: Sequence[int] = ()) -> Dict[str, Any]:
    """Gate a prepared candidate ``ServingModel`` against the serving
    incumbent over recorded traffic ``X``.  Returns the structured shadow
    report; never raises on a failing gate — rejection is a decision the
    caller reads from ``report["passed"]``."""
    X = np.atleast_2d(np.asarray(X, np.float64))
    gates: Dict[str, Any] = {}
    reasons: List[str] = []
    report: Dict[str, Any] = {"rows": int(X.shape[0]), "gates": gates,
                              "reasons": reasons}
    if X.shape[0] < max(int(min_rows), 1) or X.size == 0:
        reasons.append(f"recording too small ({X.shape[0]} rows, "
                       f"need >= {min_rows})")
        gates["recording"] = {"rows": int(X.shape[0]),
                              "min_rows": int(min_rows), "passed": False}
        report["passed"] = False
        rel_inc("lifecycle.shadow_runs")
        rel_inc("lifecycle.shadow_rejections")
        return report
    cand_pred, cand_hist = _replay(candidate, X, buckets)
    inc_pred, inc_hist = _replay(incumbent, X, buckets)

    flat_c = cand_pred.reshape(cand_pred.shape[0], -1)
    flat_i = inc_pred.reshape(inc_pred.shape[0], -1)
    diff = np.abs(flat_c - flat_i)
    div_mean = float(np.mean(diff))
    div_max = float(np.max(diff))
    gates["divergence"] = {"mean": div_mean, "max": div_max,
                           "limit": float(divergence_max),
                           "passed": div_mean <= float(divergence_max)}
    if not gates["divergence"]["passed"]:
        reasons.append(f"prediction divergence {div_mean:.4g} exceeds "
                       f"ceiling {divergence_max:g}")

    cand_metric = inc_metric = None
    if metric and labels is not None and not (
            isinstance(metric_floor, float) and math.isnan(metric_floor)):
        cand_metric, higher = _metric_value(metric, flat_c[:, 0], labels)
        inc_metric, _ = _metric_value(metric, flat_i[:, 0], labels)
        ok = cand_metric >= metric_floor if higher \
            else cand_metric <= metric_floor
        gates["metric"] = {"name": metric, "value": cand_metric,
                           "incumbent": inc_metric,
                           "floor": float(metric_floor),
                           "higher_better": higher, "passed": bool(ok)}
        if not ok:
            side = "below floor" if higher else "above ceiling"
            reasons.append(f"{metric} {cand_metric:.4g} is {side} "
                           f"{metric_floor:g}")
    else:
        gates["metric"] = {"passed": True, "skipped": True}

    cand_p50 = cand_hist.percentiles((50,))["p50"]
    inc_p50 = max(inc_hist.percentiles((50,))["p50"], 1e-3)
    ratio = cand_p50 / inc_p50
    gates["latency"] = {"candidate_p50_ms": cand_p50,
                        "incumbent_p50_ms": inc_p50, "ratio": float(ratio),
                        "limit": float(latency_max_ratio),
                        "passed": ratio <= float(latency_max_ratio)}
    if not gates["latency"]["passed"]:
        reasons.append(f"candidate p50 {cand_p50:.3g} ms is {ratio:.2f}x "
                       f"the incumbent's {inc_p50:.3g} ms (ceiling "
                       f"{latency_max_ratio:g}x)")

    report["candidate"] = {"latency_ms": cand_hist.snapshot(),
                           "metric": cand_metric}
    report["incumbent"] = {"latency_ms": inc_hist.snapshot(),
                           "metric": inc_metric}
    report["passed"] = not reasons
    rel_inc("lifecycle.shadow_runs")
    if reasons:
        rel_inc("lifecycle.shadow_rejections")
    return report
