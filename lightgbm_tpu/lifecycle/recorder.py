"""Bounded ring recorder for live serving traffic.

The shadow-validation loop (`lifecycle/shadow.py`) needs a sample of the
feature rows the server is ACTUALLY answering, not a synthetic fuzz
matrix: a candidate model is judged on the distribution it would serve.
``TrafficRecorder`` is the capture side — the prediction server copies
each admitted request's feature rows into a fixed-size ring
(`serving/server.py` ``predict`` op), so memory stays bounded no matter
how long the server runs and the newest ``capacity`` rows are always
available for replay.

Disabled (capacity 0, the default) the recorder is a single attribute
check on the request path; recording is one bounded ``ndarray`` copy
under a leaf lock (never held across a device call).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import numpy as np


class TrafficRecorder:
    """Fixed-capacity row ring: ``record`` overwrites oldest-first."""

    def __init__(self, capacity_rows: int = 0):
        self.capacity = max(int(capacity_rows), 0)
        self.enabled = self.capacity > 0
        self._lock = threading.Lock()
        self._buf: Optional[np.ndarray] = None   # (capacity, F), lazy
        self._next = 0          # next write slot
        self._size = 0          # valid rows
        self.total_rows = 0     # ever recorded (ring overwrites past this)
        self.skipped_rows = 0   # wrong-width requests, never recorded

    def record(self, X: np.ndarray) -> None:
        """Copy the rows of one request into the ring (no-op when
        disabled).  A request whose feature width disagrees with the
        first recorded one is counted and skipped — a recording must
        stay a rectangular matrix the replay can score."""
        if not self.enabled:
            return
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        with self._lock:
            if self._buf is None:
                self._buf = np.zeros((self.capacity, X.shape[1]), np.float64)
            if X.shape[1] != self._buf.shape[1]:
                self.skipped_rows += int(X.shape[0])
                from ..reliability.metrics import rel_inc
                rel_inc("lifecycle.record_width_mismatch_rows", X.shape[0])
                return
            n = X.shape[0]
            if n >= self.capacity:
                # one request larger than the whole ring: keep its tail
                self._buf[:] = X[n - self.capacity:]
                self._next = 0
                self._size = self.capacity
            else:
                end = self._next + n
                if end <= self.capacity:
                    self._buf[self._next:end] = X
                else:
                    k = self.capacity - self._next
                    self._buf[self._next:] = X[:k]
                    self._buf[:end - self.capacity] = X[k:]
                self._next = end % self.capacity
                self._size = min(self._size + n, self.capacity)
            self.total_rows += int(n)

    def __len__(self) -> int:
        with self._lock:
            return self._size

    def snapshot(self) -> np.ndarray:
        """The recorded rows, oldest first, as an owned ``(n, F)`` copy
        (empty ``(0, 0)`` when nothing was recorded)."""
        with self._lock:
            if self._buf is None or self._size == 0:
                return np.zeros((0, 0), np.float64)
            if self._size < self.capacity:
                return self._buf[:self._size].copy()
            # full ring: unroll so row order is oldest -> newest
            return np.concatenate([self._buf[self._next:],
                                   self._buf[:self._next]], axis=0)

    def drain(self) -> np.ndarray:
        """``snapshot()`` that also empties the ring (capacity and width
        are kept), so consecutive drift checks judge DISJOINT traffic
        windows instead of re-scoring overlapping rows.  ``total_rows``
        keeps counting monotonically across drains."""
        with self._lock:
            if self._buf is None or self._size == 0:
                return np.zeros((0, 0), np.float64)
            if self._size < self.capacity:
                out = self._buf[:self._size].copy()
            else:
                out = np.concatenate([self._buf[self._next:],
                                      self._buf[:self._next]], axis=0)
            self._next = 0
            self._size = 0
            return out

    def section(self) -> Dict[str, Any]:
        """The ``lifecycle.recorder`` report fragment."""
        with self._lock:
            return {"capacity": self.capacity,
                    "rows": int(self._size),
                    "total_rows": int(self.total_rows),
                    "skipped_rows": int(self.skipped_rows)}
