"""Continuous train→serve lifecycle (ROADMAP item 4).

The trainer and the server stop being two disconnected programs: a
refreshed model is trained INCREMENTALLY off the live incumbent
(``engine.train(init_model=...)`` riding the crash-safe snapshot
machinery), judged against RECORDED live traffic (shadow replay with
divergence / metric / latency gates), and atomically promoted through
the serving registry with the incumbent retained — a post-promotion
watchdog rolls back automatically when serving health regresses.

  * ``recorder``   — bounded ring capture of served feature rows
  * ``shadow``     — gated candidate-vs-incumbent replay
  * ``controller`` — ``LifecycleController``: refit → shadow → promote →
    watch, with every decision in the ``lifecycle`` telemetry section
  * ``budget``     — ``RefitBudget``: rate caps for autonomous refits
    (window cap, min spacing, cooldown-after-rollback, one-at-a-time)
  * ``autopilot``  — ``Autopilot``: the daemon that closes the loop —
    sustained drift verdicts trigger a budgeted refit cycle and a
    per-replica shadow-gated rolling upgrade (schema-v10 ``autopilot``
    report section)

Chaos-testable end to end: ``train.crash`` kills a refit mid-run (resume
is bit-identical), ``serve.predict.fail`` after a promotion drives the
watchdog's automatic rollback (`tests/test_lifecycle.py`), and the soak
drill (`tests/test_soak.py`) runs the full detect→refit→validate→promote
loop against a faulted 2-replica fleet.
"""

from .autopilot import Autopilot
from .budget import RefitBudget
from .controller import (CandidateRejected, LifecycleController,
                         RollbackWatchdog)
from .recorder import TrafficRecorder
from .shadow import shadow_validate

__all__ = ["LifecycleController", "RollbackWatchdog", "CandidateRejected",
           "TrafficRecorder", "shadow_validate", "Autopilot",
           "RefitBudget"]
