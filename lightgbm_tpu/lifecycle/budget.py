"""Refit budget: rate limits for autonomous model refits.

An autopilot that refits whenever drift looks sustained can still melt a
fleet: a pathological feature pipeline yields a permanently-drifted
verdict, every check triggers a refit, and the serving host spends its
CPU on training instead of inference.  ``RefitBudget`` is the single
choke point every autopilot cycle must pass:

  * **window cap** — at most ``max_refits_per_window`` refit *starts*
    inside any rolling ``window_s`` span (failed cycles count: they
    spent the compute);
  * **min spacing** — at least ``min_spacing_s`` between consecutive
    starts, so back-to-back drift verdicts cannot stack cycles;
  * **cooldown after rollback** — a cycle that ended in a rollback
    (shadow-gate abort mid-roll, watchdog breach) freezes refits for
    ``cooldown_s``: if the last candidate regressed, the same training
    recipe will likely regress again until the window moves on;
  * **concurrency** — a hard one-at-a-time lock; a second trigger while
    a cycle is running is suppressed, never queued.

The budget never blocks: ``try_begin`` either admits the cycle or
returns a machine-readable suppression reason the caller records.  Pure
host-side bookkeeping under one leaf lock — no JAX, no collectives.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple

__all__ = ["RefitBudget"]


class RefitBudget:
    """Admission control for autopilot refit cycles (see module doc)."""

    def __init__(self, max_refits_per_window: int = 4,
                 window_s: float = 3600.0,
                 min_spacing_s: float = 60.0,
                 cooldown_s: float = 300.0):
        self.max_refits_per_window = max(int(max_refits_per_window), 1)
        self.window_s = float(window_s)
        self.min_spacing_s = float(min_spacing_s)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._starts: list = []          # monotonic stamps, newest last
        self._last_start: Optional[float] = None
        self._cooldown_until = 0.0
        self._active = False
        self._admitted = 0
        self._suppressed: Dict[str, int] = {}

    # -- admission -----------------------------------------------------

    def try_begin(self) -> Tuple[bool, str]:
        """Admit one refit cycle or return ``(False, reason)``.

        On success the caller OWNS the budget's concurrency slot and
        must call :meth:`end` exactly once, however the cycle ends.
        """
        now = time.monotonic()
        with self._lock:
            reason = self._veto(now)
            if reason:
                self._suppressed[reason] = self._suppressed.get(reason, 0) + 1
                return False, reason
            self._active = True
            self._last_start = now
            self._starts.append(now)
            self._admitted += 1
            return True, ""

    def _veto(self, now: float) -> str:
        """Reason the cycle must not start, or '' — caller holds the
        lock."""
        if self._active:
            return "concurrent_refit"
        if now < self._cooldown_until:
            return "cooldown"
        if self._last_start is not None and \
                now - self._last_start < self.min_spacing_s:
            return "min_spacing"
        self._starts = [t for t in self._starts
                        if now - t < self.window_s]
        if len(self._starts) >= self.max_refits_per_window:
            return "window_exhausted"
        return ""

    def end(self, rolled_back: bool = False) -> None:
        """Release the concurrency slot; a rollback arms the cooldown."""
        with self._lock:
            self._active = False
            if rolled_back:
                self._cooldown_until = time.monotonic() + self.cooldown_s

    def note_rollback(self) -> None:
        """An out-of-band rollback (operator, watchdog) also cools the
        autopilot down — the serving window just proved hostile."""
        with self._lock:
            self._cooldown_until = time.monotonic() + self.cooldown_s

    # -- introspection -------------------------------------------------

    def section(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            in_window = len([t for t in self._starts
                             if now - t < self.window_s])
            return {
                "max_refits_per_window": self.max_refits_per_window,
                "window_s": self.window_s,
                "min_spacing_s": self.min_spacing_s,
                "cooldown_s": self.cooldown_s,
                "refits_in_window": in_window,
                "admitted": self._admitted,
                "active": self._active,
                "in_cooldown": now < self._cooldown_until,
                "suppressed": dict(self._suppressed),
            }
