"""Device batch predictor — all trees traversed on device in bin space.

The analogue of ``Predictor`` (`src/application/predictor.hpp:25-230`), but
instead of per-row double traversal under OpenMP, the input matrix is binned
once with the model's own mappers (exact training-time semantics) and ALL
trees traverse on device as one jitted ``lax.scan`` over packed node arrays
— each scan step advances every row through one tree level-synchronously.

Prediction early stop (`src/boosting/prediction_early_stop.cpp`) becomes a
per-row ``active`` lane re-evaluated every ``pred_early_stop_freq``
iterations: frozen rows stop accumulating, the reference's per-row early
exit (margin = 2|p| for binary, top1−top2 for multiclass).

Requires the training bin mappers — available on a trained booster or one
bound to a dataset; boosters loaded from model text fall back to the host
numpy path in ``GBDT.predict_raw``.  The jitted traversal is module-level
and keyed on pack SHAPES, so rebuilding packs per call (leaf values change
under DART reweighting) does not recompile.
"""

from __future__ import annotations

import functools
import math
from typing import List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .tree import Tree


def pack_trees(models: List[Tree], num_class: int):
    """Stack per-tree node arrays padded to the fleet maxima; inner
    (bin-space) fields, so every decision is an integer compare or a bitset
    probe."""
    T = len(models)
    ni = max(max(t.num_leaves - 1, 1) for t in models)
    nl = max(max(t.num_leaves, 1) for t in models)
    depth = max(max(int(t.leaf_depth[:t.num_leaves].max()), 1)
                for t in models)
    feat = np.zeros((T, ni), np.int32)
    thr = np.zeros((T, ni), np.int32)
    dtyp = np.zeros((T, ni), np.int32)
    lch = np.full((T, ni), -1, np.int32)
    rch = np.full((T, ni), -1, np.int32)
    # f64 leaf values/accumulation when x64 is enabled (CPU tests, dp
    # runs); the production f32 TPU path accumulates in f32 — documented
    # divergence from the host f64 sum at ~1e-7 relative per tree
    import jax as _jax
    lv_dtype = np.float64 if _jax.config.jax_enable_x64 else np.float32
    lval = np.zeros((T, nl), lv_dtype)
    cat_lo = np.zeros((T, ni), np.int32)
    cat_hi = np.zeros((T, ni), np.int32)
    cat_words: List[List[int]] = []
    tree_class = np.arange(T, dtype=np.int32) % max(num_class, 1)
    for i, t in enumerate(models):
        k = t.num_leaves - 1
        words: List[int] = []
        if t.num_leaves <= 1:
            lval[i, 0] = t.leaf_value[0]   # children -1 → leaf 0
        else:
            feat[i, :k] = t.split_feature_inner[:k]
            thr[i, :k] = t.threshold_in_bin[:k]
            dtyp[i, :k] = t.decision_type[:k]
            lch[i, :k] = t.left_child[:k]
            rch[i, :k] = t.right_child[:k]
            lval[i, :t.num_leaves] = t.leaf_value[:t.num_leaves]
            if t.num_cat > 0:
                inner = getattr(t, "_cat_bitsets_inner", {})
                for nd in range(k):
                    if t.decision_type[nd] & 1:
                        cat_idx = int(t.threshold_in_bin[nd])
                        bins = sorted(inner.get(cat_idx, ()))
                        w0 = len(words)
                        nw = (bins[-1] // 32 + 1) if bins else 0
                        chunk = [0] * nw
                        for b_ in bins:
                            chunk[b_ // 32] |= 1 << (b_ % 32)
                        words.extend(chunk)
                        cat_lo[i, nd] = w0
                        cat_hi[i, nd] = w0 + nw
        cat_words.append(words)
    W = max((len(w) for w in cat_words), default=0) or 1
    cat_bits = np.zeros((T, W), np.uint32)
    for i, words in enumerate(cat_words):
        cat_bits[i, :len(words)] = np.asarray(words, np.uint32)
    packs = dict(
        feat=jnp.asarray(feat), thr=jnp.asarray(thr),
        dtyp=jnp.asarray(dtyp), lch=jnp.asarray(lch), rch=jnp.asarray(rch),
        lval=jnp.asarray(lval), cat_bits=jnp.asarray(cat_bits),
        cat_lo=jnp.asarray(cat_lo), cat_hi=jnp.asarray(cat_hi),
        cls=jnp.asarray(tree_class))
    return packs, depth


def _one_tree(bins, p, f_missing, f_default_bin, f_nan_bin, depth):
    """(N,) leaf values of one packed tree over the binned matrix."""
    n = bins.shape[1]
    node = jnp.zeros(n, jnp.int32)
    rows = jnp.arange(n)

    def step(node, _):
        nd = jnp.maximum(node, 0)
        f = p["feat"][nd]
        fv = bins[f, rows].astype(jnp.int32)
        dt = p["dtyp"][nd]
        mt = f_missing[f]
        is_missing = ((mt == 1) & (fv == f_default_bin[f])) | \
                     ((mt == 2) & (fv == f_nan_bin[f]))
        go_left = jnp.where(is_missing, (dt & 2) != 0, fv <= p["thr"][nd])
        # categorical: inner bitset probe (CategoricalDecisionInner)
        lo = p["cat_lo"][nd]
        nw = p["cat_hi"][nd] - lo
        widx = fv >> 5
        word = p["cat_bits"][jnp.clip(lo + widx, 0,
                                      p["cat_bits"].shape[0] - 1)]
        in_set = (widx < nw) & \
            (((word >> (fv & 31).astype(jnp.uint32)) & 1) == 1)
        go_left = jnp.where((dt & 1) != 0, in_set, go_left)
        nxt = jnp.where(go_left, p["lch"][nd], p["rch"][nd])
        return jnp.where(node < 0, node, nxt), None

    node, _ = lax.scan(step, node, None, length=depth)
    leaf = jnp.where(node < 0, ~node, 0)
    return p["lval"][leaf]


@functools.partial(jax.jit, static_argnames=("depth", "K", "es", "es_freq",
                                             "es_margin"))
def _predict_all(bins, packs, f_missing, f_default_bin, f_nan_bin, *,
                 depth: int, K: int, es: bool, es_freq: int,
                 es_margin: float):
    n = bins.shape[1]
    T = packs["feat"].shape[0]
    score0 = jnp.zeros((K, n), packs["lval"].dtype)
    active0 = jnp.ones(n, jnp.bool_)

    def tree_step(carry, xs):
        score, active = carry
        t_idx, pack = xs
        vals = _one_tree(bins, pack, f_missing, f_default_bin, f_nan_bin,
                         depth)
        if es:
            # re-evaluate frozen lanes at iteration boundaries
            # (`predictor.hpp` early-stop hook cadence)
            at_check = (t_idx % (es_freq * K) == 0) & (t_idx > 0)
            if K == 1:
                margin = 2.0 * jnp.abs(score[0])
            else:
                top2 = lax.top_k(score.T, 2)[0]
                margin = top2[:, 0] - top2[:, 1]
            still = margin <= es_margin
            active = jnp.where(at_check, active & still, active)
            vals = vals * active.astype(vals.dtype)
        score = score.at[pack["cls"]].add(vals)
        return (score, active), None

    (score, _), _ = lax.scan(tree_step, (score0, active0),
                             (jnp.arange(T), packs))
    return score


class DevicePredictor:
    """Batched device inference over the model's own bin space."""

    def __init__(self, gbdt, data, num_iteration: int = -1,
                 pred_early_stop: bool = False,
                 pred_early_stop_freq: int = 10,
                 pred_early_stop_margin: float = 10.0):
        self.data = data
        n_models = gbdt._num_models_for(num_iteration)
        models = gbdt.models[:n_models]
        if not models:
            raise ValueError("no trees to predict with")
        self.K = max(gbdt.num_tree_per_iteration, 1)
        num_bin, missing, default_bin, _ = data.feature_meta_arrays()
        self.f_missing = jnp.asarray(missing)
        self.f_default_bin = jnp.asarray(default_bin)
        self.f_nan_bin = jnp.asarray(num_bin - 1)
        self.packs, self.depth = pack_trees(models, self.K)
        self.es = bool(
            pred_early_stop and gbdt.objective is not None
            and gbdt.objective.name in ("binary", "multiclass",
                                        "multiclassova"))
        self.es_freq = max(int(pred_early_stop_freq), 1)
        self.es_margin = float(pred_early_stop_margin)

    def predict_binned(self, bins: jax.Array) -> jax.Array:
        """(K, N) raw scores from an (F_pad, N) device bin matrix."""
        return _predict_all(
            bins, self.packs, self.f_missing, self.f_default_bin,
            self.f_nan_bin, depth=self.depth, K=self.K, es=self.es,
            es_freq=self.es_freq, es_margin=self.es_margin)

    # categories unseen at train time probe past every split bitset → right
    # child, matching raw-value traversal (`tree.h:250-268`)
    OOV_BIN = 1 << 20

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """(n,) or (n, K) raw scores; X binned host-side with the model's
        own mappers (raw-prediction semantics for categoricals) through the
        vectorized padded-array binner (`serving/binner.py` — golden parity
        with the per-feature ``values_to_bins_predict`` loop it replaced)."""
        from .serving.binner import BinnerArrays

        bins = BinnerArrays.for_data(self.data).bin_host(X)
        score = np.asarray(self.predict_binned(jnp.asarray(bins)))
        return score[0] if self.K == 1 else score.T


class PredictionBinSchema:
    """Duck-typed stand-in for ``_ConstructedDataset`` covering exactly the
    surface the device predictor and binner read: ``bin_mappers``,
    ``used_feature_map``, ``feature_meta_arrays`` and the padded feature
    count.  Built by ``reconstruct_bin_schema`` for boosters loaded from
    model text (no training data attached)."""

    FEATURE_TILE = 8  # match _ConstructedDataset's feature-axis padding

    def __init__(self, bin_mappers, used_feature_map):
        self.bin_mappers = list(bin_mappers)
        self.used_feature_map = np.asarray(used_feature_map, dtype=np.int32)
        fu = len(self.bin_mappers)
        f_pad = ((max(fu, 1) + self.FEATURE_TILE - 1)
                 // self.FEATURE_TILE) * self.FEATURE_TILE
        # shape carrier only — the schema never holds binned rows
        self.bins = np.zeros((f_pad, 0), dtype=np.uint16)
        self._feature_meta = None
        self._binner_arrays = None

    @property
    def num_used_features(self) -> int:
        return len(self.bin_mappers)

    def feature_meta_arrays(self):
        if self._feature_meta is None:
            from .binning import BIN_CATEGORICAL
            num_bin = np.array([m.num_bin for m in self.bin_mappers],
                               dtype=np.int32)
            missing = np.array([m.missing_type for m in self.bin_mappers],
                               dtype=np.int32)
            default_bin = np.array([m.default_bin for m in self.bin_mappers],
                                   dtype=np.int32)
            is_categorical = np.array([m.bin_type == BIN_CATEGORICAL
                                       for m in self.bin_mappers], dtype=bool)
            self._feature_meta = (num_bin, missing, default_bin,
                                  is_categorical)
        return self._feature_meta


def reconstruct_bin_schema(gbdt) -> PredictionBinSchema:
    """Rebuild a servable bin space for a text-loaded booster.

    The model text carries raw thresholds, per-node missing semantics and
    the categorical vocabularies (``feature_infos``) but not the training
    bin boundaries.  For PREDICTION none of the boundaries between
    thresholds matter: a synthetic mapper whose upper bounds are exactly
    the feature's split thresholds (plus the ±kZeroThreshold pair when a
    node uses zero-as-missing, plus the NaN bin when a node uses NaN
    missing) reproduces raw traversal decisions bit-for-bit —
    ``v <= t  ⇔  bin(v) <= bin(t)`` when every ``t`` is itself a bound.

    Side effect: every tree is rebound into the synthetic bin space
    (``split_feature_inner`` / ``threshold_in_bin`` / inner cat bitsets),
    after which the booster serves on device like a freshly trained one.
    """
    from .binning import (BIN_CATEGORICAL, BIN_NUMERICAL, MISSING_NAN,
                          MISSING_ZERO, BinMapper, kZeroThreshold)
    from .boosting.gbdt import rebind_tree_to_dataset

    models = gbdt.models
    nfeat = int(gbdt.max_feature_idx) + 1
    thresholds = [set() for _ in range(nfeat)]
    bitset_cats = [set() for _ in range(nfeat)]
    missing = [0] * nfeat
    is_cat = [False] * nfeat
    for t in models:
        for nd in range(t.num_leaves - 1):
            j = int(t.split_feature[nd])
            dt = int(t.decision_type[nd])
            missing[j] = max(missing[j], (dt >> 2) & 3)
            if dt & 1:
                is_cat[j] = True
                cat_idx = int(t.threshold[nd])
                lo, hi = t.cat_boundaries[cat_idx], \
                    t.cat_boundaries[cat_idx + 1]
                for w in range(lo, hi):
                    word = int(t.cat_threshold[w])
                    for b in range(32):
                        if (word >> b) & 1:
                            bitset_cats[j].add(32 * (w - lo) + b)
            else:
                thresholds[j].add(float(t.threshold[nd]))

    # used features: the training-time non-trivial set when feature_infos
    # is intact, else every feature the trees actually split on
    infos = list(getattr(gbdt, "feature_infos", []) or [])
    if len(infos) == nfeat:
        used = [j for j in range(nfeat) if infos[j] != "none"]
    else:
        infos = ["none"] * nfeat
        used = sorted(j for j in range(nfeat)
                      if thresholds[j] or is_cat[j])

    mappers = []
    for j in used:
        m = BinMapper()
        m.missing_type = missing[j]
        m.is_trivial = False
        info = infos[j]
        if is_cat[j] or (info not in ("none", "") and not
                         info.startswith("[")):
            m.bin_type = BIN_CATEGORICAL
            if info not in ("none", "") and not info.startswith("["):
                cats = [int(c) for c in info.split(":")]
            else:
                cats = sorted(bitset_cats[j])
                if m.missing_type == MISSING_NAN:
                    cats.append(-1)
            m.bin_2_categorical = cats
            m.categorical_2_bin = {c: i for i, c in enumerate(cats)}
            m.num_bin = max(len(cats), 1)
            m.default_bin = m.categorical_2_bin.get(0, m.num_bin - 1)
        else:
            m.bin_type = BIN_NUMERICAL
            bounds = set(thresholds[j])
            if m.missing_type == MISSING_ZERO:
                bounds.update((-kZeroThreshold, kZeroThreshold))
            bounds = sorted(bounds) + [math.inf]
            if m.missing_type == MISSING_NAN:
                bounds.append(math.nan)
            m.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
            m.num_bin = len(bounds)
            m.default_bin = int(m.value_to_bin(0.0))
        mappers.append(m)

    schema = PredictionBinSchema(mappers, used)
    for t in models:
        t.needs_rebind = True
        rebind_tree_to_dataset(t, schema)
    return schema
