// Native dense text parser — the C++ fast path behind
// ``lightgbm_tpu.io.parser.load_data_file``.
//
// Role analogue: the reference's Parser/DatasetLoader text pipeline
// (`src/io/parser.cpp`, `src/io/dataset_loader.cpp:160-264`), which parses
// CSV/TSV with hand-rolled Atof under OpenMP.  Here: one pass to index line
// starts, then std::thread workers strtod-parse disjoint line ranges into a
// preallocated row-major buffer.
//
// Exported C ABI (ctypes):
//   long lgbt_parse_dense(path, delim, skip_rows, &data, &rows, &cols)
//     delim == ' '  → any run of spaces/tabs separates fields
//     otherwise     → single-char delimiter; empty interior fields = NaN,
//                     trailing delimiters ignored (numpy-fallback parity)
//   void lgbt_free(data)
//
// Build: python -m lightgbm_tpu.native.build  (g++ -O3 -shared -fPIC)

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <string>
#include <thread>
#include <vector>

namespace {

// number of data fields in [p, end) for the given delimiter
long count_fields(const char* p, const char* end, char delim) {
  long n = 0;
  if (delim == ' ') {
    bool in_tok = false;
    for (; p < end; ++p) {
      bool ws = (*p == ' ' || *p == '\t');
      if (!ws && !in_tok) { ++n; in_tok = true; }
      if (ws) in_tok = false;
    }
  } else {
    // trailing delimiters do not open a new field
    const char* last = end;
    while (last > p && (last[-1] == delim)) --last;
    if (last > p) {
      n = 1;
      for (const char* q = p; q < last; ++q)
        if (*q == delim) ++n;
    }
  }
  return n;
}

// parse one line's fields into out[0..cols); missing fields -> NaN
void parse_line(const char* p, const char* end, char delim, double* out,
                long cols) {
  const double kNaN = std::numeric_limits<double>::quiet_NaN();
  long c = 0;
  if (delim == ' ') {
    while (p < end && c < cols) {
      while (p < end && (*p == ' ' || *p == '\t')) ++p;
      if (p >= end) break;
      char* q;
      out[c++] = std::strtod(p, &q);
      if (q == p) {  // unparsable token: NaN, skip it
        out[c - 1] = kNaN;
        while (p < end && !(*p == ' ' || *p == '\t')) ++p;
      } else {
        p = q;
      }
    }
  } else {
    while (c < cols) {
      const char* tok_end = p;
      while (tok_end < end && *tok_end != delim) ++tok_end;
      if (tok_end == p) {
        out[c++] = kNaN;  // empty field
      } else {
        char* q;
        double v = std::strtod(p, &q);
        out[c++] = (q == p) ? kNaN : v;
      }
      if (tok_end >= end) break;
      p = tok_end + 1;
    }
  }
  for (; c < cols; ++c) out[c] = kNaN;
}

}  // namespace

extern "C" {

long lgbt_parse_dense(const char* path, char delim, int skip_rows,
                      double** out_data, long* out_rows, long* out_cols) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::string buf(static_cast<size_t>(size), '\0');
  if (size > 0 && std::fread(&buf[0], 1, static_cast<size_t>(size), f) !=
                      static_cast<size_t>(size)) {
    std::fclose(f);
    return -2;
  }
  std::fclose(f);

  // index non-empty lines
  std::vector<std::pair<const char*, const char*>> lines;
  const char* p = buf.data();
  const char* end = buf.data() + size;
  while (p < end) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(end - p)));
    const char* le = nl ? nl : end;
    const char* trimmed = le;
    while (trimmed > p && (trimmed[-1] == '\r')) --trimmed;
    bool blank = true;
    for (const char* q = p; q < trimmed; ++q)
      if (!std::isspace(static_cast<unsigned char>(*q))) { blank = false; break; }
    if (!blank) lines.emplace_back(p, trimmed);
    p = nl ? nl + 1 : end;
  }
  if (static_cast<size_t>(skip_rows) >= lines.size()) return -3;
  lines.erase(lines.begin(), lines.begin() + skip_rows);

  long rows = static_cast<long>(lines.size());
  long cols = count_fields(lines[0].first, lines[0].second, delim);
  if (cols <= 0) return -4;

  double* data = static_cast<double*>(
      std::malloc(sizeof(double) * static_cast<size_t>(rows) *
                  static_cast<size_t>(cols)));
  if (!data) return -5;

  unsigned nthreads = std::thread::hardware_concurrency();
  if (nthreads == 0) nthreads = 1;
  if (rows < 4096) nthreads = 1;
  std::vector<std::thread> workers;
  long chunk = (rows + nthreads - 1) / nthreads;
  for (unsigned t = 0; t < nthreads; ++t) {
    long lo = t * chunk;
    long hi = std::min(rows, lo + chunk);
    if (lo >= hi) break;
    workers.emplace_back([&, lo, hi]() {
      for (long i = lo; i < hi; ++i)
        parse_line(lines[static_cast<size_t>(i)].first,
                   lines[static_cast<size_t>(i)].second, delim,
                   data + i * cols, cols);
    });
  }
  for (auto& w : workers) w.join();

  *out_data = data;
  *out_rows = rows;
  *out_cols = cols;
  return rows * cols;
}

void lgbt_free(double* pdata) { std::free(pdata); }

}  // extern "C"
