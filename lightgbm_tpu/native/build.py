"""Build the native shared library: ``python -m lightgbm_tpu.native.build``.

Compiles ``parse.cpp`` (and any future native sources) into ``_native.so``
next to this file with g++.  ``lightgbm_tpu.native`` also attempts this
automatically on first import when the library is missing or stale.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
SOURCES = [os.path.join(_HERE, "parse.cpp")]
TARGET = os.path.join(_HERE, "_native.so")


def build(quiet: bool = False) -> str:
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        raise RuntimeError("no C++ compiler found (set $CXX)")
    cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", TARGET] + SOURCES
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(f"native build failed:\n{res.stderr}")
    if not quiet:
        print(f"built {TARGET}")
    return TARGET


def is_stale() -> bool:
    if not os.path.exists(TARGET):
        return True
    t = os.path.getmtime(TARGET)
    return any(os.path.getmtime(s) > t for s in SOURCES)


if __name__ == "__main__":
    build()
    sys.exit(0)
