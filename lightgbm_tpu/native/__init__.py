"""Native (C++) runtime pieces, loaded via ctypes.

Currently: the dense text parser fast path (``parse_dense``) — the analogue
of the reference's OpenMP text parsing (`src/io/parser.cpp`,
`src/io/dataset_loader.cpp:160-264`).  The library auto-builds on first
import when a C++ toolchain is available; without one, importing names from
this package raises ImportError and callers fall back to numpy paths.
"""

from __future__ import annotations

import ctypes
import os
import warnings

import numpy as np

from . import build as _build

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if _build.is_stale():
        try:
            _build.build(quiet=True)
        except Exception as e:  # no toolchain / compile error → soft-fail
            raise ImportError(f"lightgbm_tpu native library unavailable: {e}")
    lib = ctypes.CDLL(_build.TARGET)
    lib.lgbt_parse_dense.restype = ctypes.c_long
    lib.lgbt_parse_dense.argtypes = [
        ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
        ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
    lib.lgbt_free.restype = None
    lib.lgbt_free.argtypes = [ctypes.POINTER(ctypes.c_double)]
    _lib = lib
    return lib


def parse_dense(path: str, delim: str = " ", skip_rows: int = 0) -> np.ndarray:
    """Parse a dense delimited text file to an (rows, cols) f64 matrix.

    delim ' ' means any run of spaces/tabs; otherwise a single-char
    delimiter with interior empty fields as NaN (numpy-fallback parity).
    """
    lib = _load()
    data = ctypes.POINTER(ctypes.c_double)()
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    rc = lib.lgbt_parse_dense(path.encode(), delim.encode(), skip_rows,
                              ctypes.byref(data), ctypes.byref(rows),
                              ctypes.byref(cols))
    if rc < 0:
        raise IOError(f"native parse of {path!r} failed (code {rc})")
    try:
        out = np.ctypeslib.as_array(data, shape=(rows.value, cols.value)).copy()
    finally:
        lib.lgbt_free(data)
    return out
