"""Static per-program cost ledger: pin FLOPs, bytes and exchange payloads.

Four rounds of perf work (Pallas kernels, overlap, quantized gradients,
pod) are queued behind one TPU session, so a CPU-only PR can silently
regress the compute/byte profile of the very programs the hardware round
will validate.  The existing gate pins collective *sites* and *order*
(budgets.json / sequences.json); this pass pins how much WORK and MEMORY
each traced program does:

  * **flops / bytes_accessed** — XLA's own ``cost_analysis()`` over the
    lowered (not compiled) program: the closed jaxpr is rebuilt into a
    callable (``jaxpr_as_fun``), lowered for the gate's CPU platform and
    its analytical cost model read back.  Deterministic for a fixed jax
    version and platform.
  * **exchange_bytes** — per-collective-primitive payload bytes from the
    jaxpr walk (`jaxpr_lint.collect_stats`), generalizing the one-off
    int16-exchange pin: EVERY program's collective payload profile is
    pinned, exact by default.
  * **peak_live_bytes** — a liveness-walk estimate over the jaxpr: each
    value allocates at its defining eqn and frees after its last use
    (program outputs live to the end); sub-jaxpr (while/scan/cond body)
    peaks ride on top of the live set at their call site.  An estimate —
    XLA fuses and rematerializes — but a deterministic one, and a 2x
    jump here is a real regression no matter what the scheduler does.

All of it is pinned in the checked-in ``analysis/costs.json`` with
per-metric relative tolerance bands (``tolerance``); ``--dump-costs``
re-derives the file byte-identically (same review-artifact workflow as
budgets/sequences).  A gate failure names the program, the metric, the
pinned vs measured values, and the heaviest jaxpr primitives so review
starts at the offending region instead of a diff hunt.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from . import jaxpr_lint
from .common import COSTS_PATH, Finding, load_costs

#: pinned metrics, in report order
METRICS = ("flops", "bytes_accessed", "peak_live_bytes", "exchange_bytes")

#: default relative tolerance bands (two-sided).  flops/bytes ride XLA's
#: cost model, which shifts slightly across jax versions — a band absorbs
#: that; the exchange payload is OUR wire contract and stays exact.
DEFAULT_TOLERANCE = {
    "flops": 0.10,
    "bytes_accessed": 0.15,
    "peak_live_bytes": 0.15,
    "exchange_bytes": 0.0,
}


def _aval_bytes(v: Any) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    import numpy as np
    size = 1
    for d in shape:
        size *= int(d)
    return size * np.dtype(dtype).itemsize


def xla_costs(closed_jaxpr) -> Tuple[int, int]:
    """(flops, bytes_accessed) from XLA's analytical cost model over the
    LOWERED program — no compilation, no execution."""
    import jax

    fn = jax.core.jaxpr_as_fun(closed_jaxpr)
    args = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
            for v in closed_jaxpr.jaxpr.invars]
    lowered = jax.jit(fn).lower(*args)
    ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):          # per-device list on some
        ca = ca[0] if ca else {}               # jax versions
    ca = ca or {}
    return int(round(float(ca.get("flops", 0.0)))), \
        int(round(float(ca.get("bytes accessed", 0.0))))


def _sub_jaxprs(eqn) -> List[Any]:
    out = []
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else [v]
        for s in vs:
            inner = getattr(s, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                out.append(inner)
            elif hasattr(s, "eqns"):
                out.append(s)
    return out


def peak_live_bytes(jaxpr) -> int:
    """Liveness-walk peak over one (open) jaxpr: values allocate at
    their defining eqn, free after their last use; inputs/constants are
    live from the start, outputs to the end.  A sub-jaxpr's peak rides
    on top of the live set at its call-site eqn."""
    eqns = list(jaxpr.eqns)
    n = len(eqns)
    if n == 0:
        return sum(_aval_bytes(v)
                   for v in list(jaxpr.invars) + list(jaxpr.constvars))

    def_idx: Dict[Any, int] = {}
    last_use: Dict[Any, int] = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        def_idx[v] = 0
        last_use[v] = 0
    for i, eqn in enumerate(eqns):
        for iv in eqn.invars:
            if hasattr(iv, "val"):             # Literal: no lifetime
                continue
            last_use[iv] = i
            def_idx.setdefault(iv, 0)
        for ov in eqn.outvars:
            def_idx[ov] = i
            last_use[ov] = max(last_use.get(ov, i), i)
    for v in jaxpr.outvars:
        if hasattr(v, "val"):
            continue
        last_use[v] = n - 1
        def_idx.setdefault(v, 0)

    delta = [0] * (n + 1)
    for v, d in def_idx.items():
        delta[d] += _aval_bytes(v)
        delta[last_use[v] + 1] -= _aval_bytes(v)
    live = 0
    live_at = [0] * n
    for i in range(n):
        live += delta[i]
        live_at[i] = live
    peak = max(live_at)
    for i, eqn in enumerate(eqns):
        subs = _sub_jaxprs(eqn)
        if subs:
            peak = max(peak, live_at[i] + max(peak_live_bytes(s)
                                              for s in subs))
    return peak


def measure(closed_jaxpr) -> Dict[str, Any]:
    """The full cost row for one traced program."""
    flops, bytes_accessed = xla_costs(closed_jaxpr)
    stats = jaxpr_lint.collect_stats(closed_jaxpr)
    return {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "peak_live_bytes": int(peak_live_bytes(closed_jaxpr.jaxpr)),
        "exchange_bytes": dict(sorted(stats["collective_bytes"].items())),
        "eqns": int(stats["eqns"]),
    }


def _heaviest_region(closed_jaxpr, top: int = 3) -> str:
    """The review starting point a cost failure names: the heaviest
    primitives in the program by total output bytes."""
    weights: Dict[str, Tuple[int, int]] = {}
    for eqn in jaxpr_lint.iter_eqns(closed_jaxpr.jaxpr):
        nb = sum(_aval_bytes(ov) for ov in eqn.outvars)
        cnt, tot = weights.get(eqn.primitive.name, (0, 0))
        weights[eqn.primitive.name] = (cnt + 1, tot + nb)
    ranked = sorted(weights.items(), key=lambda kv: -kv[1][1])[:top]
    return ", ".join(f"{name} x{cnt} ({tot} out bytes)"
                     for name, (cnt, tot) in ranked)


def costs_from(traced: jaxpr_lint.TracedPrograms,
               tolerance: Optional[Dict[str, float]] = None
               ) -> Dict[str, Any]:
    """A costs.json payload pinning the CURRENT measured costs
    (``--dump-costs``).  Moving a pin is a deliberate, reviewed act."""
    return {
        "_comment": "Per-program static cost ledger (XLA cost_analysis "
                    "flops/bytes, jaxpr collective payload bytes, "
                    "liveness-walk peak-live bytes). Re-derive with "
                    "--dump-costs and commit the diff when a reviewed "
                    "change legitimately moves a cost; tolerance bands "
                    "are relative, two-sided, per metric.",
        "tolerance": dict(tolerance if tolerance is not None
                          else DEFAULT_TOLERANCE),
        "programs": {name: measure(closed)
                     for name, closed in sorted(traced.closed.items())},
    }


def dump_costs(traced: jaxpr_lint.TracedPrograms, path: str = COSTS_PATH,
               tolerance: Optional[Dict[str, float]] = None
               ) -> Dict[str, Any]:
    """Atomically (re)write ``costs.json`` — byte-stable: sorted keys,
    2-space indent, trailing newline (the budgets/sequences workflow)."""
    payload = costs_from(traced, tolerance=tolerance)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return payload


def _check_scalar(name: str, metric: str, pinned: int, measured: int,
                  tol: float, closed, file: str) -> Optional[Finding]:
    band = abs(pinned) * max(float(tol), 0.0)
    if abs(measured - pinned) <= band:
        return None
    direction = "above" if measured > pinned else "below"
    return Finding(
        "costmodel", "cost-regression", file,
        f"program {name!r} {metric}: measured {measured} vs pinned "
        f"{pinned} (±{tol:.0%} band) — {direction} the band; heaviest "
        f"region: {_heaviest_region(closed)}. A reviewed change that "
        f"legitimately moves this cost re-pins it via --dump-costs",
        symbol=name)


def check_costs(name: str, closed_jaxpr, entry: Dict[str, Any],
                tolerance: Dict[str, float],
                measured: Optional[Dict[str, Any]] = None
                ) -> List[Finding]:
    """Findings for one traced program against its costs.json entry."""
    file = jaxpr_lint.PROGRAM_FILES.get(name, "lightgbm_tpu")
    if measured is None:
        measured = measure(closed_jaxpr)
    if not entry:
        return [Finding(
            "costmodel", "cost-unpinned", file,
            f"program {name!r} has no analysis/costs.json entry — pin "
            f"its cost ledger with --dump-costs", symbol=name)]
    findings: List[Finding] = []
    for metric in ("flops", "bytes_accessed", "peak_live_bytes"):
        if metric not in entry:
            findings.append(Finding(
                "costmodel", "cost-unpinned", file,
                f"program {name!r} pins no {metric!r} — re-derive "
                f"costs.json with --dump-costs", symbol=name))
            continue
        f = _check_scalar(name, metric, int(entry[metric]),
                          int(measured[metric]),
                          float(tolerance.get(metric, 0.0)),
                          closed_jaxpr, file)
        if f is not None:
            findings.append(f)
    pinned_ex: Dict[str, int] = {
        k: int(v) for k, v in (entry.get("exchange_bytes") or {}).items()}
    measured_ex: Dict[str, int] = dict(measured["exchange_bytes"])
    tol = float(tolerance.get("exchange_bytes", 0.0))
    for prim in sorted(set(pinned_ex) | set(measured_ex)):
        p, m = pinned_ex.get(prim, 0), measured_ex.get(prim, 0)
        if abs(m - p) <= abs(p) * tol:
            continue
        findings.append(Finding(
            "costmodel", "cost-regression", file,
            f"program {name!r} exchange_bytes[{prim}]: measured {m} vs "
            f"pinned {p} — the collective payload contract moved (e.g. a "
            f"quantized wire tier silently widening); re-pin via "
            f"--dump-costs only with review", symbol=name))
    return findings


def run(costs: Optional[Dict[str, Any]] = None,
        traced: Optional[jaxpr_lint.TracedPrograms] = None):
    """Check every traced program against the checked-in ledger.

    Returns ``(findings, measured, skipped)``: ``measured`` maps program
    name to its cost row (surfaced in the JSON report), ``skipped`` maps
    untraced programs to reasons.  ``traced`` reuses the gate's shared
    trace cache (this pass lowers but never compiles)."""
    if costs is None:
        costs = load_costs()
    if traced is None:
        traced = jaxpr_lint.trace_programs()
    tolerance = {**DEFAULT_TOLERANCE, **costs.get("tolerance", {})}
    pinned = costs.get("programs", {})
    findings: List[Finding] = []
    measured: Dict[str, Dict[str, Any]] = {}
    for name, closed in sorted(traced.closed.items()):
        row = measure(closed)
        measured[name] = row
        findings.extend(check_costs(name, closed,
                                    pinned.get(name, {}), tolerance,
                                    measured=row))
    # a pin whose program no longer exists is ledger rot, same class as
    # a stale allowlist entry
    for name in sorted(pinned):
        if name not in jaxpr_lint.PROGRAM_FILES:
            findings.append(Finding(
                "costmodel", "cost-stale-pin", "analysis/costs.json",
                f"costs.json pins unknown program {name!r} (removed or "
                f"renamed) — re-derive with --dump-costs", symbol=name))
    return findings, measured, dict(traced.skipped)
