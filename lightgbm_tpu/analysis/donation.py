"""Use-after-donate analysis (LGB009) + donation-liveness runtime assert.

PR 12's cross-iteration buffer donation (`Config.tpu_donate_buffers`,
``jax.jit(..., donate_argnums=...)``) frees the previous iteration's
grad/hess/score HBM for the next — on a TPU that is the difference
between fitting the 1M-row problem and OOMing.  Two silent failure
modes guard-rail it:

  * **use-after-donate** — a donated buffer is INVALID after the call;
    jax raises only when the deleted array is actually touched, which on
    the async dispatch path can be iterations later and rank-dependent.
    The AST pass maps every ``jax.jit(..., donate_argnums=...)`` site
    (assignment, ternary assignment, or ``functools.partial`` decorator)
    to its donated positions — including one hop through wrapper methods
    that forward their own parameters into donated slots, and through
    factory methods that *return* a donating jit — then flags any read
    of a donated binding after the call in the same scope (LGB009),
    plus any single call passing one binding to BOTH a donated and a
    non-donated position (aliased donation: the runtime either copies,
    silently un-donating, or consumes the alias).
  * **donation silently dropped** — donation is a *compile option*, not
    part of the jaxpr; a refactor that rebuilds the jit without
    ``donate_argnums`` (or a platform that declines the alias) loses the
    PR 12 win with zero test signal.  :func:`check_hlo_aliasing` lowers
    each designated donating program and asserts the compiled HLO
    carries ``input_output_alias`` — the gate's runtime proof that the
    donation survived all the way through XLA.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .common import Finding, PKG_ROOT, apply_allowlist, load_allowlist, \
    rel_file

# -- donating-callable discovery ----------------------------------------------


def _donate_argnums(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """The ``donate_argnums`` of a ``jax.jit(...)`` /
    ``functools.partial(jax.jit, ...)`` call node, or None."""
    name = ""
    f = call.func
    if isinstance(f, ast.Attribute):
        name = f.attr
    elif isinstance(f, ast.Name):
        name = f.id
    if name == "partial":
        inner = call.args[0] if call.args else None
        target = inner.attr if isinstance(inner, ast.Attribute) else (
            inner.id if isinstance(inner, ast.Name) else "")
        if target != "jit":
            return None
    elif name != "jit":
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Tuple):
            nums = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.append(e.value)
            return tuple(nums)
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
    return None


def _target_name(node: ast.expr) -> str:
    """Bare name an assignment binds: ``self._jit_fused`` -> ``_jit_fused``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _iter_functions(tree: ast.Module):
    """(name, node) for every function at any nesting depth."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node


def collect_donators(trees: Sequence[Tuple[str, ast.Module]]
                     ) -> Dict[str, Set[int]]:
    """Bare callable name -> donated positional indices, package-wide.

    Three layers, each one AST sweep:

    1. direct sites — ``X = jax.jit(fn, donate_argnums=...)`` (either arm
       of a ternary) and ``@functools.partial(jax.jit, ...,
       donate_argnums=...)`` decorators;
    2. factories — a method whose ``return`` yields a known donating
       binding (``_fused_iter_fn`` returning ``self._jit_fused``): a
       call of its RESULT donates at the same positions;
    3. wrappers — a method that forwards its own positional parameters
       into donated slots of a known donator (``train_async`` passing
       ``grad, hess`` into ``self._jit_tree_w``): callers of the wrapper
       donate at the corresponding parameter positions (``self``
       excluded, defaulted trailing params never marked).
    """
    donators: Dict[str, Set[int]] = {}
    # layer 1: direct jit sites
    for _, tree in trees:
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                values = [node.value]
                if isinstance(node.value, ast.IfExp):
                    values = [node.value.body, node.value.orelse]
                for v in values:
                    if not isinstance(v, ast.Call):
                        continue
                    nums = _donate_argnums(v)
                    if not nums:
                        continue
                    for tgt in node.targets:
                        name = _target_name(tgt)
                        if name:
                            donators.setdefault(name, set()).update(nums)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        nums = _donate_argnums(dec)
                        if nums:
                            donators.setdefault(node.name,
                                                set()).update(nums)
    # layer 2: factories returning a donating binding
    for _, tree in trees:
        for fname, fn in _iter_functions(tree):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Return) or node.value is None:
                    continue
                ret = _target_name(node.value)
                if ret in donators:
                    donators.setdefault(fname, set()).update(donators[ret])
    # layer 3: wrappers forwarding parameters into donated slots
    wrappers: Dict[str, Set[int]] = {}
    for _, tree in trees:
        for fname, fn in _iter_functions(tree):
            params = [a.arg for a in fn.args.args]
            offset = 1 if params[:1] == ["self"] else 0
            callable_params = params[offset:]
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = _target_name(node.func)
                nums = donators.get(callee)
                if not nums:
                    continue
                for pos in nums:
                    if pos >= len(node.args):
                        continue
                    arg = node.args[pos]
                    if isinstance(arg, ast.Name) and \
                            arg.id in callable_params:
                        wrappers.setdefault(fname, set()).add(
                            callable_params.index(arg.id))
    for name, nums in wrappers.items():
        donators.setdefault(name, set()).update(nums)
    return donators


# -- per-scope use-after-donate checking --------------------------------------


def _expr_text(node: ast.expr) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _donating_calls(fn: ast.AST, donators: Dict[str, Set[int]]):
    """(call, donated positions adjusted for boundness) in ``fn``.  A
    ``self.method(...)`` / ``obj.method(...)`` call binds the receiver,
    so the AST positions equal the donator's recorded positions for
    methods discovered via their jit-binding name (the jit wraps the
    unbound callable only when decorated — handled per sweep below)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = _target_name(node.func)
        nums = donators.get(callee)
        if nums:
            yield node, sorted(nums)
        elif isinstance(node.func, ast.Call):
            # factory-result call: self._fused_iter_fn()(score, ...)
            inner = _target_name(node.func.func)
            nums = donators.get(inner)
            if nums:
                yield node, sorted(nums)


def check_scope(fn: ast.AST, qualname: str, rf: str,
                donators: Dict[str, Set[int]]) -> List[Finding]:
    findings: List[Finding] = []
    for call, nums in _donating_calls(fn, donators):
        texts: Dict[int, str] = {}
        for pos in nums:
            if pos < len(call.args):
                t = _expr_text(call.args[pos])
                if t:
                    texts[pos] = t
        # aliased donation: one binding at a donated AND another position
        for pos, t in texts.items():
            for j, other in enumerate(call.args):
                # a donated pair reports once, from its lower position
                if j == pos or (j in texts and j < pos):
                    continue
                if _expr_text(other) == t:
                    findings.append(Finding(
                        "donation", "LGB009-use-after-donate", rf,
                        f"{t!r} passed to donated position {pos} AND "
                        f"position {j} of the same call — the aliased "
                        f"buffer is either copied (donation silently "
                        f"dropped) or consumed out from under the other "
                        f"argument; pass distinct buffers",
                        line=call.lineno, symbol=qualname))
                    break
        # use-after-donate: a read of the donated binding later in the
        # scope, before a rebinding kills it
        end = getattr(call, "end_lineno", call.lineno)
        for t in set(texts.values()):
            kill = None
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)) and \
                        node.lineno >= call.lineno:
                    targets = node.targets if isinstance(
                        node, ast.Assign) else [node.target]
                    flat: List[ast.expr] = []
                    for x in targets:
                        flat.extend(x.elts if isinstance(
                            x, (ast.Tuple, ast.List)) else [x])
                    if any(_expr_text(x) == t for x in flat):
                        if kill is None or node.lineno < kill:
                            kill = node.lineno
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Name, ast.Attribute)):
                    continue
                if not isinstance(getattr(node, "ctx", None), ast.Load):
                    continue
                if node.lineno <= end or _expr_text(node) != t:
                    continue
                if kill is not None and node.lineno >= kill:
                    continue
                findings.append(Finding(
                    "donation", "LGB009-use-after-donate", rf,
                    f"{t!r} is donated at line {call.lineno} and read "
                    f"again at line {node.lineno} — a donated buffer is "
                    f"invalid after the call (the failure surfaces "
                    f"asynchronously, possibly iterations later); "
                    f"rebind before reuse",
                    line=node.lineno, symbol=qualname))
                break           # one finding per donated binding
    return findings


def _qualnames(tree: ast.Module) -> List[Tuple[str, ast.AST]]:
    out: List[Tuple[str, ast.AST]] = []

    def visit(node: ast.AST, stack: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((".".join(stack + [child.name]), child))
                visit(child, stack + [child.name])
            elif isinstance(child, ast.ClassDef):
                visit(child, stack + [child.name])
            else:
                visit(child, stack)

    visit(tree, [])
    return out


def _package_trees(paths: Optional[Sequence[str]] = None
                   ) -> List[Tuple[str, ast.Module]]:
    if paths is None:
        paths = []
        for dirpath, dirnames, filenames in os.walk(PKG_ROOT):
            dirnames[:] = sorted(x for x in dirnames if x != "__pycache__")
            paths.extend(os.path.join(dirpath, f) for f in sorted(filenames)
                         if f.endswith(".py"))
    trees = []
    for p in paths:
        with open(p) as fh:
            trees.append((p, ast.parse(fh.read(), filename=p)))
    return trees


def use_after_donate(paths: Optional[Sequence[str]] = None
                     ) -> List[Finding]:
    """LGB009 findings package-wide (no allowlist applied)."""
    trees = _package_trees(paths)
    donators = collect_donators(trees)
    findings: List[Finding] = []
    for path, tree in trees:
        rf = rel_file(path)
        for qualname, fn in _qualnames(tree):
            findings.extend(check_scope(fn, qualname, rf, donators))
    return findings


# -- runtime donation-liveness assert -----------------------------------------

#: the designated donating programs: name -> (source file, min devices).
#: Each MUST lower with input->output aliasing in the compiled HLO when
#: tpu_donate_buffers is forced on — otherwise the PR 12 HBM win is
#: silently gone.
DONATING_PROGRAMS = {
    "learner_wave": ("lightgbm_tpu/learner_wave.py", 1),
    "feature_sharded": ("lightgbm_tpu/parallel/feature_sharded.py", 2),
    "gbdt_fused": ("lightgbm_tpu/boosting/gbdt.py", 1),
}

_ALIAS_MARK = "input_output_alias"


def _hlo_learner_wave() -> str:
    import jax
    import jax.numpy as jnp

    from ..config import Config
    from ..learner_wave import WaveTPUTreeLearner
    from .jaxpr_lint import _BASE_PARAMS, _toy_dataset

    params = dict(_BASE_PARAMS, tpu_donate_buffers="on")
    ds = _toy_dataset(512, 4, params)
    learner = WaveTPUTreeLearner(Config.from_params(params), ds.constructed)
    assert learner._donate, "tpu_donate_buffers=on did not engage"
    n = ds.constructed.num_data_padded
    g, h, b = (jnp.zeros(n, jnp.float32) for _ in range(3))
    fmask = jnp.ones(learner.num_features, bool)
    return learner._jit_tree_w.lower(
        learner.bins_packed(), g, h, b, fmask).compile().as_text()


def _hlo_feature_sharded() -> str:
    from ..config import Config
    from ..parallel.feature_sharded import FeatureShardedWaveLearner
    from ..parallel.mesh import make_mesh
    from .jaxpr_lint import _BASE_PARAMS, _toy_dataset

    params = dict(_BASE_PARAMS, enable_bundle=False,
                  tree_learner="feature", tpu_donate_buffers="on")
    ds = _toy_dataset(2048, 8, params)
    learner = FeatureShardedWaveLearner(
        Config.from_params(params), ds.constructed, make_mesh(2))
    assert learner._donate, "tpu_donate_buffers=on did not engage"
    return learner.lowered_hlo_text()


def _hlo_gbdt_fused() -> str:
    import jax.numpy as jnp

    import lightgbm_tpu as lgb

    from .jaxpr_lint import _BASE_PARAMS, _toy_dataset

    ds = _toy_dataset(512, 4, dict(_BASE_PARAMS))
    bst = lgb.Booster(dict(_BASE_PARAMS), ds)
    g = bst.gbdt
    assert g._can_fuse(), "fused gbdt step unavailable on this config"
    fn = g._fused_iter_fn()
    return fn.lower(
        g.train_score.score, g.learner.bins_packed(), g._bag_mask,
        g._feature_sample(), jnp.float32(0.1)).compile().as_text()


_HLO_BUILDERS = {
    "learner_wave": _hlo_learner_wave,
    "feature_sharded": _hlo_feature_sharded,
    "gbdt_fused": _hlo_gbdt_fused,
}


def check_hlo_aliasing(names: Optional[Sequence[str]] = None
                       ) -> Tuple[List[Finding], Dict[str, str]]:
    """Lower + compile each designated donating program and assert the
    HLO text carries ``input_output_alias``.  Returns ``(findings,
    status)`` where status maps program -> "aliased" | skip reason."""
    import jax

    ndev = jax.device_count()
    findings: List[Finding] = []
    status: Dict[str, str] = {}
    for name, (file, min_dev) in sorted(DONATING_PROGRAMS.items()):
        if names is not None and name not in names:
            status[name] = "skipped: not selected by --programs"
            continue
        if ndev < min_dev:
            status[name] = f"skipped: needs {min_dev} devices, have {ndev}"
            continue
        text = _HLO_BUILDERS[name]()
        if _ALIAS_MARK in text:
            status[name] = "aliased"
        else:
            status[name] = "missing"
            findings.append(Finding(
                "donation", "donation-dropped", file,
                f"donating program {name!r} compiled WITHOUT "
                f"input->output aliasing — donate_argnums was lost (or "
                f"the platform declined it); the cross-iteration HBM "
                f"reuse is silently gone", symbol=name))
    return findings, status


# -- pass entry ---------------------------------------------------------------


def run(paths: Optional[Sequence[str]] = None,
        allowlist: Optional[Sequence[dict]] = None,
        with_hlo: bool = True,
        hlo_programs: Optional[Sequence[str]] = None):
    """The donation gate pass.  ``hlo_programs`` narrows the runtime
    asserts (None = all designated programs).  Returns ``(findings,
    suppressed, hlo_status)``."""
    if allowlist is None:
        allowlist = load_allowlist()
    findings = use_after_donate(paths)
    hlo_status: Dict[str, str] = {}
    if with_hlo:
        hlo_findings, hlo_status = check_hlo_aliasing(hlo_programs)
        findings += hlo_findings
    kept, suppressed = apply_allowlist(findings, allowlist)
    return kept, suppressed, hlo_status
