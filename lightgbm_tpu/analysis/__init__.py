"""Program-invariant static analysis for the lightgbm_tpu tree.

The reference C++ LightGBM keeps a 20k-LoC trainer honest with compiler
diagnostics and sanitizers; this package is the JAX port's equivalent — a
correctness-tooling layer that catches the regression classes PRs 1-4
fixed by hand (K collectives per stall event, corrupt length prefixes
driving multi-GB allocs, recompiles on every new row count) at ANALYSIS
time instead of in chaos tests or on-device profiles.

Eight passes, one gate:

  * ``jaxpr_lint``  — trace the wave tree step, the sharded learners and
    the serving binner/traversal programs; walk the closed jaxprs and
    enforce per-program collective-site budgets (``budgets.json``), no
    host callbacks in hot loops, no f64 when x64 is off, and a
    baked-constant size ceiling.  Each program is traced ONCE per gate
    run and the trace is shared with the spmd pass.
  * ``spmd``        — the SPMD safety analyzer: per-program collective
    ORDER pinned against ``sequences.json`` (counts alone miss a moved
    collective — the silent-pod-hang class), order equality across mesh
    factorizations of the same mode, rank-divergent host control flow
    around collectives (LGB008), and blocking calls on the fleet
    gateway's selector thread (LGB010).
  * ``donation``    — use-after-donate: every ``donate_argnums`` site
    mapped to its donated bindings, reads-after-call and aliased
    donations flagged (LGB009); plus a runtime assert that each
    designated donating program's compiled HLO actually carries
    input->output aliasing (donation silently dropped = the PR 12 HBM
    win silently lost).
  * ``recompile``   — fingerprint jit caches; fail when a warmed serving
    bucket or training step retraces.
  * ``races``       — AST lock-acquisition graph across the serving +
    network modules; flag lock-order cycles and fields mutated both
    inside and outside a lock.  Plus a runtime lock-discipline monitor
    usable from tests.
  * ``lint``        — repo-specific AST rules (socket timeouts, atomic
    writes, seeded RNGs, no bare except, no wall clocks in traced code)
    with a checked-in allowlist for vetted exceptions.
  * ``costmodel``   — the static cost-model ledger: per traced program,
    XLA's analytical FLOPs and bytes-accessed, a jaxpr-liveness
    peak-live-bytes estimate and per-primitive collective exchange
    payloads, pinned in ``costs.json`` with per-metric tolerance bands
    and re-derivable byte-identically via ``--dump-costs``.  A 2x FLOP
    regression or a doubled psum payload fails the gate on a CPU-only
    box — no TPU profile needed to catch it.
  * ``resources``   — resource-lifecycle pass over the host-side modules
    (serving/, lifecycle/, elastic/, io/, observability/): every
    started thread joined on the teardown path (LGB011), every
    socket/selector/file closed on all paths including error paths
    (LGB012), every subprocess reaped — ``wait``/``communicate`` or a
    kill-and-reap arm, and no unbounded ``subprocess.run`` (LGB013).
    Proves clean shutdown without hardware, the same
    allowlist-with-reason workflow as ``lint``.

The gate also always runs an allowlist-staleness check: every vetted
exception must still resolve to an existing file and symbol.

Gate: ``python -m lightgbm_tpu.analysis --json report.json`` exits
non-zero on any finding; the report validates against
``analysis/schema.json`` (same contract style as
``observability/schema.json``).  See README "Static analysis".

This module stays import-light (no jax at import time) so the AST passes
run anywhere.
"""

from .common import (Finding, apply_allowlist, build_report, is_allowed,
                     load_allowlist, load_budgets, load_costs, load_schema,
                     load_sequences, stale_allowlist_findings,
                     validate_findings_report)

__all__ = ["Finding", "apply_allowlist", "build_report", "is_allowed",
           "load_allowlist", "load_budgets", "load_costs", "load_schema",
           "load_sequences", "stale_allowlist_findings",
           "validate_findings_report"]
