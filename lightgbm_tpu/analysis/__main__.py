"""The analysis gate: ``python -m lightgbm_tpu.analysis [--json out.json]``.

Runs the six passes (lint, races, spmd, donation, jaxpr, recompile),
prints a summary, optionally writes the schema-validated JSON findings
report, and exits non-zero when any unsuppressed finding remains — so it
can run as a pre-merge check.

The traced-program passes share ONE trace cache: each budgeted program
is traced exactly once per gate run and consumed by both the jaxpr
budget lints and the spmd collective-order checks; per-program trace
seconds land in the JSON report.  ``--programs <glob>`` narrows the
traced set for scoped CI/local runs (AST passes always run in full).

``--dump-budgets`` re-derives ``budgets.json`` and ``--dump-sequences``
re-derives ``sequences.json`` from the currently traced programs (run
them when a reviewed learner change legitimately moves a collective
count or reorders the schedule, and commit the diff).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

from . import donation, jaxpr_lint, lint, races, recompile, spmd
from .common import (BUDGETS_PATH, SEQUENCES_PATH, Finding, build_report,
                     validate_findings_report)

ALL_PASSES = ("lint", "races", "spmd", "donation", "jaxpr", "recompile")

#: passes that need a live jax backend (the rest are pure-AST)
_JAX_PASSES = frozenset({"spmd", "donation", "jaxpr", "recompile"})


def _ensure_cpu_platform() -> None:
    """Force the 8-virtual-device CPU platform BEFORE the jax backend
    initializes (mirrors tests/conftest.py: the environment may pin a
    remote TPU platform)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass                    # backend already initialized (library use)


def _environment() -> Dict[str, object]:
    import jax
    return {"platform": jax.devices()[0].platform,
            "device_count": len(jax.devices()),
            "x64_enabled": bool(jax.config.jax_enable_x64),
            "jax_version": jax.__version__}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="Static program-invariant analysis gate")
    ap.add_argument("--json", metavar="PATH", default="",
                    help="write the schema-validated findings report here "
                         "(convention: reports/analysis_report.json, next "
                         "to the observability report artifacts)")
    ap.add_argument("--passes", default=",".join(ALL_PASSES),
                    help="comma list from "
                         "{lint,races,spmd,donation,jaxpr,recompile}")
    ap.add_argument("--programs", metavar="GLOB", default="",
                    help="fnmatch glob narrowing the traced-program set "
                         "(jaxpr budgets + spmd sequences + donation HLO "
                         "asserts) for scoped runs, e.g. 'wave_sharded*'")
    ap.add_argument("--dump-budgets", metavar="PATH", nargs="?",
                    const=BUDGETS_PATH, default="",
                    help="trace the program set and (re)write budgets.json "
                         "instead of gating")
    ap.add_argument("--dump-sequences", metavar="PATH", nargs="?",
                    const=SEQUENCES_PATH, default="",
                    help="trace the program set and (re)write "
                         "sequences.json instead of gating")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    selected = [p.strip() for p in args.passes.split(",") if p.strip()]
    unknown = [p for p in selected if p not in ALL_PASSES]
    if unknown:
        ap.error(f"unknown pass(es): {unknown}; choose from {ALL_PASSES}")

    def log(msg: str) -> None:
        if not args.quiet:
            print(f"[lightgbm_tpu.analysis] {msg}", flush=True)

    dumping = args.dump_budgets or args.dump_sequences
    if dumping or (_JAX_PASSES & set(selected)):
        _ensure_cpu_platform()

    if dumping:
        log("tracing the program set to derive pinned artifacts ...")
        traced = jaxpr_lint.trace_programs()
        if traced.skipped:
            log(f"WARNING: programs not traced on this platform: "
                f"{sorted(traced.skipped)} — pinned artifacts incomplete")
            return 1
        if args.dump_budgets:
            stats = {name: jaxpr_lint.collect_stats(closed)
                     for name, closed in traced.closed.items()}
            payload = jaxpr_lint.budgets_from_stats(stats)
            with open(args.dump_budgets, "w") as fh:
                json.dump(payload, fh, indent=2)
                fh.write("\n")
            log(f"wrote {args.dump_budgets}")
            for name, st in sorted(stats.items()):
                log(f"  {name}: collectives={st['collectives']} "
                    f"const_bytes={st['const_bytes']}")
        if args.dump_sequences:
            spmd.dump_sequences(traced, args.dump_sequences)
            log(f"wrote {args.dump_sequences}")
            for name, closed in sorted(traced.closed.items()):
                seq = spmd.extract_sequence(closed)
                log(f"  {name}: {len(seq)} collective(s) in order")
        return 0

    findings: List[Finding] = []
    pass_results: Dict[str, Dict[str, object]] = {}
    n = len(selected)
    step = iter(range(1, n + 1))

    # one trace per program, shared by the spmd order checks and the
    # jaxpr budget lints (satellite: the gate must not re-trace)
    traced = None
    if "spmd" in selected or "jaxpr" in selected:
        log("tracing the program set once (shared by spmd + jaxpr) ...")
        traced = jaxpr_lint.trace_programs(glob=args.programs or None)

    if "lint" in selected:
        log(f"pass {next(step)}/{n}: AST repo lint + report schema "
            "drift ...")
        kept, suppressed = lint.run()
        # LGB006: the emitted telemetry/serving reports vs schema.json —
        # drift (a section key without a schema property, or a report the
        # validator rejects) gates the same way an AST finding does
        from .common import apply_allowlist, load_allowlist
        drift_kept, drift_sup = apply_allowlist(lint.schema_drift(),
                                                load_allowlist())
        kept = kept + drift_kept
        findings.extend(kept)
        pass_results["lint"] = {
            "status": "findings" if kept else "ok",
            "findings": len(kept),
            "suppressed": len(suppressed) + len(drift_sup)}

    if "races" in selected:
        log(f"pass {next(step)}/{n}: lock-order race detector ...")
        kept, suppressed = races.run()
        findings.extend(kept)
        pass_results["races"] = {
            "status": "findings" if kept else "ok",
            "findings": len(kept), "suppressed": len(suppressed)}

    if "spmd" in selected:
        log(f"pass {next(step)}/{n}: SPMD safety — rank-divergence "
            "(LGB008), event-loop blocking (LGB010), collective-order "
            "pins ...")
        kept, suppressed = spmd.run(traced=traced)
        findings.extend(kept)
        pass_results["spmd"] = {
            "status": "findings" if kept else "ok",
            "findings": len(kept), "suppressed": len(suppressed)}

    if "donation" in selected:
        log(f"pass {next(step)}/{n}: use-after-donate (LGB009) + HLO "
            "donation-liveness asserts (this compiles the donating "
            "programs) ...")
        import fnmatch
        hlo_names = [p for p in donation.DONATING_PROGRAMS
                     if not args.programs
                     or fnmatch.fnmatch(p, args.programs)]
        kept, suppressed, hlo_status = donation.run(
            with_hlo=bool(hlo_names), hlo_programs=hlo_names)
        findings.extend(kept)
        pass_results["donation"] = {
            "status": "findings" if kept else "ok",
            "findings": len(kept), "suppressed": len(suppressed),
            "detail": "; ".join(f"{k}={v}" for k, v in
                                sorted(hlo_status.items()))
            or f"hlo asserts not selected by --programs {args.programs!r}"}

    if "jaxpr" in selected:
        log(f"pass {next(step)}/{n}: traced-program lints (no "
            "compilation) ...")
        fs, stats, skipped = jaxpr_lint.run(traced=traced)
        findings.extend(fs)
        pass_results["jaxpr"] = {
            "status": "findings" if fs else "ok",
            "findings": len(fs),
            "programs": {name: {"collectives": st["collectives"],
                                "const_bytes": st["const_bytes"],
                                "eqns": st["eqns"],
                                "trace_seconds": round(
                                    traced.seconds.get(name, 0.0), 3)}
                         for name, st in stats.items()},
            "detail": ("skipped: " + "; ".join(
                f"{k} ({v})" for k, v in sorted(skipped.items()))
                if skipped else "all programs traced")}

    if "recompile" in selected:
        log(f"pass {next(step)}/{n}: recompile sentinel (compiles and "
            "runs a tiny train + serving warm path) ...")
        fs, detail, skip_reason = recompile.run()
        findings.extend(fs)
        pass_results["recompile"] = {
            "status": ("skipped" if skip_reason
                       else "findings" if fs else "ok"),
            "findings": len(fs),
            "programs": detail,
            **({"detail": skip_reason} if skip_reason else {})}

    report = build_report(pass_results, findings,
                          environment=_environment()
                          if (_JAX_PASSES & set(selected)) else None)
    errs = validate_findings_report(report)
    if errs:
        log("INTERNAL: findings report violates analysis/schema.json: "
            + "; ".join(errs[:5]))
        return 2

    if args.json:
        d = os.path.dirname(args.json)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(args.json + ".tmp", "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")
        os.replace(args.json + ".tmp", args.json)
        log(f"report written to {args.json}")

    for f in findings:
        print(f"FINDING: {f}", flush=True)
    total = len(findings)
    statuses = ", ".join(f"{k}={v['status']}"
                         for k, v in pass_results.items())
    log(f"{total} finding(s) [{statuses}]")
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
