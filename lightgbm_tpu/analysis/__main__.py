"""The analysis gate: ``python -m lightgbm_tpu.analysis [--json out.json]``.

Runs the four passes (lint, races, jaxpr, recompile), prints a summary,
optionally writes the schema-validated JSON findings report, and exits
non-zero when any unsuppressed finding remains — so it can run as a
pre-merge check.

``--dump-budgets`` re-derives ``budgets.json`` from the currently traced
programs (run it when a reviewed learner change legitimately moves a
collective count, and commit the diff).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

from . import jaxpr_lint, lint, races, recompile
from .common import (BUDGETS_PATH, Finding, build_report,
                     validate_findings_report)

ALL_PASSES = ("lint", "races", "jaxpr", "recompile")


def _ensure_cpu_platform() -> None:
    """Force the 8-virtual-device CPU platform BEFORE the jax backend
    initializes (mirrors tests/conftest.py: the environment may pin a
    remote TPU platform)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass                    # backend already initialized (library use)


def _environment() -> Dict[str, object]:
    import jax
    return {"platform": jax.devices()[0].platform,
            "device_count": len(jax.devices()),
            "x64_enabled": bool(jax.config.jax_enable_x64),
            "jax_version": jax.__version__}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="Static program-invariant analysis gate")
    ap.add_argument("--json", metavar="PATH", default="",
                    help="write the schema-validated findings report here")
    ap.add_argument("--passes", default=",".join(ALL_PASSES),
                    help="comma list from {lint,races,jaxpr,recompile}")
    ap.add_argument("--dump-budgets", metavar="PATH", nargs="?",
                    const=BUDGETS_PATH, default="",
                    help="trace the program set and (re)write budgets.json "
                         "instead of gating")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    selected = [p.strip() for p in args.passes.split(",") if p.strip()]
    unknown = [p for p in selected if p not in ALL_PASSES]
    if unknown:
        ap.error(f"unknown pass(es): {unknown}; choose from {ALL_PASSES}")

    def log(msg: str) -> None:
        if not args.quiet:
            print(f"[lightgbm_tpu.analysis] {msg}", flush=True)

    if args.dump_budgets or "jaxpr" in selected or "recompile" in selected:
        _ensure_cpu_platform()

    if args.dump_budgets:
        log("tracing the program set to derive budgets ...")
        _, stats, skipped = jaxpr_lint.run(budgets={"max_const_bytes": 0,
                                                    "programs": {}})
        if skipped:
            log(f"WARNING: programs not traced on this platform: "
                f"{sorted(skipped)} — budgets incomplete")
            return 1
        payload = jaxpr_lint.budgets_from_stats(stats)
        with open(args.dump_budgets, "w") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")
        log(f"wrote {args.dump_budgets}")
        for name, st in sorted(stats.items()):
            log(f"  {name}: collectives={st['collectives']} "
                f"const_bytes={st['const_bytes']}")
        return 0

    findings: List[Finding] = []
    pass_results: Dict[str, Dict[str, object]] = {}

    if "lint" in selected:
        log("pass 1/4: AST repo lint + report schema drift ...")
        kept, suppressed = lint.run()
        # LGB006: the emitted telemetry/serving reports vs schema.json —
        # drift (a section key without a schema property, or a report the
        # validator rejects) gates the same way an AST finding does
        from .common import apply_allowlist, load_allowlist
        drift_kept, drift_sup = apply_allowlist(lint.schema_drift(),
                                                load_allowlist())
        kept = kept + drift_kept
        findings.extend(kept)
        pass_results["lint"] = {
            "status": "findings" if kept else "ok",
            "findings": len(kept),
            "suppressed": len(suppressed) + len(drift_sup)}

    if "races" in selected:
        log("pass 2/4: lock-order race detector ...")
        kept, suppressed = races.run()
        findings.extend(kept)
        pass_results["races"] = {
            "status": "findings" if kept else "ok",
            "findings": len(kept), "suppressed": len(suppressed)}

    if "jaxpr" in selected:
        log("pass 3/4: traced-program lints (this traces the tree "
            "programs; no compilation) ...")
        fs, stats, skipped = jaxpr_lint.run()
        findings.extend(fs)
        pass_results["jaxpr"] = {
            "status": "findings" if fs else "ok",
            "findings": len(fs),
            "programs": {name: {"collectives": st["collectives"],
                                "const_bytes": st["const_bytes"],
                                "eqns": st["eqns"]}
                         for name, st in stats.items()},
            "detail": ("skipped: " + "; ".join(
                f"{k} ({v})" for k, v in sorted(skipped.items()))
                if skipped else "all programs traced")}

    if "recompile" in selected:
        log("pass 4/4: recompile sentinel (compiles and runs a tiny "
            "train + serving warm path) ...")
        fs, detail, skip_reason = recompile.run()
        findings.extend(fs)
        pass_results["recompile"] = {
            "status": ("skipped" if skip_reason
                       else "findings" if fs else "ok"),
            "findings": len(fs),
            "programs": detail,
            **({"detail": skip_reason} if skip_reason else {})}

    report = build_report(pass_results, findings,
                          environment=_environment()
                          if ("jaxpr" in selected or
                              "recompile" in selected) else None)
    errs = validate_findings_report(report)
    if errs:
        log("INTERNAL: findings report violates analysis/schema.json: "
            + "; ".join(errs[:5]))
        return 2

    if args.json:
        with open(args.json + ".tmp", "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")
        os.replace(args.json + ".tmp", args.json)
        log(f"report written to {args.json}")

    for f in findings:
        print(f"FINDING: {f}", flush=True)
    total = len(findings)
    statuses = ", ".join(f"{k}={v['status']}"
                         for k, v in pass_results.items())
    log(f"{total} finding(s) [{statuses}]")
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
