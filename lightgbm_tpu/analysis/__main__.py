"""The analysis gate: ``python -m lightgbm_tpu.analysis [--json out.json]``.

Runs the eight passes (lint, races, resources, spmd, donation, jaxpr,
costmodel, recompile) plus the always-on allowlist-staleness check,
prints a summary with per-pass wall time, optionally writes the
schema-validated JSON findings report, and exits non-zero when any
unsuppressed finding remains — so it can run as a pre-merge check.

The traced-program passes share ONE trace cache: each budgeted program
is traced exactly once per gate run and consumed by the jaxpr budget
lints, the spmd collective-order checks and the cost-model ledger;
per-program trace seconds land in the JSON report.  ``--programs
<glob>`` narrows the traced set for scoped CI/local runs (AST passes
always run in full); ``--changed-only REF`` scopes BOTH the AST file
sets and the traced-program set to files ``git diff --name-only REF``
reports (the recompile sentinel still runs — cache-identity bugs do not
localize to a diff).

``--dump-budgets`` re-derives ``budgets.json``, ``--dump-sequences``
re-derives ``sequences.json`` and ``--dump-costs`` re-derives
``costs.json`` from the currently traced programs (run them when a
reviewed learner change legitimately moves a collective count, reorders
the schedule or shifts a pinned cost, and commit the diff).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Sequence

from . import (costmodel, donation, jaxpr_lint, lint, races, recompile,
               resources, spmd)
from .common import (BUDGETS_PATH, COSTS_PATH, REPO_ROOT, SEQUENCES_PATH,
                     Finding, build_report, rel_file,
                     stale_allowlist_findings, validate_findings_report)

ALL_PASSES = ("lint", "races", "resources", "spmd", "donation", "jaxpr",
              "costmodel", "recompile")

#: passes that need a live jax backend (the rest are pure-AST)
_JAX_PASSES = frozenset({"spmd", "donation", "jaxpr", "costmodel",
                         "recompile"})


def _ensure_cpu_platform() -> None:
    """Force the 8-virtual-device CPU platform BEFORE the jax backend
    initializes (mirrors tests/conftest.py: the environment may pin a
    remote TPU platform)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass                    # backend already initialized (library use)


def _environment() -> Dict[str, object]:
    import jax
    return {"platform": jax.devices()[0].platform,
            "device_count": len(jax.devices()),
            "x64_enabled": bool(jax.config.jax_enable_x64),
            "jax_version": jax.__version__}


def _changed_files(ref: str) -> Optional[set]:
    """Repo-relative paths touched since ``ref`` (tracked diffs plus
    untracked files), or None when git cannot answer."""
    import subprocess
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"], cwd=REPO_ROOT,
            capture_output=True, text=True, timeout=30.0, check=True).stdout
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=30.0,
            check=True).stdout
    except (OSError, subprocess.SubprocessError):
        return None
    return {ln.strip() for ln in (diff + untracked).splitlines()
            if ln.strip()}


def _walk_py(root: str) -> List[str]:
    out: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        out.extend(os.path.join(dirpath, f) for f in sorted(filenames)
                   if f.endswith(".py"))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="Static program-invariant analysis gate")
    ap.add_argument("--json", metavar="PATH", default="",
                    help="write the schema-validated findings report here "
                         "(convention: reports/analysis_report.json, next "
                         "to the observability report artifacts)")
    ap.add_argument("--passes", default=",".join(ALL_PASSES),
                    help="comma list from {" + ",".join(ALL_PASSES) + "}")
    ap.add_argument("--programs", metavar="GLOB", default="",
                    help="fnmatch glob narrowing the traced-program set "
                         "(jaxpr budgets + spmd sequences + donation HLO "
                         "asserts + cost ledger) for scoped runs, e.g. "
                         "'wave_sharded*'")
    ap.add_argument("--changed-only", metavar="REF", default="",
                    help="scope the AST passes and the traced-program set "
                         "to files changed since REF (git diff + "
                         "untracked); the recompile sentinel and the "
                         "allowlist-staleness check still run in full. "
                         "Falls back to the full gate when the analyzer "
                         "itself changed or git fails.")
    ap.add_argument("--dump-budgets", metavar="PATH", nargs="?",
                    const=BUDGETS_PATH, default="",
                    help="trace the program set and (re)write budgets.json "
                         "instead of gating")
    ap.add_argument("--dump-sequences", metavar="PATH", nargs="?",
                    const=SEQUENCES_PATH, default="",
                    help="trace the program set and (re)write "
                         "sequences.json instead of gating")
    ap.add_argument("--dump-costs", metavar="PATH", nargs="?",
                    const=COSTS_PATH, default="",
                    help="trace the program set and (re)write costs.json "
                         "(the static cost-model ledger) instead of gating")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    selected = [p.strip() for p in args.passes.split(",") if p.strip()]
    unknown = [p for p in selected if p not in ALL_PASSES]
    if unknown:
        ap.error(f"unknown pass(es): {unknown}; choose from {ALL_PASSES}")

    def log(msg: str) -> None:
        if not args.quiet:
            print(f"[lightgbm_tpu.analysis] {msg}", flush=True)

    dumping = args.dump_budgets or args.dump_sequences or args.dump_costs
    if dumping or (_JAX_PASSES & set(selected)):
        _ensure_cpu_platform()

    if dumping:
        log("tracing the program set to derive pinned artifacts ...")
        traced = jaxpr_lint.trace_programs()
        if traced.skipped:
            log(f"WARNING: programs not traced on this platform: "
                f"{sorted(traced.skipped)} — pinned artifacts incomplete")
            return 1
        if args.dump_budgets:
            stats = {name: jaxpr_lint.collect_stats(closed)
                     for name, closed in traced.closed.items()}
            payload = jaxpr_lint.budgets_from_stats(stats)
            with open(args.dump_budgets, "w") as fh:
                json.dump(payload, fh, indent=2)
                fh.write("\n")
            log(f"wrote {args.dump_budgets}")
            for name, st in sorted(stats.items()):
                log(f"  {name}: collectives={st['collectives']} "
                    f"const_bytes={st['const_bytes']}")
        if args.dump_sequences:
            spmd.dump_sequences(traced, args.dump_sequences)
            log(f"wrote {args.dump_sequences}")
            for name, closed in sorted(traced.closed.items()):
                seq = spmd.extract_sequence(closed)
                log(f"  {name}: {len(seq)} collective(s) in order")
        if args.dump_costs:
            payload = costmodel.dump_costs(traced, args.dump_costs)
            log(f"wrote {args.dump_costs}")
            for name, entry in sorted(payload["programs"].items()):
                ex = sum(entry["exchange_bytes"].values())
                log(f"  {name}: flops={entry['flops']} "
                    f"bytes={entry['bytes_accessed']} "
                    f"peak={entry['peak_live_bytes']} exchange={ex}")
        return 0

    # --changed-only REF: scope the AST file sets and the traced set to
    # the diff.  A change under analysis/ (the analyzer, its pins, the
    # allowlist) invalidates every scoping assumption — run in full.
    changed: Optional[set] = None
    if args.changed_only:
        changed = _changed_files(args.changed_only)
        if changed is None:
            log(f"WARNING: git diff against {args.changed_only!r} failed "
                "— running the full gate")
        elif any(p.startswith("lightgbm_tpu/analysis/") for p in changed):
            log("--changed-only: analysis/ itself changed — running the "
                "full gate")
            changed = None
        else:
            log(f"--changed-only {args.changed_only}: "
                f"{len(changed)} changed file(s)")

    def scoped(default_paths: Sequence[str]) -> Optional[List[str]]:
        """None = pass default (full scan); a list = the changed subset."""
        if changed is None:
            return None
        return [p for p in default_paths if rel_file(p) in changed]

    findings: List[Finding] = []
    pass_results: Dict[str, Dict[str, object]] = {}
    pass_seconds: Dict[str, float] = {}
    n = len(selected)
    step = iter(range(1, n + 1))

    def finish(name: str, t0: float, kept: Sequence[Finding],
               result: Dict[str, object]) -> None:
        secs = round(time.perf_counter() - t0, 3)
        result["seconds"] = secs
        pass_seconds[name] = secs
        findings.extend(kept)
        pass_results[name] = result
        log(f"  {name}: {len(kept)} finding(s) in {secs:.2f}s")

    # the allowlist-staleness check always runs: a rotted vetted
    # exception (file moved, symbol renamed) silently suppresses the
    # wrong thing, so no pass selection may skip it
    t0 = time.perf_counter()
    stale = stale_allowlist_findings()
    finish("allowlist", t0, stale,
           {"status": "findings" if stale else "ok",
            "findings": len(stale)})

    # one trace per program, shared by the spmd order checks, the jaxpr
    # budget lints and the cost-model ledger (the gate must not re-trace)
    traced = None
    if {"spmd", "jaxpr", "costmodel"} & set(selected):
        only = None
        if changed is not None:
            only = {name for name, f in jaxpr_lint.PROGRAM_FILES.items()
                    if f in changed}
        log("tracing the program set once (shared by spmd + jaxpr + "
            "costmodel) ...")
        t0 = time.perf_counter()
        traced = jaxpr_lint.trace_programs(glob=args.programs or None,
                                           only=only)
        log(f"  traced {len(traced.closed)} program(s) in "
            f"{time.perf_counter() - t0:.2f}s")

    if "lint" in selected:
        log(f"pass {next(step)}/{n}: AST repo lint + report schema "
            "drift ...")
        t0 = time.perf_counter()
        kept, suppressed = lint.run(
            paths=scoped(list(lint.iter_package_files())))
        # LGB006: the emitted telemetry/serving reports vs schema.json —
        # drift (a section key without a schema property, or a report the
        # validator rejects) gates the same way an AST finding does
        from .common import apply_allowlist, load_allowlist
        drift_kept, drift_sup = apply_allowlist(lint.schema_drift(),
                                                load_allowlist())
        kept = kept + drift_kept
        finish("lint", t0, kept, {
            "status": "findings" if kept else "ok",
            "findings": len(kept),
            "suppressed": len(suppressed) + len(drift_sup)})

    if "races" in selected:
        log(f"pass {next(step)}/{n}: lock-order race detector ...")
        t0 = time.perf_counter()
        kept, suppressed = races.run(paths=scoped(
            [os.path.join(races.PKG_ROOT, p) for p in races.DEFAULT_FILES]))
        finish("races", t0, kept, {
            "status": "findings" if kept else "ok",
            "findings": len(kept), "suppressed": len(suppressed)})

    if "resources" in selected:
        log(f"pass {next(step)}/{n}: resource lifecycle — thread "
            "join-on-stop (LGB011), close-on-all-paths (LGB012), "
            "subprocess reaping (LGB013) ...")
        t0 = time.perf_counter()
        kept, suppressed = resources.run(
            paths=scoped(list(resources.iter_scan_files())))
        finish("resources", t0, kept, {
            "status": "findings" if kept else "ok",
            "findings": len(kept), "suppressed": len(suppressed)})

    if "spmd" in selected:
        log(f"pass {next(step)}/{n}: SPMD safety — rank-divergence "
            "(LGB008), event-loop blocking (LGB010), collective-order "
            "pins ...")
        t0 = time.perf_counter()
        rank_default = [p for d in spmd.RANK_DIRS
                        for p in _walk_py(os.path.join(spmd.PKG_ROOT, d))]
        loop_default = [os.path.join(spmd.PKG_ROOT, p)
                        for p in spmd.LOOP_FILES]
        kept, suppressed = spmd.run(rank_paths=scoped(rank_default),
                                    loop_paths=scoped(loop_default),
                                    traced=traced)
        finish("spmd", t0, kept, {
            "status": "findings" if kept else "ok",
            "findings": len(kept), "suppressed": len(suppressed)})

    if "donation" in selected:
        log(f"pass {next(step)}/{n}: use-after-donate (LGB009) + HLO "
            "donation-liveness asserts (this compiles the donating "
            "programs) ...")
        t0 = time.perf_counter()
        import fnmatch
        hlo_names = [p for p in donation.DONATING_PROGRAMS
                     if (not args.programs
                         or fnmatch.fnmatch(p, args.programs))
                     and (changed is None
                          or jaxpr_lint.PROGRAM_FILES.get(p) in changed)]
        kept, suppressed, hlo_status = donation.run(
            with_hlo=bool(hlo_names), hlo_programs=hlo_names)
        finish("donation", t0, kept, {
            "status": "findings" if kept else "ok",
            "findings": len(kept), "suppressed": len(suppressed),
            "detail": "; ".join(f"{k}={v}" for k, v in
                                sorted(hlo_status.items()))
            or "hlo asserts not selected by "
               f"--programs/--changed-only"})

    if "jaxpr" in selected:
        log(f"pass {next(step)}/{n}: traced-program lints (no "
            "compilation) ...")
        t0 = time.perf_counter()
        fs, stats, skipped = jaxpr_lint.run(traced=traced)
        finish("jaxpr", t0, fs, {
            "status": "findings" if fs else "ok",
            "findings": len(fs),
            "programs": {name: {"collectives": st["collectives"],
                                "const_bytes": st["const_bytes"],
                                "eqns": st["eqns"],
                                "trace_seconds": round(
                                    traced.seconds.get(name, 0.0), 3)}
                         for name, st in stats.items()},
            "detail": ("skipped: " + "; ".join(
                f"{k} ({v})" for k, v in sorted(skipped.items()))
                if skipped else "all programs traced")})

    if "costmodel" in selected:
        log(f"pass {next(step)}/{n}: static cost-model ledger — XLA "
            "flops/bytes, liveness peak, exchange payloads vs costs.json "
            "(no compilation) ...")
        t0 = time.perf_counter()
        fs, measured, skipped = costmodel.run(traced=traced)
        finish("costmodel", t0, fs, {
            "status": "findings" if fs else "ok",
            "findings": len(fs),
            "programs": {name: {
                "flops": m["flops"],
                "bytes_accessed": m["bytes_accessed"],
                "peak_live_bytes": m["peak_live_bytes"],
                "exchange_bytes": dict(m["exchange_bytes"]),
                "eqns": m["eqns"]} for name, m in measured.items()},
            "detail": ("skipped: " + "; ".join(
                f"{k} ({v})" for k, v in sorted(skipped.items()))
                if skipped else "all programs measured")})

    if "recompile" in selected:
        log(f"pass {next(step)}/{n}: recompile sentinel (compiles and "
            "runs a tiny train + serving warm path) ...")
        t0 = time.perf_counter()
        fs, detail, skip_reason = recompile.run()
        finish("recompile", t0, fs, {
            "status": ("skipped" if skip_reason
                       else "findings" if fs else "ok"),
            "findings": len(fs),
            "programs": detail,
            **({"detail": skip_reason} if skip_reason else {})})

    report = build_report(pass_results, findings,
                          environment=_environment()
                          if (_JAX_PASSES & set(selected)) else None)
    errs = validate_findings_report(report)
    if errs:
        log("INTERNAL: findings report violates analysis/schema.json: "
            + "; ".join(errs[:5]))
        return 2

    if args.json:
        d = os.path.dirname(args.json)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(args.json + ".tmp", "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")
        os.replace(args.json + ".tmp", args.json)
        log(f"report written to {args.json}")

    for f in findings:
        print(f"FINDING: {f}", flush=True)
    total = len(findings)
    statuses = ", ".join(f"{k}={v['status']}"
                         for k, v in pass_results.items())
    timings = " ".join(f"{k}={pass_seconds[k]:.2f}s"
                       for k in pass_seconds)
    log(f"per-pass wall time: {timings}")
    log(f"{total} finding(s) [{statuses}]")
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
