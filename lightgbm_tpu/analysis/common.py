"""Shared findings plumbing for the static-analysis passes.

Every pass (`jaxpr_lint` / `recompile` / `races` / `lint`) reports
violations as ``Finding`` rows; the gate (`python -m lightgbm_tpu.analysis`)
assembles them into one JSON report validated against the checked-in
``schema.json`` — the same schema-subset contract the telemetry report uses
(`observability/schema.json`, validated by the same dependency-free
validator).

Vetted exceptions live in ``allowlist.json``: one entry per suppressed
finding, matched on (rule, file suffix, optional symbol), each carrying a
human-readable reason.  A finding the allowlist matches is counted as
``suppressed`` in the report, never silently dropped.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

SCHEMA_VERSION = 2

_HERE = os.path.dirname(os.path.abspath(__file__))
SCHEMA_PATH = os.path.join(_HERE, "schema.json")
ALLOWLIST_PATH = os.path.join(_HERE, "allowlist.json")
BUDGETS_PATH = os.path.join(_HERE, "budgets.json")
SEQUENCES_PATH = os.path.join(_HERE, "sequences.json")

#: the package under analysis (lightgbm_tpu/) and the repo root above it
PKG_ROOT = os.path.dirname(_HERE)
REPO_ROOT = os.path.dirname(PKG_ROOT)


@dataclass
class Finding:
    """One violation.  ``file`` is repo-relative with forward slashes;
    ``symbol`` is the qualified function/class (or program name for the
    traced-program passes) the finding anchors to."""

    pass_name: str          # "lint" | "races" | "jaxpr" | "recompile"
    rule: str               # e.g. "LGB001-socket-timeout", "lock-order-cycle"
    file: str
    message: str
    line: int = 0
    symbol: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"pass": self.pass_name, "rule": self.rule, "file": self.file,
                "line": int(self.line), "symbol": self.symbol,
                "message": self.message}

    def __str__(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.rule} {loc}{sym}: {self.message}"


def rel_file(path: str) -> str:
    """Repo-relative, forward-slash path for findings/allowlist matching."""
    p = os.path.abspath(path)
    try:
        p = os.path.relpath(p, REPO_ROOT)
    except ValueError:
        pass
    return p.replace(os.sep, "/")


def _load_json(path: str):
    with open(path) as fh:
        return json.load(fh)


def load_schema() -> Dict[str, Any]:
    return _load_json(SCHEMA_PATH)


def load_allowlist(path: Optional[str] = None) -> List[Dict[str, Any]]:
    p = ALLOWLIST_PATH if path is None else path
    if not os.path.exists(p):
        return []
    data = _load_json(p)
    return list(data.get("allow", []))


def load_budgets(path: Optional[str] = None) -> Dict[str, Any]:
    p = BUDGETS_PATH if path is None else path
    if not os.path.exists(p):
        return {"max_const_bytes": 0, "programs": {}}
    return _load_json(p)


def load_sequences(path: Optional[str] = None) -> Dict[str, Any]:
    """The checked-in per-program collective-order sequences
    (``sequences.json``, re-derivable via ``--dump-sequences``)."""
    p = SEQUENCES_PATH if path is None else path
    if not os.path.exists(p):
        return {"programs": {}}
    return _load_json(p)


def is_allowed(finding: Finding, allowlist: Sequence[Dict[str, Any]]) -> bool:
    """True when an allowlist entry vouches for this finding.  An entry
    matches on exact rule, file suffix, and — when it names one — exact
    symbol; the ``reason`` field is documentation, not matching input."""
    for entry in allowlist:
        if entry.get("rule") != finding.rule:
            continue
        f = entry.get("file", "")
        if not f or not finding.file.endswith(f):
            continue
        sym = entry.get("symbol")
        if sym is not None and sym != finding.symbol:
            continue
        return True
    return False


def apply_allowlist(findings: Sequence[Finding],
                    allowlist: Sequence[Dict[str, Any]]
                    ) -> Tuple[List[Finding], List[Finding]]:
    """Split into (kept, suppressed)."""
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        (suppressed if is_allowed(f, allowlist) else kept).append(f)
    return kept, suppressed


def build_report(pass_results: Dict[str, Dict[str, Any]],
                 findings: Sequence[Finding],
                 environment: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """Assemble the gate's JSON report.  ``pass_results`` maps pass name to
    ``{"status": ..., "findings": n, ...extras}``."""
    by_pass: Dict[str, int] = {}
    for f in findings:
        by_pass[f.pass_name] = by_pass.get(f.pass_name, 0) + 1
    env = dict(environment or {})
    env.setdefault("platform", "unknown")
    env.setdefault("device_count", 0)
    env.setdefault("x64_enabled", False)
    return {
        "schema_version": SCHEMA_VERSION,
        "environment": env,
        "passes": {name: dict(res) for name, res in pass_results.items()},
        "findings": [f.to_dict() for f in findings],
        "summary": {"total": len(findings), "by_pass": by_pass},
    }


def validate_findings_report(report: Any) -> List[str]:
    """Violation strings (empty = valid), via the same JSON-Schema-subset
    validator the telemetry report uses."""
    from ..observability.report import validate_report
    return validate_report(report, load_schema())
