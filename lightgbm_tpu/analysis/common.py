"""Shared findings plumbing for the static-analysis passes.

Every pass (`jaxpr_lint` / `recompile` / `races` / `lint`) reports
violations as ``Finding`` rows; the gate (`python -m lightgbm_tpu.analysis`)
assembles them into one JSON report validated against the checked-in
``schema.json`` — the same schema-subset contract the telemetry report uses
(`observability/schema.json`, validated by the same dependency-free
validator).

Vetted exceptions live in ``allowlist.json``: one entry per suppressed
finding, matched on (rule, file suffix, optional symbol), each carrying a
human-readable reason.  A finding the allowlist matches is counted as
``suppressed`` in the report, never silently dropped.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

SCHEMA_VERSION = 3

_HERE = os.path.dirname(os.path.abspath(__file__))
SCHEMA_PATH = os.path.join(_HERE, "schema.json")
ALLOWLIST_PATH = os.path.join(_HERE, "allowlist.json")
BUDGETS_PATH = os.path.join(_HERE, "budgets.json")
SEQUENCES_PATH = os.path.join(_HERE, "sequences.json")
COSTS_PATH = os.path.join(_HERE, "costs.json")

#: the package under analysis (lightgbm_tpu/) and the repo root above it
PKG_ROOT = os.path.dirname(_HERE)
REPO_ROOT = os.path.dirname(PKG_ROOT)


@dataclass
class Finding:
    """One violation.  ``file`` is repo-relative with forward slashes;
    ``symbol`` is the qualified function/class (or program name for the
    traced-program passes) the finding anchors to."""

    pass_name: str          # "lint" | "races" | "jaxpr" | "recompile"
    rule: str               # e.g. "LGB001-socket-timeout", "lock-order-cycle"
    file: str
    message: str
    line: int = 0
    symbol: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"pass": self.pass_name, "rule": self.rule, "file": self.file,
                "line": int(self.line), "symbol": self.symbol,
                "message": self.message}

    def __str__(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.rule} {loc}{sym}: {self.message}"


def rel_file(path: str) -> str:
    """Repo-relative, forward-slash path for findings/allowlist matching."""
    p = os.path.abspath(path)
    try:
        p = os.path.relpath(p, REPO_ROOT)
    except ValueError:
        pass
    return p.replace(os.sep, "/")


def _load_json(path: str):
    with open(path) as fh:
        return json.load(fh)


def load_schema() -> Dict[str, Any]:
    return _load_json(SCHEMA_PATH)


def load_allowlist(path: Optional[str] = None) -> List[Dict[str, Any]]:
    p = ALLOWLIST_PATH if path is None else path
    if not os.path.exists(p):
        return []
    data = _load_json(p)
    return list(data.get("allow", []))


def load_budgets(path: Optional[str] = None) -> Dict[str, Any]:
    p = BUDGETS_PATH if path is None else path
    if not os.path.exists(p):
        return {"max_const_bytes": 0, "programs": {}}
    return _load_json(p)


def load_sequences(path: Optional[str] = None) -> Dict[str, Any]:
    """The checked-in per-program collective-order sequences
    (``sequences.json``, re-derivable via ``--dump-sequences``)."""
    p = SEQUENCES_PATH if path is None else path
    if not os.path.exists(p):
        return {"programs": {}}
    return _load_json(p)


def load_costs(path: Optional[str] = None) -> Dict[str, Any]:
    """The checked-in per-program cost ledger (``costs.json``,
    re-derivable via ``--dump-costs``)."""
    p = COSTS_PATH if path is None else path
    if not os.path.exists(p):
        return {"tolerance": {}, "programs": {}}
    return _load_json(p)


def _file_qualnames(path: str) -> set:
    """Every dotted function/class qualname defined in ``path`` (for
    stale-allowlist symbol resolution)."""
    import ast
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    quals: set = set()

    def visit(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                quals.add(".".join(stack + [child.name]))
                visit(child, stack + [child.name])
            else:
                visit(child, stack)

    visit(tree, [])
    return quals


def _resolve_allow_file(suffix: str) -> Optional[str]:
    """The on-disk file an allowlist ``file`` suffix points at (findings
    match on suffix, so the entry may be shorter than repo-relative)."""
    direct = os.path.join(REPO_ROOT, suffix)
    if os.path.isfile(direct):
        return direct
    for dirpath, dirnames, filenames in os.walk(PKG_ROOT):
        dirnames[:] = [d for d in sorted(dirnames) if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            if rel_file(p).endswith(suffix):
                return p
    return None


def stale_allowlist_findings(allowlist: Optional[Sequence[Dict[str, Any]]]
                             = None) -> List[Finding]:
    """Every allowlist entry must still resolve: the file must exist and
    the named symbol must still be defined in it — otherwise the vetted
    exception has rotted (the file moved, the function was renamed) and
    is silently suppressing nothing, or worse, the wrong thing."""
    if allowlist is None:
        allowlist = load_allowlist()
    findings: List[Finding] = []
    for i, entry in enumerate(allowlist):
        where = f"allowlist entry #{i} (rule {entry.get('rule')!r})"
        suffix = entry.get("file", "")
        if not suffix:
            findings.append(Finding(
                "allowlist", "stale-allowlist", "analysis/allowlist.json",
                f"{where} names no file — every vetted exception must "
                f"pin the file it excuses", symbol=entry.get("symbol")))
            continue
        path = _resolve_allow_file(suffix)
        if path is None:
            findings.append(Finding(
                "allowlist", "stale-allowlist", "analysis/allowlist.json",
                f"{where} points at {suffix!r}, which no longer exists — "
                f"delete the entry or fix the path",
                symbol=entry.get("symbol")))
            continue
        sym = entry.get("symbol")
        if sym is None:
            continue
        quals = _file_qualnames(path)
        if sym in quals or any(q.endswith("." + sym) for q in quals):
            continue
        findings.append(Finding(
            "allowlist", "stale-allowlist", "analysis/allowlist.json",
            f"{where} names symbol {sym!r}, not defined in {suffix!r} "
            f"anymore — delete the entry or fix the symbol", symbol=sym))
    return findings


def is_allowed(finding: Finding, allowlist: Sequence[Dict[str, Any]]) -> bool:
    """True when an allowlist entry vouches for this finding.  An entry
    matches on exact rule, file suffix, and — when it names one — exact
    symbol; the ``reason`` field is documentation, not matching input."""
    for entry in allowlist:
        if entry.get("rule") != finding.rule:
            continue
        f = entry.get("file", "")
        if not f or not finding.file.endswith(f):
            continue
        sym = entry.get("symbol")
        if sym is not None and sym != finding.symbol:
            continue
        return True
    return False


def apply_allowlist(findings: Sequence[Finding],
                    allowlist: Sequence[Dict[str, Any]]
                    ) -> Tuple[List[Finding], List[Finding]]:
    """Split into (kept, suppressed)."""
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        (suppressed if is_allowed(f, allowlist) else kept).append(f)
    return kept, suppressed


def build_report(pass_results: Dict[str, Dict[str, Any]],
                 findings: Sequence[Finding],
                 environment: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """Assemble the gate's JSON report.  ``pass_results`` maps pass name to
    ``{"status": ..., "findings": n, ...extras}``."""
    by_pass: Dict[str, int] = {}
    for f in findings:
        by_pass[f.pass_name] = by_pass.get(f.pass_name, 0) + 1
    env = dict(environment or {})
    env.setdefault("platform", "unknown")
    env.setdefault("device_count", 0)
    env.setdefault("x64_enabled", False)
    return {
        "schema_version": SCHEMA_VERSION,
        "environment": env,
        "passes": {name: dict(res) for name, res in pass_results.items()},
        "findings": [f.to_dict() for f in findings],
        "summary": {"total": len(findings), "by_pass": by_pass},
    }


def validate_findings_report(report: Any) -> List[str]:
    """Violation strings (empty = valid), via the same JSON-Schema-subset
    validator the telemetry report uses."""
    from ..observability.report import validate_report
    return validate_report(report, load_schema())
