"""Traced-program lints: walk closed jaxprs and enforce budgets.

The training and serving hot paths are a handful of jitted programs; the
regressions that hurt are *structural* and visible at trace time, long
before a device profile:

  * a new collective slipping into the wave body multiplies per-tree
    exchanges (the PR-1 class: K psums per stall event instead of one);
  * an f64 op leaking into a traced path while x64 is off means an
    unintended cast chain (and on TPU, an emulated-precision cliff);
  * a ``pure_callback`` / infeed / outfeed in the hot loop is a host sync
    per iteration;
  * a large array baked into the program as a constant (instead of passed
    as an argument) bloats every executable and defeats donation.

``run()`` traces the standard program set — the serial wave tree step
(`learner_wave.py`), the sharded learners (`parallel/`), and the serving
binner + traversal programs — and checks each against the checked-in
per-program budgets (``budgets.json``).  Budgets count **static collective
call sites** in the traced program (the same notion
`observability.CollectiveLedger` records): a site inside ``lax.while_loop``
executes once per iteration, so site count is the per-tree multiplier that
matters.  Any learner change that adds a collective site must raise the
budget explicitly in the same commit.

The f64 rule only runs when x64 is off (the gate's configuration); the
test suite runs with x64 on for parity tests, where f64 is legitimate.
"""

from __future__ import annotations

import fnmatch
import functools
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from .common import Finding, load_budgets

#: jaxpr primitive names that are cross-device collectives
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pargmax", "pargmin",
})

#: primitive-name substrings that mean a host round-trip inside the program
BANNED_SUBSTRINGS = ("callback", "infeed", "outfeed")

#: program name -> the source file a finding anchors to
PROGRAM_FILES = {
    "wave_serial": "lightgbm_tpu/learner_wave.py",
    # the serial wave program with BOTH round-6 Pallas kernels forced on
    # (stable partition replacing the re-compaction sort + fused split
    # scan) — traced in interpret mode off-TPU, which exercises the same
    # jaxpr structure the TPU path compiles
    "wave_serial_pallas": "lightgbm_tpu/ops/partition_pallas.py",
    # round-8 quantized-gradient programs: the serial step with int8/int16
    # discretization, and the data-sharded step whose histogram exchange
    # rides the int16 wire tier (ops/quant.py) — its psum_scatter payload
    # is pinned at HALF the f32 program's (checked pairwise in run())
    "wave_serial_quant": "lightgbm_tpu/ops/quant.py",
    "wave_sharded_data_quant": "lightgbm_tpu/parallel/compact_sharded.py",
    "wave_sharded_data": "lightgbm_tpu/parallel/wave_sharded.py",
    "wave_sharded_voting": "lightgbm_tpu/parallel/wave_sharded.py",
    "wave_feature": "lightgbm_tpu/parallel/feature_sharded.py",
    "wave_sharded_2d": "lightgbm_tpu/parallel/wave2d_sharded.py",
    # pod-shaped variants: the SAME programs traced at the 2-host virtual
    # layout (`parallel/multihost.py` — 8 global devices = 2 hosts x 4
    # local).  Collective structure must not change with host count (only
    # shard widths do); a cross-host-only collective slipping in shows up
    # as a site-count delta against these budgets.
    "wave_sharded_data_pod": "lightgbm_tpu/parallel/wave_sharded.py",
    "wave_sharded_2d_pod": "lightgbm_tpu/parallel/wave2d_sharded.py",
    "serving_bin": "lightgbm_tpu/serving/binner.py",
    "serving_traverse": "lightgbm_tpu/predictor.py",
}


def iter_eqns(jaxpr) -> Iterable[Any]:
    """Every eqn, recursing into sub-jaxprs (pjit / while / cond / scan /
    shard_map bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            vs = v if isinstance(v, (list, tuple)) else [v]
            for s in vs:
                inner = getattr(s, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    yield from iter_eqns(inner)
                elif hasattr(s, "eqns"):
                    yield from iter_eqns(s)


def collect_stats(closed_jaxpr) -> Dict[str, Any]:
    """Structural stats of one closed jaxpr: eqn count, per-primitive
    collective site counts, banned-primitive sites, f64 op count, and the
    total bytes of baked-in constants."""
    import numpy as np

    collectives: Dict[str, int] = {}
    collective_bytes: Dict[str, int] = {}
    banned: List[str] = []
    f64_ops = 0
    eqns = 0
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        eqns += 1
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            collectives[name] = collectives.get(name, 0) + 1
            # wire payload per execution of this site: the input operands'
            # aval bytes (per-device shapes under shard_map).  This is what
            # the int16 histogram-exchange tier shrinks — the site COUNT
            # stays identical, the bytes halve.
            nb = 0
            for iv in eqn.invars:
                aval = getattr(iv, "aval", None)
                shape = getattr(aval, "shape", None)
                dt = getattr(aval, "dtype", None)
                if shape is not None and dt is not None:
                    size = 1
                    for d in shape:
                        size *= int(d)
                    nb += size * np.dtype(dt).itemsize
            collective_bytes[name] = collective_bytes.get(name, 0) + nb
        if any(b in name for b in BANNED_SUBSTRINGS):
            banned.append(name)
        for ov in eqn.outvars:
            dt = getattr(getattr(ov, "aval", None), "dtype", None)
            if dt is not None and dt == np.dtype("float64"):
                f64_ops += 1
                break
    const_bytes = sum(int(getattr(c, "nbytes", 0))
                      for c in closed_jaxpr.consts)
    return {"eqns": eqns, "collectives": collectives,
            "collective_bytes": collective_bytes, "banned": banned,
            "f64_ops": f64_ops, "const_bytes": const_bytes}


def lint_program(name: str, closed_jaxpr, budget: Dict[str, Any],
                 max_const_bytes: int, x64_off: bool,
                 file: Optional[str] = None
                 ) -> Tuple[List[Finding], Dict[str, Any]]:
    """Findings for one traced program against its budget entry."""
    stats = collect_stats(closed_jaxpr)
    file = file or PROGRAM_FILES.get(name, "lightgbm_tpu")
    allowed: Dict[str, int] = dict(budget.get("collectives", {}))
    findings: List[Finding] = []
    for prim, count in sorted(stats["collectives"].items()):
        cap = int(allowed.get(prim, 0))
        if count > cap:
            findings.append(Finding(
                "jaxpr", "collective-budget", file,
                f"program {name!r} traces {count} {prim} site(s), budget "
                f"allows {cap} — a new collective must raise "
                f"analysis/budgets.json explicitly", symbol=name))
    byte_caps: Dict[str, int] = dict(budget.get("collective_bytes", {}))
    for prim, cap in sorted(byte_caps.items()):
        traced = int(stats["collective_bytes"].get(prim, 0))
        if traced > int(cap):
            findings.append(Finding(
                "jaxpr", "collective-payload", file,
                f"program {name!r} traces {traced} {prim} payload bytes, "
                f"budget pins {cap} — a payload regression (e.g. the int16 "
                f"exchange tier silently falling back to f32) must raise "
                f"analysis/budgets.json explicitly", symbol=name))
    for prim in stats["banned"]:
        findings.append(Finding(
            "jaxpr", "host-callback", file,
            f"program {name!r} contains host-sync primitive {prim!r} "
            f"inside the traced hot path", symbol=name))
    if x64_off and stats["f64_ops"]:
        findings.append(Finding(
            "jaxpr", "f64-leak", file,
            f"program {name!r} traces {stats['f64_ops']} float64 op(s) "
            f"with x64 disabled — an unintended f64 cast chain",
            symbol=name))
    cap = int(budget.get("max_const_bytes", max_const_bytes))
    if cap and stats["const_bytes"] > cap:
        findings.append(Finding(
            "jaxpr", "baked-constants", file,
            f"program {name!r} bakes {stats['const_bytes']} bytes of "
            f"constants into the trace (ceiling {cap}) — pass large "
            f"arrays as arguments", symbol=name))
    return findings, stats


# -- the standard program set ------------------------------------------------

def _toy_dataset(n: int, f: int, params: Dict[str, Any]):
    """Deterministic synthetic problem (seeded Generator — rule LGB003)."""
    import numpy as np

    import lightgbm_tpu as lgb

    rng = np.random.default_rng(0)
    X = rng.standard_normal((n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    ds = lgb.Dataset(X, label=y, params=params)
    ds.construct()
    return ds


_BASE_PARAMS = {"objective": "binary", "num_leaves": 15,
                "min_data_in_leaf": 5, "verbosity": -1}


def _trace_wave_serial():
    import jax
    import jax.numpy as jnp

    from ..config import Config
    from ..learner_wave import WaveTPUTreeLearner

    ds = _toy_dataset(512, 4, dict(_BASE_PARAMS))
    learner = WaveTPUTreeLearner(Config.from_params(_BASE_PARAMS),
                                 ds.constructed)
    z = jnp.zeros(ds.constructed.num_data_padded, jnp.float32)
    fmask = jnp.ones(learner.num_features, bool)
    return jax.make_jaxpr(learner._train_tree_wave)(
        learner.bins_packed(), z, z, z, fmask)


def _trace_wave_serial_pallas():
    import jax
    import jax.numpy as jnp

    from ..config import Config
    from ..learner_wave import WaveTPUTreeLearner

    ds = _toy_dataset(512, 4, dict(_BASE_PARAMS))
    cfg = Config.from_params(dict(
        _BASE_PARAMS, tpu_wave_pallas_partition="on",
        tpu_wave_pallas_scan="on",
        # CI-sized windows must clear the sortable cutoff or the
        # partition cond never traces its kernel branch
        tpu_wave_sort_cutoff=64, tpu_sort_cutoff=32))
    learner = WaveTPUTreeLearner(cfg, ds.constructed)
    assert learner._use_partition and learner._use_scan, \
        "forced Pallas knobs did not resolve on"
    z = jnp.zeros(ds.constructed.num_data_padded, jnp.float32)
    fmask = jnp.ones(learner.num_features, bool)
    return jax.make_jaxpr(learner._train_tree_wave)(
        learner.bins_packed(), z, z, z, fmask)


def _trace_wave_serial_quant():
    import jax
    import jax.numpy as jnp

    from ..config import Config
    from ..learner_wave import WaveTPUTreeLearner

    ds = _toy_dataset(512, 4, dict(_BASE_PARAMS))
    learner = WaveTPUTreeLearner(
        Config.from_params(dict(_BASE_PARAMS, tpu_quantized_grad="on")),
        ds.constructed)
    assert learner._quant, learner._quant_reason
    z = jnp.zeros(ds.constructed.num_data_padded, jnp.float32)
    fmask = jnp.ones(learner.num_features, bool)
    return jax.make_jaxpr(learner._train_tree_wave)(
        learner.bins_packed(), z, z, z, fmask)


def _trace_wave_sharded(kind: str, quant: bool = False, ndev: int = 2):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..config import Config
    from ..parallel.compact_sharded import shard_map
    from ..parallel.mesh import make_mesh
    from ..parallel.feature_sharded import FeatureShardedWaveLearner
    from ..parallel.wave_sharded import ShardedVotingWaveLearner, \
        ShardedWaveLearner

    params = dict(_BASE_PARAMS, enable_bundle=False)
    ds = _toy_dataset(2048, 8, params)
    mesh = make_mesh(ndev)
    cfg_params = dict(params, tree_learner={
        "data": "data", "voting": "voting", "feature": "feature"}[kind])
    if quant:
        # 2048 global rows keep the int16 exchange tier active
        # (HMAX·N <= 32767, ops/quant.py)
        cfg_params["tpu_quantized_grad"] = "on"
    cfg = Config.from_params(cfg_params)
    if kind == "feature":
        learner = FeatureShardedWaveLearner(cfg, ds.constructed, mesh)
        body = learner._train_tree_feature_wave
        in_specs = (P(None, None), P(), P(), P(), P())
        out_specs = (P(), P(), P(), P(), P())
    else:
        cls = ShardedWaveLearner if kind == "data" else \
            ShardedVotingWaveLearner
        learner = cls(cfg, ds.constructed, mesh)
        body = learner._train_tree_wave_sharded
        ax = learner.axis
        in_specs = (P(None, ax), P(ax), P(ax), P(ax), P())
        out_specs = (P(), P(), P(), P(ax), P())
    if quant:
        assert learner._quant, learner._quant_reason
        assert learner._wire_int16(), "int16 exchange tier did not engage"
    kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    try:
        fn = shard_map(body, check_vma=False, **kw)
    except TypeError:
        fn = shard_map(body, check_rep=False, **kw)
    z = jnp.zeros(learner.n_pad, jnp.float32)
    fmask_pad = jnp.ones(learner.f_pad, bool)
    return jax.make_jaxpr(fn)(learner.sharded_bins(), z, z, z, fmask_pad)


def _trace_wave_sharded_2d(shape: Tuple[int, int] = (2, 2),
                           features: int = 8):
    """The 2-D hybrid wave tree step on a (data, feature) mesh.  The
    toy dataset's 8 padded features pack to 2 words, so feature-axis=2 is
    the word-aligned tile limit at this width (tests use wider problems
    for 2x4 shapes); the pod variant scales the DATA axis instead
    ((4, 2) — the 2-host x 4-local virtual layout, row axis host-major).
    ``features`` widens the toy problem for the mesh-factorization sweep
    (spmd.py needs feature-axis=4 eligible, i.e. 16 features -> 4 words)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..config import Config
    from ..parallel.compact_sharded import shard_map
    from ..parallel.sharding import AXIS_DATA, AXIS_FEATURE, make_mesh
    from ..parallel.wave2d_sharded import ShardedWave2DLearner, \
        wave2d_ineligible_reason

    params = dict(_BASE_PARAMS, enable_bundle=False)
    ds = _toy_dataset(2048, features, params)
    mesh = make_mesh(shape=shape, axis_names=(AXIS_DATA, AXIS_FEATURE))
    cfg = Config.from_params(dict(params, tree_learner="data_feature"))
    reason = wave2d_ineligible_reason(cfg, ds.constructed, mesh)
    assert reason is None, f"gate dataset ineligible for 2D: {reason}"
    learner = ShardedWave2DLearner(cfg, ds.constructed, mesh)
    ax, fx = learner.axis, learner.faxis
    kw = dict(mesh=mesh,
              in_specs=(P(fx, ax), P(ax), P(ax), P(ax), P()),
              out_specs=(P(), P(), P(), P(ax), P()))
    try:
        fn = shard_map(learner._train_tree_wave_sharded, check_vma=False,
                       **kw)
    except TypeError:
        fn = shard_map(learner._train_tree_wave_sharded, check_rep=False,
                       **kw)
    z = jnp.zeros(learner.n_pad, jnp.float32)
    fmask_pad = jnp.ones(learner.f_pad, bool)
    return jax.make_jaxpr(fn)(learner.sharded_bins(), z, z, z, fmask_pad)


def _trace_serving_bin():
    import jax
    import numpy as np

    from ..serving.binner import BinnerArrays

    ds = _toy_dataset(512, 4, dict(_BASE_PARAMS))
    arrays = BinnerArrays.for_data(ds.constructed)
    xu = np.zeros((64, max(arrays.num_used, 1)), np.float64)
    return jax.make_jaxpr(arrays.bin_device)(xu)


def _trace_serving_traverse():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..predictor import _predict_all

    # shape-realistic fake packs: the traversal's structure (and therefore
    # its collective/callback/f64 profile) depends only on shapes
    T, ni, nl, F = 6, 14, 15, 8
    rng = np.random.default_rng(0)
    packs = dict(
        feat=jnp.asarray(rng.integers(0, F, (T, ni)), jnp.int32),
        thr=jnp.asarray(rng.integers(0, 16, (T, ni)), jnp.int32),
        dtyp=jnp.zeros((T, ni), jnp.int32),
        lch=jnp.full((T, ni), -1, jnp.int32),
        rch=jnp.full((T, ni), -1, jnp.int32),
        lval=jnp.zeros((T, nl), jnp.float32),
        cat_bits=jnp.zeros((T, 1), jnp.uint32),
        cat_lo=jnp.zeros((T, ni), jnp.int32),
        cat_hi=jnp.zeros((T, ni), jnp.int32),
        cls=jnp.zeros(T, jnp.int32))
    meta = jnp.zeros(F, jnp.int32)
    bins = jnp.zeros((F, 64), jnp.int32)
    fn = functools.partial(_predict_all, depth=4, K=1, es=False,
                           es_freq=10, es_margin=10.0)
    return jax.make_jaxpr(fn)(bins, packs, meta, meta, meta)


def program_builders(need_mesh_of: int = 2
                     ) -> Dict[str, Callable[[], Any]]:
    """Name -> zero-arg tracer for the standard program set.  Sharded
    programs are included only when the platform exposes enough devices
    (the gate forces an 8-virtual-device CPU platform)."""
    import jax

    builders: Dict[str, Callable[[], Any]] = {
        "wave_serial": _trace_wave_serial,
        "wave_serial_pallas": _trace_wave_serial_pallas,
        "wave_serial_quant": _trace_wave_serial_quant,
        "serving_bin": _trace_serving_bin,
        "serving_traverse": _trace_serving_traverse,
    }
    if len(jax.devices()) >= need_mesh_of:
        builders["wave_sharded_data"] = lambda: _trace_wave_sharded("data")
        builders["wave_sharded_data_quant"] = \
            lambda: _trace_wave_sharded("data", quant=True)
        builders["wave_sharded_voting"] = \
            lambda: _trace_wave_sharded("voting")
        builders["wave_feature"] = lambda: _trace_wave_sharded("feature")
    if len(jax.devices()) >= 2 * need_mesh_of:
        builders["wave_sharded_2d"] = _trace_wave_sharded_2d
    if len(jax.devices()) >= 8:
        # pod shapes: the 2-host x 4-local virtual layout flattened onto
        # the gate's 8 devices (1D data row axis, and a (4, 2) 2D mesh)
        builders["wave_sharded_data_pod"] = \
            lambda: _trace_wave_sharded("data", ndev=8)
        builders["wave_sharded_2d_pod"] = \
            lambda: _trace_wave_sharded_2d(shape=(4, 2))
    return builders


class TracedPrograms:
    """One trace of the standard program set, shared across passes.

    The budget, sequence-order, f64 and const-ceiling checks all walk the
    SAME closed jaxprs — tracing each program once (seconds apiece for the
    sharded learners) instead of once per pass is the gate's dominant
    cost.  ``closed`` maps program name -> closed jaxpr, ``seconds`` the
    per-program tracing wall time (surfaced in the JSON report), and
    ``skipped`` maps untraceable programs to reasons."""

    def __init__(self) -> None:
        self.closed: Dict[str, Any] = {}
        self.seconds: Dict[str, float] = {}
        self.skipped: Dict[str, str] = {}


def trace_programs(programs: Optional[Dict[str, Callable[[], Any]]] = None,
                   glob: Optional[str] = None,
                   only: Optional[set] = None) -> TracedPrograms:
    """Trace the standard program set once (``--programs <glob>`` narrows
    the selection) and return the shared :class:`TracedPrograms` cache.
    ``only`` (a set of program names, or None for all) is the
    ``--changed-only`` narrowing: programs outside it are skipped with a
    reason that names the flag, so the report stays auditable."""
    if programs is None:
        programs = program_builders()
    tp = TracedPrograms()
    for name in sorted(PROGRAM_FILES):
        if glob and not fnmatch.fnmatch(name, glob):
            tp.skipped[name] = f"not selected by --programs {glob!r}"
            continue
        if only is not None and name not in only:
            tp.skipped[name] = "source file unchanged under --changed-only"
            continue
        builder = programs.get(name)
        if builder is None:
            tp.skipped[name] = "not traceable on this platform " \
                "(needs a multi-device mesh)"
            continue
        t0 = time.perf_counter()
        tp.closed[name] = builder()
        tp.seconds[name] = time.perf_counter() - t0
    return tp


def run(budgets: Optional[Dict[str, Any]] = None,
        programs: Optional[Dict[str, Callable[[], Any]]] = None,
        x64_off: Optional[bool] = None,
        traced: Optional[TracedPrograms] = None):
    """Lint the standard program set against its budgets.

    Returns ``(findings, program_stats, skipped)`` where ``program_stats``
    maps program name to its :func:`collect_stats` output (the input for
    ``--dump-budgets``) and ``skipped`` maps missing programs to reasons.
    ``traced`` reuses an existing :func:`trace_programs` cache instead of
    re-tracing (the gate shares one cache with the sequence pass).
    """
    import jax

    if budgets is None:
        budgets = load_budgets()
    if traced is None:
        traced = trace_programs(programs)
    if x64_off is None:
        x64_off = not jax.config.jax_enable_x64
    max_const = int(budgets.get("max_const_bytes", 0))
    prog_budgets = budgets.get("programs", {})

    findings: List[Finding] = []
    stats: Dict[str, Dict[str, Any]] = {}
    skipped: Dict[str, str] = dict(traced.skipped)
    for name, closed in sorted(traced.closed.items()):
        fs, st = lint_program(name, closed, prog_budgets.get(name, {}),
                              max_const, x64_off)
        findings.extend(fs)
        stats[name] = st
    # paired payload check: the quantized data-sharded program's histogram
    # exchange must move at most HALF the f32 program's bytes (the int16
    # wire tier's whole point); checked structurally so a silent fallback
    # to the f32 path fails the gate even before budgets are re-pinned
    qs = stats.get("wave_sharded_data_quant")
    fs32 = stats.get("wave_sharded_data")
    if qs is not None and fs32 is not None:
        qb = int(qs["collective_bytes"].get("psum_scatter", 0))
        fb = int(fs32["collective_bytes"].get("psum_scatter", 0))
        if fb and 2 * qb > fb:
            findings.append(Finding(
                "jaxpr", "quant-exchange-payload",
                PROGRAM_FILES["wave_sharded_data_quant"],
                f"quantized data-sharded histogram exchange traces {qb} "
                f"psum_scatter payload bytes, more than half the f32 "
                f"program's {fb} — the int16 wire tier is not engaging",
                symbol="wave_sharded_data_quant"))
    return findings, stats, skipped


def budgets_from_stats(stats: Dict[str, Dict[str, Any]],
                       max_const_bytes: int = 1 << 20) -> Dict[str, Any]:
    """A budgets.json payload pinning the CURRENT collective site counts
    (``--dump-budgets``).  Raising a number is a deliberate, reviewed act."""
    return {
        "_comment": "Per-program collective-site budgets derived from the "
                    "traced programs. A learner change that adds a "
                    "collective site MUST raise its budget here, in the "
                    "same commit, with the why in the commit message.",
        "max_const_bytes": int(max_const_bytes),
        "programs": {
            name: {"collectives": dict(sorted(
                st["collectives"].items())),
                "collective_bytes": dict(sorted(
                    st["collective_bytes"].items()))}
            for name, st in sorted(stats.items())
        },
    }
