"""AST resource-lifecycle pass: threads join, fds close, children reap.

The host-side surface (fleet gateway selector loop, autopilot daemon,
watchdogs, elastic per-epoch workers) is exactly where a leaked thread,
unclosed socket or unreaped subprocess hides until a minutes-long soak —
the reference C++ LightGBM scopes its ``Network``/thread teardown by
construction; this pass is the static equivalent for the Python tree.
Rules (scanned over ``serving/``, ``lifecycle/``, ``elastic/``, ``io/``,
``observability/``):

  * **LGB011-thread-lifecycle** — every ``threading.Thread`` must have a
    reachable join:

      - stored on ``self``: some method of the class must join that
        attribute (directly, through a one-level local alias
        ``t = self._thread`` / ``getattr(self, "_thread")``, or through
        a ``for t in (self._a, self._b):`` tuple walk).  A class whose
        ``stop()``/``close()``/``shutdown()`` merely sets a stop event
        is the finding this rule exists for — signalling is not
        quiescence.  The one sanctioned joinless shape is the
        stop-event+daemon pattern: ``daemon=True`` AND the class has no
        teardown-named method at all (callers wait on a done-event
        instead — the ``RollbackWatchdog`` shape).
      - fire-and-forget ``threading.Thread(...).start()``: must be
        ``daemon=True`` (a non-daemon anonymous thread can never be
        joined and blocks interpreter exit).
      - local: needs ``daemon=True`` or a ``join`` call in the same
        function (the scatter/join worker-list shape).

  * **LGB012-close-on-all-paths** — sockets / socketpairs / selectors /
    non-``with`` ``open`` results must close: a ``with`` block, a close
    in the creating function, or — when stored on ``self`` — a close of
    that attribute somewhere in the class (same alias forms as LGB011).
    Handing the object off (argument, return, container store) transfers
    ownership and is not a finding here.

  * **LGB013-subprocess-reap** — every ``subprocess.Popen`` result needs
    a reachable ``wait``/``communicate``/``terminate``/``kill`` (or a
    ``with`` block, whose exit waits); ``subprocess.run`` and the
    ``check_*`` wrappers must pass ``timeout=`` so a wedged child cannot
    block teardown forever.

All heuristics are one-file AST checks with the established
allowlist-with-reason workflow; vetted exceptions go to
``allowlist.json`` naming the exact symbol.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .common import Finding, PKG_ROOT, apply_allowlist, load_allowlist, \
    rel_file

#: package dirs with a host-side concurrency/io surface worth scanning
SCAN_DIRS = ("serving", "lifecycle", "elastic", "io", "observability")

_THREAD_CTORS = {"threading.Thread", "Thread"}
_FD_CTORS = {"socket.socket", "socket.create_connection",
             "socket.socketpair", "selectors.DefaultSelector",
             "selectors.SelectSelector", "selectors.PollSelector",
             "selectors.EpollSelector", "selectors.KqueueSelector"}
_POPEN_CTORS = {"subprocess.Popen", "Popen"}
_RUN_CALLS = {"subprocess.run", "subprocess.call",
              "subprocess.check_call", "subprocess.check_output"}

_JOIN = {"join"}
_CLOSE = {"close"}
_REAP = {"wait", "communicate", "terminate", "kill"}
_TEARDOWN_METHODS = {"stop", "close", "shutdown", "__exit__", "__del__"}


def iter_scan_files(root: Optional[str] = None) -> Iterable[str]:
    root = PKG_ROOT if root is None else root
    for d in SCAN_DIRS:
        top = os.path.join(root, d)
        if not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [x for x in sorted(dirnames) if x != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _call_name(call: ast.Call) -> str:
    try:
        return ast.unparse(call.func)
    except Exception:
        return ""


def _is_self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``"X"`` (else None)."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _getattr_target(node: ast.AST) -> Optional[str]:
    """``getattr(self, "X"[, default])`` -> ``"X"`` (else None)."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "getattr" and len(node.args) >= 2 \
            and isinstance(node.args[0], ast.Name) \
            and node.args[0].id == "self" \
            and isinstance(node.args[1], ast.Constant) \
            and isinstance(node.args[1].value, str):
        return node.args[1].value
    return None


class _Fn:
    """One function plus the class (qualname) that owns it, if any."""

    def __init__(self, node: ast.AST, qualname: str,
                 cls: Optional[str]) -> None:
        self.node = node
        self.qualname = qualname
        self.cls = cls


def _collect_fns(tree: ast.Module) -> List[_Fn]:
    fns: List[_Fn] = []

    def visit(node: ast.AST, stack: List[str], cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns.append(_Fn(child, ".".join(stack + [child.name]), cls))
                # nested defs stay attributed to the enclosing class
                visit(child, stack + [child.name], cls)
            elif isinstance(child, ast.ClassDef):
                visit(child, stack + [child.name],
                      ".".join(stack + [child.name]))
            else:
                visit(child, stack, cls)

    visit(tree, [], None)
    return fns


def _own_nodes(fn: _Fn, all_fns: List[_Fn]) -> List[ast.AST]:
    """Nodes of this function excluding nested function bodies (a nested
    def is its own _Fn and analyzed separately)."""
    nested = {id(f.node) for f in all_fns if f.node is not fn.node}
    out: List[ast.AST] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if id(child) in nested:
                continue
            out.append(child)
            walk(child)

    walk(fn.node)
    return out


def _aliases(nodes: Sequence[ast.AST]) -> Dict[str, Set[str]]:
    """Local name -> the ``self.*`` attr(s) it aliases, one level deep:
    ``t = self._thread``, ``t = getattr(self, "_thread")`` and
    ``for s in (self._a, self._b):``."""
    out: Dict[str, Set[str]] = {}
    for node in nodes:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            attr = _is_self_attr(node.value) or _getattr_target(node.value)
            if attr is not None:
                out.setdefault(node.targets[0].id, set()).add(attr)
        elif isinstance(node, ast.For) and isinstance(node.target, ast.Name) \
                and isinstance(node.iter, (ast.Tuple, ast.List)):
            attrs = {a for a in map(_is_self_attr, node.iter.elts)
                     if a is not None}
            if attrs:
                out.setdefault(node.target.id, set()).update(attrs)
    return out


def _attr_method_calls(nodes: Sequence[ast.AST],
                       methods: Set[str]) -> Set[str]:
    """Attrs X for which ``self.X.<m>()`` (or an aliased local's
    ``<m>()``) is called, m in ``methods``."""
    aliases = _aliases(nodes)
    out: Set[str] = set()
    for node in nodes:
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in methods):
            continue
        base = node.func.value
        attr = _is_self_attr(base) or _getattr_target(base)
        if attr is not None:
            out.add(attr)
        elif isinstance(base, ast.Name) and base.id in aliases:
            out.update(aliases[base.id])
    return out


def _local_method_calls(nodes: Sequence[ast.AST], var: str,
                        methods: Set[str]) -> bool:
    for node in nodes:
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in methods \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == var:
            return True
    return False


def _any_method_call(nodes: Sequence[ast.AST], methods: Set[str]) -> bool:
    return any(isinstance(n, ast.Call)
               and isinstance(n.func, ast.Attribute)
               and n.func.attr in methods for n in nodes)


def _daemon_true(call: ast.Call) -> bool:
    return any(kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
               and kw.value.value is True for kw in call.keywords)


def _has_timeout_kwarg(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords)


def _binding(call: ast.Call, nodes: Sequence[ast.AST]
             ) -> Tuple[str, Optional[ast.AST]]:
    """How the creation's result is bound: ``with`` / ``assign`` (target
    returned) / ``arg`` (passed straight into another call) / ``method``
    (immediately invoked, e.g. ``Thread(...).start()``) / ``return`` /
    ``other``."""
    for node in nodes:
        if isinstance(node, ast.withitem) and node.context_expr is call:
            return "with", None
        if isinstance(node, ast.Assign) and node.value is call:
            return "assign", node.targets[0]
        if isinstance(node, ast.Call) and node is not call:
            if call in node.args or \
                    any(kw.value is call for kw in node.keywords):
                return "arg", None
            if isinstance(node.func, ast.Attribute) \
                    and node.func.value is call:
                return "method", node.func
        if isinstance(node, ast.Return) and node.value is call:
            return "return", None
    return "other", None


def _target_attrs(target: ast.AST) -> List[str]:
    """Assign target -> the ``self.*`` attrs it stores to (empty when
    the target is not attribute-shaped)."""
    elts = target.elts if isinstance(target, (ast.Tuple, ast.List)) \
        else [target]
    attrs = [a for a in map(_is_self_attr, elts) if a is not None]
    return attrs if len(attrs) == len(elts) else attrs


def _target_names(target: ast.AST) -> List[str]:
    elts = target.elts if isinstance(target, (ast.Tuple, ast.List)) \
        else [target]
    return [e.id for e in elts if isinstance(e, ast.Name)]


def _direct_name(expr: ast.AST, var: str) -> bool:
    """True when ``expr`` hands off the bare handle: ``var`` itself or a
    tuple/list containing it (``Thread(args=(conn,))``).  Derived values
    (``var.pid``, ``var.read(10)``) are NOT a handoff."""
    if isinstance(expr, ast.Name):
        return expr.id == var
    if isinstance(expr, (ast.Tuple, ast.List)):
        return any(_direct_name(e, var) for e in expr.elts)
    return False


def _escapes(nodes: Sequence[ast.AST], var: str) -> Tuple[bool, List[str]]:
    """Does local ``var`` hand off ownership?  Returns (escaped,
    transferred_self_attrs): passed as a call argument, returned, stored
    into a container, or assigned onto ``self.X`` (those attrs are
    returned so the caller can hold the class to the attr rules)."""
    attrs: List[str] = []
    escaped = False
    for node in nodes:
        if isinstance(node, ast.Assign):
            # only a DIRECT `x = var` store transfers the handle;
            # `self.port = var.getsockname()[1]` derives a value from it
            if not (isinstance(node.value, ast.Name)
                    and node.value.id == var):
                continue
            for tgt in node.targets:
                attr = _is_self_attr(tgt)
                if attr is not None:
                    attrs.append(attr)
                elif isinstance(tgt, ast.Subscript):
                    escaped = True
        elif isinstance(node, ast.Return) and node.value is not None:
            if _direct_name(node.value, var):
                escaped = True
        elif isinstance(node, ast.Call):
            # `v` as an argument transfers ownership; `v.meth()` does not
            if isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == var:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if _direct_name(arg, var):
                    escaped = True
    return escaped, attrs


class _ClassInfo:
    """Class-wide teardown facts, unioned over every method."""

    def __init__(self) -> None:
        self.joined: Set[str] = set()
        self.closed: Set[str] = set()
        self.reaped: Set[str] = set()
        self.method_names: Set[str] = set()


def _class_infos(fns: List[_Fn], all_fns: List[_Fn]
                 ) -> Dict[str, _ClassInfo]:
    infos: Dict[str, _ClassInfo] = {}
    for fn in fns:
        if fn.cls is None:
            continue
        info = infos.setdefault(fn.cls, _ClassInfo())
        info.method_names.add(fn.node.name)
        nodes = _own_nodes(fn, all_fns)
        info.joined |= _attr_method_calls(nodes, _JOIN)
        info.closed |= _attr_method_calls(nodes, _CLOSE)
        info.reaped |= _attr_method_calls(nodes, _REAP)
    return infos


def scan_file(path: str) -> List[Finding]:
    """All LGB011/LGB012/LGB013 findings for one file (no allowlist)."""
    with open(path) as fh:
        src = fh.read()
    tree = ast.parse(src, filename=path)
    rf = rel_file(path)
    fns = _collect_fns(tree)
    classes = _class_infos(fns, fns)
    findings: List[Finding] = []

    for fn in fns:
        nodes = _own_nodes(fn, fns)
        cls = classes.get(fn.cls) if fn.cls else None
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name in _THREAD_CTORS:
                findings.extend(_check_thread(node, nodes, fn, cls, rf))
            elif name in _FD_CTORS or name == "open":
                findings.extend(_check_fd(node, name, nodes, fn, cls, rf))
            elif name in _POPEN_CTORS:
                findings.extend(_check_popen(node, nodes, fn, cls, rf))
            elif name in _RUN_CALLS and not _has_timeout_kwarg(node):
                findings.append(Finding(
                    "resources", "LGB013-subprocess-reap", rf,
                    f"{name}() without timeout= — a wedged child blocks "
                    f"teardown forever; pass timeout= (or use Popen with "
                    f"an explicit wait/kill path)",
                    line=node.lineno, symbol=fn.qualname))
    return findings


def _attr_join_ok(attr: str, call: ast.Call, cls: Optional[_ClassInfo]
                  ) -> Tuple[bool, str]:
    if cls is not None and attr in cls.joined:
        return True, ""
    if _daemon_true(call) and cls is not None \
            and not (cls.method_names & _TEARDOWN_METHODS):
        # the sanctioned stop-event+daemon shape: no teardown-named
        # method exists, so no caller is promised quiescence
        return True, ""
    return False, (
        f"thread stored on self.{attr} is never joined by this class — "
        f"a stop()/close() that only sets a flag leaves the thread "
        f"running; join the attribute in the teardown method")


def _check_thread(call: ast.Call, nodes: Sequence[ast.AST], fn: _Fn,
                  cls: Optional[_ClassInfo], rf: str) -> List[Finding]:
    kind, detail = _binding(call, nodes)
    if kind == "method":
        # fire-and-forget Thread(...).start(): unjoinable by construction
        if detail.attr == "start" and not _daemon_true(call):
            return [Finding(
                "resources", "LGB011-thread-lifecycle", rf,
                "fire-and-forget Thread(...).start() without daemon=True "
                "can never be joined and blocks interpreter exit",
                line=call.lineno, symbol=fn.qualname)]
        return []
    if kind == "assign":
        attrs = _target_attrs(detail)
        names = _target_names(detail) if not attrs else []
        for var in names:
            escaped, xfer = _escapes(nodes, var)
            attrs.extend(xfer)
            if not xfer and (escaped
                             or _local_method_calls(nodes, var, _JOIN)):
                return []
        out: List[Finding] = []
        for attr in attrs:
            ok, msg = _attr_join_ok(attr, call, cls)
            if not ok:
                out.append(Finding(
                    "resources", "LGB011-thread-lifecycle", rf, msg,
                    line=call.lineno, symbol=fn.qualname))
        if attrs or not names:
            return out
    # local (or unbound) thread: a join in this function or daemon=True
    if _daemon_true(call) or _any_method_call(nodes, _JOIN):
        return []
    return [Finding(
        "resources", "LGB011-thread-lifecycle", rf,
        "thread has no reachable join in this function and is not "
        "daemon=True — join the worker (or mark it daemon and signal "
        "it with a stop event)",
        line=call.lineno, symbol=fn.qualname)]


def _check_fd(call: ast.Call, name: str, nodes: Sequence[ast.AST],
              fn: _Fn, cls: Optional[_ClassInfo], rf: str) -> List[Finding]:
    kind, detail = _binding(call, nodes)
    if kind in ("with", "arg", "return"):
        return []
    if kind in ("method", "other"):
        # immediately consumed / discarded: nothing trackable to close
        return []
    attrs = _target_attrs(detail)
    names = _target_names(detail) if not attrs else []
    for var in names:
        if _local_method_calls(nodes, var, _CLOSE):
            continue
        escaped, xfer = _escapes(nodes, var)
        if xfer:
            attrs.extend(xfer)
        elif not escaped:
            return [Finding(
                "resources", "LGB012-close-on-all-paths", rf,
                f"{name}() result ({var}) is neither closed in this "
                f"function nor handed off — close it in a finally/with "
                f"or store it where teardown closes it",
                line=call.lineno, symbol=fn.qualname)]
    out: List[Finding] = []
    for attr in attrs:
        if cls is not None and attr in cls.closed:
            continue
        out.append(Finding(
            "resources", "LGB012-close-on-all-paths", rf,
            f"{name}() result stored on self.{attr} but no method of "
            f"the class closes that attribute — teardown must close "
            f"every fd it owns",
            line=call.lineno, symbol=fn.qualname))
    return out


def _check_popen(call: ast.Call, nodes: Sequence[ast.AST], fn: _Fn,
                 cls: Optional[_ClassInfo], rf: str) -> List[Finding]:
    kind, detail = _binding(call, nodes)
    if kind in ("with", "arg", "return"):
        return []                     # Popen.__exit__ waits; handoff ok
    if kind in ("method", "other"):
        return [Finding(
            "resources", "LGB013-subprocess-reap", rf,
            "Popen(...) result is discarded — the child is never "
            "wait()ed and becomes a zombie",
            line=call.lineno, symbol=fn.qualname)]
    attrs = _target_attrs(detail)
    names = _target_names(detail) if not attrs else []
    for var in names:
        if _local_method_calls(nodes, var, _REAP):
            continue
        escaped, xfer = _escapes(nodes, var)
        if xfer:
            attrs.extend(xfer)
        elif not escaped:
            return [Finding(
                "resources", "LGB013-subprocess-reap", rf,
                f"Popen result ({var}) has no wait/communicate/"
                f"terminate/kill path in this function — reap the child "
                f"on every exit arm",
                line=call.lineno, symbol=fn.qualname)]
    out: List[Finding] = []
    for attr in attrs:
        if cls is not None and attr in cls.reaped:
            continue
        out.append(Finding(
            "resources", "LGB013-subprocess-reap", rf,
            f"Popen result stored on self.{attr} but no method of the "
            f"class waits/kills it — teardown must reap the child",
            line=call.lineno, symbol=fn.qualname))
    return out


def run(paths: Optional[Sequence[str]] = None,
        allowlist: Optional[Sequence[dict]] = None):
    """Run the resource-lifecycle pass; ``(findings, suppressed)`` after
    allowlist filtering.  ``paths`` defaults to every module under the
    scanned package dirs."""
    if paths is None:
        paths = list(iter_scan_files())
    if allowlist is None:
        allowlist = load_allowlist()
    findings: List[Finding] = []
    for p in paths:
        findings.extend(scan_file(p))
    return apply_allowlist(findings, allowlist)
