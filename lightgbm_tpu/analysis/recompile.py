"""Recompile sentinel: fingerprint jit caches, fail on silent retraces.

The serving path's whole latency story rests on "warmed buckets never
compile" (`serving/batcher.py`), and the training loop's on "one tree
program per shape signature" — both regressed silently in the past
(recompiles on every new row count, PR-2 motivation).  The sentinel makes
that invariant checkable:

  * ``register(name, fn)`` a jitted callable (anything exposing the
    ``_cache_size()`` introspection jax gives jitted functions);
  * ``arm()`` after warmup to snapshot every cache's entry count — the
    fingerprint;
  * ``check()`` after exercising the steady-state path: any cache that
    GREW retraced a warmed program and yields a finding.

``run()`` is the gate pass: it trains a tiny booster for two iterations
(warmup), fingerprints the tree-step jit, trains two more and verifies
zero retraces; then it warms a ``ServingModel`` over two row buckets,
fingerprints the binner + traversal jits, replays in-bucket requests of
several distinct row counts and verifies the request path never compiled —
the same invariant `tests/test_serving.py::test_zero_recompiles_within_bucket`
asserts over the socket, enforced here without a server.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from .common import Finding


def jit_cache_size(fn: Any) -> Optional[int]:
    """Entry count of a jitted callable's cache, or None when this jax
    version does not expose it."""
    try:
        return int(fn._cache_size())
    except Exception:
        return None


class RecompileSentinel:
    """Snapshot-and-compare over named jit caches."""

    def __init__(self) -> None:
        self._fns: Dict[str, Tuple[Any, str]] = {}
        self._snap: Dict[str, Optional[int]] = {}

    def register(self, name: str, fn: Any,
                 file: str = "lightgbm_tpu") -> None:
        self._fns[name] = (fn, file)

    def arm(self) -> Dict[str, Optional[int]]:
        """Fingerprint every registered cache (call after warmup)."""
        self._snap = {name: jit_cache_size(fn)
                      for name, (fn, _) in self._fns.items()}
        return dict(self._snap)

    def deltas(self) -> Dict[str, Tuple[Optional[int], Optional[int]]]:
        return {name: (self._snap.get(name), jit_cache_size(fn))
                for name, (fn, _) in self._fns.items()}

    def check(self) -> List[Finding]:
        """Findings for every program whose cache grew since ``arm()``."""
        out: List[Finding] = []
        for name, (fn, file) in self._fns.items():
            before = self._snap.get(name)
            after = jit_cache_size(fn)
            if before is None or after is None:
                continue
            if after > before:
                out.append(Finding(
                    "recompile", "retrace", file,
                    f"warmed program {name!r} retraced: jit cache grew "
                    f"{before} -> {after} entries after warmup",
                    symbol=name))
        return out

    def supported(self) -> bool:
        return any(jit_cache_size(fn) is not None
                   for fn, _ in self._fns.values())


# -- the gate pass -----------------------------------------------------------

def _tiny_booster(n: int = 256, f: int = 4, iters: int = 2,
                  extra: Optional[Dict[str, Any]] = None):
    import numpy as np

    import lightgbm_tpu as lgb

    rng = np.random.default_rng(0)
    X = rng.standard_normal((n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
              "verbosity": -1}
    if extra:
        params.update(extra)
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params, ds)
    for _ in range(iters):
        bst.update()
    return bst


def _learner_jits(learner) -> Dict[str, Any]:
    out = {}
    for attr in ("_jit_tree_w", "_jit_tree_c"):
        fn = getattr(learner, attr, None)
        if fn is not None and jit_cache_size(fn) is not None:
            out[f"train_step{attr}"] = fn
    return out


def run() -> Tuple[List[Finding], Dict[str, Any], Optional[str]]:
    """Gate pass: ``(findings, detail, skip_reason)``.  ``detail`` records
    the per-program (before, after) cache fingerprints."""
    import jax
    import numpy as np

    from ..predictor import _predict_all
    from ..serving.binner import _bin_device
    from ..serving.registry import ServingModel

    sentinel = RecompileSentinel()

    # -- training step: two warmup iterations, then two steady-state ones
    bst = _tiny_booster(iters=2)
    learner = bst.gbdt.learner
    jits = _learner_jits(learner)
    for name, fn in jits.items():
        sentinel.register(name, fn, "lightgbm_tpu/learner_wave.py")

    # -- quantized training step (tpu_quantized_grad=on): the per-round
    # scales ride TRACE-TIME attributes (learner_wave._init_root_wave) —
    # a value-dependent leak there would retrace the warmed step on every
    # boosting round, exactly the regression class this sentinel exists for
    bstq = _tiny_booster(iters=2, extra={"tpu_quantized_grad": "on"})
    if getattr(bstq.gbdt.learner, "_quant", False):
        for name, fn in _learner_jits(bstq.gbdt.learner).items():
            sentinel.register(f"quant_{name}", fn,
                              "lightgbm_tpu/ops/quant.py")
    else:
        bstq = None

    # -- 2D hybrid training step (tree_learner=data_feature on a 2x2
    # mesh): the warmed wave program must not retrace across steady-state
    # iterations — a mesh-shape or placement change that silently
    # invalidates the shard_map cache shows up here
    bst2 = None
    if len(jax.devices()) >= 4:
        import lightgbm_tpu as lgb

        from ..parallel.wave2d_sharded import ShardedWave2DLearner

        rng = np.random.default_rng(1)
        X2 = rng.standard_normal((2048, 8))
        y2 = (X2[:, 0] + 0.5 * X2[:, 1] > 0).astype(float)
        params2 = {"objective": "binary", "num_leaves": 7,
                   "min_data_in_leaf": 5, "verbosity": -1,
                   "tree_learner": "data_feature", "parallel_mesh": "2x2",
                   "enable_bundle": False}
        ds2 = lgb.Dataset(X2, label=y2, params=params2)
        bst2 = lgb.Booster(params2, ds2)
        for _ in range(2):
            bst2.update()
        if isinstance(bst2.gbdt.learner, ShardedWave2DLearner):
            for name, fn in _learner_jits(bst2.gbdt.learner).items():
                sentinel.register(
                    f"2d_{name}", fn,
                    "lightgbm_tpu/parallel/wave2d_sharded.py")
        else:
            bst2 = None                      # routed elsewhere: skip leg

    # -- serving: warm two buckets, fingerprint, replay in-bucket sizes
    model = ServingModel(bst)
    buckets = (32, 64)
    model.warm(buckets)
    sentinel.register("serving_bin", _bin_device,
                      "lightgbm_tpu/serving/binner.py")
    sentinel.register("serving_traverse", _predict_all,
                      "lightgbm_tpu/predictor.py")
    if not sentinel.supported():
        return [], {}, "jit cache introspection (_cache_size) unavailable " \
            "on this jax version"

    snap = sentinel.arm()
    for _ in range(2):
        bst.update()                         # same shapes: must not retrace
    if bstq is not None:
        for _ in range(2):
            bstq.update()                    # warmed quantized step likewise
    if bst2 is not None:
        for _ in range(2):
            bst2.update()                    # warmed 2D wave step likewise
    for bucket in buckets:
        for m in (1, bucket // 2, bucket):   # distinct in-bucket row counts
            Xpad = np.zeros((bucket, model.num_features))
            model.predict_padded(Xpad, m)
    findings = sentinel.check()
    detail = {name: {"before": b, "after": a}
              for name, (b, a) in sentinel.deltas().items()}
    detail["armed"] = {k: v for k, v in snap.items()}
    return findings, detail, None
