"""Lock-order race detector: static AST pass + runtime instrumentation.

The serving layer is the one genuinely multi-threaded subsystem (accept
loop, per-connection handlers, batcher workers, hot-swap registry), and its
locks span four modules.  This pass extracts the **lock-acquisition graph**
statically:

  * lock identities are ``module.Class.field`` for ``self.<field> =
    threading.Lock()`` (and RLock/Condition/Semaphore) plus
    ``module.<name>`` for module-level locks;
  * an edge A -> B is recorded when lock B is acquired while A is held —
    directly (nested ``with``), or through a call whose transitive closure
    acquires B (``self.m()``, ``self.attr.m()`` with the attr's class
    inferred from its constructor assignment, and cross-module helpers
    like ``rel_inc``);
  * a **cycle** in the graph is a potential deadlock
    (``lock-order-cycle``);
  * a field mutated both inside and outside any lock of its class
    (``unlocked-mutation``) is a data-race candidate — ``__init__`` is
    construction-time and exempt.

The static pass is conservative about aliasing (it resolves only
``self.x = ClassName(...)`` attribute types) — by design: the analyzed
modules are a closed set and the point is catching *structural* inversions,
not proving absence.

For dynamic coverage, ``LockOrderMonitor`` provides a runtime
lock-discipline mode: tests build ``monitor.make_lock(name)`` locks (or
wrap existing ones into subsystem objects) and every acquisition is checked
against the accumulated order graph on the fly — an inversion is recorded
the moment the second ordering appears, without needing the interleaving
that actually deadlocks.
"""

from __future__ import annotations

import ast
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .common import Finding, PKG_ROOT, apply_allowlist, load_allowlist, \
    rel_file

#: the default analysis set: every module whose locks interlock
DEFAULT_FILES = (
    os.path.join("serving", "batcher.py"),
    os.path.join("serving", "registry.py"),
    os.path.join("serving", "server.py"),
    os.path.join("serving", "fleet", "wire.py"),
    os.path.join("serving", "fleet", "gateway.py"),
    os.path.join("serving", "fleet", "replicas.py"),
    os.path.join("io", "net.py"),
    os.path.join("reliability", "degrade.py"),
    os.path.join("reliability", "metrics.py"),
    os.path.join("lifecycle", "recorder.py"),
    os.path.join("lifecycle", "controller.py"),
    os.path.join("lifecycle", "budget.py"),
    os.path.join("lifecycle", "autopilot.py"),
    os.path.join("observability", "trace.py"),
    os.path.join("observability", "metrics_export.py"),
    os.path.join("observability", "drift.py"),
    os.path.join("elastic", "controller.py"),
    os.path.join("elastic", "epoch.py"),
)

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}


def _is_lock_ctor(value: ast.expr) -> bool:
    for node in ast.walk(value):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr in _LOCK_FACTORIES \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "threading":
            return True
    return False


class _ClassInfo:
    def __init__(self, module: str, name: str, node: ast.ClassDef):
        self.module = module
        self.name = name
        self.node = node
        self.lock_fields: Set[str] = set()
        self.attr_types: Dict[str, str] = {}        # self.<attr> -> ClassName
        self.methods: Dict[str, ast.FunctionDef] = {}

    def lock_id(self, field: str) -> str:
        return f"{self.module}.{self.name}.{field}"


class _Model:
    """The parsed world: classes, module locks, module functions."""

    def __init__(self) -> None:
        self.classes: Dict[str, _ClassInfo] = {}            # by class name
        self.mod_locks: Dict[Tuple[str, str], str] = {}     # (mod, var) -> id
        self.mod_funcs: Dict[str, Tuple[str, ast.FunctionDef]] = {}
        self.files: Dict[str, str] = {}                     # module -> file


def _build_model(paths: Sequence[str]) -> _Model:
    model = _Model()
    for path in paths:
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
        mod = os.path.splitext(os.path.basename(path))[0]
        model.files[mod] = rel_file(path)
        for node in tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        model.mod_locks[(mod, tgt.id)] = f"{mod}.{tgt.id}"
            elif isinstance(node, ast.FunctionDef):
                model.mod_funcs[node.name] = (mod, node)
            elif isinstance(node, ast.ClassDef):
                ci = _ClassInfo(mod, node.name, node)
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        ci.methods[item.name] = item
                model.classes[node.name] = ci
    # second pass: lock fields + attribute types (needs the class map)
    for ci in model.classes.values():
        for meth in ci.methods.values():
            for node in ast.walk(meth):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        if _is_lock_ctor(node.value):
                            ci.lock_fields.add(tgt.attr)
                        else:
                            for c in ast.walk(node.value):
                                if isinstance(c, ast.Call) and \
                                        isinstance(c.func, ast.Name) and \
                                        c.func.id in model.classes:
                                    ci.attr_types[tgt.attr] = c.func.id
                                    break
    return model


def _with_lock_of(item: ast.withitem, ci: Optional[_ClassInfo],
                  mod: str, model: _Model) -> Optional[str]:
    e = item.context_expr
    # `with self._lock:` / `self._lock.acquire()` context form
    if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) and \
            e.value.id == "self" and ci is not None and \
            e.attr in ci.lock_fields:
        return ci.lock_id(e.attr)
    if isinstance(e, ast.Name) and (mod, e.id) in model.mod_locks:
        return model.mod_locks[(mod, e.id)]
    return None


def _callee_key(call: ast.Call, ci: Optional[_ClassInfo],
                model: _Model) -> Optional[Tuple[str, str]]:
    """Resolve a call to (ClassName|'', method/function name) within the
    analyzed set, or None."""
    f = call.func
    if isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Name) and v.id == "self" and ci is not None:
            if f.attr in ci.methods:
                return (ci.name, f.attr)
        if isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name) \
                and v.value.id == "self" and ci is not None:
            tname = ci.attr_types.get(v.attr)
            if tname and f.attr in model.classes[tname].methods:
                return (tname, f.attr)
    elif isinstance(f, ast.Name) and f.id in model.mod_funcs:
        return ("", f.id)
    return None


def _direct_acquisitions(fn: ast.AST, ci: Optional[_ClassInfo], mod: str,
                         model: _Model) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                lock = _with_lock_of(item, ci, mod, model)
                if lock:
                    out.add(lock)
    return out


def _acquire_closure(model: _Model) -> Dict[Tuple[str, str], Set[str]]:
    """(Class, method) -> every lock it may acquire, transitively."""
    direct: Dict[Tuple[str, str], Set[str]] = {}
    calls: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}

    def scan(key: Tuple[str, str], fn: ast.AST, ci: Optional[_ClassInfo],
             mod: str) -> None:
        direct[key] = _direct_acquisitions(fn, ci, mod, model)
        cs: Set[Tuple[str, str]] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                ck = _callee_key(node, ci, model)
                if ck is not None and ck != key:
                    cs.add(ck)
        calls[key] = cs

    for ci in model.classes.values():
        for mname, fn in ci.methods.items():
            scan((ci.name, mname), fn, ci, ci.module)
    for fname, (mod, fn) in model.mod_funcs.items():
        scan(("", fname), fn, None, mod)

    closure = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for key, cs in calls.items():
            for ck in cs:
                extra = closure.get(ck, set()) - closure[key]
                if extra:
                    closure[key] |= extra
                    changed = True
    return closure


def _find_cycle(edges: Dict[str, Set[str]]) -> Optional[List[str]]:
    """One simple cycle in the lock graph, as a node list, or None."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in
             set(edges) | {m for vs in edges.values() for m in vs}}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GRAY
        stack.append(n)
        for m in sorted(edges.get(n, ())):
            if color[m] == GRAY:
                return stack[stack.index(m):] + [m]
            if color[m] == WHITE:
                cyc = dfs(m)
                if cyc:
                    return cyc
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(color):
        if color[n] == WHITE:
            cyc = dfs(n)
            if cyc:
                return cyc
    return None


class RaceReport:
    def __init__(self) -> None:
        # (held, acquired) -> (file, line, holder symbol)
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self.cycle: Optional[List[str]] = None
        # Class.field -> {"locked": [(file,line,sym)], "unlocked": [...]}
        self.mixed: Dict[str, Dict[str, List[Tuple[str, int, str]]]] = {}

    def graph(self) -> Dict[str, Set[str]]:
        g: Dict[str, Set[str]] = {}
        for a, b in self.edges:
            g.setdefault(a, set()).add(b)
        return g


def analyze(paths: Optional[Sequence[str]] = None) -> RaceReport:
    if paths is None:
        paths = [os.path.join(PKG_ROOT, p) for p in DEFAULT_FILES]
    model = _build_model(paths)
    closure = _acquire_closure(model)
    report = RaceReport()

    def walk_fn(key: Tuple[str, str], fn: ast.FunctionDef,
                ci: Optional[_ClassInfo], mod: str, rf: str) -> None:
        sym = f"{key[0]}.{key[1]}" if key[0] else key[1]

        def check(node: ast.AST, held: Tuple[str, ...]) -> None:
            """Examine ONE node under the current held-lock set, then
            recurse into its children."""
            if isinstance(node, ast.With):
                locks = [lk for item in node.items
                         for lk in [_with_lock_of(item, ci, mod, model)]
                         if lk]
                for lk in locks:
                    for h in held:
                        if h != lk:
                            report.edges.setdefault(
                                (h, lk), (rf, node.lineno, sym))
                inner = held + tuple(locks)
                for b in node.body:
                    check(b, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return            # nested defs run later, not under `held`
            if isinstance(node, ast.Call) and held:
                ck = _callee_key(node, ci, model)
                if ck is not None:
                    for lk in closure.get(ck, ()):
                        for h in held:
                            if h != lk:
                                report.edges.setdefault(
                                    (h, lk), (rf, node.lineno, sym))
            # field mutations (rule: unlocked-mutation), __init__ exempt
            if ci is not None and key[1] != "__init__" and \
                    isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in tgts:
                    base = tgt
                    if isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Attribute) and \
                            isinstance(base.value, ast.Name) and \
                            base.value.id == "self" and \
                            base.attr not in ci.lock_fields:
                        fid = f"{ci.name}.{base.attr}"
                        kind = "locked" if held else "unlocked"
                        report.mixed.setdefault(
                            fid, {"locked": [], "unlocked": []}
                        )[kind].append((rf, node.lineno, sym))
            for child in ast.iter_child_nodes(node):
                check(child, held)

        for child in ast.iter_child_nodes(fn):
            check(child, ())

    for ci in model.classes.values():
        rf = model.files[ci.module]
        for mname, fn in ci.methods.items():
            walk_fn((ci.name, mname), fn, ci, ci.module, rf)
    for fname, (mod, fn) in model.mod_funcs.items():
        walk_fn(("", fname), fn, None, mod, model.files[mod])

    report.cycle = _find_cycle(report.graph())
    return report


def findings_from(report: RaceReport) -> List[Finding]:
    out: List[Finding] = []
    if report.cycle:
        cyc = report.cycle
        witness = []
        for a, b in zip(cyc, cyc[1:]):
            f, ln, sym = report.edges[(a, b)]
            witness.append(f"{a}->{b} at {f}:{ln} ({sym})")
        f0, ln0, sym0 = report.edges[(cyc[0], cyc[1])]
        out.append(Finding(
            "races", "lock-order-cycle", f0,
            "lock acquisition cycle " + " -> ".join(cyc) + "; "
            + "; ".join(witness),
            line=ln0, symbol=sym0))
    for fid, sites in sorted(report.mixed.items()):
        if sites["locked"] and sites["unlocked"]:
            lf, lln, _ = sites["locked"][0]
            uf, uln, usym = sites["unlocked"][0]
            out.append(Finding(
                "races", "unlocked-mutation", uf,
                f"field {fid} is mutated under a lock at {lf}:{lln} but "
                f"without one at {uf}:{uln} — racy read-modify-write",
                line=uln, symbol=usym))
    return out


def run(paths: Optional[Sequence[str]] = None,
        allowlist: Optional[Sequence[dict]] = None):
    """Static pass entry: ``(findings, suppressed)``."""
    if allowlist is None:
        allowlist = load_allowlist()
    return apply_allowlist(findings_from(analyze(paths)), allowlist)


# -- runtime lock-discipline instrumentation ---------------------------------

class LockOrderMonitor:
    """Runtime lock-order tracker for tests.

    Locks built via ``make_lock`` report every acquisition; the monitor
    accumulates the order graph across ALL threads and records a violation
    the moment an acquisition closes a cycle — i.e. the two inverse
    orderings only ever need to happen, not interleave.

    Usage::

        mon = LockOrderMonitor()
        a, b = mon.make_lock("a"), mon.make_lock("b")
        ... run the system under test with a/b injected ...
        assert mon.violations == []
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._edges: Dict[str, Set[str]] = {}
        self._tls = threading.local()
        self.violations: List[Dict[str, Any]] = []

    def make_lock(self, name: str, factory=threading.Lock
                  ) -> "InstrumentedLock":
        return InstrumentedLock(self, name, factory())

    def _held(self) -> List[str]:
        if not hasattr(self._tls, "stack"):
            self._tls.stack = []
        return self._tls.stack

    def _reaches(self, src: str, dst: str) -> bool:
        seen = {src}
        frontier = [src]
        while frontier:
            n = frontier.pop()
            if n == dst:
                return True
            for m in self._edges.get(n, ()):
                if m not in seen:
                    seen.add(m)
                    frontier.append(m)
        return False

    def on_acquired(self, name: str) -> None:
        held = self._held()
        with self._mu:
            for h in held:
                if h == name:
                    continue
                if self._reaches(name, h):
                    self.violations.append({
                        "held": h, "acquiring": name,
                        "thread": threading.current_thread().name,
                        "message": f"acquired {name!r} while holding "
                                   f"{h!r}, but the inverse order "
                                   f"{name!r} -> {h!r} was also observed",
                    })
                self._edges.setdefault(h, set()).add(name)
        held.append(name)

    def on_released(self, name: str) -> None:
        held = self._held()
        if name in held:
            held.remove(name)

    def findings(self) -> List[Finding]:
        return [Finding("races", "runtime-lock-order", "<runtime>",
                        v["message"], symbol=v["thread"])
                for v in self.violations]


class InstrumentedLock:
    """A lock whose acquisitions feed a ``LockOrderMonitor``."""

    def __init__(self, monitor: LockOrderMonitor, name: str, lock):
        self._monitor = monitor
        self.name = name
        self._lock = lock

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._monitor.on_acquired(self.name)
        return ok

    def release(self) -> None:
        self._lock.release()
        self._monitor.on_released(self.name)

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()
