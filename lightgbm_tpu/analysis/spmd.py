"""SPMD safety analyzer: collective-order pinning + rank-divergence +
event-loop blocking lints.

The reference trains multi-machine GBDTs over a FIXED Allreduce /
ReduceScatter / Allgather schedule (`src/network`); the cardinal SPMD
invariant is that every rank issues the same collectives in the same
order — a divergence is a silent cluster hang, not an error.  The
budgets pass (`jaxpr_lint.py`) pins collective *counts* per program;
this module pins the rest of the invariant:

  * **collective-order pinning** — walk the already-traced closed jaxprs
    of every budgeted program and extract the ordered collective
    *sequence* ``(primitive, axis_names, shard shape, dtype)``; check it
    against the checked-in ``sequences.json`` (re-derivable with
    ``--dump-sequences``, the budgets.json workflow).  A collective that
    MOVES — same site count, different order — is invisible to budgets
    but still deadlocks a pod when only some ranks take the new path.
  * **cross-factorization order diff** — the same mode traced at
    different mesh factorizations (data at 2/4/8 devices; the 2-D
    hybrid at 1x4 / 2x2 / 4x1 and the (4,2) pod layout) must issue the
    identical ``(primitive, axes)`` order: shard widths may change with
    the mesh, the schedule may not.  This pins host-transparency
    structurally — the property PR 13's pod emulation only sampled.
  * **LGB008 rank-divergence** — AST pass over ``parallel/``, ``io/``
    and ``boosting/``: host control flow conditioned on rank identity
    (``process_index()``, ``rank ==``, heartbeat / dead-rank results)
    that dominates a collective or net op on only one branch is exactly
    the deadlock class elastic recovery (ROADMAP item 2) will
    introduce.  Vetted sites (the SocketNet star protocol, root-only
    lagged GC) carry ``allowlist.json`` entries with reasons.
  * **LGB010 event-loop blocking** — the fleet gateway's selector
    thread (and the batcher ``_done`` callbacks it hands out) must
    never block: no ``time.sleep``, no ``block_until_ready``, no
    unbounded frame recv, and every socket op must sit in the
    non-blocking idiom (an enclosing ``BlockingIOError`` handler — the
    gateway's sockets are all ``setblocking(False)``).

The AST passes stay import-light (no jax); the sequence checks consume
the shared :class:`jaxpr_lint.TracedPrograms` cache, so the gate traces
each program exactly once for budgets + sequences + f64 + const rules.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, \
    Tuple

from .common import Finding, PKG_ROOT, apply_allowlist, load_allowlist, \
    load_sequences, rel_file
from .jaxpr_lint import COLLECTIVE_PRIMS, PROGRAM_FILES, iter_eqns

# -- collective-order sequences ----------------------------------------------


def extract_sequence(closed_jaxpr) -> List[Dict[str, Any]]:
    """The ordered collective sequence of one traced program: for every
    collective eqn (in trace order, recursing into while/cond/scan/pjit
    bodies) the ``(primitive, axis_names, shard shape, dtype)`` tuple.
    Shapes are the first operand's per-device aval — what actually hits
    the wire under shard_map."""
    seq: List[Dict[str, Any]] = []
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMS:
            continue
        axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
        if not isinstance(axes, (list, tuple)):
            axes = (axes,)
        shape: List[int] = []
        dtype = ""
        for iv in eqn.invars:
            aval = getattr(iv, "aval", None)
            if getattr(aval, "shape", None) is not None:
                shape = [int(d) for d in aval.shape]
                dtype = str(getattr(aval, "dtype", ""))
                break
        seq.append({"prim": name, "axes": [str(a) for a in axes],
                    "shape": shape, "dtype": dtype})
    return seq


def order_signature(seq: Sequence[Dict[str, Any]]
                    ) -> List[Tuple[str, Tuple[str, ...]]]:
    """The factorization-invariant view of a sequence: ``(primitive,
    axis_names)`` in order, shard widths and dtypes dropped — what must
    agree across mesh shapes of the same mode."""
    return [(e["prim"], tuple(e["axes"])) for e in seq]


def _fmt_entry(e: Dict[str, Any]) -> str:
    return "%s@%s %s%s" % (e["prim"], ",".join(e["axes"]), e["dtype"],
                           list(e["shape"]))


def sequences_from(traced) -> Dict[str, Any]:
    """The ``sequences.json`` payload pinning the CURRENT collective
    order of every traced program (``--dump-sequences``).  Reordering a
    collective is a deliberate, reviewed act — same contract as
    ``budgets_from_stats``."""
    return {
        "_comment": "Per-program ordered collective sequences (primitive, "
                    "axis names, per-device shard shape, dtype) extracted "
                    "from the traced programs. Every rank must issue these "
                    "in exactly this order; a change that moves or "
                    "reshapes a collective MUST regenerate this file "
                    "(python -m lightgbm_tpu.analysis --dump-sequences) "
                    "in the same commit, with the why in the commit "
                    "message.",
        "programs": {
            name: extract_sequence(closed)
            for name, closed in sorted(traced.closed.items())
        },
    }


def check_sequences(traced, sequences: Optional[Dict[str, Any]] = None
                    ) -> List[Finding]:
    """Diff every traced program's collective sequence against the
    checked-in pin.  Order, axis names, shard shape and dtype must all
    match exactly — rule ``collective-order``."""
    if sequences is None:
        sequences = load_sequences()
    pinned = sequences.get("programs", {})
    findings: List[Finding] = []
    for name, closed in sorted(traced.closed.items()):
        file = PROGRAM_FILES.get(name, "lightgbm_tpu")
        want = pinned.get(name)
        got = extract_sequence(closed)
        if want is None:
            findings.append(Finding(
                "spmd", "collective-order", file,
                f"program {name!r} has no pinned sequence in "
                f"analysis/sequences.json — run --dump-sequences and "
                f"commit the diff", symbol=name))
            continue
        if got == want:
            continue
        detail = _first_divergence(want, got)
        findings.append(Finding(
            "spmd", "collective-order", file,
            f"program {name!r} collective order diverges from "
            f"analysis/sequences.json ({detail}) — every rank must issue "
            f"the same collectives in the same order; a reviewed change "
            f"must regenerate sequences.json in the same commit",
            symbol=name))
    return findings


def _first_divergence(want: Sequence[Dict[str, Any]],
                      got: Sequence[Dict[str, Any]]) -> str:
    if len(want) != len(got):
        return f"pinned {len(want)} collective(s), traced {len(got)}"
    for i, (w, g) in enumerate(zip(want, got)):
        if w != g:
            return (f"site {i}: pinned {_fmt_entry(w)}, "
                    f"traced {_fmt_entry(g)}")
    return "sequences differ"


#: mode -> the budgeted programs that are the SAME program at different
#: mesh factorizations; their (primitive, axes) order must be identical
FACTORIZATION_GROUPS = {
    "data": ("wave_sharded_data", "wave_sharded_data_pod"),
    "data_feature": ("wave_sharded_2d", "wave_sharded_2d_pod"),
}


def cross_factorization_findings(traced, groups: Optional[Dict[str, Tuple[
        str, ...]]] = None) -> List[Finding]:
    """Rule ``collective-order-factorization``: within each mode, every
    traced factorization must issue the identical ``(primitive, axes)``
    order.  Shard widths differ per mesh shape (the budgets pass pins
    bytes); ORDER differing means the program is not host-transparent —
    some layouts would enter a collective other layouts never reach."""
    if groups is None:
        groups = FACTORIZATION_GROUPS
    findings: List[Finding] = []
    for mode, names in sorted(groups.items()):
        have = [(n, order_signature(extract_sequence(traced.closed[n])))
                for n in names if n in traced.closed]
        if len(have) < 2:
            continue
        ref_name, ref_sig = have[0]
        for name, sig in have[1:]:
            if sig == ref_sig:
                continue
            detail = "differing length" if len(sig) != len(ref_sig) else \
                next(f"site {i}: {a} vs {b}"
                     for i, (a, b) in enumerate(zip(ref_sig, sig))
                     if a != b)
            findings.append(Finding(
                "spmd", "collective-order-factorization",
                PROGRAM_FILES.get(name, "lightgbm_tpu"),
                f"mode {mode!r}: programs {ref_name!r} and {name!r} are "
                f"the same learner at different mesh factorizations but "
                f"issue different collective orders ({detail}) — the "
                f"schedule must be mesh-shape-invariant", symbol=name))
    return findings


# -- LGB008: rank-divergent control flow around collectives -------------------

#: the default LGB008 analysis set (the layers elastic recovery touches,
#: plus lifecycle/ — the autopilot daemon must stay host-only with ZERO
#: collective sites, and this scan is what proves it)
RANK_DIRS = ("parallel", "io", "boosting", "elastic", "lifecycle")

#: call names (attribute suffixes) that ARE collective / net ops: the
#: host-side net seams (SocketNet / DistributedNet / LoopbackNet), the
#: KV-store ops DistributedNet rides, and the jax collectives themselves
#: (host code constructing a rank-conditional traced collective)
_COLLECTIVE_CALLS = frozenset({
    "allgather", "sync_min", "sync_max", "heartbeat", "barrier",
    "_send_msg", "_recv_msg", "_recv_deadline", "_abort_survivors",
    "key_value_set_bytes", "blocking_key_value_get_bytes",
    "key_value_delete", "wait_at_barrier",
}) | COLLECTIVE_PRIMS

#: identifier fragments that mean "this condition depends on rank
#: identity or liveness results" — `self.rank`, `rank == 0`,
#: `jax.process_index()`, heartbeat / dead-rank verdicts
_RANK_TOKENS = ("process_index", "dead_rank", "heartbeat", "is_master",
                "missing_rank")


def _is_rank_conditioned(test: ast.expr) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id == "rank":
            return True
        if isinstance(node, ast.Attribute) and node.attr == "rank":
            return True
        if isinstance(node, (ast.Name, ast.Attribute)):
            ident = node.id if isinstance(node, ast.Name) else node.attr
            if any(t in ident for t in _RANK_TOKENS):
                return True
    return False


def _collective_calls_in(nodes: Iterable[ast.AST]) -> Set[str]:
    out: Set[str] = set()
    for root in nodes:
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if name in _COLLECTIVE_CALLS:
                out.add(name)
    return out


def _rank_scope_stack(tree: ast.Module) -> List[Tuple[str, ast.AST]]:
    """(qualname, function node) for every function, classes joined in."""
    out: List[Tuple[str, ast.AST]] = []

    def visit(node: ast.AST, stack: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((".".join(stack + [child.name]), child))
                visit(child, stack + [child.name])
            elif isinstance(child, ast.ClassDef):
                visit(child, stack + [child.name])
            else:
                visit(child, stack)

    visit(tree, [])
    return out


def rank_divergence_file(path: str) -> List[Finding]:
    """LGB008 findings for one file (no allowlist applied)."""
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    rf = rel_file(path)
    findings: List[Finding] = []
    for qualname, fn in _rank_scope_stack(tree):
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                body, orelse = node.body, getattr(node, "orelse", [])
            elif isinstance(node, ast.IfExp):
                body, orelse = [node.body], [node.orelse]
            else:
                continue
            if not _is_rank_conditioned(node.test):
                continue
            in_body = _collective_calls_in(body)
            in_else = _collective_calls_in(orelse)
            if in_body == in_else:
                continue       # symmetric (or no) collectives: every rank
            diverging = sorted(in_body ^ in_else)
            findings.append(Finding(
                "spmd", "LGB008-rank-divergence", rf,
                f"rank-conditioned branch dominates collective/net op(s) "
                f"{diverging} on only one side — ranks taking different "
                f"paths around a collective is a silent cluster hang; "
                f"make the schedule rank-symmetric or allowlist this "
                f"vetted site with a reason",
                line=node.lineno, symbol=qualname))
    return findings


def rank_divergence(paths: Optional[Sequence[str]] = None
                    ) -> List[Finding]:
    """LGB008 over ``parallel/``, ``io/``, ``boosting/`` (no allowlist
    applied — :func:`run` does that)."""
    if paths is None:
        paths = []
        for d in RANK_DIRS:
            root = os.path.join(PKG_ROOT, d)
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = sorted(x for x in dirnames
                                     if x != "__pycache__")
                paths.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py"))
    findings: List[Finding] = []
    for p in paths:
        findings.extend(rank_divergence_file(p))
    return findings


# -- LGB010: blocking calls on the gateway's selector thread ------------------

#: the event-loop analysis set: the selector gateway (loop thread +
#: the _done callbacks it hands to batcher workers)
LOOP_FILES = (os.path.join("serving", "fleet", "gateway.py"),)

#: the loop entry point: everything reachable from here via self-calls
#: runs on the selector thread
_LOOP_ENTRY = "_loop"

#: socket methods that park the calling thread unless the socket is
#: non-blocking (the gateway idiom: an enclosing BlockingIOError handler)
_SOCKET_OPS = frozenset({"recv", "recv_into", "accept", "send", "sendall",
                         "connect", "makefile"})

#: calls that block unconditionally — never allowed on the loop thread
_HARD_BLOCKERS = {
    "time.sleep": "time.sleep parks the selector thread",
    "block_until_ready": "block_until_ready syncs on device work",
    "_recv_msg": "length-prefixed frame recv blocks until a full frame",
    "recv_frame": "length-prefixed frame recv blocks until a full frame",
    "create_connection": "blocking connect",
}


def _loop_callables(tree: ast.Module) -> Dict[str, ast.AST]:
    """name -> function node for every method of every class plus nested
    callback defs, with nested defs keyed ``outer.<name>``."""
    out: Dict[str, ast.AST] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{prefix}.{child.name}" if prefix else child.name
                out[key] = child
                visit(child, key)
            elif isinstance(child, ast.ClassDef):
                visit(child, "")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def _thread_targets(fn: ast.AST) -> Set[str]:
    """Names handed to ``threading.Thread(target=...)`` inside ``fn`` —
    those run on their OWN thread and are exempt from the loop rule."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = ""
            f = node.func
            if isinstance(f, ast.Attribute):
                name = f.attr
            elif isinstance(f, ast.Name):
                name = f.id
            if name != "Thread":
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    for n in ast.walk(kw.value):
                        if isinstance(n, ast.Name):
                            out.add(n.id)
                        elif isinstance(n, ast.Attribute):
                            out.add(n.attr)
    return out


def _loop_closure(callables: Dict[str, ast.AST]) -> Dict[str, str]:
    """Every callable transitively reachable from the loop entry on the
    SAME thread -> how it got there (the call chain for the message).
    ``self.m()`` follows methods; nested defs handed to anything OTHER
    than threading.Thread (the batcher callback surface) are reachable
    from their definition site."""
    if _LOOP_ENTRY not in callables:
        return {}
    reach: Dict[str, str] = {_LOOP_ENTRY: _LOOP_ENTRY}
    frontier = [_LOOP_ENTRY]
    while frontier:
        cur = frontier.pop()
        fn = callables[cur]
        exempt = _thread_targets(fn)
        # nested callbacks defined here (minus Thread targets) run on
        # worker threads invoked FOR the loop's request path — the
        # batcher _done callbacks; they must obey the same no-block rule
        for name in callables:
            if name.startswith(cur + ".") and \
                    name.rsplit(".", 1)[1] not in exempt and \
                    name not in reach:
                reach[name] = f"{reach[cur]} -> {name}"
                frontier.append(name)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and f.value.id == "self":
                callee = f.attr
                if callee in callables and callee not in exempt and \
                        callee not in reach:
                    reach[callee] = f"{reach[cur]} -> {callee}"
                    frontier.append(callee)
    return reach


def _in_blocking_guard(fn: ast.AST, call: ast.Call) -> bool:
    """True when ``call`` sits inside a ``try`` whose handlers name
    ``BlockingIOError`` — the gateway's proof that the socket op is
    non-blocking (EAGAIN is expected and handled, never a park)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Try):
            continue
        if not any(isinstance(sub, ast.Call) and sub is call
                   for body in node.body for sub in ast.walk(body)):
            continue
        for handler in node.handlers:
            if handler.type is None:
                continue
            names = handler.type.elts if isinstance(
                handler.type, ast.Tuple) else [handler.type]
            for n in names:
                ident = n.id if isinstance(n, ast.Name) else \
                    getattr(n, "attr", "")
                if ident == "BlockingIOError":
                    return True
    return False


def event_loop_blocking(paths: Optional[Sequence[str]] = None
                        ) -> List[Finding]:
    """LGB010 findings (no allowlist applied)."""
    if paths is None:
        paths = [os.path.join(PKG_ROOT, p) for p in LOOP_FILES]
    findings: List[Finding] = []
    for path in paths:
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
        rf = rel_file(path)
        callables = _loop_callables(tree)
        reach = _loop_closure(callables)
        for name, chain in sorted(reach.items()):
            fn = callables[name]
            nested = {id(v) for k, v in callables.items()
                      if k != name and k.startswith(name + ".")}

            def own_calls(node: ast.AST):
                for child in ast.iter_child_nodes(node):
                    if id(child) in nested:
                        continue
                    if isinstance(child, ast.Call):
                        yield child
                    yield from own_calls(child)

            for call in own_calls(fn):
                f = call.func
                dotted = ""
                attr = ""
                if isinstance(f, ast.Attribute):
                    attr = f.attr
                    try:
                        dotted = ast.unparse(f)
                    except Exception:
                        dotted = attr
                elif isinstance(f, ast.Name):
                    attr = dotted = f.id
                why = _HARD_BLOCKERS.get(dotted) or \
                    _HARD_BLOCKERS.get(attr)
                if why is not None:
                    findings.append(Finding(
                        "spmd", "LGB010-event-loop-blocking", rf,
                        f"{dotted}() on the selector thread ({chain}): "
                        f"{why} — the event loop must never block",
                        line=call.lineno, symbol=name))
                    continue
                if attr in _SOCKET_OPS and isinstance(f, ast.Attribute):
                    if attr in ("sendall", "connect", "makefile") or \
                            not _in_blocking_guard(fn, call):
                        findings.append(Finding(
                            "spmd", "LGB010-event-loop-blocking", rf,
                            f"{dotted}() on the selector thread ({chain}) "
                            f"without a BlockingIOError guard — a "
                            f"blocking socket op parks the whole "
                            f"gateway; use the non-blocking idiom",
                            line=call.lineno, symbol=name))
    return findings


# -- pass entry ---------------------------------------------------------------

def run(rank_paths: Optional[Sequence[str]] = None,
        loop_paths: Optional[Sequence[str]] = None,
        allowlist: Optional[Sequence[dict]] = None,
        traced=None, sequences: Optional[Dict[str, Any]] = None):
    """The spmd gate pass: LGB008 + LGB010 (AST, always) plus the
    sequence-order checks when a :class:`jaxpr_lint.TracedPrograms`
    cache is supplied.  Returns ``(findings, suppressed)``."""
    if allowlist is None:
        allowlist = load_allowlist()
    findings = rank_divergence(rank_paths) + \
        event_loop_blocking(loop_paths)
    if traced is not None:
        findings += check_sequences(traced, sequences)
        findings += cross_factorization_findings(traced)
    return apply_allowlist(findings, allowlist)


def dump_sequences(traced, path: str) -> None:
    """Write ``sequences.json`` (the ``--dump-sequences`` payload) —
    byte-stable: same traced programs, same bytes."""
    payload = sequences_from(traced)
    with open(path + ".tmp", "w") as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")
    os.replace(path + ".tmp", path)
