"""AST repo lint: repo-specific invariants checked at analysis time.

The reference C++ tree leans on compiler diagnostics and clang-tidy to keep
its network and IO layers honest; this is the Python/JAX equivalent, tuned
to the failure classes PRs 1-4 fixed by hand.  Rules:

  * **LGB001-socket-timeout** — every socket this package creates
    (``socket.socket`` / ``socket.create_connection`` / ``accept()``) must
    carry a deadline discipline: a ``timeout=`` argument at the call, a
    ``settimeout`` on the result within the same function, or a
    ``setblocking`` on it (a non-blocking socket on a selector loop —
    the fleet gateway's accept path — can never park a thread; the
    selector's own timeout is the deadline).  A blocking socket with no
    deadline is how a dead peer becomes a silent 120 s hang (the PR-4
    class).
  * **LGB002-atomic-write** — a function that opens a file for writing must
    either go through the temp-file idiom (``tempfile.mkstemp`` in scope)
    or publish with ``os.replace``; a plain ``open(path, "w")`` leaves a
    truncated file behind on preemption (the snapshot/model-write class).
    Vetted streaming writers are allowlisted.
  * **LGB003-global-np-random** — no ``np.random.<fn>()`` through the
    global generator; only seeded ``RandomState`` / ``default_rng``
    instances keep runs reproducible across processes.
  * **LGB004-bare-except** — no bare ``except:``, and no
    ``except BaseException`` handler that fails to re-raise: swallowing
    ``KeyboardInterrupt`` / ``SystemExit`` turns an operator abort into a
    wedged thread.  Thread-boundary handlers that surface the error
    elsewhere are allowlisted with the reason.
  * **LGB005-wallclock-in-traced** — no ``time.time()`` (or monotonic /
    perf_counter) in modules whose functions are traced into XLA programs:
    a wall clock read at trace time bakes a constant into the compiled
    program, silently wrong on every later call.
  * **LGB006-schema-drift** — every key the telemetry/serving reports
    actually emit must have a property in ``observability/schema.json``
    (and the emitted reports must validate).  A report key added without
    a schema entry is exactly how "schema-validated" silently stops
    meaning anything; the drift becomes a gate finding instead
    (``schema_drift()``, run by ``python -m lightgbm_tpu.analysis``).

All rules are heuristic AST checks scoped to one function at a time
(LGB006 builds live reports instead); the checked-in ``allowlist.json``
records every vetted exception with a reason.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .common import Finding, PKG_ROOT, apply_allowlist, load_allowlist, \
    rel_file

# modules whose function bodies are traced into XLA programs (wall-clock
# reads there are trace-time constants, rule LGB005)
TRACED_DIRS = ("ops", "parallel")
TRACED_FILES = ("learner.py", "learner_compact.py", "learner_wave.py",
                "predictor.py", os.path.join("serving", "binner.py"))

# the np.random attributes that ARE the seeded-generator surface
_SAFE_NP_RANDOM = {"RandomState", "default_rng", "Generator", "SeedSequence",
                   "PCG64", "Philox", "MT19937", "BitGenerator"}

_WALLCLOCK_FNS = {"time", "monotonic", "perf_counter", "process_time"}

_WRITE_MODES = ("w", "a", "x")


def iter_package_files(root: Optional[str] = None) -> Iterable[str]:
    root = PKG_ROOT if root is None else root
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames) if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def is_traced_module(path: str) -> bool:
    rel = os.path.relpath(os.path.abspath(path), PKG_ROOT)
    parts = rel.split(os.sep)
    return parts[0] in TRACED_DIRS or rel in TRACED_FILES


# -- scope walking -----------------------------------------------------------

class _Scope:
    """One function (or the module body) — the unit every rule reasons
    over."""

    def __init__(self, node: ast.AST, qualname: str):
        self.node = node
        self.qualname = qualname
        self.socket_calls: List[Tuple[ast.Call, str, Optional[str]]] = []
        self.settimeout_targets: Set[str] = set()
        self.open_calls: List[ast.Call] = []
        self.has_replace = False
        self.has_mkstemp = False


def _call_name(call: ast.Call) -> str:
    """Dotted name of the called expression ('' when not a plain chain)."""
    try:
        return ast.unparse(call.func)
    except Exception:
        return ""


def _assign_target_for(call: ast.Call, scope_node: ast.AST) -> Optional[str]:
    """The (unparsed) variable the call's result lands in, following one
    level of tuple unpack (``conn, addr = srv.accept()`` -> ``conn``)."""
    for node in ast.walk(scope_node):
        if isinstance(node, ast.Assign) and node.value is call:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Tuple) and tgt.elts:
                tgt = tgt.elts[0]
            try:
                return ast.unparse(tgt)
            except Exception:
                return None
        if isinstance(node, ast.withitem) and node.context_expr is call:
            if node.optional_vars is not None:
                try:
                    return ast.unparse(node.optional_vars)
                except Exception:
                    return None
    return None


def _collect_scopes(tree: ast.Module) -> List[_Scope]:
    scopes: List[_Scope] = [_Scope(tree, "<module>")]

    def visit(node: ast.AST, stack: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(_Scope(child, ".".join(stack + [child.name])))
                visit(child, stack + [child.name])
            elif isinstance(child, ast.ClassDef):
                visit(child, stack + [child.name])
            else:
                visit(child, stack)

    visit(tree, [])
    return scopes


def _own_nodes(scope: _Scope, all_scopes: List[_Scope]) -> Iterable[ast.AST]:
    """Nodes belonging to this scope, excluding nested function bodies."""
    nested = {id(s.node) for s in all_scopes if s.node is not scope.node}

    def walk(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if id(child) in nested:
                continue
            yield child
            yield from walk(child)

    yield from walk(scope.node)


# -- the rules ---------------------------------------------------------------

def _scan_scope(scope: _Scope, all_scopes: List[_Scope]) -> None:
    for node in _own_nodes(scope, all_scopes):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name in ("socket.socket",):
            scope.socket_calls.append((node, "socket.socket",
                                       _assign_target_for(node, scope.node)))
        elif name in ("socket.create_connection",):
            scope.socket_calls.append((node, "socket.create_connection",
                                       _assign_target_for(node, scope.node)))
        elif name.endswith(".accept") and isinstance(node.func,
                                                     ast.Attribute):
            scope.socket_calls.append((node, "accept",
                                       _assign_target_for(node, scope.node)))
        elif (name.endswith(".settimeout")
              or name.endswith(".setblocking")) and \
                isinstance(node.func, ast.Attribute):
            # setblocking(False) satisfies the rule the same way a
            # timeout does: a non-blocking socket on a selector loop
            # (serving/fleet/gateway.py) can never park a thread in
            # recv/accept — the selector's own timeout is the deadline
            try:
                scope.settimeout_targets.add(ast.unparse(node.func.value))
            except Exception:
                pass
        elif name in ("os.replace",):
            scope.has_replace = True
        elif name in ("tempfile.mkstemp", "tempfile.NamedTemporaryFile",
                      "tempfile.TemporaryFile"):
            scope.has_mkstemp = True
        if _is_write_open(node, name):
            scope.open_calls.append(node)


def _is_write_open(call: ast.Call, name: str) -> bool:
    if not (name == "open" or name.endswith(".open")
            or name.endswith(".fdopen")):
        return False
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return isinstance(mode, str) and mode.startswith(_WRITE_MODES)


def _has_timeout_kwarg(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords)


def _handler_reraises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise) and node.exc is None:
            return True
    return False


def _names_base_exception(expr: Optional[ast.expr]) -> bool:
    if expr is None:
        return False
    exprs = expr.elts if isinstance(expr, ast.Tuple) else [expr]
    for e in exprs:
        if isinstance(e, ast.Name) and e.id == "BaseException":
            return True
        if isinstance(e, ast.Attribute) and e.attr == "BaseException":
            return True
    return False


def lint_file(path: str, traced: Optional[bool] = None) -> List[Finding]:
    """All rule findings for one file (no allowlist applied)."""
    with open(path) as fh:
        src = fh.read()
    tree = ast.parse(src, filename=path)
    rf = rel_file(path)
    traced = is_traced_module(path) if traced is None else traced
    findings: List[Finding] = []

    scopes = _collect_scopes(tree)
    for scope in scopes:
        _scan_scope(scope, scopes)

        # LGB001: sockets must carry timeouts
        for call, kind, target in scope.socket_calls:
            if _has_timeout_kwarg(call):
                continue
            if target is not None and target in scope.settimeout_targets:
                continue
            findings.append(Finding(
                "lint", "LGB001-socket-timeout", rf,
                f"{kind} result "
                f"{'(' + target + ') ' if target else ''}has no timeout: "
                f"pass timeout= or call settimeout() in the same function",
                line=call.lineno, symbol=scope.qualname))

        # LGB002: durable writes must be atomic
        if not (scope.has_replace or scope.has_mkstemp):
            for call in scope.open_calls:
                findings.append(Finding(
                    "lint", "LGB002-atomic-write", rf,
                    "file opened for writing without os.replace or a "
                    "tempfile in scope — a crash mid-write leaves a "
                    "truncated file",
                    line=call.lineno, symbol=scope.qualname))

    for node in ast.walk(tree):
        # LGB003: global numpy RNG
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            f = node.func
            if isinstance(f.value, ast.Attribute) and \
                    f.value.attr == "random" and \
                    isinstance(f.value.value, ast.Name) and \
                    f.value.value.id in ("np", "numpy") and \
                    f.attr not in _SAFE_NP_RANDOM:
                findings.append(Finding(
                    "lint", "LGB003-global-np-random", rf,
                    f"np.random.{f.attr}() uses the GLOBAL generator; "
                    f"use a seeded np.random.default_rng/RandomState",
                    line=node.lineno))

        # LGB004: bare / swallowing-BaseException handlers
        if isinstance(node, ast.ExceptHandler):
            if node.type is None:
                findings.append(Finding(
                    "lint", "LGB004-bare-except", rf,
                    "bare `except:` catches SystemExit/KeyboardInterrupt; "
                    "name the exception types",
                    line=node.lineno))
            elif _names_base_exception(node.type) and \
                    not _handler_reraises(node):
                findings.append(Finding(
                    "lint", "LGB004-bare-except", rf,
                    "`except BaseException` without re-raise swallows "
                    "KeyboardInterrupt/SystemExit; catch Exception or "
                    "re-raise",
                    line=node.lineno))

        # LGB005: wall clocks in traced modules
        if traced and isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _WALLCLOCK_FNS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in ("time", "_time"):
            findings.append(Finding(
                "lint", "LGB005-wallclock-in-traced", rf,
                f"time.{node.func.attr}() in a traced module bakes a "
                f"trace-time constant into the compiled program",
                line=node.lineno))

    return findings


def schema_drift() -> List[Finding]:
    """LGB006: build the real telemetry and serving reports and check
    every emitted section key has an ``observability/schema.json``
    property — plus a full validator pass over both.  Run as part of the
    gate's lint pass so adding a report key without a schema entry (or
    vice versa breaking validation) is a pre-merge finding, not a
    surprise when a driver chokes on the report."""
    from ..observability.report import load_schema, validate_report
    from ..observability.telemetry import Telemetry
    from ..serving.batcher import ServingStats

    sfile = "lightgbm_tpu/observability/schema.json"
    schema = load_schema()
    props = schema.get("properties", {})
    findings: List[Finding] = []
    reports = {
        "Telemetry.report": Telemetry(True).report(),
        "ServingStats.report": ServingStats().report(),
    }
    for sym, rep in reports.items():
        for key in rep:
            if key not in props:
                findings.append(Finding(
                    "lint", "LGB006-schema-drift", sfile,
                    f"report section {key!r} emitted by {sym} has no "
                    f"schema.json property — add it (or stop emitting it)",
                    symbol=sym))
        for err in validate_report(rep, schema):
            findings.append(Finding(
                "lint", "LGB006-schema-drift", sfile,
                f"{sym} report violates schema.json: {err}", symbol=sym))
    serving_props = props.get("serving", {}).get("properties", {})
    for key in reports["ServingStats.report"].get("serving", {}):
        if key not in serving_props:
            findings.append(Finding(
                "lint", "LGB006-schema-drift", sfile,
                f"serving section key {key!r} (ServingStats."
                f"serving_section) has no schema.json property",
                symbol="ServingStats.serving_section"))
    return findings


def run(paths: Optional[Sequence[str]] = None,
        allowlist: Optional[Sequence[dict]] = None,
        traced: Optional[bool] = None):
    """Run the repo lint.  Returns ``(findings, suppressed)`` after
    allowlist filtering.  ``paths`` defaults to every module under
    ``lightgbm_tpu/``; pass ``traced=True`` to force LGB005 on explicit
    paths (fixture tests)."""
    if paths is None:
        paths = list(iter_package_files())
    if allowlist is None:
        allowlist = load_allowlist()
    findings: List[Finding] = []
    for p in paths:
        findings.extend(lint_file(p, traced=traced))
    return apply_allowlist(findings, allowlist)
