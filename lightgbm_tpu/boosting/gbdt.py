"""GBDT — the main boosting loop.

TPU-native re-design of ``GBDT`` (`src/boosting/gbdt.{h,cpp}`): the Python
host drives iterations while every O(N) step — gradient computation, bagged
histogram trees, score updates, validation-score tree traversal — runs as
jitted device work over the padded row axis.

Loop structure mirrors ``GBDT::TrainOneIter`` (`gbdt.cpp:333-413`):
boost-from-average (`gbdt.cpp:309-331`), gradients (`gbdt.cpp:149-157`),
bagging (`gbdt.cpp:180-241`), per-class tree training, objective leaf
renewal, shrinkage, score update (`gbdt.cpp:451-474`), metric output with
early-stopping bookkeeping (`gbdt.cpp:476-533`), and the ``AddBias`` /
``AsConstantTree`` init-score folding.  Model text serialization follows
`src/boosting/gbdt_model_text.cpp:244-341`.
"""

from __future__ import annotations

import math
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..binning import kEpsilon
from ..config import Config
from ..dataset import Dataset, _ConstructedDataset
from ..learner import TPUTreeLearner
from ..metrics import Metric, create_metric
from ..objectives import ObjectiveFunction, create_objective
from ..ops.histogram import _on_tpu
from ..ops.lookup import lookup_f32 as _lookup_small
from ..tree import Tree

K_MODEL_VERSION = "v2"


class ScoreUpdater:
    """Running raw scores for one dataset (`src/boosting/score_updater.hpp`).
    Scores live on device as (K, N_pad) f32."""

    def __init__(self, data: _ConstructedDataset, num_class: int):
        self.data = data
        self.num_class = num_class
        self.num_data = data.num_data
        n_pad = data.num_data_padded
        score = np.zeros((num_class, n_pad), dtype=np.float32)
        self.has_init_score = False
        init = data.metadata.init_score
        if init is not None:
            self.has_init_score = True
            init = np.asarray(init, dtype=np.float32)
            if len(init) == self.num_data * num_class:
                score[:, :self.num_data] = init.reshape(num_class, self.num_data)
            else:
                score[:, :self.num_data] = init[None, :self.num_data]
        self.score = jnp.asarray(score)
        self._bins_cache = None

    def add_constant(self, val: float, class_id: int) -> None:
        self.score = self.score.at[class_id].add(np.float32(val))

    def add_by_leaf_id(self, leaf_values: np.ndarray, leaf_id: jax.Array,
                       class_id: int) -> None:
        """Train-side update: gather the (host-renewed, shrunk) leaf values by
        the learner's final leaf partition (`score_updater.hpp:74-96`).

        On TPU the per-row lookup is a one-hot matmul, not an XLA gather — a
        1M-row gather from a small table costs ~8 ms there while the MXU
        one-hot contraction is ~0.5 ms (profiling/profile_gather_alts.py);
        on CPU/GPU backends a plain gather is cheaper and the results are
        bit-identical either way (lookup_f32 is exact)."""
        lv = jnp.asarray(leaf_values.astype(np.float32))
        if _on_tpu():
            upd = _lookup_small(lv, leaf_id)
        else:
            upd = lv[leaf_id]
        self.score = self.score.at[class_id].add(upd)

    def add_by_tree(self, tree: Tree, class_id: int) -> None:
        """Valid-side update: traverse the tree over this dataset's binned
        matrix on device (`score_updater.hpp:97-105` AddScore(tree))."""
        if tree.num_leaves <= 1:
            self.add_constant(float(tree.leaf_value[0]), class_id)
            return
        delta = _traverse_tree_binned(self.data, tree)
        self.score = self.score.at[class_id].add(delta)

    def np_score(self) -> np.ndarray:
        """(n, K) raw scores on host (unpadded)."""
        s = np.asarray(self.score)[:, :self.num_data]
        return s.T if self.num_class > 1 else s[0]


def rebind_tree_to_dataset(tree: Tree, data: _ConstructedDataset) -> None:
    """Reconstruct the inner (bin-space) split fields of a deserialized tree
    — ``split_feature_inner`` / ``threshold_in_bin`` are not part of the model
    text format (`src/io/tree.cpp:207-240`); the reference rebuilds them on
    load the same way (real feature index → used-feature slot, real threshold
    → bin via the mapper's upper bounds)."""
    if not getattr(tree, "needs_rebind", False):
        return
    from ..tree import _in_bitset

    real2inner = {int(j): k for k, j in enumerate(data.used_feature_map)}
    tree._cat_bitsets_inner = {}
    for nd in range(tree.num_leaves - 1):
        real = int(tree.split_feature[nd])
        inner = real2inner.get(real)
        if inner is None:
            raise ValueError(
                f"Model splits on feature {real} which is trivial/unused in "
                "the training data; cannot continue training on this dataset")
        tree.split_feature_inner[nd] = inner
        if not (tree.decision_type[nd] & 1):  # numerical
            tree.threshold_in_bin[nd] = data.bin_mappers[inner].value_to_bin(
                float(tree.threshold[nd]))
        else:
            # categorical: rebuild the inner (bin-space) bitset from the
            # stored category-value bitset via the mapper
            cat_idx = int(tree.threshold[nd])
            tree.threshold_in_bin[nd] = cat_idx
            lo, hi = tree.cat_boundaries[cat_idx], \
                tree.cat_boundaries[cat_idx + 1]
            mapper = data.bin_mappers[inner]
            bins = {mapper.categorical_2_bin[c]
                    for c in mapper.categorical_2_bin
                    if c >= 0 and _in_bitset(tree.cat_threshold, lo, hi, c)}
            tree._cat_bitsets_inner[cat_idx] = bins
    # the cached traversal pack (if any) was built from the previous bin
    # space — the bin-space transition owns its invalidation
    if hasattr(tree, "_traverse_pack"):
        del tree._traverse_pack
    tree.needs_rebind = False


def _traverse_tree_binned(data: _ConstructedDataset, tree: Tree) -> jax.Array:
    """Vectorized inner-bin traversal (``NumericalDecisionInner``,
    `tree.h:233-249`) over all rows of a binned dataset.

    The per-node device arrays depend only on the tree and the bin mappers,
    so they are cached per bin-space (reference-linked valid sets share the
    train set's mapper list, `dataset.py:329`, and reuse one pack) — a
    train/valid/train alternation does not rebuild.
    """
    import weakref

    ni = tree.num_leaves - 1
    packs = getattr(tree, "_traverse_pack", None)
    if packs is None or packs[0] != tree.num_leaves:
        packs = (tree.num_leaves, {})
        tree._traverse_pack = packs
    # keyed by the mapper list's id (reference-linked valid sets share the
    # train set's list and reuse one pack), guarded by a weakref to a dataset
    # owning that list so a recycled address after GC can never serve a
    # stale bin space
    key = id(data.bin_mappers)
    entry = packs[1].get(key)
    pack = None
    if entry is not None:
        owner = entry[0]()
        if owner is not None and owner.bin_mappers is data.bin_mappers:
            pack = entry[1]
    if pack is None:
        num_bin, missing, default_bin, _ = data.feature_meta_arrays()
        feat = tree.split_feature_inner[:ni]
        depth = int(tree.leaf_depth[:tree.num_leaves].max())
        w = (int(data.max_num_bin) + 31) // 32
        is_cat_n = (tree.decision_type[:ni] & 1) != 0
        cat_bits = np.zeros((ni, w), dtype=np.uint32)
        if is_cat_n.any():
            inner_sets = getattr(tree, "_cat_bitsets_inner", {})
            for nd in np.where(is_cat_n)[0]:
                for b in inner_sets.get(int(tree.threshold_in_bin[nd]), ()):
                    cat_bits[nd, b // 32] |= np.uint32(1 << (b % 32))
        pack = (depth,
                jnp.asarray(feat), jnp.asarray(tree.threshold_in_bin[:ni]),
                jnp.asarray(missing[feat]), jnp.asarray(default_bin[feat]),
                jnp.asarray(num_bin[feat] - 1),
                jnp.asarray((tree.decision_type[:ni] & 2) != 0),
                jnp.asarray(tree.left_child[:ni]),
                jnp.asarray(tree.right_child[:ni]),
                jnp.asarray(is_cat_n), jnp.asarray(cat_bits))
        packs[1][key] = (weakref.ref(data), pack)
    depth, feat, thr, node_missing, node_default_bin, node_nan_bin, \
        node_default_left, left_child, right_child, node_is_cat, \
        node_cat_bits = pack
    # leaf values change under DART re-shrinkage, so always ship them fresh
    leaf_value = jnp.asarray(tree.leaf_value[:tree.num_leaves]
                             .astype(np.float32))
    return _traverse_jit(
        data.device_bins(), feat, thr, node_missing, node_default_bin,
        node_nan_bin, node_default_left, left_child, right_child,
        node_is_cat, node_cat_bits, leaf_value, depth)


import functools


@functools.partial(jax.jit, static_argnames=("depth",))
def _traverse_jit(bins, feat, thr, node_missing, node_default_bin,
                  node_nan_bin, node_default_left, left_child, right_child,
                  node_is_cat, node_cat_bits, leaf_value, depth):
    n = bins.shape[1]
    node = jnp.zeros(n, dtype=jnp.int32)
    rows = jnp.arange(n)

    def step(node, _):
        nd = jnp.maximum(node, 0)  # leaves encoded negative; keep stable
        f = feat[nd]
        fv = bins[f, rows].astype(jnp.int32)
        mt = node_missing[nd]
        is_missing = ((mt == 1) & (fv == node_default_bin[nd])) | \
                     ((mt == 2) & (fv == node_nan_bin[nd]))
        go_left = jnp.where(is_missing, node_default_left[nd], fv <= thr[nd])
        # categorical nodes: bitset membership (CategoricalDecisionInner)
        word = jnp.take_along_axis(node_cat_bits[nd], (fv >> 5)[:, None],
                                   axis=1)[:, 0]
        cat_left = ((word >> (fv & 31).astype(jnp.uint32)) & 1).astype(bool)
        go_left = jnp.where(node_is_cat[nd], cat_left, go_left)
        nxt = jnp.where(go_left, left_child[nd], right_child[nd])
        return jnp.where(node < 0, node, nxt), None

    node, _ = jax.lax.scan(step, node, None, length=depth)
    leaf = jnp.where(node < 0, ~node, 0)
    return leaf_value[leaf]


@functools.partial(jax.jit, static_argnames=("k",), donate_argnums=(0,))
def _score_add_leaf(score, leaf_output, leaf_id, lr, k):
    """Device-side training-score update from the learner's final leaf
    partition — the sync-free fast path of ``ScoreUpdater.add_by_leaf_id``."""
    return score.at[k].add(lr * jnp.take(leaf_output, leaf_id))


class GBDT:
    """Reference `src/boosting/gbdt.h:24`.

    The boosting loop is PIPELINED when the objective doesn't renew leaf
    outputs and there are no validation sets: every per-iteration step
    (gradients, tree build, score update) stays on device with zero host
    syncs, and the small per-split record arrays are fetched lazily — host
    trees are assembled only when something actually reads ``self.models``
    (eval, save, predict).  On a remote-attached TPU this removes the
    dominant cost of an iteration (host round trips), the analogue of the
    reference keeping its whole iteration inside the OpenMP region.
    """

    name = "gbdt"
    _supports_pipeline = True

    def __init__(self, cfg: Config, train_data: Optional[Dataset] = None,
                 objective: Optional[ObjectiveFunction] = None):
        self.cfg = cfg
        self.iter_ = 0
        from ..observability import SampledSync, Telemetry
        self.telemetry = Telemetry(bool(getattr(cfg, "telemetry", False)))
        # sampled-sync attribution bracket (observability/attribution.py):
        # inert unless telemetry AND telemetry_sync_every > 0
        self._sync_sampler = SampledSync(
            self.telemetry, int(getattr(cfg, "telemetry_sync_every", 0)))
        self._pending: List[tuple] = []
        self._stopped = False
        self._model_version = 0          # bumped on in-place tree mutation
        self._device_predictor = None    # (key, DevicePredictor) cache
        self._pred_schema = None         # 1-tuple cache (loaded boosters)
        self._jit_grad_fn = None
        self._lr_dev = None
        self._lr_dev_val = None
        self.models: List[Tree] = []
        self.train_data: Optional[_ConstructedDataset] = None
        self.objective = objective
        self.num_tree_per_iteration = 1
        self.shrinkage_rate = cfg.learning_rate
        self.max_feature_idx = 0
        self.label_idx = 0
        self.feature_names: List[str] = []
        self.feature_infos: List[str] = []
        self.learner: Optional[TPUTreeLearner] = None
        self.train_score: Optional[ScoreUpdater] = None
        self.valid_scores: List[ScoreUpdater] = []
        self.valid_names: List[str] = []
        self.training_metrics: List[Metric] = []
        self.valid_metrics: List[List[Metric]] = []
        self.best_score: List[List[float]] = []
        self.best_iter: List[List[int]] = []
        self.best_msg: List[List[str]] = []
        self.class_need_train: List[bool] = []
        self._bag_rng = np.random.RandomState(cfg.bagging_seed)
        self._feat_rng = np.random.RandomState(cfg.feature_fraction_seed)
        self.loaded_parameter = ""
        self.average_output = False
        self.pandas_categorical: Optional[list] = None
        self.eval_history: Dict[str, Dict[str, List[float]]] = {}
        if train_data is not None:
            self.init(train_data, objective)

    # -- pipelined tree materialization --------------------------------------

    @property
    def models(self) -> List[Tree]:
        self._flush_pending()
        return self._models

    @models.setter
    def models(self, value) -> None:
        self._flush_pending()
        self._models = list(value)

    def _flush_pending(self, keep: int = 0) -> None:
        """Assemble host trees for pipelined iterations dispatched so
        far, then run the deferred no-more-splits stop check
        (`gbdt.cpp:379-387` in the sync loop).

        ``keep`` leaves the newest ``keep`` queue entries un-assembled —
        the cross-iteration pipelining seam: the boosting loop flushes
        with ``keep = tpu_pipeline_flush_depth`` every iteration, so each
        step assembles exactly ONE tree whose device program retired many
        iterations ago (its record copies are host-resident) while the
        devices keep executing the queued tail.  The round-5 batch flush
        (keep=0 every 16th iteration) drained the whole queue in one
        device-idle stall — 15-25 ms/tree of host assembly plus the queue
        sync, the largest non-device cost in the trace."""
        pend = getattr(self, "_pending", None)
        if not pend or len(pend) <= keep:
            return
        if keep > 0:
            pend, self._pending = pend[:-keep], pend[-keep:]
        else:
            self._pending = []
        tel = self.telemetry
        _flush_t0 = time.perf_counter() if tel.enabled else 0.0
        # the record arrays were copy_to_host_async'd at dispatch time, so
        # these np.asarray calls find host-resident data (~0.2 ms each);
        # only records of still-executing queued trees block, on execution
        # itself.  (A cold fetch costs ~105 ms flat on the axon tunnel —
        # the earlier stack+3-fetch flush paid ~0.3 s plus a first-call
        # compile; per-tree cold fetches would cost ~5 s per flush.)
        first_idx = len(self._models)
        for entry in pend:
            first_idx = min(first_idx, self._assemble_entry(entry))
        # deferred stop detection over the flushed iterations only: the first
        # iteration in which NO class grew a tree ends training; later
        # iterations repeated the draw and are dropped (`gbdt.cpp:379-387`),
        # including rolling their contributions back out of the training
        # score (under bagging a later draw may have split)
        k = max(self.num_tree_per_iteration, 1)
        for it in range(first_idx // k, len(self._models) // k):
            trees = self._models[it * k:(it + 1) * k]
            if trees and all(t is not None and t.num_leaves <= 1
                             for t in trees):
                # a rolling flush may still hold queued post-stop
                # iterations whose device score updates already applied —
                # drain them so the rollback below covers every tree
                if self._pending:
                    tail, self._pending = self._pending, []
                    for entry in tail:
                        self._assemble_entry(entry)
                # keep iteration 0's constant trees (the sync path's
                # first-iteration case keeps them too); everything after the
                # stop iteration is rolled back and dropped
                drop_from = max(it, 1) * k
                for di in range(drop_from, len(self._models)):
                    t = self._models[di]
                    if t is not None and t.num_leaves > 1:
                        t.apply_shrinkage(-1.0)
                        delta = _traverse_tree_binned(self.train_data, t)
                        self.train_score.score = \
                            self.train_score.score.at[di % k].add(delta)
                del self._models[drop_from:]
                self.iter_ = it
                self._stopped = True
                import warnings
                warnings.warn("Stopped training because there are no more "
                              "leaves that meet the split requirements")
                break
        if tel.enabled:
            # t0 makes the flush land as a trace span too (trace_out)
            tel.add_phase_time("pipeline_flush",
                               time.perf_counter() - _flush_t0,
                               t0=_flush_t0)
            tel.inc("pipeline_flushes")
            tel.inc("trees_assembled", len(pend))
            if keep == 0:
                # the per-tree device counter vectors rode the same async
                # copies as the records — decode them now, off the hot
                # path (a rolling flush keeps queued trees executing, so
                # their counters are decoded at the next full flush)
                tel.flush_device()

    def _assemble_entry(self, entry) -> int:
        """Materialize one queued pipelined tree into ``self._models``;
        returns its model index."""
        idx, rf, ri, rc, init_sc = entry
        # span only when telemetry is on: an attached-but-idle recorder on
        # a telemetry-off booster must record nothing (same invariant the
        # phase timers keep)
        tr = self.telemetry.tracer if self.telemetry.enabled else None
        _t0 = time.perf_counter() if tr is not None else 0.0
        tree = self.learner.assemble_host(np.asarray(rf), np.asarray(ri),
                                          np.asarray(rc))
        if tr is not None:
            # per-tree host-assembly span: which tree a long flush spent
            # its time on (the aggregate lands in phase pipeline_flush)
            tr.add_complete("tree_assemble", _t0,
                            time.perf_counter() - _t0, cat="train",
                            args={"model_index": int(idx)})
        if tree.num_leaves > 1:
            tree.apply_shrinkage(self.shrinkage_rate)
            if abs(init_sc) > kEpsilon:
                tree.leaf_value[:tree.num_leaves] += init_sc
                tree.shrinkage = 1.0
        elif idx < self.num_tree_per_iteration:
            # nothing splittable on the very first iteration: keep the
            # boost-from-average constant model and add its output to the
            # training score, matching the sync path (`gbdt.cpp:395-404`)
            tree.leaf_value[0] = init_sc
            if abs(init_sc) > kEpsilon:
                self.train_score.add_constant(
                    init_sc, idx % self.num_tree_per_iteration)
        self._models[idx] = tree
        return idx

    # -- GBDT::Init (`gbdt.cpp:45-137`) -------------------------------------

    def init(self, train_data: Dataset, objective: Optional[ObjectiveFunction],
             training_metrics: Sequence[Metric] = ()) -> None:
        data = train_data.constructed
        self.train_data = data
        self.objective = objective
        self.num_tree_per_iteration = (
            objective.num_model_per_iteration if objective is not None
            else max(self.cfg.num_class, 1))
        if objective is not None:
            objective.init(data.metadata, data.num_data, data.num_data_padded)
        from ..learner_compact import create_tree_learner
        self.learner = create_tree_learner(self.cfg, data)
        if self.cfg.forcedsplits_filename and \
                hasattr(self.learner, "set_forced_splits"):
            from ..forced import load_forced_splits
            forced = load_forced_splits(self.cfg.forcedsplits_filename, data)
            if forced and len(forced) > self.cfg.num_leaves - 1:
                import warnings
                warnings.warn(
                    f"forced-splits tree has {len(forced)} splits but "
                    f"num_leaves={self.cfg.num_leaves} allows "
                    f"{self.cfg.num_leaves - 1}; truncating in BFS order")
                forced = forced[:self.cfg.num_leaves - 1]
            self.learner.set_forced_splits(forced)
        self.train_score = ScoreUpdater(data, self.num_tree_per_iteration)
        self.training_metrics = list(training_metrics)
        self.max_feature_idx = data.num_total_features - 1
        self.feature_names = list(data.feature_names)
        self.feature_infos = _feature_infos(data)
        self.pandas_categorical = getattr(train_data, "pandas_categorical",
                                          None)
        self.class_need_train = [
            objective.class_need_train(k) if objective is not None else True
            for k in range(self.num_tree_per_iteration)]
        n_pad = data.num_data_padded
        base = np.zeros(n_pad, dtype=np.float32)
        base[:data.num_data] = 1.0
        self._valid_rows = jnp.asarray(base)     # 0 on padded rows
        self.num_data = data.num_data
        self._bag_mask = self._valid_rows
        self._bag_cnt = data.num_data
        self._np_bag_mask = np.asarray(base)
        # parallel tree learning: shard over the local mesh so the jitted
        # steps compile under GSPMD with ICI collectives
        # (`tree_learner=data|feature|voting`, SURVEY §2.7)
        self._mesh = None
        self._parallel_mode = None
        if self.cfg.tree_learner in ("data", "feature", "voting",
                                     "data_feature") \
                and len(jax.devices()) > 1:
            from ..parallel.learners import apply_parallel_sharding
            # multihost.mesh_for_config == sharding.mesh_for_config on one
            # host; on a pod it resolves the parallel_mesh grammar over the
            # GLOBAL device list and host-alignment-checks the row axis
            from ..parallel.multihost import mesh_for_config
            apply_parallel_sharding(self, mesh_for_config(self.cfg),
                                    self.cfg.tree_learner)

    def add_valid_data(self, valid_data: Dataset, name: str,
                       metrics: Sequence[Metric]) -> None:
        data = valid_data.constructed
        self.valid_scores.append(ScoreUpdater(data, self.num_tree_per_iteration))
        self.valid_names.append(name)
        self.valid_metrics.append(list(metrics))
        self.best_score.append([-math.inf] * len(metrics))
        self.best_iter.append([0] * len(metrics))
        self.best_msg.append([""] * len(metrics))

    # -- bagging (`gbdt.cpp:180-241`, `ResetBaggingConfig` `gbdt.cpp:689`) ---

    def _place_rows(self, arr: np.ndarray) -> jax.Array:
        """Upload a row-aligned vector, sharded like the training rows."""
        if self._mesh is not None and self._parallel_mode in \
                ("data", "voting", "data_feature"):
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ..parallel.sharding import row_axis
            return jax.device_put(arr, NamedSharding(
                self._mesh, P(row_axis(self._mesh))))
        return jnp.asarray(arr)

    def _bagging(self, iter_: int) -> None:
        cfg = self.cfg
        if cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0 \
                and iter_ % cfg.bagging_freq == 0:
            with self.telemetry.phase("bagging"):
                n = self.num_data
                bag_cnt = int(cfg.bagging_fraction * n)
                idx = self._bag_rng.choice(n, bag_cnt, replace=False)
                mask = np.zeros(self.train_data.num_data_padded,
                                dtype=np.float32)
                mask[idx] = 1.0
                self._bag_mask = self._place_rows(mask)
                self._np_bag_mask = mask
                self._bag_cnt = bag_cnt

    def _np_bag(self) -> np.ndarray:
        """Host copy of the bagging mask, materialized lazily (device-side
        samplers like GOSS leave it None until a renew path needs it)."""
        if self._np_bag_mask is None:
            self._np_bag_mask = np.asarray(self._bag_mask)
        return self._np_bag_mask

    def _feature_sample(self) -> jax.Array:
        """Per-tree feature_fraction sampling (`serial_tree_learner.cpp:255-283`)."""
        f = self.train_data.num_used_features
        frac = self.cfg.feature_fraction
        if frac >= 1.0:
            if getattr(self, "_full_fmask", None) is None \
                    or self._full_fmask.shape[0] != f:
                self._full_fmask = jnp.ones(f, dtype=bool)
            return self._full_fmask
        used = max(1, int(round(f * frac)))
        idx = self._feat_rng.choice(f, used, replace=False)
        mask = np.zeros(f, dtype=bool)
        mask[idx] = True
        return jnp.asarray(mask)

    # -- gradients -----------------------------------------------------------

    #: objective attributes that hold row-aligned device arrays — the same
    #: list `parallel/learners.py` shards over the mesh
    _OBJ_ARRAYS = ("label", "weights", "trans_label", "label_sign",
                   "label_w", "label_weight", "label_onehot")

    def _compute_gradients(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(K, N_pad) gradients/hessians from the objective (`gbdt.cpp:149`),
        as ONE jitted dispatch.  The objective's row-aligned arrays enter as
        jit ARGUMENTS, not closure constants: under a multi-process mesh
        (`parallel/multihost.py`) they span non-addressable devices, and
        closing over such an array is an error — passing them as args is
        equivalent (they are fixed for the life of the booster) and legal
        everywhere."""
        if self._jit_grad_fn is None:
            obj = self.objective
            K = self.num_tree_per_iteration

            def grad_all(score, arrs):
                saved = {n: getattr(obj, n) for n in arrs}
                for n, v in arrs.items():
                    setattr(obj, n, v)
                try:
                    if obj.name == "multiclass":
                        return obj.get_gradients_all(score)
                    gs, hs = [], []
                    for k in range(K):
                        g, h = obj.get_gradients(score[k], k)
                        gs.append(g)
                        hs.append(h)
                    return jnp.stack(gs), jnp.stack(hs)
                finally:
                    for n, v in saved.items():
                        setattr(obj, n, v)

            self._jit_grad_fn = jax.jit(grad_all)
        obj = self.objective
        arrs = {n: getattr(obj, n) for n in self._OBJ_ARRAYS
                if getattr(obj, n, None) is not None
                and hasattr(getattr(obj, n), "shape")}
        t0 = time.perf_counter()
        with self.telemetry.phase("gradients"):
            g, h = self._jit_grad_fn(self.train_score.score, arrs)
        self._sync_sampler.leg("gradients", t0, (g, h))
        return g, h

    # -- one boosting iteration (`gbdt.cpp:333-413`) -------------------------

    def _pad_external_gradients(self, gradients, hessians):
        grad = jnp.asarray(np.asarray(gradients, dtype=np.float32)
                           .reshape(self.num_tree_per_iteration, -1))
        hess = jnp.asarray(np.asarray(hessians, dtype=np.float32)
                           .reshape(self.num_tree_per_iteration, -1))
        if grad.shape[1] != self.train_data.num_data_padded:
            pad = self.train_data.num_data_padded - grad.shape[1]
            grad = jnp.pad(grad, ((0, 0), (0, pad)))
            hess = jnp.pad(hess, ((0, 0), (0, pad)))
        return grad, hess

    def train_one_iter(self, gradients: Optional[np.ndarray] = None,
                       hessians: Optional[np.ndarray] = None) -> bool:
        """Returns True when training cannot continue (no splittable leaves)."""
        if not self.telemetry.enabled:
            return self._train_one_iter_inner(gradients, hessians)
        ss = self._sync_sampler
        if ss.sampled(self.iter_):
            # sampled-sync bracket: drain the queued pipeline so the
            # measured iteration holds only its own work, sync each leg
            # (the ss.leg calls on the dispatch paths), then sync the
            # whole iteration so ``sync.iteration`` is a true wall.  All
            # ranks sample on the lockstep iteration counter, so the
            # probe's collective is entered pod-wide together.
            from ..observability import force_sync
            ss.drain(self.train_score.score)
            ss.active = True
            t0 = time.perf_counter()
            try:
                with self.telemetry.phase("iteration"):
                    ret = self._train_one_iter_inner(gradients, hessians)
                    force_sync(self.train_score.score)
            finally:
                ss.active = False
            self.telemetry.add_phase_time(
                "sync.iteration", time.perf_counter() - t0, t0=t0)
            ss.probe_exchange(self.learner)
            return ret
        with self.telemetry.phase("iteration"):
            return self._train_one_iter_inner(gradients, hessians)

    def _train_one_iter_inner(self, gradients=None, hessians=None) -> bool:
        if self._stopped:
            return True
        init_scores = [0.0] * self.num_tree_per_iteration
        if gradients is None or hessians is None:
            for k in range(self.num_tree_per_iteration):
                init_scores[k] = self._boost_from_average(k, update_scorer=True)
            if self._can_fuse():
                # gradients are computed INSIDE the fused program
                self._bagging(self.iter_)
                return self._train_trees_fused(init_scores)
            grad, hess = self._compute_gradients()
        else:
            grad, hess = self._pad_external_gradients(gradients, hessians)
        self._bagging(self.iter_)
        return self._train_trees(grad, hess, init_scores)

    def _can_fuse(self) -> bool:
        """One jit program per iteration (gradients -> tree -> score
        update): removes two dispatch gaps (~2.5 ms each on the tunnel)
        and the grad/hess HBM round-trip.  Plain single-class GBDT on the
        serial compact/wave learners only — GOSS/DART reorder around
        gradients, and the sharded learners own their shard_map programs."""
        from ..learner_compact import CompactTPUTreeLearner
        return (self.name == "gbdt"
                and self.num_tree_per_iteration == 1
                and self._can_pipeline()
                and type(self.learner).__module__.startswith(
                    "lightgbm_tpu.learner")
                and isinstance(self.learner, CompactTPUTreeLearner))

    def _fused_iter_fn(self):
        if getattr(self, "_jit_fused", None) is None:
            obj = self.objective
            learner = self.learner
            from ..learner_wave import WaveTPUTreeLearner
            tree_fn = learner._train_tree_wave \
                if isinstance(learner, WaveTPUTreeLearner) \
                else learner._train_tree_compact

            def step(score, bins_p, bag, fmask, lr):
                g, h = obj.get_gradients(score[0], 0)
                out = tree_fn(bins_p, g, h, bag, fmask)
                rec_f, rec_i, rec_cat, leaf_id, leaf_out = out[:5]
                score = score.at[0].add(lr * jnp.take(leaf_out, leaf_id))
                # out[5:] is the telemetry counter lane (present only when
                # cfg.telemetry — the program is unchanged otherwise)
                return (score, rec_f, rec_i, rec_cat) + tuple(out[5:])

            self._jit_fused = jax.jit(step, donate_argnums=(0,))
        return self._jit_fused

    def _train_trees_fused(self, init_scores) -> bool:
        tel = self.telemetry
        if self.shrinkage_rate != self._lr_dev_val:
            self._lr_dev = jnp.float32(self.shrinkage_rate)
            self._lr_dev_val = self.shrinkage_rate
        fmask = self._feature_sample()
        _t0 = time.perf_counter()
        with tel.phase("tree_dispatch"):
            out = self._fused_iter_fn()(
                self.train_score.score, self.learner.bins_packed(),
                self._bag_mask, fmask, self._lr_dev)
        # on sampled iterations the fused program IS the whole tree leg
        # (gradients -> tree -> score update in one dispatch)
        self._sync_sampler.leg("tree_build", _t0, out)
        score, rec_f, rec_i, rec_cat = out[:4]
        telem = out[4] if len(out) > 4 else None
        self.train_score.score = score
        # start the device->host record copies NOW: they stream behind the
        # still-queued tree programs, so the 16-iteration flush finds them
        # host-resident (a cold fetch costs ~105 ms flat on the axon
        # tunnel; pre-copied ~0.2 ms — profiling/probe_async_fetch.py)
        for a in (rec_f, rec_i, rec_cat) + (() if telem is None
                                            else (telem,)):
            a.copy_to_host_async()
        tel.device_telem(telem)
        self._pending.append((len(self._models), rec_f, rec_i, rec_cat,
                              init_scores[0]))
        self._models.append(None)
        self.iter_ += 1
        # cross-iteration pipelining: assemble ONE depth-old tree per
        # iteration (host work overlaps the executing queue) instead of
        # draining 16 in a device-idle stall; depth <= 0 restores the
        # round-5 batch flush
        depth = int(getattr(self.cfg, "tpu_pipeline_flush_depth", 8))
        if depth > 0:
            self._flush_pending(keep=depth)
        elif len(self._pending) >= 16:
            self._flush_pending()
        return self._stopped

    def _can_pipeline(self) -> bool:
        return (self._supports_pipeline
                and self.objective is not None
                and not self.objective.needs_renew_tree_output
                and not self.valid_scores
                and all(self.class_need_train)
                and self.train_data.num_used_features > 0
                and hasattr(self.learner, "train_async"))

    def _train_trees_pipelined(self, grad, hess, init_scores) -> bool:
        """Sync-free iteration: tree build + device score update dispatched
        asynchronously; host trees materialize lazily in ``_flush_pending``."""
        tel = self.telemetry
        if self.shrinkage_rate != self._lr_dev_val:
            self._lr_dev = jnp.float32(self.shrinkage_rate)
            self._lr_dev_val = self.shrinkage_rate
        for k in range(self.num_tree_per_iteration):
            fmask = self._feature_sample()
            _t0 = time.perf_counter()
            with tel.phase("tree_dispatch"):
                rec_f, rec_i, rec_cat, leaf_id, leaf_out = \
                    self.learner.train_async(grad[k], hess[k],
                                             self._bag_mask, fmask)
            self._sync_sampler.leg(
                "tree_build", _t0, (rec_f, rec_i, rec_cat, leaf_id,
                                    leaf_out))
            _t0 = time.perf_counter()
            with tel.phase("score_update"):
                self.train_score.score = _score_add_leaf(
                    self.train_score.score, leaf_out, leaf_id,
                    self._lr_dev, k)
            self._sync_sampler.leg("score_update", _t0,
                                   (self.train_score.score,))
            telem = self.learner.take_telemetry() \
                if tel.enabled and hasattr(self.learner, "take_telemetry") \
                else None
            for a in (rec_f, rec_i, rec_cat) + (() if telem is None
                                                else (telem,)):
                a.copy_to_host_async()  # see _train_trees_fused
            tel.device_telem(telem)
            self._pending.append((len(self._models), rec_f, rec_i, rec_cat,
                                  init_scores[k]))
            self._models.append(None)
        self.iter_ += 1
        # bound stop-detection staleness without stalling the pipeline: the
        # arrays synced here finished many iterations ago (see
        # _train_trees_fused for the rolling-flush rationale)
        depth = int(getattr(self.cfg, "tpu_pipeline_flush_depth", 8))
        if depth > 0:
            self._flush_pending(keep=depth * self.num_tree_per_iteration)
        elif len(self._pending) >= 16 * self.num_tree_per_iteration:
            self._flush_pending()
        return self._stopped

    def _train_trees(self, grad, hess, init_scores) -> bool:
        """Per-class tree loop shared by GBDT/GOSS/DART
        (`gbdt.cpp:348-413`)."""
        if self._can_pipeline():
            return self._train_trees_pipelined(grad, hess, init_scores)
        tel = self.telemetry
        should_continue = False
        for k in range(self.num_tree_per_iteration):
            new_tree = Tree(2)
            leaf_id = None
            if self.class_need_train[k] and self.train_data.num_used_features > 0:
                fmask = self._feature_sample()
                _t0 = time.perf_counter()
                with tel.phase("tree_train"):
                    new_tree, leaf_id = self.learner.train(
                        grad[k], hess[k], self._bag_mask, fmask)
                # on sampled iterations record tree_train as a sync leg:
                # the host phase's global mean undercounts the sampled
                # wall (iteration 0's compile is always sampled)
                self._sync_sampler.leg("tree_train", _t0, (leaf_id,))
                if tel.enabled and hasattr(self.learner, "take_telemetry"):
                    telem = self.learner.take_telemetry()
                    if telem is not None:
                        telem.copy_to_host_async()
                        tel.device_telem(telem)
            if new_tree.num_leaves > 1:
                should_continue = True
                # score_update here covers the whole post-tree host leg
                # (output renewal + train AND valid score updates) so the
                # attribution table's leg sum tracks the iteration wall on
                # the non-pipelined path too
                _t0 = time.perf_counter()
                with tel.phase("score_update"):
                    if self.objective is not None:
                        score_np = np.asarray(self.train_score.score[k])
                        self.objective.renew_tree_output(
                            new_tree, score_np[:self.num_data],
                            leaf_id, self._np_bag())
                    new_tree.apply_shrinkage(self.shrinkage_rate)
                    self.train_score.add_by_leaf_id(
                        new_tree.leaf_value[:new_tree.num_leaves], leaf_id, k)
                    for vs in self.valid_scores:
                        vs.add_by_tree(new_tree, k)
                self._sync_sampler.leg("score_update", _t0, ())
                if abs(init_scores[k]) > kEpsilon:
                    new_tree.leaf_value[:new_tree.num_leaves] += init_scores[k]
                    new_tree.shrinkage = 1.0
            else:
                # constant tree for the never-trained / unsplittable case
                if len(self.models) < self.num_tree_per_iteration:
                    if not self.class_need_train[k] and self.objective is not None:
                        output = self.objective.boost_from_score(k)
                    else:
                        output = init_scores[k]
                    new_tree = Tree(2)
                    new_tree.num_leaves = 1
                    new_tree.leaf_value[0] = output
                    self.train_score.add_constant(output, k)
                    for vs in self.valid_scores:
                        vs.add_constant(output, k)
            self.models.append(new_tree)

        if not should_continue:
            import warnings
            warnings.warn("Stopped training because there are no more leaves "
                          "that meet the split requirements")
            if len(self.models) > self.num_tree_per_iteration:
                del self.models[-self.num_tree_per_iteration:]
            return True
        self.iter_ += 1
        return False

    def _boost_from_average(self, class_id: int, update_scorer: bool) -> float:
        """`gbdt.cpp:309-331`."""
        if self._models or self.train_score.has_init_score \
                or self.objective is None:
            return 0.0
        if not (self.cfg.boost_from_average or self.train_data.num_used_features == 0):
            return 0.0
        init_score = self.objective.boost_from_score(class_id)
        if abs(init_score) > kEpsilon:
            if update_scorer:
                self.train_score.add_constant(init_score, class_id)
                for vs in self.valid_scores:
                    vs.add_constant(init_score, class_id)
            return init_score
        return 0.0

    # -- full training loop (`gbdt.cpp:243-261`) -----------------------------

    def train(self, snapshot_freq: int = -1, model_output_path: str = "",
              log_fn: Optional[Callable[[str], None]] = None) -> None:
        log = log_fn or (lambda s: print(f"[LightGBM-TPU] [Info] {s}")
                         if self.cfg.verbosity >= 1 else None)
        start = time.time()
        finished = False
        for it in range(self.cfg.num_iterations):
            if finished:
                break
            finished = self.train_one_iter()
            if not finished:
                finished = self.eval_and_check_early_stopping(log)
            if log:
                log(f"{time.time()-start:.6f} seconds elapsed, finished "
                    f"iteration {it + 1}")
            if snapshot_freq > 0 and (it + 1) % snapshot_freq == 0:
                # atomic write + fingerprint sidecar + keep-last-K
                # retention (cfg.snapshot_keep) in one call
                from ..reliability.resume import save_snapshot
                save_snapshot(self, model_output_path, it + 1, self.cfg)

    # -- eval / early stop (`gbdt.cpp:432-533`) ------------------------------

    def eval_and_check_early_stopping(self, log=None) -> bool:
        msg = self.output_metric(self.iter_, log)
        if msg:
            if log:
                log(f"Early stopping at iteration {self.iter_}, the best "
                    f"iteration round is {self.iter_ - self.cfg.early_stopping_round}")
            drop = self.cfg.early_stopping_round * self.num_tree_per_iteration
            del self.models[-drop:]
            return True
        return False

    def output_metric(self, iter_: int, log=None) -> str:
        cfg = self.cfg
        need_output = (iter_ % cfg.metric_freq) == 0
        ret = ""
        msg_lines: List[str] = []
        if need_output:
            for m in self.training_metrics:
                for name, val in m.eval(self._metric_score(self.train_score),
                                        self.objective):
                    line = f"Iteration:{iter_}, training {name} : {val:g}"
                    if log:
                        log(line)
                    self.eval_history.setdefault("training", {}).setdefault(
                        name, []).append(val)
                    if cfg.early_stopping_round > 0:
                        msg_lines.append(line)
        meet = []
        if need_output or cfg.early_stopping_round > 0:
            for i, metrics in enumerate(self.valid_metrics):
                for j, m in enumerate(metrics):
                    results = m.eval(self._metric_score(self.valid_scores[i]),
                                     self.objective)
                    dname = self.valid_names[i]
                    for name, val in results:
                        line = f"Iteration:{iter_}, valid_{i+1} {name} : {val:g}"
                        if need_output and log:
                            log(line)
                        self.eval_history.setdefault(dname, {}).setdefault(
                            name, []).append(val)
                        if cfg.early_stopping_round > 0:
                            msg_lines.append(line)
                    if not ret and cfg.early_stopping_round > 0:
                        factor = 1.0 if m.is_higher_better else -1.0
                        cur = factor * results[-1][1]
                        if cur > self.best_score[i][j]:
                            self.best_score[i][j] = cur
                            self.best_iter[i][j] = iter_
                            meet.append((i, j))
                        elif iter_ - self.best_iter[i][j] >= cfg.early_stopping_round:
                            ret = self.best_msg[i][j]
        for i, j in meet:
            self.best_msg[i][j] = "\n".join(msg_lines)
        return ret

    def _metric_score(self, updater: ScoreUpdater) -> np.ndarray:
        return updater.np_score()

    # -- telemetry (observability/) ------------------------------------------

    def get_telemetry(self, light: bool = False) -> Dict[str, Any]:
        """The JSON telemetry report (observability/schema.json).

        ``light=True`` skips flushing queued pipelined trees — safe to
        call every iteration (``callback.record_telemetry``) because it
        never forces a device sync; the default flushes so the report
        covers every dispatched tree."""
        tel = self.telemetry
        if not light:
            self._flush_pending()
            tel.flush_device()
        if tel.enabled:
            tel.set_provenance(
                tree_learner=str(self.cfg.tree_learner),
                learner=(type(self.learner).__name__
                         if self.learner is not None else None),
                mesh_shape=(str(dict(self._mesh.shape))
                            if self._mesh is not None else None))
            if self._sync_sampler.every > 0:
                tel.set_distributed(sync_every=self._sync_sampler.every)
        ledger = getattr(self.learner, "_ledger", None)
        gauges = {}
        if self.learner is not None and \
                hasattr(self.learner, "memory_gauges"):
            gauges["wave_working_set"] = self.learner.memory_gauges()
        if self.learner is not None:
            gauges["learner"] = type(self.learner).__name__
            # batched-extras reserve: counters["stall_extras"] is usage
            # against this per-tree cap (learner_wave._stall_extras_cap)
            if hasattr(self.learner, "_extras_cap"):
                gauges["stall_extras_cap"] = int(self.learner._extras_cap)
                gauges["stall_vec_cap"] = int(self.learner._vec_cap)
        return tel.report(ledger=ledger, extra_gauges=gauges, light=light)

    # -- prediction ----------------------------------------------------------

    def predict_raw(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        X = np.ascontiguousarray(X, dtype=np.float64)
        n = X.shape[0]
        k = self.num_tree_per_iteration
        num_models = self._num_models_for(num_iteration)
        cfg = self.cfg
        # device batch predictor (`predictor.py`): exact bin-space traversal
        # of all trees in one scan.  Trained boosters bin against the
        # training mappers; text-loaded boosters get a synthetic bin schema
        # reconstructed from the model text (thresholds become bounds —
        # `predictor.reconstruct_bin_schema`), so they serve on device too.
        # Trees pending a rebind (refit/continue-training on a NEW dataset)
        # must not take this path until rebound.
        big = num_models > 0 and (n * num_models >= 200_000
                                  or cfg.pred_early_stop)
        pred_data = self.train_data
        if pred_data is None and big:
            pred_data = self._prediction_schema()
        use_device = (pred_data is not None and big
                      and not any(getattr(t, "needs_rebind", False)
                                  for t in self.models[:num_models]))
        if use_device:
            from ..predictor import DevicePredictor
            key = (num_models, self._model_version, cfg.pred_early_stop,
                   cfg.pred_early_stop_freq, cfg.pred_early_stop_margin)
            if self._device_predictor is None \
                    or self._device_predictor[0] != key:
                self._device_predictor = (key, DevicePredictor(
                    self, pred_data, num_iteration,
                    pred_early_stop=cfg.pred_early_stop,
                    pred_early_stop_freq=cfg.pred_early_stop_freq,
                    pred_early_stop_margin=cfg.pred_early_stop_margin))
            out = self._device_predictor[1].predict_raw(X)
            return out.astype(np.float64)
        out = np.zeros((n, k), dtype=np.float64)
        for i in range(num_models):
            out[:, i % k] += self.models[i].predict(X)
        return out[:, 0] if k == 1 else out

    def _prediction_schema(self):
        """Synthetic bin schema for a dataset-less (text-loaded) booster,
        built once and cached; ``None`` when reconstruction isn't possible
        (the host numpy path still serves those)."""
        if self._pred_schema is None:
            from ..predictor import reconstruct_bin_schema
            try:
                self._pred_schema = (reconstruct_bin_schema(self),)
            except Exception as e:  # unexpected model text shapes
                import warnings
                warnings.warn("could not reconstruct a device bin schema "
                              f"from the model text ({e}); predictions use "
                              "the host path")
                self._pred_schema = (None,)
        return self._pred_schema[0]

    def predict(self, X: np.ndarray, num_iteration: int = -1,
                raw_score: bool = False, pred_leaf: bool = False) -> np.ndarray:
        if pred_leaf:
            num_models = self._num_models_for(num_iteration)
            X = np.ascontiguousarray(X, dtype=np.float64)
            return np.stack([self.models[i].predict_leaf_index(X)
                             for i in range(num_models)], axis=1)
        raw = self.predict_raw(X, num_iteration)
        if raw_score or self.objective is None:
            return raw
        return self.objective.convert_output(raw)

    def _num_models_for(self, num_iteration: int) -> int:
        if num_iteration <= 0:
            return len(self.models)
        return min(len(self.models),
                   num_iteration * self.num_tree_per_iteration)

    @property
    def num_iterations_trained(self) -> int:
        return len(self.models) // max(self.num_tree_per_iteration, 1)

    def rollback_one_iter(self) -> None:
        """`gbdt.cpp:414-431` — drop the last iteration's trees and undo their
        score contribution."""
        if self.iter_ <= 0:
            return
        self._model_version += 1
        for k in range(self.num_tree_per_iteration):
            idx = len(self.models) - self.num_tree_per_iteration + k
            tree = self.models[idx]
            tree.apply_shrinkage(-1.0)
            if tree.num_leaves > 1:
                delta = _traverse_tree_binned(self.train_data, tree)
                self.train_score.score = self.train_score.score.at[k].add(delta)
                for vs in self.valid_scores:
                    vs.add_by_tree(tree, k)
            else:
                self.train_score.add_constant(float(tree.leaf_value[0]), k)
                for vs in self.valid_scores:
                    vs.add_constant(float(tree.leaf_value[0]), k)
        del self.models[-self.num_tree_per_iteration:]
        self.iter_ -= 1

    # -- serialization (`gbdt_model_text.cpp:244-341`) -----------------------

    def save_model_to_string(self, start_iteration: int = 0,
                             num_iteration: int = -1) -> str:
        out = [self.name]
        out.append(f"version={K_MODEL_VERSION}")
        out.append(f"num_class={max(self.cfg.num_class, 1)}")
        out.append(f"num_tree_per_iteration={self.num_tree_per_iteration}")
        out.append(f"label_index={self.label_idx}")
        out.append(f"max_feature_idx={self.max_feature_idx}")
        if self.objective is not None:
            out.append(f"objective={self.objective.to_string()}")
        if self.average_output:
            out.append("average_output")
        out.append("feature_names=" + " ".join(self.feature_names))
        out.append("feature_infos=" + " ".join(self.feature_infos))

        num_used = len(self.models)
        total_iter = num_used // max(self.num_tree_per_iteration, 1)
        start_iteration = min(max(start_iteration, 0), total_iter)
        if num_iteration > 0:
            num_used = min((start_iteration + num_iteration)
                           * self.num_tree_per_iteration, num_used)
        start_model = start_iteration * self.num_tree_per_iteration
        tree_strs = []
        for i in range(start_model, num_used):
            s = f"Tree={i - start_model}\n" + self.models[i].to_string() + "\n"
            tree_strs.append(s)
        out.append("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs))
        out.append("")
        body = "\n".join(out) + "\n" + "".join(tree_strs)
        body += "end of trees\n"
        imps = self.feature_importance("split")
        pairs = [(int(v), self.feature_names[i]) for i, v in enumerate(imps) if v > 0]
        pairs.sort(key=lambda p: -p[0])
        body += "\nfeature importances:\n"
        for v, name in pairs:
            body += f"{name}={v}\n"
        # pandas category mapping, the python layer's final model line
        # (`basic.py:2233` _dump_pandas_categorical)
        import json as _json
        body += "\npandas_categorical:%s\n" % _json.dumps(
            self.pandas_categorical, default=str)
        return body

    def save_model_to_file(self, filename: str, start_iteration: int = 0,
                           num_iteration: int = -1) -> None:
        """Atomic write: tempfile in the target directory + ``os.replace``,
        so a preemption mid-write (snapshot_iter_* checkpoints especially)
        never leaves a truncated model behind."""
        import os
        import tempfile

        s = self.save_model_to_string(start_iteration, num_iteration)
        d = os.path.dirname(os.path.abspath(filename))
        fd, tmp = tempfile.mkstemp(
            prefix=os.path.basename(filename) + ".", suffix=".tmp", dir=d)
        try:
            with os.fdopen(fd, "w") as fh:
                fh.write(s)
            os.replace(tmp, filename)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- JSON dump (`gbdt_model_text.cpp:15-60` DumpModel) -------------------

    def dump_model(self, start_iteration: int = 0, num_iteration: int = -1
                   ) -> Dict[str, Any]:
        """Model as a JSON-able dict, the reference ``DumpModel`` schema."""
        k = max(self.num_tree_per_iteration, 1)
        models = self.models
        total_iteration = len(models) // k
        start_iteration = min(max(start_iteration, 0), total_iteration)
        num_used = len(models)
        if num_iteration > 0:
            num_used = min((start_iteration + num_iteration) * k, num_used)
        out: Dict[str, Any] = {
            "name": "tree",
            "version": K_MODEL_VERSION,
            "num_class": max(self.cfg.num_class, 1),
            "num_tree_per_iteration": self.num_tree_per_iteration,
            "label_index": self.label_idx,
            "max_feature_idx": self.max_feature_idx,
            "average_output": self.average_output,
        }
        if self.objective is not None:
            out["objective"] = self.objective.to_string()
        out["feature_names"] = list(self.feature_names)
        out["tree_info"] = [
            dict(tree_index=i - start_iteration * k,
                 **models[i].to_json())
            for i in range(start_iteration * k, num_used)]
        return out

    # -- refit (`gbdt.cpp` RefitTree + `serial_tree_learner.cpp`
    #    FitByExistingTree) --------------------------------------------------

    def refit_leaf_preds(self, leaf_preds: np.ndarray,
                         decay_rate: float = 0.9) -> None:
        """Refit every tree's leaf values on this booster's CURRENT train
        data: per iteration, gradients at the running score, per-leaf
        grad/hess sums, ``decay·old + (1-decay)·new·shrinkage``."""
        models = self.models  # flush pending
        self._model_version += 1
        k = max(self.num_tree_per_iteration, 1)
        n = self.num_data
        assert leaf_preds.shape == (n, len(models)), \
            (leaf_preds.shape, n, len(models))
        from ..ops.split import calculate_leaf_output
        cfg = self.cfg
        # zero the running score — refit replays boosting from scratch
        self.train_score.score = jnp.zeros_like(self.train_score.score)
        for it in range(len(models) // k):
            grad, hess = self._compute_gradients()
            g_np = np.asarray(grad)[:, :n]
            h_np = np.asarray(hess)[:, :n]
            for tid in range(k):
                mi = it * k + tid
                tree = models[mi]
                lp = leaf_preds[:, mi].astype(np.int64)
                nl = tree.num_leaves
                sum_g = np.bincount(lp, weights=g_np[tid], minlength=nl)
                sum_h = np.bincount(lp, weights=h_np[tid],
                                    minlength=nl) + kEpsilon
                new_out = np.asarray(calculate_leaf_output(
                    jnp.asarray(sum_g), jnp.asarray(sum_h),
                    float(cfg.lambda_l1), float(cfg.lambda_l2),
                    float(cfg.max_delta_step)))
                old = tree.leaf_value[:nl]
                tree.leaf_value[:nl] = (decay_rate * old
                                        + (1.0 - decay_rate)
                                        * new_out * tree.shrinkage)
                # AddScore with the new leaf values over the refit data
                lv = jnp.asarray(tree.leaf_value[:nl].astype(np.float32))
                pad = self.train_data.num_data_padded - n
                lp_pad = jnp.asarray(np.pad(lp, (0, pad)))
                self.train_score.score = self.train_score.score.at[tid].add(
                    jnp.where(jnp.arange(len(lp_pad)) < n, lv[lp_pad], 0.0))
                if hasattr(tree, "_traverse_pack"):
                    del tree._traverse_pack

    def load_model_from_string(self, s: str) -> "GBDT":
        """`gbdt_model_text.cpp:343-440`."""
        for line in s.rsplit("\n", 3)[1:]:
            if line.startswith("pandas_categorical:"):
                import json as _json
                try:
                    self.pandas_categorical = _json.loads(
                        line[len("pandas_categorical:"):])
                except ValueError:
                    self.pandas_categorical = None
        lines, trees_part = s.split("tree_sizes=", 1)
        header: Dict[str, str] = {}
        for line in lines.strip().split("\n"):
            if "=" in line:
                k, v = line.split("=", 1)
                header[k] = v
            elif line.strip() == "average_output":
                self.average_output = True
        self.num_tree_per_iteration = int(header.get("num_tree_per_iteration", 1))
        self.cfg.num_class = int(header.get("num_class", 1))
        self.label_idx = int(header.get("label_index", 0))
        self.max_feature_idx = int(header.get("max_feature_idx", 0))
        self.feature_names = header.get("feature_names", "").split()
        self.feature_infos = header.get("feature_infos", "").split()
        if "objective" in header and self.objective is None:
            obj_str = header["objective"]
            self.cfg.objective = _objective_from_string(obj_str, self.cfg)
            self.objective = create_objective(self.cfg)
        self.models = []
        body = trees_part.split("\n", 1)[1]
        for block in body.split("Tree=")[1:]:
            tree_txt = block.split("\n\n")[0]
            tree_txt = tree_txt.split("end of trees")[0]
            tree_txt = tree_txt.split("\n", 1)[1]  # drop the tree index line
            self.models.append(Tree.from_string(tree_txt))
        self.iter_ = len(self.models) // max(self.num_tree_per_iteration, 1)
        return self

    # -- importances (`boosting.h:224`, `gbdt.cpp` FeatureImportance) --------

    def feature_importance(self, importance_type: str = "split",
                           num_iteration: int = -1) -> np.ndarray:
        num_models = self._num_models_for(num_iteration)
        out = np.zeros(self.max_feature_idx + 1, dtype=np.float64)
        for i in range(num_models):
            t = self.models[i]
            for nd in range(t.num_leaves - 1):
                if importance_type == "split":
                    out[t.split_feature[nd]] += 1.0
                else:
                    out[t.split_feature[nd]] += max(t.split_gain[nd], 0.0)
        return out


def _feature_infos(data: _ConstructedDataset) -> List[str]:
    """``feature_infos`` strings: [min:max] per feature or categorical list
    (`dataset.cpp` SaveModelToString feature info)."""
    out = ["none"] * data.num_total_features
    for k, m in enumerate(data.bin_mappers):
        j = int(data.used_feature_map[k])
        if m.bin_type == 1:
            out[j] = ":".join(str(c) for c in m.bin_2_categorical)
        else:
            out[j] = f"[{m.min_val:g}:{m.max_val:g}]"
    return out


def _objective_from_string(s: str, cfg: Config) -> str:
    parts = s.split()
    name = parts[0]
    for tok in parts[1:]:
        if ":" in tok:
            k, v = tok.split(":", 1)
            try:
                setattr(cfg, k, type(getattr(cfg, k, 0.0))(v))
            except Exception:
                pass
    return {"xentropy": "cross_entropy", "xentlambda": "cross_entropy_lambda"
            }.get(name, name)
