"""DART — dropout boosting (`src/boosting/dart.hpp:29-210`).

Per iteration: randomly drop trained trees (weighted or uniform), subtract
their contribution from the training score, fit the new tree against the
reduced ensemble, then renormalize the dropped trees and the new tree so
expected predictions stay consistent (`dart.hpp:152-196` Normalize).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..binning import kEpsilon
from .gbdt import GBDT, _traverse_tree_binned


class DART(GBDT):
    name = "dart"
    # drop/normalize touch host trees every iteration — no async pipeline
    _supports_pipeline = False

    def __init__(self, cfg, train_data=None, objective=None):
        self.tree_weight: List[float] = []
        self.sum_weight = 0.0
        self.drop_index: List[int] = []
        super().__init__(cfg, train_data, objective)
        self._drop_rng = np.random.RandomState(cfg.drop_seed)

    def _add_tree_score_train(self, tree, class_id):
        if tree.num_leaves > 1:
            delta = _traverse_tree_binned(self.train_data, tree)
            self.train_score.score = self.train_score.score.at[class_id].add(delta)
        else:
            self.train_score.add_constant(float(tree.leaf_value[0]), class_id)

    def _dropping_trees(self) -> None:
        """`dart.hpp:90-143`."""
        cfg = self.cfg
        self.drop_index = []
        if self._drop_rng.rand() >= cfg.skip_drop:
            drop_rate = cfg.drop_rate
            if not cfg.uniform_drop:
                if self.sum_weight > 0:
                    inv_avg = len(self.tree_weight) / self.sum_weight
                    if cfg.max_drop > 0:
                        drop_rate = min(drop_rate,
                                        cfg.max_drop * inv_avg / self.sum_weight)
                    for i in range(self.iter_):
                        if self._drop_rng.rand() < drop_rate * self.tree_weight[i] * inv_avg:
                            self.drop_index.append(i)
                            if len(self.drop_index) >= cfg.max_drop > 0:
                                break
            else:
                if cfg.max_drop > 0 and self.iter_ > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / self.iter_)
                for i in range(self.iter_):
                    if self._drop_rng.rand() < drop_rate:
                        self.drop_index.append(i)
                        if len(self.drop_index) >= cfg.max_drop > 0:
                            break
        # subtract dropped trees from the training score
        for i in self.drop_index:
            for k in range(self.num_tree_per_iteration):
                tree = self.models[i * self.num_tree_per_iteration + k]
                tree.apply_shrinkage(-1.0)
                self._add_tree_score_train(tree, k)
        n_drop = len(self.drop_index)
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + n_drop)
        else:
            self.shrinkage_rate = cfg.learning_rate if n_drop == 0 else \
                cfg.learning_rate / (cfg.learning_rate + n_drop)

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        self._model_version += 1   # drops/normalize mutate old trees in place
        self._dropping_trees()
        ret = super().train_one_iter(gradients, hessians)
        if ret:
            # failed iteration: undo the drop exactly (un-negate the dropped
            # trees and restore their training-score contribution)
            for i in self.drop_index:
                for k in range(self.num_tree_per_iteration):
                    tree = self.models[i * self.num_tree_per_iteration + k]
                    tree.apply_shrinkage(-1.0)
                    self._add_tree_score_train(tree, k)
            self.shrinkage_rate = self.cfg.learning_rate
            return ret
        self._normalize()
        if not self.cfg.uniform_drop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        return False

    def eval_and_check_early_stopping(self, log=None) -> bool:
        # DART never early-stops (`dart.hpp:83-86`)
        self.output_metric(self.iter_, log)
        return False

    def _normalize(self) -> None:
        """`dart.hpp:152-196`."""
        cfg = self.cfg
        k = float(len(self.drop_index))
        for i in self.drop_index:
            for cid in range(self.num_tree_per_iteration):
                tree = self.models[i * self.num_tree_per_iteration + cid]
                if not cfg.xgboost_dart_mode:
                    tree.apply_shrinkage(1.0 / (k + 1.0))
                    for vs in self.valid_scores:
                        vs.add_by_tree(tree, cid)
                    tree.apply_shrinkage(-k)
                    self._add_tree_score_train(tree, cid)
                else:
                    tree.apply_shrinkage(self.shrinkage_rate)
                    for vs in self.valid_scores:
                        vs.add_by_tree(tree, cid)
                    tree.apply_shrinkage(-k / cfg.learning_rate)
                    self._add_tree_score_train(tree, cid)
            if not cfg.uniform_drop:
                if not cfg.xgboost_dart_mode:
                    self.sum_weight -= self.tree_weight[i] * (1.0 / (k + 1.0))
                    self.tree_weight[i] *= k / (k + 1.0)
                else:
                    self.sum_weight -= self.tree_weight[i] * (1.0 / (k + cfg.learning_rate))
                    self.tree_weight[i] *= k / (k + cfg.learning_rate)
