from .gbdt import GBDT
from .dart import DART
from .goss import GOSS
from .rf import RF


def create_boosting(cfg, train_data=None, objective=None):
    """Factory (`src/boosting/boosting.cpp:30-63`)."""
    table = {"gbdt": GBDT, "gbrt": GBDT, "dart": DART, "goss": GOSS, "rf": RF,
             "random_forest": RF}
    if cfg.boosting not in table:
        raise ValueError(f"Unknown boosting type {cfg.boosting}")
    return table[cfg.boosting](cfg, train_data, objective)
