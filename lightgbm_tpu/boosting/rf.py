"""Random forest mode (`src/boosting/rf.hpp:18-180`).

Bagged trees fit once against the init-score gradients (no boosting), no
shrinkage, averaged output (``average_output``): the running score is kept as
the average of trees so metrics see the ensemble mean.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..binning import kEpsilon
from ..tree import Tree
from .gbdt import GBDT


class RF(GBDT):
    name = "rf"

    def init(self, train_data, objective, training_metrics=()):
        cfg = self.cfg
        if not (cfg.bagging_freq > 0 and 0.0 < cfg.bagging_fraction < 1.0):
            raise ValueError("RF mode requires bagging "
                             "(bagging_freq > 0 and bagging_fraction in (0,1))")
        if not (0.0 < cfg.feature_fraction <= 1.0):
            raise ValueError("RF mode requires feature_fraction in (0, 1]")
        super().init(train_data, objective, training_metrics)
        self.average_output = True
        self.shrinkage_rate = 1.0
        # gradients are computed once from the constant init score (`rf.hpp:76-95`)
        self.init_scores = [
            (self.objective.boost_from_score(k)
             if (self.objective is not None and cfg.boost_from_average) else 0.0)
            for k in range(self.num_tree_per_iteration)]
        n_pad = self.train_data.num_data_padded
        self._rf_grad = []
        self._rf_hess = []
        for k in range(self.num_tree_per_iteration):
            const_score = jnp.full(n_pad, np.float32(self.init_scores[k]))
            if self.objective.name == "multiclass":
                continue
            g, h = self.objective.get_gradients(const_score, k)
            self._rf_grad.append(g)
            self._rf_hess.append(h)
        if self.objective is not None and self.objective.name == "multiclass":
            const = jnp.stack([jnp.full(n_pad, np.float32(s))
                               for s in self.init_scores])
            g, h = self.objective.get_gradients_all(const)
            self._rf_grad = [g[k] for k in range(self.num_tree_per_iteration)]
            self._rf_hess = [h[k] for k in range(self.num_tree_per_iteration)]

    def _multiply_score(self, class_id: int, factor: float) -> None:
        self.train_score.score = self.train_score.score.at[class_id].multiply(
            np.float32(factor))
        for vs in self.valid_scores:
            vs.score = vs.score.at[class_id].multiply(np.float32(factor))

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        self._bagging(self.iter_)
        should_continue = False
        for k in range(self.num_tree_per_iteration):
            new_tree = Tree(2)
            leaf_id = None
            if self.class_need_train[k]:
                fmask = self._feature_sample()
                new_tree, leaf_id = self.learner.train(
                    self._rf_grad[k], self._rf_hess[k], self._bag_mask, fmask)
            if new_tree.num_leaves > 1:
                should_continue = True
                if self.objective is not None:
                    const_score = np.full(self.num_data,
                                          self.init_scores[k], dtype=np.float64)
                    self.objective.renew_tree_output(
                        new_tree, const_score, leaf_id, self._np_bag())
                if abs(self.init_scores[k]) > kEpsilon:
                    new_tree.leaf_value[:new_tree.num_leaves] += self.init_scores[k]
                # running average of tree outputs (`rf.hpp:131-134`)
                self._multiply_score(k, self.iter_)
                self.train_score.add_by_leaf_id(
                    new_tree.leaf_value[:new_tree.num_leaves], leaf_id, k)
                for vs in self.valid_scores:
                    vs.add_by_tree(new_tree, k)
                self._multiply_score(k, 1.0 / (self.iter_ + 1))
            else:
                if len(self.models) < self.num_tree_per_iteration:
                    output = (self.objective.boost_from_score(k)
                              if (self.objective is not None
                                  and not self.class_need_train[k])
                              else self.init_scores[k])
                    new_tree = Tree(2)
                    new_tree.num_leaves = 1
                    new_tree.leaf_value[0] = output
                    self.train_score.add_constant(output, k)
                    for vs in self.valid_scores:
                        vs.add_constant(output, k)
            self.models.append(new_tree)
        if not should_continue:
            if len(self.models) > self.num_tree_per_iteration:
                del self.models[-self.num_tree_per_iteration:]
            return True
        self.iter_ += 1
        return False

    def predict_raw(self, X, num_iteration: int = -1):
        raw = super().predict_raw(X, num_iteration)
        n_iter = self._num_models_for(num_iteration) // max(
            self.num_tree_per_iteration, 1)
        return raw / max(n_iter, 1)
