"""GOSS — gradient-based one-side sampling (`src/boosting/goss.hpp:26-200`).

Keep the top ``top_rate`` fraction of rows by |grad·hess|, sample
``other_rate`` of the rest uniformly and amplify their gradients by
``(1-top_rate)/other_rate`` so histogram sums stay unbiased.  The reference
builds an index subset on the host; here the whole selection is ONE jitted
device computation (threshold from a device sort, uniform sampling from a
fold_in'd PRNG key, amplification normalized by the ACTUAL sampled count) —
no per-iteration host round trip, so GOSS pipelines like plain GBDT.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from .gbdt import GBDT


@functools.partial(jax.jit, static_argnames=("top_k", "other_k"))
def _goss_select(grad, hess, valid_rows, key, *, top_k: int, other_k: int):
    """Device GOSS sampling: returns (bag mask f32, per-row amplification)."""
    mag = jnp.sum(jnp.abs(grad * hess), axis=0)
    neg_inf = jnp.float32(-jnp.inf)
    magv = jnp.where(valid_rows > 0.5, mag, neg_inf)
    # exact top_k membership by magnitude (a threshold cut would evict
    # strictly-larger rows on ties)
    vals, idx = jax.lax.top_k(magv, top_k)
    is_top = jnp.zeros(mag.shape, bool).at[idx].set(
        ~jnp.isneginf(vals), mode="drop")
    rest = (valid_rows > 0.5) & ~is_top
    n_rest = jnp.sum(rest.astype(jnp.int32))
    p = jnp.minimum(other_k / jnp.maximum(n_rest, 1), 1.0)
    u = jax.random.uniform(key, mag.shape)
    sampled = rest & (u < p)
    n_samp = jnp.maximum(jnp.sum(sampled.astype(jnp.int32)), 1)
    multiply = n_rest.astype(jnp.float32) / n_samp.astype(jnp.float32)
    bag = (is_top | sampled).astype(jnp.float32)
    amp = jnp.where(sampled, multiply, 1.0).astype(jnp.float32)
    return bag, amp


class GOSS(GBDT):
    name = "goss"

    def init(self, train_data, objective, training_metrics=()):
        cfg = self.cfg
        if not (cfg.top_rate + cfg.other_rate <= 1.0
                and cfg.top_rate > 0 and cfg.other_rate > 0):
            raise ValueError("top_rate + other_rate must be in (0, 1] with both "
                             "positive for GOSS")
        if cfg.bagging_freq > 0 and cfg.bagging_fraction != 1.0:
            raise ValueError("Cannot use bagging in GOSS")
        super().init(train_data, objective, training_metrics)
        self._goss_key = jax.random.PRNGKey(cfg.bagging_seed)

    def _bagging(self, iter_):  # sampling handled in train_one_iter
        pass

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        if self._stopped:
            return True
        init_scores = [0.0] * self.num_tree_per_iteration
        if gradients is None or hessians is None:
            for k in range(self.num_tree_per_iteration):
                init_scores[k] = self._boost_from_average(k, update_scorer=True)
            grad, hess = self._compute_gradients()
        else:
            grad, hess = self._pad_external_gradients(gradients, hessians)

        cfg = self.cfg
        n = self.num_data
        # not subsampled for the first 1/learning_rate iterations
        # (`goss.hpp:139-141`)
        if self.iter_ >= int(1.0 / cfg.learning_rate):
            top_k = max(1, int(n * cfg.top_rate))
            other_k = max(1, int(n * cfg.other_rate))
            key = jax.random.fold_in(self._goss_key, self.iter_)
            bag, amp = _goss_select(grad, hess, self._valid_rows, key,
                                    top_k=top_k, other_k=other_k)
            self._bag_mask = bag
            self._np_bag_mask = None   # materialized lazily (renew path)
            grad = grad * amp[None, :]
            hess = hess * amp[None, :]
        else:
            self._bag_mask = self._valid_rows
            self._np_bag_mask = None

        return self._train_trees(grad, hess, init_scores)
