"""GOSS — gradient-based one-side sampling (`src/boosting/goss.hpp:26-200`).

Keep the top ``top_rate`` fraction of rows by |grad·hess|, sample
``other_rate`` of the rest uniformly and amplify their gradients by
``(1-top_rate)/other_rate`` so histogram sums stay unbiased.  The reference
builds an index subset; here sampling is a device-side mask and the
amplification is folded into the gradients before tree construction — the
cnt histogram channel still counts real rows because the bagging mask stays
0/1.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .gbdt import GBDT


class GOSS(GBDT):
    name = "goss"

    def init(self, train_data, objective, training_metrics=()):
        cfg = self.cfg
        if not (cfg.top_rate + cfg.other_rate <= 1.0
                and cfg.top_rate > 0 and cfg.other_rate > 0):
            raise ValueError("top_rate + other_rate must be in (0, 1] with both "
                             "positive for GOSS")
        if cfg.bagging_freq > 0 and cfg.bagging_fraction != 1.0:
            raise ValueError("Cannot use bagging in GOSS")
        super().init(train_data, objective, training_metrics)
        self._goss_rng = np.random.RandomState(cfg.bagging_seed)
        self._amplified = None

    def _bagging(self, iter_):  # sampling handled in train_one_iter
        pass

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        if self._stopped:
            return True
        init_scores = [0.0] * self.num_tree_per_iteration
        if gradients is None or hessians is None:
            for k in range(self.num_tree_per_iteration):
                init_scores[k] = self._boost_from_average(k, update_scorer=True)
            grad, hess = self._compute_gradients()
        else:
            grad, hess = self._pad_external_gradients(gradients, hessians)

        cfg = self.cfg
        n = self.num_data
        # not subsampled for the first 1/learning_rate iterations
        # (`goss.hpp:139-141`)
        if self.iter_ >= int(1.0 / cfg.learning_rate):
            mag = jnp.sum(jnp.abs(grad * hess), axis=0)
            mag = np.asarray(mag)[:n]
            top_k = max(1, int(n * cfg.top_rate))
            other_k = max(1, int(n * cfg.other_rate))
            order = np.argsort(-mag, kind="stable")
            top_idx = order[:top_k]
            rest_idx = order[top_k:]
            sampled = self._goss_rng.choice(
                len(rest_idx), min(other_k, len(rest_idx)), replace=False)
            other_idx = rest_idx[sampled]
            multiply = (n - top_k) / other_k
            mask = np.zeros(self.train_data.num_data_padded, dtype=np.float32)
            mask[top_idx] = 1.0
            mask[other_idx] = 1.0
            amp = np.ones(self.train_data.num_data_padded, dtype=np.float32)
            amp[other_idx] = multiply
            self._bag_mask = self._place_rows(mask)
            self._np_bag_mask = mask
            amp_d = self._place_rows(amp)[None, :]
            grad = grad * amp_d
            hess = hess * amp_d
        else:
            self._bag_mask = self._valid_rows
            self._np_bag_mask = np.asarray(self._valid_rows)

        return self._train_trees(grad, hess, init_scores)
