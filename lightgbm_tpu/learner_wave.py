"""Frontier-wave TPU tree learner: batched speculative leaf-wise growth.

The sequential compact learner (`learner_compact.py`) builds a tree as 254
dependent split steps inside one XLA program; at 1M rows the program floors
at ~90 ms/tree of per-step bookkeeping and per-window sort latency before
any real data work (profiling/PROFILE.md).  This learner restructures the
growth into ~13 *frontier waves* while preserving exact best-first
(leaf-wise) semantics:

  1. **Grow.**  Each wave splits the top-W positive-gain frontier leaves at
     once: one full-array stable sort re-compacts every split window
     simultaneously (per-row split parameters come from an MXU mask-matmul,
     never an XLA gather — `profiling/profile_gather_alts.py`), then the
     smaller-child histograms run per member (subtraction for siblings) and
     all 2W children are scanned in one batched split finder.  Replayed
     against real split sequences, top-W selection reproduces the true
     greedy split set with ~zero waste in ~12.6 waves
     (`scratch/wave_sim.py`).
  2. **Trim.**  An exact greedy replay over the grown forest re-derives the
     reference's pop order (`serial_tree_learner.cpp:185-218`: split the
     globally best leaf, insert its children): children's gains are all
     known, so the replay is pure bookkeeping — ~6 ms of tiny ops.  The
     replayed pop sequence assigns the reference leaf numbering (left child
     inherits the parent index, right child gets ``num_leaves``), emits the
     host-assembly records in pop order, and maps speculative leaves back
     to their final ancestors.
  3. **Correct.**  If the replay wants to pop a leaf the growth never split
     (possible near the num_leaves budget where speculation and greedy can
     diverge), it splits that leaf on the spot — a mask-mode single split —
     and continues.  Slot arrays are sized so this path can never overflow
     (growth ≤ budget splits, stalls ≤ budget pops), so the result is
     always *exactly* the best-first tree.

Everything the sequential learners guarantee is preserved: identical gain
math and tie-breaks (lowest leaf index, `serial_tree_learner.cpp:505-520`),
smaller-child histogram + sibling subtraction (`:371-385`), monotone
constraint propagation, categorical bitset splits, EFB bundle decoding,
exact integer bagged counts, and the host record format — so
``assemble_host`` and the whole boosting loop are unchanged.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .binning import MISSING_NAN, MISSING_ZERO
from .config import Config
from .dataset import _ConstructedDataset
from .learner import NUM_REC_FIELDS
from .learner_compact import (CF_GAIN, CF_LCNT, CF_LOUT, CF_LSG, CF_LSH,
                              CF_RCNT, CF_ROUT, CF_RSG, CF_RSH, CI_FEAT,
                              CI_FLAGS, CI_THR, LF_CNT, LF_DEPTH, LF_MAX_C,
                              LF_MIN_C, LF_OUT, LF_SUM_G, LF_SUM_H, NUM_CF,
                              NUM_CI, NUM_LF, CompactTPUTreeLearner)
from .observability.telemetry import (TEL_FROZEN_MEMBERS, TEL_GROW_SPLITS,
                                      TEL_NSLOTS, TEL_POPS,
                                      TEL_STALL_EXTRAS, TEL_STALL_SORT_MODE,
                                      TEL_STALL_SPLITS, TEL_TOTAL_SPLITS,
                                      TEL_WAVE_MEMBERS, TEL_WAVE_SORTS,
                                      TEL_WAVES)
from .ops.histogram import _on_tpu
from .ops.lookup import lookup_int

_HIGH = lax.Precision.HIGHEST


def _stall_extras_cap(budget: int) -> int:
    """Cap on speculative batch EXTRAS (members beyond the sim's stalled
    top) across the whole replay — a dedicated counter in the replay loop
    enforces it, so the slot/pool reserve stays tight."""
    return min(budget - 1, 64)


def _resolve_stall_batch(cfg: Config) -> int:
    """``tpu_wave_stall_batch`` with -1 = auto.  Auto is 4 at every
    measured scale (the round-5 K sweep winner over {1, 8, 16}; the
    round-6 re-sweep {2, 3, 6} rides profile_stall_batch.py and bakes
    its winner here)."""
    k = int(getattr(cfg, "tpu_wave_stall_batch", -1))
    if k < 0:
        k = 4
    return max(1, min(k, 16))


def _correction_reserve(cfg: Config, budget: int) -> int:
    """Worst-case replay correction splits, for slot/hist-pool sizing.

    Every stalled TOP maps to a distinct pop, so tops <= budget; batch
    extras (stall_batch > 1) are counted separately in the replay loop
    and capped at ``_stall_extras_cap``.  Shared by ``_init_wave_dims``
    and ``wave_budget_reason`` so the formulas cannot drift."""
    k = _resolve_stall_batch(cfg)
    return budget if k == 1 else budget + _stall_extras_cap(budget)


def _resolve_overshoot(cfg: Config, local_rows: int) -> float:
    """Auto for ``tpu_wave_overshoot`` (see config.py).

    With batched mask-mode replay corrections (``tpu_wave_stall_batch`` >
    1, the default) a speculation miss costs ~window-sized work amortized
    over K members, so buying misses down with extra speculative waves —
    whose full-array passes cost ∝N — no longer pays AT ANY SCALE:
    overshoot 0 wins (v5e: 9.28 vs 8.05 it/s at 1M, 0.854 vs 0.770 at
    10.5M).  The single-miss-per-pass path (stall_batch=1) keeps the
    round-4 scale-dependent optimum (0.7 at 1M, 0.25 at 10.5M)."""
    ov = float(cfg.tpu_wave_overshoot)
    if ov < 0:
        if _resolve_stall_batch(cfg) > 1:
            ov = 0.0
        else:
            ov = 0.7 if local_rows <= 2_000_000 else 0.25
    return ov


class WaveState(NamedTuple):
    # row payloads, permuted so every leaf's rows are contiguous
    bins_p: jax.Array     # (fw, N) int32 packed bin words
    w_p: jax.Array        # (3, N) f32 (g*bag, h*bag, bag)
    rid_p: jax.Array      # (N,) int32 original row ids
    lid_p: jax.Array      # (N,) int32 node-slot ids
    key_p: jax.Array      # (N,) int32 window-order sort keys (2*start+bit)
    # per-node-slot state (M slots; a split allocates 2 fresh child slots)
    node_i: jax.Array     # (M, 2) int32 LOGICAL window [start, width]
    phys_i: jax.Array     # (M, 2) int32 materialized covering span (equals
    #                       node_i except for children created on a
    #                       sort-DEFERRING wave, whose rows still live in
    #                       the parent's span until the next sort)
    node_f: jax.Array     # (M, NUM_LF) acc sums/cnt/out/depth/bounds
    cand_f: jax.Array     # (M, NUM_CF) acc best-split floats
    cand_i: jax.Array     # (M, NUM_CI) int32 feature/threshold/flags
    cand_b: jax.Array     # (M, Wc) uint32 categorical bitsets
    parent: jax.Array     # (M,) int32
    child0: jax.Array     # (M,) int32 left child slot (right = +1)
    hslot: jax.Array      # (M,) int32 histogram pool slot
    split_m: jax.Array    # (M,) bool node has been split
    cnt_i: jax.Array      # (M, 2) int32 exact bagged child counts at split
    hist_pool: jax.Array  # (H, F, B, 3)
    num_nodes: jax.Array  # () int32
    num_splits: jax.Array  # () int32
    pending: jax.Array    # () bool — keys assigned but not yet sorted
    # (TEL_NSLOTS,) int32 device counter lane, or None when telemetry is
    # off — None is an empty pytree, so the disabled program is unchanged
    telem: Optional[jax.Array] = None


class WaveTPUTreeLearner(CompactTPUTreeLearner):
    """Frontier-wave serial learner (factory slot
    `src/treelearner/tree_learner.cpp:9-33`, tree_learner=serial,
    device_type=tpu; supersedes the sequential compact learner where
    eligible)."""

    def __init__(self, cfg: Config, data: _ConstructedDataset,
                 hist_backend: str = "auto"):
        super().__init__(cfg, data, hist_backend)
        self._init_wave_dims(cfg)
        F = self.num_features
        if self._bundle is not None:
            col = np.asarray(self._bundle.f_gcol, np.int32)
            goff = np.asarray(self._bundle.f_off, np.int32)
            bnd = np.asarray(self._bundle.f_bundled, np.int32)
        else:
            col = np.arange(F, dtype=np.int32)
            goff = np.zeros(F, np.int32)
            bnd = np.zeros(F, np.int32)
        self.fw_col = jnp.asarray(col)
        self.fw_goff = jnp.asarray(goff)
        self.fw_bnd = jnp.asarray(bnd)
        rb = min(2048, self.n_pad)
        while self.n_pad % rb:
            rb //= 2
        self._seg_rb = rb
        # fused Pallas split-scan (Config.tpu_wave_pallas_scan): the
        # batched child scans run as one kernel; constrained/categorical/
        # penalized/f64 configs keep the XLA path (scan_ineligible_reason)
        from .ops.scan_pallas import scan_ineligible_reason
        sp = str(getattr(cfg, "tpu_wave_pallas_scan", "auto"))
        s_reason = scan_ineligible_reason(
            self.num_features, self.num_bins_padded, self.has_monotone,
            self.has_categorical, self.has_penalty, self.hist_dp)
        if sp == "on":
            self._use_scan = s_reason is None
            self._scan_interpret = not _on_tpu()
        elif sp == "auto":
            self._use_scan = self._use_pallas and s_reason is None
            self._scan_interpret = False
        else:
            self._use_scan = False
            self._scan_interpret = False
        if self._donate:
            # jax matches donated inputs to outputs by EXACT aval at
            # num_partitions=1 (mlir._set_up_aliases) and the tree
            # program has no f32[n_pad] output, so a bare donate_argnums
            # here is silently dropped ("donated buffers were not
            # usable") — the sharded learners only escape because the
            # SPMD path routes donation through XLA's size-matching
            # buffer_donor pass.  Bitcasting leaf_id (int32[n_pad]) out
            # as its f32 bit-pattern gives the donated grad buffer a
            # landing slot; train_async casts it back at the call seam.
            # The analysis gate asserts input_output_alias in this
            # program's compiled HLO (analysis/donation.py).
            def _tree_w_donating(bins_p, grad, hess, bag, fmask):
                out = self._train_tree_wave(bins_p, grad, hess, bag,
                                            fmask)
                leaf_f32 = jax.lax.bitcast_convert_type(out[3],
                                                        jnp.float32)
                return out[:3] + (leaf_f32,) + out[4:]

            self._jit_tree_w = jax.jit(_tree_w_donating,
                                       donate_argnums=(1, 2))
            self._tree_w_bitcast = True
        else:
            self._jit_tree_w = jax.jit(self._train_tree_wave)
            self._tree_w_bitcast = False

    def _fused_ok(self) -> bool:
        """Whether this learner runs the fused hist→subtract→fix→scan
        chain (``ops/scan_pallas.py:fused_child_scans``).  Quant mode
        only (the packed-histogram layout is what makes one kernel pay),
        and only where BOTH the batched scan path and the serial member
        hists apply — the sharded subclasses interpose a collective
        between the member hists and the scans, which the fused kernel
        cannot straddle."""
        from .ops.scan_pallas import fused_scan_ineligible_reason
        return (self._quant and getattr(self, "_use_scan", False)
                and self._bundle is None and not self._ablate
                and type(self)._cand_rows_batch
                is WaveTPUTreeLearner._cand_rows_batch
                and type(self)._wave_member_hists
                is WaveTPUTreeLearner._wave_member_hists
                and fused_scan_ineligible_reason(
                    self.num_features, self._hist_nbins) is None)

    def _init_wave_dims(self, cfg: Config) -> None:
        """Wave sizing/bookkeeping shared by the serial and sharded wave
        learners (kept in one place so the slot/pool formulas can't drift
        from ``wave_ineligible_reason``'s byte estimate).

        Growth OVERSHOOTS the split budget: speculative top-W selection
        near the end of the budget misses leaves the exact greedy replay
        wants (measured: 40 replay stalls per 255-leaf tree at 1M rows,
        each a full sequential split step), while extra bottom waves are
        cheap (small windows freeze — no sort).  The replay still pops
        exactly ``budget`` splits, so the tree is unchanged.  Slot/pool
        sizing makes overflow impossible: growth performs <= grow_budget
        splits, the replay correction <= ``_correction_reserve`` more."""
        self.budget = self.num_leaves - 1
        self.W = max(1, min(int(cfg.tpu_wave_width), self.budget))
        try:
            rows = self._rows_len()
        except AttributeError:
            # sharded learners reach here mid-MRO (WaveTPUTreeLearner's
            # __init__ runs before ShardedCompactLearner sets n_local);
            # their own __init__ re-runs _init_wave_dims with local rows
            rows = self.n_pad
        ov = _resolve_overshoot(cfg, rows)
        self.grow_budget = min(
            self.budget + int(np.ceil(self.budget * ov)),
            2 * self.budget)
        # level-wise opening depth (see Config.tpu_wave_open_levels).
        # MEASURED on the v5e (round 5, profiling/profile_opening.py + a
        # device trace): a full-array multi-slot hist pass floors at ~6 ms
        # of one-hot VPU work regardless of K, so an opening level costs
        # ~8-18 ms against the ~10.6 ms wave it replaces, plus a ~6 ms
        # materialization sort — a NET LOSS at every depth on the bench
        # workload.  Auto therefore DISABLES the opening; the knob remains
        # for exactness tests and future kernels that beat the floor.
        ol = int(getattr(cfg, "tpu_wave_open_levels", -1))
        if ol < 0:
            ol = 0
        self.open_levels = max(0, min(ol, (self.budget + 1).bit_length() - 1))
        # sort-deferral alternation (Config.tpu_wave_defer_sorts)
        self._defer_sorts = bool(getattr(cfg, "tpu_wave_defer_sorts", True))
        # replay stall-correction batch width (Config.tpu_wave_stall_batch)
        self._stall_batch = _resolve_stall_batch(cfg)
        self._stall_fuse_top = bool(
            getattr(cfg, "tpu_wave_stall_fuse_top", True))
        self._extras_cap = _stall_extras_cap(self.budget)
        # vectorized-partition span cap (tests shrink it via config so the
        # replicated gate is exercised at CI sizes)
        vc = int(getattr(cfg, "tpu_wave_vec_cap", -1))
        self._vec_cap = self._VEC_CAP if vc <= 0 else vc
        corr = _correction_reserve(cfg, self.budget)
        self.M = 1 + 2 * (self.grow_budget + corr)
        self.H = self.grow_budget + corr + 2
        # row-chunk bound for the per-row mask contractions: bounds the
        # (rows, W) transients to ~256 MB at any N (lax.map'd above it)
        self._row_chunk = 1 << 20
        # frozen (shared-span) windows can be as large as the wave cutoff,
        # so phase-2 stall splits may only sort above it (a sort-mode
        # partition of a shared window would reorder sibling rows)
        self._wave_cutoff = int(cfg.tpu_wave_sort_cutoff)
        self._stall_cutoff = max(self._sort_cutoff, self._wave_cutoff)
        # Pallas stable-partition kernel (Config.tpu_wave_pallas_partition):
        # replaces the full-array re-compaction sort with exact
        # destination computation + a chunked permute kernel.  Partition
        # mode runs WITHOUT sort-deferral: each wave materializes its own
        # windows (a partition pass is cheap enough that halving pass
        # count no longer pays for deferred waves' double-area member
        # hists), which also means phys_i always equals node_i at the
        # replay and the dest lane is wave-local (no carried key state)
        from .ops.partition_pallas import partition_ineligible_reason
        pp = str(getattr(cfg, "tpu_wave_pallas_partition", "auto"))
        reason = partition_ineligible_reason(rows, self.M, self.open_levels)
        if pp == "on":
            self._use_partition = reason is None
            self._partition_interpret = not _on_tpu()
        elif pp == "auto":
            self._use_partition = (getattr(self, "_use_pallas", False)
                                   and reason is None)
            self._partition_interpret = False
        else:
            self._use_partition = False
            self._partition_interpret = False
        if self._use_partition:
            self._defer_sorts = False
        # quantized-gradient training (Config.tpu_quantized_grad): int8
        # gradient / int16 hessian discretization with stochastic rounding
        # (ops/quant.py — the LightGBM quantized-training recipe).  Set
        # HERE, not in __init__: the 2-D sharded learner re-runs
        # _init_wave_dims without ever entering WaveTPUTreeLearner's
        # __init__, and every wave learner must agree on the gate
        from .ops.quant import quant_ineligible_reason
        qg = str(getattr(cfg, "tpu_quantized_grad", "auto"))
        # gate on the GLOBAL padded row count (a reduced histogram bin can
        # hold every row), not the shard-local window the wave sizing uses
        q_reason = quant_ineligible_reason(self.n_pad, self.hist_dp)
        if qg == "on":
            self._quant = q_reason is None
        else:
            # auto stays OFF until the on-hardware win is recorded
            # (BENCH_r08 carries the CPU evidence; ROADMAP item 1 tracks
            # the TPU leg) — same posture scan/partition auto took before
            # their device sweeps landed
            self._quant = False
            if q_reason is None:
                q_reason = "tpu_quantized_grad=%s (quantization is " \
                           "opt-in)" % qg
        self._quant_reason = None if self._quant else q_reason
        self._q_inv = None
        self._q_scales = None
        self._q_raw = None
        self._q_cnt = None
        self._q_mbar = None
        # cross-iteration buffer donation (Config.tpu_donate_buffers):
        # grad/hess enter the tree program donated so iteration N+1 reuses
        # iteration N's HBM; auto = on-TPU only (the CPU backend gains
        # nothing and donation muddies interpret-mode debugging)
        dn = str(getattr(cfg, "tpu_donate_buffers", "auto"))
        self._donate = dn == "on" or (dn == "auto" and _on_tpu())
        if str(getattr(cfg, "boosting", "gbdt")) == "rf":
            # random forest refits from ONE retained gradient set every
            # iteration (rf.py keeps _rf_grad across iters); donating
            # those buffers would invalidate them after the first tree
            self._donate = False
        # dev-only phase ablation for profiling (profile_wave_phases.py):
        # comma-set of {nohist, noscan, nosort} — NOT a user knob; a leaked
        # env var would silently train WRONG trees, so warn loudly
        import os
        self._ablate = set(
            t for t in os.environ.get("LGBMTPU_WAVE_ABLATE", "").split(",")
            if t)
        if self._ablate:
            import warnings
            warnings.warn(
                "LGBMTPU_WAVE_ABLATE=%s is set: the wave learner is running "
                "in a PROFILING-ONLY ablation mode and will produce WRONG "
                "trees. Unset it for real training." %
                os.environ["LGBMTPU_WAVE_ABLATE"])

    # -- batched split finder -------------------------------------------------

    def _cand_rows_batch(self, hists, sg, sh, cn, feature_mask, depth_ok,
                         constraints):
        """Best-split rows for K children in one vmapped scan
        (generalizes ``_cand_rows_pair``).  With the fused Pallas
        split-scan enabled the whole (K, F, B) search — cumulative
        scans, gain masks, per-feature argmax — runs as one kernel."""
        if getattr(self, "_use_scan", False) and constraints is None:
            from .learner import _FeatCand
            from .ops.scan_pallas import find_best_splits_batched
            h = hists
            if self._bundle is not None:
                h = jax.vmap(self._unbundle_hist)(h, sg, sh, cn)
            h = jax.vmap(self._fix_histogram)(h, sg, sh, cn)
            kw = {k: v for k, v in self._split_kwargs.items()
                  if k != "skip_missing_scan"}
            num = find_best_splits_batched(
                h, sg, sh, cn, self.f_num_bin, self.f_missing,
                self.f_default_bin, feature_mask & self._cat_mask,
                interpret=self._scan_interpret, **kw)
            kk = num.gain.shape[0]
            f = self.num_features
            cands = _FeatCand(
                gain=num.gain, threshold=num.threshold,
                default_left=num.default_left,
                is_cat=jnp.zeros((kk, f), bool),
                cat_bits=jnp.zeros((kk, f, self.cat_W), jnp.uint32),
                left_sum_g=num.left_sum_g, left_sum_h=num.left_sum_h,
                left_cnt=num.left_cnt, right_sum_g=num.right_sum_g,
                right_sum_h=num.right_sum_h, right_cnt=num.right_cnt,
                left_output=num.left_output,
                right_output=num.right_output)
            return self._pack_cand_rows(cands, depth_ok)
        if constraints is not None:
            mins, maxs = constraints
            cands = jax.vmap(
                lambda h, g, hh, c, mn, mx: self._feature_cands(
                    h, g, hh, c, feature_mask, mn, mx)
            )(hists, sg, sh, cn, mins, maxs)
        else:
            cands = jax.vmap(
                lambda h, g, hh, c: self._feature_cands(h, g, hh, c,
                                                        feature_mask)
            )(hists, sg, sh, cn)
        return self._pack_cand_rows(cands, depth_ok)

    # -- root -----------------------------------------------------------------

    def _init_root_wave(self, bins_p, grad, hess, bag, feature_mask
                        ) -> WaveState:
        n, L, M, H = self._rows_len(), self.num_leaves, self.M, self.H
        acc = self._acc
        self._coll_ctx = ("root", "tree")
        if self._quant:
            # per-round discretization (ops/quant.py): power-of-two
            # scales from the GLOBAL |g|/h maxima, stochastic rounding
            # keyed on the global row index.  The weight lanes carry the
            # DEQUANTIZED values gq*sg / hq*sh — exact in bf16, so the
            # Pallas quant hist path and sibling subtraction stay
            # bit-exact — and the scale tuple rides trace-time attributes
            # that the hist-branch closures read within this same trace.
            from .ops.quant import quantize_gradients
            gb = (grad * bag).astype(jnp.float32)
            hb = (hess * bag).astype(jnp.float32)
            mx = self._global_max(jnp.stack([jnp.max(jnp.abs(gb)),
                                             jnp.max(hb)]))
            gd, hd, sg, sh = quantize_gradients(
                gb, hb, bag, self._global_row_offset(), mx[0], mx[1])
            self._q_scales = (sg, sh)
            self._q_inv = (1.0 / sg, 1.0 / sh)
            self._q_raw = (gb, hb)     # retained f32 for leaf renewal
            w = jnp.stack([gd, hd, bag], axis=0)
            # count-channel normalization, BEFORE any histogram builds
            # (the branch closures read _q_cnt): the channel carries
            # Σhq/m̄ — hessian mass over the mean mass per bagged row —
            # so min_data_in_leaf keeps its row-count scale (raw Σhq
            # admits ~m̄× smaller leaves and the trees grow much deeper,
            # see ops/quant.py).  All three sums are exact integer
            # multiples of their scale within the F32_EXACT_ROWS gate,
            # so m̄ and every derived rescale are order-independent and
            # the sharded learners stay record-exact.
            q_tot = self._global_scalar(jnp.stack(
                [jnp.sum(gd.astype(acc)), jnp.sum(hd.astype(acc)),
                 jnp.sum(bag.astype(acc))]))
            mbar = jnp.maximum(q_tot[1] * self._q_inv[1], 1.0) \
                / jnp.maximum(q_tot[2], 1.0)
            self._q_mbar = mbar
            self._q_cnt = self._q_inv[1] / mbar
        else:
            w = jnp.stack([grad * bag, hess * bag, bag], axis=0)
        lid0 = jnp.zeros(n, jnp.int32)
        root_hist = self._reduce_hist(
            self._hist_branches[-1](bins_p, w, lid0, jnp.int32(0),
                                    jnp.int32(n), jnp.int32(0)))
        if self._quant:
            # root totals from the DEQUANTIZED lanes so FixHistogram's
            # totals-minus-others algebra matches the histogram contents;
            # the count total rides the same normalized Σhq/m̄ scale as
            # the histogram count channel
            sum_g, sum_h = q_tot[0], q_tot[1]
            cnt = (sum_h * self._q_cnt).astype(acc)
        else:
            sum_g = self._global_scalar(jnp.sum((grad * bag).astype(acc)))
            sum_h = self._global_scalar(jnp.sum((hess * bag).astype(acc)))
            cnt = self._global_scalar(jnp.sum(bag.astype(acc)))
        md = int(self.cfg.max_depth)
        depth_ok = jnp.asarray([True if md <= 0 else md > 0])
        cf, ci, cb = self._cand_rows_batch(
            root_hist[None], sum_g[None], sum_h[None], cnt[None],
            feature_mask, depth_ok, None)
        root_lf = jnp.asarray([0.0, 0.0, 0.0, 0.0, 0.0, -jnp.inf, jnp.inf],
                              acc)
        root_lf = root_lf.at[LF_SUM_G].set(sum_g).at[LF_SUM_H].set(sum_h) \
                         .at[LF_CNT].set(cnt)
        return WaveState(
            bins_p=bins_p, w_p=w,
            rid_p=jnp.arange(n, dtype=jnp.int32),
            lid_p=lid0,
            key_p=jnp.zeros(n, jnp.int32),
            node_i=jnp.zeros((M, 2), jnp.int32).at[0, 1].set(n),
            phys_i=jnp.zeros((M, 2), jnp.int32).at[0, 1].set(n),
            node_f=jnp.zeros((M, NUM_LF), acc)
                      .at[:, LF_MIN_C].set(-jnp.inf)
                      .at[:, LF_MAX_C].set(jnp.inf)
                      .at[0].set(root_lf),
            cand_f=jnp.zeros((M, NUM_CF), acc)
                      .at[:, CF_GAIN].set(-jnp.inf)
                      .at[0].set(cf[0]),
            cand_i=jnp.zeros((M, NUM_CI), jnp.int32).at[0].set(ci[0]),
            cand_b=jnp.zeros((M, self.cat_W), jnp.uint32).at[0].set(cb[0]),
            parent=jnp.zeros(M, jnp.int32),
            child0=jnp.zeros(M, jnp.int32),
            hslot=jnp.zeros(M, jnp.int32),
            split_m=jnp.zeros(M, bool),
            cnt_i=jnp.zeros((M, 2), jnp.int32),
            hist_pool=jnp.zeros((H,) + root_hist.shape, root_hist.dtype)
                         .at[0].set(root_hist),
            num_nodes=jnp.asarray(1, jnp.int32),
            num_splits=jnp.asarray(0, jnp.int32),
            pending=jnp.asarray(False),
            telem=(jnp.zeros(TEL_NSLOTS, jnp.int32)
                   if self._telemetry else None))

    # -- one growth wave ------------------------------------------------------

    def _pool_gains(self, st: WaveState):
        alive = (jnp.arange(self.M) < st.num_nodes) & ~st.split_m
        return jnp.where(alive, st.cand_f[:, CF_GAIN], -jnp.inf)

    def _children_bookkeeping(self, st, wi, valid, lslot, rslot, lc_bag,
                              c_bag, li, ri, lh, rh, hists2, feature_mask,
                              phys_l=None, phys_r=None, fused_parts=None):
        """Shared by the wave body (K=W) and the stall split (K=1): writes
        all per-child node state given the children's histograms.
        ``phys_l/phys_r`` are the children's materialized covering spans
        (default: the logical windows — correct whenever the caller's rows
        are physically compacted, as in the stall split).

        ``fused_parts`` (quant fused mode): ``(h_small, ph, left_small,
        lh_w, rh_w)`` — the caller computed ONLY the smaller-child
        histograms and ``hists2`` is None; sibling subtraction, the
        default-bin fix and both child split scans run inside one Pallas
        kernel here (``ops/scan_pallas.py:fused_child_scans``), which
        also hands back the raw child histograms for the pool writes."""
        if phys_l is None:
            phys_l, phys_r = li, ri
        acc = self._acc
        K = wi.shape[0]
        pcf = st.cand_f[wi]                       # (K, NUM_CF)
        pci = st.cand_i[wi]
        pnf = st.node_f[wi]
        cd = pnf[:, LF_DEPTH] + 1.0
        md = int(self.cfg.max_depth)
        if md <= 0:
            depth_ok = jnp.ones(2 * K, bool)
        else:
            depth_ok = jnp.repeat(cd < md, 2)
        # monotone constraint propagation (`serial_tree_learner.cpp:765-776`)
        pmin = pnf[:, LF_MIN_C]
        pmax = pnf[:, LF_MAX_C]
        if self.has_monotone:
            feat = pci[:, CI_FEAT]
            is_cat = (pci[:, CI_FLAGS] & 2) == 2
            mono_t = jnp.where(is_cat, 0, self.f_monotone[feat])
            mid = ((pcf[:, CF_LOUT] + pcf[:, CF_ROUT]) / 2.0).astype(acc)
            lmin = jnp.where(mono_t < 0, mid, pmin)
            lmax = jnp.where(mono_t > 0, mid, pmax)
            rmin = jnp.where(mono_t > 0, mid, pmin)
            rmax = jnp.where(mono_t < 0, mid, pmax)
            mins2 = jnp.stack([lmin, rmin], 1).reshape(-1)
            maxs2 = jnp.stack([lmax, rmax], 1).reshape(-1)
            constraints = (mins2, maxs2)
        else:
            lmin = rmin = pmin
            lmax = rmax = pmax
            constraints = None
        # batched child split scans
        i2 = lambda a, b: jnp.stack([a, b], 1).reshape(-1)  # interleave K->2K
        sg2 = i2(pcf[:, CF_LSG], pcf[:, CF_RSG])
        sh2 = i2(pcf[:, CF_LSH], pcf[:, CF_RSH])
        cn2 = i2(pcf[:, CF_LCNT], pcf[:, CF_RCNT])
        if "noscan" in self._ablate:  # profiling: fabricated candidates
            g2 = jnp.repeat(pcf[:, CF_GAIN], 2) * 0.9
            cf2 = jnp.zeros((2 * K, NUM_CF), self._acc) \
                .at[:, CF_GAIN].set(g2) \
                .at[:, CF_LCNT].set(cn2 / 2).at[:, CF_RCNT].set(cn2 / 2) \
                .at[:, CF_LSG].set(sg2 / 2).at[:, CF_RSG].set(sg2 / 2) \
                .at[:, CF_LSH].set(sh2 / 2).at[:, CF_RSH].set(sh2 / 2)
            ci2 = jnp.zeros((2 * K, NUM_CI), jnp.int32).at[:, CI_THR].set(127)
            cb2 = jnp.zeros((2 * K, self.cat_W), jnp.uint32)
        elif fused_parts is not None:
            from .learner import _FeatCand
            from .ops.scan_pallas import fused_child_scans
            h_small, ph_k, left_small, lh_w, rh_w = fused_parts
            h_par = st.hist_pool[ph_k]
            kw = {k: v for k, v in self._split_kwargs.items()
                  if k != "skip_missing_scan"}
            num, hl, hr = fused_child_scans(
                h_small, h_par, left_small, sg2, sh2, cn2,
                self.f_num_bin, self.f_missing, self.f_default_bin,
                feature_mask & self._cat_mask,
                interpret=self._scan_interpret, **kw)
            st = st._replace(
                hist_pool=st.hist_pool.at[lh_w].set(hl).at[rh_w].set(hr))
            f = self.num_features
            cands = _FeatCand(
                gain=num.gain, threshold=num.threshold,
                default_left=num.default_left,
                is_cat=jnp.zeros((2 * K, f), bool),
                cat_bits=jnp.zeros((2 * K, f, self.cat_W), jnp.uint32),
                left_sum_g=num.left_sum_g, left_sum_h=num.left_sum_h,
                left_cnt=num.left_cnt, right_sum_g=num.right_sum_g,
                right_sum_h=num.right_sum_h, right_cnt=num.right_cnt,
                left_output=num.left_output,
                right_output=num.right_output)
            cf2, ci2, cb2 = self._pack_cand_rows(cands, depth_ok)
        else:
            cf2, ci2, cb2 = self._cand_rows_batch(
                hists2, sg2, sh2, cn2, feature_mask, depth_ok, constraints)
        # per-child leaf rows
        lf_l = jnp.stack([pcf[:, CF_LSG], pcf[:, CF_LSH], pcf[:, CF_LCNT],
                          pcf[:, CF_LOUT], cd, lmin, lmax], 1)
        lf_r = jnp.stack([pcf[:, CF_RSG], pcf[:, CF_RSH], pcf[:, CF_RCNT],
                          pcf[:, CF_ROUT], cd, rmin, rmax], 1)
        lf2 = jnp.stack([lf_l, lf_r], 1).reshape(2 * K, NUM_LF).astype(acc)
        # scatter everything (invalid members write out of bounds -> dropped)
        oob = jnp.int32(self.M + 7)
        ls_w = jnp.where(valid, lslot, oob)
        rs_w = jnp.where(valid, rslot, oob)
        s2 = i2(ls_w, rs_w)
        st = st._replace(
            node_i=st.node_i.at[ls_w].set(li).at[rs_w].set(ri),
            phys_i=st.phys_i.at[ls_w].set(phys_l).at[rs_w].set(phys_r),
            node_f=st.node_f.at[s2].set(lf2),
            cand_f=st.cand_f.at[s2].set(cf2),
            cand_i=st.cand_i.at[s2].set(ci2),
            cand_b=st.cand_b.at[s2].set(cb2),
            parent=st.parent.at[s2].set(jnp.repeat(wi, 2)),
            child0=st.child0.at[jnp.where(valid, wi, oob)].set(lslot),
            hslot=st.hslot.at[ls_w].set(lh).at[rs_w].set(rh),
            split_m=st.split_m.at[jnp.where(valid, wi, oob)].set(True),
            cnt_i=st.cnt_i.at[jnp.where(valid, wi, oob)].set(
                jnp.stack([lc_bag, c_bag - lc_bag], 1).astype(jnp.int32)),
            num_nodes=st.num_nodes
            + 2 * jnp.sum(valid, dtype=jnp.int32).astype(jnp.int32),
            num_splits=st.num_splits
            + jnp.sum(valid, dtype=jnp.int32).astype(jnp.int32))
        return st

    def _wave_body(self, st: WaveState, feature_mask, width: int = 0,
                   opening: bool = False) -> WaveState:
        """One growth wave.  ``width`` overrides the member cap (0 = the
        configured W).  ``opening=True`` runs the wave in LEVEL-OPENING
        mode: no sort executes — every valid member's children get distinct
        LOGICAL windows and their rows get the matching sort keys, so a
        single later materialization sort (``_materialize_sort``) compacts
        all opening levels at once; member histograms run as full-array
        lid-masked passes (``_opening_hists``) since no window is
        physically contiguous yet."""
        W = width or self.W
        M, n = self.M, self._rows_len()
        fw = self.fw
        self._coll_ctx = ("grow_wave", "wave")
        # ---- select the wave: top-W positive-gain frontier leaves
        g = self._pool_gains(st)
        gv, wi = lax.top_k(g, W)
        rem = self.grow_budget - st.num_splits
        valid = (gv > 0.0) & (jnp.arange(W) < rem)
        pos = jnp.cumsum(valid.astype(jnp.int32)) - valid.astype(jnp.int32)
        lslot = st.num_nodes + 2 * pos
        rslot = lslot + 1
        # ---- per-member split params (small gathers over node tables)
        feat = st.cand_i[wi, CI_FEAT]
        thr = st.cand_i[wi, CI_THR]
        flags = st.cand_i[wi, CI_FLAGS]
        dleft = (flags & 1).astype(jnp.float32)
        iscat = ((flags & 2) >> 1).astype(jnp.float32)
        ps = st.node_i[wi, 0]
        cw = st.node_i[wi, 1]
        col = self.fw_col[feat]
        widx = col // 4
        shift = (col % 4) * 8
        mt = self.f_missing[feat]
        db = self.f_default_bin[feat]
        nb = self.f_num_bin[feat]
        boff = self.fw_goff[feat]
        bnd = self.fw_bnd[feat]
        # members at or below the wave cutoff split in place (lid rewrite,
        # children share the parent span); only keyed members' rows get new
        # window keys.  Opening mode keys EVERY valid member (children get
        # logical windows now, physical compaction happens at the deferred
        # materialization sort); normal mode keys the members it sorts
        if opening:
            sortable = valid
        else:
            sortable = valid & (cw > self._wave_cutoff)
        P = jnp.stack([widx.astype(jnp.float32), shift.astype(jnp.float32),
                       thr.astype(jnp.float32), dleft, iscat,
                       mt.astype(jnp.float32), db.astype(jnp.float32),
                       nb.astype(jnp.float32), boff.astype(jnp.float32),
                       bnd.astype(jnp.float32), lslot.astype(jnp.float32),
                       rslot.astype(jnp.float32),
                       sortable.astype(jnp.float32)],
                      axis=1)                                       # (W, C)
        cat16 = None
        if self.has_categorical:
            cb_w = st.cand_b[wi]                                # (W, Wc)
            cat16 = jnp.concatenate(
                [(cb_w & jnp.uint32(0xFFFF)).astype(jnp.float32),
                 (cb_w >> jnp.uint32(16)).astype(jnp.float32)], axis=1)

        # -- pass 1 (per row chunk): wave-member mask -> split params via
        # MXU mask-matmul (gathers are ~5 ms/M rows on TPU, the one-hot
        # contraction ~0.5 ms), per-row decision, partial exact counts
        def decide(bins_c, lid_c, bag_c):
            ch_n = lid_c.shape[0]
            mask = (lid_c[:, None] == wi[None, :]) & valid[None, :]
            mask_f = mask.astype(jnp.float32)
            pm = lax.dot_general(mask_f, P, (((1,), (0,)), ((), ())),
                                 precision=_HIGH)               # (ch, C)
            in_wave = jnp.any(mask, axis=1)
            ri = lambda c: jnp.rint(pm[:, c]).astype(jnp.int32)
            widx_r, shift_r, thr_r = ri(0), ri(1), ri(2)
            dleft_r = pm[:, 3] > 0.5
            iscat_r = pm[:, 4] > 0.5
            mt_r, db_r, nb_r = ri(5), ri(6), ri(7)
            boff_r, bnd_r = ri(8), ri(9)
            lslot_r, rslot_r = ri(10), ri(11)
            sortable_r = pm[:, 12] > 0.5
            # per-row decision (NumericalDecisionInner `tree.h:233-249`)
            word = self._word_select(bins_c, widx_r)
            code = (word >> shift_r) & 0xFF
            if self._bundle is not None:
                r = code - boff_r
                in_r = (r >= 0) & (r < nb_r - 1)
                dec = r + (r >= db_r).astype(r.dtype)
                frow = jnp.where(bnd_r == 1, jnp.where(in_r, dec, db_r),
                                 code)
            else:
                frow = code
            is_missing = ((mt_r == MISSING_ZERO) & (frow == db_r)) | \
                         ((mt_r == MISSING_NAN) & (frow == nb_r - 1))
            go_left = jnp.where(is_missing, dleft_r, frow <= thr_r)
            if self.has_categorical:
                catpm = lax.dot_general(mask_f, cat16,
                                        (((1,), (0,)), ((), ())),
                                        precision=_HIGH)        # (ch, 2*Wc)
                j = frow >> 5
                lo = jnp.zeros(ch_n, jnp.float32)
                hi = jnp.zeros(ch_n, jnp.float32)
                for jj in range(self.cat_W):
                    sel = j == jj
                    lo = lo + jnp.where(sel, catpm[:, jj], 0.0)
                    hi = hi + jnp.where(sel, catpm[:, self.cat_W + jj], 0.0)
                catw = (jnp.rint(hi).astype(jnp.int32).astype(jnp.uint32)
                        << jnp.uint32(16)) | \
                    jnp.rint(lo).astype(jnp.int32).astype(jnp.uint32)
                cat_left = (catw >> (frow & 31).astype(jnp.uint32)) & 1
                go_left = jnp.where(iscat_r, cat_left == 1, go_left)
            go_left = go_left & in_wave
            # exact integer counts via f32-exact one-hot contractions: the
            # chunk bound keeps per-chunk counts <= 2^20 (f32-exact); the
            # cross-chunk sum runs in int32, so exactness holds at ANY row
            # count (this was the old `n_pad < 2^24` eligibility gate)
            gl_f = go_left.astype(jnp.float32)
            bag_f = bag_c.astype(jnp.float32)
            w3 = jnp.stack([gl_f, gl_f * bag_f, bag_f], 0)
            cnt3 = lax.dot_general(w3, mask_f, (((1,), (0,)), ((), ())),
                                   precision=_HIGH)             # (3, W)
            lid_new = jnp.where(in_wave,
                                jnp.where(go_left, lslot_r, rslot_r), lid_c)
            return (go_left, in_wave & sortable_r, lid_new,
                    jnp.rint(cnt3).astype(jnp.int32))

        Cm = 1
        while n // Cm > self._row_chunk and Cm < 1024 \
                and n % (Cm * 2) == 0:
            Cm *= 2
        bag_b = st.w_p[2] > 0.5
        if Cm == 1:
            go_left, sort_r, lid_p, cnt3 = decide(st.bins_p, st.lid_p, bag_b)
        else:
            ch = n // Cm
            go_left, sort_r, lid_p, cnt3c = lax.map(
                lambda a: decide(*a),
                (st.bins_p.reshape(fw, Cm, ch).transpose(1, 0, 2),
                 st.lid_p.reshape(Cm, ch), bag_b.reshape(Cm, ch)))
            go_left = go_left.reshape(-1)
            sort_r = sort_r.reshape(-1)
            lid_p = lid_p.reshape(-1)
            cnt3 = jnp.sum(cnt3c, axis=0, dtype=jnp.int32)
        cnt3 = self._sync_counts3(cnt3)
        lc_w = cnt3[0]
        lc_bag = cnt3[1]
        c_bag = cnt3[2]

        # -- pass 2: window-order keys.  INVARIANT: every leaf's rows carry
        # key = 2 * (its window start) — strictly increasing with position,
        # so the stable sort is the identity on untouched leaves and
        # partitions each split window in place.  The children's starts are
        # already known pre-sort (s and s+lc), so both get final keys here.
        # Starts are routed through the contraction as hi/lo 12-bit planes
        # (one nonzero per row -> each plane f32-exact at any N).
        # Partition mode needs no carried keys (each wave materializes its
        # own windows from wave-local destinations) — the pass is skipped.
        if self._use_partition and not opening:
            key_p = st.key_p
        else:
            starts2 = jnp.stack([ps, ps + lc_w], axis=1)        # (W, 2)
            planes = jnp.concatenate(
                [(starts2 >> 12).astype(jnp.float32),
                 (starts2 & 0xFFF).astype(jnp.float32)], axis=1)  # (W, 4)

            def keys(lid_old_c, go_c, sort_c, key_c):
                mask_f = ((lid_old_c[:, None] == wi[None, :])
                          & valid[None, :]).astype(jnp.float32)
                ks = lax.dot_general(mask_f, planes,
                                     (((1,), (0,)), ((), ())),
                                     precision=_HIGH)           # (ch, 4)
                ki = jnp.rint(ks).astype(jnp.int32)
                kl = 2 * ((ki[:, 0] << 12) + ki[:, 2])
                kr = 2 * ((ki[:, 1] << 12) + ki[:, 3])
                return jnp.where(sort_c, jnp.where(go_c, kl, kr), key_c)

            if Cm == 1:
                key_p = keys(st.lid_p, go_left, sort_r, st.key_p)
            else:
                ch = n // Cm
                key_p = lax.map(
                    lambda a: keys(*a),
                    (st.lid_p.reshape(Cm, ch), go_left.reshape(Cm, ch),
                     sort_r.reshape(Cm, ch),
                     st.key_p.reshape(Cm, ch))).reshape(-1)
        # ---- ONE stable sort re-compacts every sortable split window.
        # Skipped when the whole wave froze (the tree's bottom waves), when
        # opening mode defers ALL compaction to the materialization sort,
        # and — under sort-deferral alternation — on every wave without a
        # PENDING key set: a deferring wave only assigns logical windows +
        # keys, and the NEXT wave's single sort materializes both levels.
        do_sort = jnp.any(sortable)
        if opening:
            st = st._replace(lid_p=lid_p, key_p=key_p)
            sorted_now = jnp.asarray(False)
        elif self._use_partition and "nosort" not in self._ablate:
            # ---- Pallas stable partition (ops/partition_pallas.py): the
            # permutation the stable sort produces, computed directly —
            # per-row destinations from two exclusive prefix sums over
            # the left/right flags plus per-member window bases routed
            # through the same mask-matmul as the key pass, then one
            # chunked byte-plane permute kernel.  Record-exact vs the
            # sort (tests/test_partition.py).
            sort_now = do_sort

            def run_partition(args):
                from .ops.partition_pallas import (apply_partition,
                                                   exclusive_cumsum_i32)
                bins_p_i, w_p_i, rid_p_i, lid_p_i = args
                gl = sort_r & go_left
                gr = sort_r & ~go_left
                cum = exclusive_cumsum_i32(
                    jnp.stack([gl, gr]).astype(jnp.int32))
                cl, cr = cum[0], cum[1]
                active = sortable
                ps_s = jnp.where(active, ps, 0)
                cl_ps = jnp.take(cl, ps_s)
                cr_ps = jnp.take(cr, ps_s)
                # member bases shifted by +n so the 13/12-bit plane split
                # stays non-negative (each plane has one nonzero per row
                # -> f32-exact at any N <= 2^24)
                base_l = ps + n - cl_ps
                base_r = ps + lc_w + n - cr_ps
                dplanes = jnp.stack(
                    [(base_l >> 12).astype(jnp.float32),
                     (base_l & 0xFFF).astype(jnp.float32),
                     (base_r >> 12).astype(jnp.float32),
                     (base_r & 0xFFF).astype(jnp.float32)],
                    axis=1)                                     # (W, 4)

                def dests(lid_old_c, go_c, sort_c, pos_c, cl_c, cr_c):
                    mask_f = ((lid_old_c[:, None] == wi[None, :])
                              & valid[None, :]).astype(jnp.float32)
                    ks = lax.dot_general(mask_f, dplanes,
                                         (((1,), (0,)), ((), ())),
                                         precision=_HIGH)       # (ch, 4)
                    ki = jnp.rint(ks).astype(jnp.int32)
                    bl = (ki[:, 0] << 12) + ki[:, 1] - n
                    br = (ki[:, 2] << 12) + ki[:, 3] - n
                    return jnp.where(
                        sort_c, jnp.where(go_c, bl + cl_c, br + cr_c),
                        pos_c)

                pos = jnp.arange(n, dtype=jnp.int32)
                if Cm == 1:
                    dest = dests(st.lid_p, go_left, sort_r, pos, cl, cr)
                else:
                    ch = n // Cm
                    dest = lax.map(
                        lambda a: dests(*a),
                        (st.lid_p.reshape(Cm, ch),
                         go_left.reshape(Cm, ch),
                         sort_r.reshape(Cm, ch), pos.reshape(Cm, ch),
                         cl.reshape(Cm, ch),
                         cr.reshape(Cm, ch))).reshape(-1)
                return apply_partition(
                    bins_p_i, w_p_i, rid_p_i, lid_p_i, dest,
                    sort_r.astype(jnp.int32), ps, lc_w, cw, active,
                    cl, cr, cl_ps, cr_ps,
                    interpret=self._partition_interpret)

            bins_p, w_p, rid_p, lid_p = lax.cond(
                sort_now, run_partition, lambda a: a,
                (st.bins_p, st.w_p, st.rid_p, lid_p))
            st = st._replace(bins_p=bins_p, w_p=w_p, rid_p=rid_p,
                             lid_p=lid_p)
            sorted_now = sort_now
        elif "nosort" not in self._ablate:
            if self._defer_sorts:
                sort_now = st.pending
            else:
                sort_now = do_sort

            def run_sort(args):
                key_p, bins_p, w_p, rid_p, lid_p = args
                ops = ([key_p] + [bins_p[i] for i in range(fw)]
                       + [w_p[0], w_p[1], w_p[2], rid_p, lid_p])
                sd = lax.sort(ops, num_keys=1, is_stable=True)
                return (sd[0], jnp.stack(sd[1:1 + fw]),
                        jnp.stack(sd[1 + fw:4 + fw]), sd[4 + fw], sd[5 + fw])

            key_p, bins_p, w_p, rid_p, lid_p = lax.cond(
                sort_now, run_sort, lambda a: a,
                (key_p, st.bins_p, st.w_p, st.rid_p, lid_p))
            st = st._replace(bins_p=bins_p, w_p=w_p, rid_p=rid_p,
                             lid_p=lid_p, key_p=key_p)
            sorted_now = sort_now
        else:  # profiling skeleton: windows stay unsorted (garbage layout)
            st = st._replace(lid_p=lid_p, key_p=key_p)
            sorted_now = do_sort
        st = st._replace(pending=(st.pending | do_sort) & ~sorted_now)
        # ---- child windows: sortable members split [s,lc)/[s+lc,..);
        # frozen members' children share the parent span
        li = jnp.stack([ps, jnp.where(sortable, lc_w, cw)], 1)
        ri2 = jnp.stack([jnp.where(sortable, ps + lc_w, ps),
                         jnp.where(sortable, cw - lc_w, cw)], 1)
        # children's materialized covering spans: the logical windows when
        # this wave sorted (everything compacts), the MEMBER's span when
        # the sort was deferred (rows haven't moved)
        mphys = st.phys_i[wi]                                   # (W, 2)
        phys_l = jnp.where(sorted_now, li, mphys)
        phys_r = jnp.where(sorted_now, ri2, mphys)
        # ---- smaller-child histograms (+ sibling subtraction) per member.
        # Post-sort, every member's window is materialized — scan the
        # logical child window (or the shared node span for frozen
        # members); on a deferring wave scan the member's covering span
        # with the lid mask doing the selection
        left_small = lc_bag <= (c_bag - lc_bag)
        sm_slot = jnp.where(left_small, lslot, rslot)
        sm_start = jnp.where(sorted_now,
                             jnp.where(sortable & ~left_small, ps + lc_w,
                                       ps),
                             mphys[:, 0])
        sm_cnt = jnp.where(sorted_now,
                           jnp.where(sortable,
                                     jnp.where(left_small, lc_w,
                                               cw - lc_w), cw),
                           mphys[:, 1])
        ph = st.hslot[wi]
        rh = 1 + st.num_splits + pos
        oobh = jnp.int32(self.H + 7)
        lh_w = jnp.where(valid, ph, oobh)
        rh_w = jnp.where(valid, rh, oobh)

        if not opening and getattr(self, "_use_fused", False):
            # fused chain: only the smaller-child histograms run here —
            # subtraction, select, FixHistogram and both child scans
            # collapse into one Pallas launch in _children_bookkeeping
            h_small = self._member_small_hists(st, sm_slot, sm_start,
                                               sm_cnt, valid)
            st = self._children_bookkeeping(
                st, wi, valid, lslot, rslot, lc_bag, c_bag, li, ri2, ph,
                rh, None, feature_mask, phys_l, phys_r,
                fused_parts=(h_small, ph, left_small, lh_w, rh_w))
        else:
            if opening:
                # sm_start/sm_cnt reference LOGICAL windows (nothing has
                # been compacted yet) — opening hists mask by lid over
                # the full array
                pool, hl, hr = self._opening_hists(
                    st, sm_slot, valid, ph, lh_w, rh_w, left_small)
            else:
                pool, hl, hr = self._wave_member_hists(
                    st, sm_slot, sm_start, sm_cnt, valid, ph, lh_w, rh_w,
                    left_small)
            st = st._replace(hist_pool=pool)
            hists2 = jnp.stack([hl, hr], 1).reshape((2 * W,)
                                                   + hl.shape[1:])
            st = self._children_bookkeeping(
                st, wi, valid, lslot, rslot, lc_bag, c_bag, li, ri2, ph,
                rh, hists2, feature_mask, phys_l, phys_r)
        if st.telem is not None:
            st = st._replace(telem=st.telem
                             .at[TEL_WAVES].add(1)
                             .at[TEL_WAVE_SORTS].add(
                                 sorted_now.astype(jnp.int32))
                             .at[TEL_WAVE_MEMBERS].add(
                                 jnp.sum(valid, dtype=jnp.int32))
                             .at[TEL_FROZEN_MEMBERS].add(
                                 jnp.sum(valid & ~sortable,
                                         dtype=jnp.int32)))
        # a sort materializes EVERY node (stale covering spans from the
        # previous deferring wave included), not just this wave's children
        return st._replace(phys_i=jnp.where(sorted_now, st.node_i,
                                            st.phys_i))

    def _member_small_hists(self, st: WaveState, sm_slot, sm_start, sm_cnt,
                            valid):
        """Smaller-child histograms ONLY (no subtraction / pool writes) —
        the fused wave step (``_use_fused``) folds everything downstream
        into the ``fused_child_scans`` kernel."""
        if self._use_pallas:
            return self._segment_hists(st, sm_slot, sm_start, sm_cnt,
                                       valid)

        def hist_member(carry, xs):
            slot, start, cnt, vk = xs

            def compute(_):
                hidx = self._bucket_idx(jnp.maximum(cnt, 1))
                return lax.switch(hidx, self._hist_branches, st.bins_p,
                                  st.w_p, st.lid_p, start, cnt, slot)

            return carry, lax.cond(
                vk, compute, lambda _: jnp.zeros_like(st.hist_pool[0]),
                0)

        _, h_small = lax.scan(hist_member, 0,
                              (sm_slot, sm_start, sm_cnt, valid))
        return h_small

    def _wave_member_hists(self, st: WaveState, sm_slot, sm_start, sm_cnt,
                           valid, ph, lh_w, rh_w, left_small):
        """Smaller-child histograms for all wave members + sibling
        subtraction + pool writes; returns (pool, hl, hr).  The sharded
        subclass overrides this to reduce-scatter the W local histograms
        over the feature axis before subtraction."""
        if "nohist" in self._ablate:
            shp = (sm_slot.shape[0], self._hist_cols, self._hist_nbins, 3)
            hl = hr = jnp.zeros(shp, st.hist_pool.dtype)
            return st.hist_pool, hl, hr
        if self._use_pallas:
            h_small = self._segment_hists(st, sm_slot, sm_start, sm_cnt,
                                          valid)
            h_par = st.hist_pool[ph]                   # (W, F, B, 3)
            h_large = h_par - h_small
            lsm = left_small[:, None, None, None]
            hl = jnp.where(lsm, h_small, h_large)
            hr = jnp.where(lsm, h_large, h_small)
            pool = st.hist_pool.at[lh_w].set(hl).at[rh_w].set(hr)
            return pool, hl, hr

        def hist_member(pool, xs):
            slot, start, cnt, phk, lhk, rhk, lsm, vk = xs

            def compute(pool):
                hidx = self._bucket_idx(jnp.maximum(cnt, 1))
                h_small = lax.switch(hidx, self._hist_branches,
                                     st.bins_p, st.w_p, st.lid_p, start,
                                     cnt, slot)
                h_par = pool[phk]
                h_large = h_par - h_small
                hl = jnp.where(lsm, h_small, h_large)
                hr = jnp.where(lsm, h_large, h_small)
                return pool.at[lhk].set(hl).at[rhk].set(hr), (hl, hr)

            def skip(pool):
                z = jnp.zeros_like(pool[0])
                return pool, (z, z)

            # only the valid prefix holds members — the cond keeps
            # invalid slots from paying a histogram pass
            return lax.cond(vk, compute, skip, pool)

        pool, (hl, hr) = lax.scan(
            hist_member, st.hist_pool,
            (sm_slot, sm_start, sm_cnt, ph, lh_w, rh_w, left_small,
             valid))
        return pool, hl, hr

    def _opening_hists(self, st: WaveState, sm_slot, valid, ph, lh_w, rh_w,
                       left_small):
        """Smaller-child histograms for one OPENING level: rows are still
        in root order (no sort has run), so the segment kernel's chunk walk
        cannot apply.  Serial TPU: ONE multi-slot full pass
        (`ops/hist_pallas.py:build_histogram_multislot`) — the bin one-hot
        is built once and shared across the K members.  Fallback (CPU /
        f64 / sharded subclasses): per-member full-span lid-masked scans
        through the regular member-hist seam, which keeps the sharded
        psum_scatter exchange intact."""
        if self._use_pallas and type(self)._wave_member_hists is \
                WaveTPUTreeLearner._wave_member_hists:
            from .ops.hist_pallas import build_histogram_multislot
            K = sm_slot.shape[0]
            sl = jnp.where(valid, sm_slot, -1)
            slot_r = jnp.full(st.lid_p.shape, K, jnp.int32)
            for k in range(K):
                slot_r = jnp.where(st.lid_p == sl[k], k, slot_r)
            h_small = build_histogram_multislot(
                st.bins_p, st.w_p, slot_r, num_bins=self._hist_nbins,
                n_slots=K, row_block=self._seg_rb,
                nterms=self._hist_nterms,
                quant=self._quant)[:, :self._hist_cols]
            if self._quant:
                h_small = h_small * jnp.stack(
                    [jnp.float32(1.0), jnp.float32(1.0), self._q_cnt])
            h_par = st.hist_pool[ph]
            h_large = h_par - h_small
            lsm = left_small[:, None, None, None]
            hl = jnp.where(lsm, h_small, h_large)
            hr = jnp.where(lsm, h_large, h_small)
            pool = st.hist_pool.at[lh_w].set(hl).at[rh_w].set(hr)
            return pool, hl, hr
        n = self._rows_len()
        return self._wave_member_hists(
            st, sm_slot, jnp.zeros_like(sm_slot),
            jnp.full_like(sm_slot, n), valid, ph, lh_w, rh_w, left_small)

    def _materialize_sort(self, st: WaveState) -> WaveState:
        """One stable full-array sort on the window keys assigned by the
        opening levels: every leaf's rows land contiguously at its logical
        window (keys are 2×(window start), strictly increasing with
        position — the invariant the per-wave sorts maintain), after which
        the regular wave flow's physical-window machinery applies."""
        fw = self.fw
        ops = ([st.key_p] + [st.bins_p[i] for i in range(fw)]
               + [st.w_p[0], st.w_p[1], st.w_p[2], st.rid_p, st.lid_p])
        sd = lax.sort(ops, num_keys=1, is_stable=True)
        return st._replace(key_p=sd[0], bins_p=jnp.stack(sd[1:1 + fw]),
                           w_p=jnp.stack(sd[1 + fw:4 + fw]),
                           rid_p=sd[4 + fw], lid_p=sd[5 + fw],
                           phys_i=st.node_i, pending=jnp.asarray(False))

    def _segment_hists(self, st: WaveState, sm_slot, sm_start, sm_cnt,
                       valid, t_cap: Optional[int] = None):
        """Smaller-child histograms for every wave member in ONE Pallas
        call (`ops/hist_pallas.py:build_histogram_segments`): the chunk
        list walks each member's row-blocks; rows are masked by lid so
        block alignment never matters.  Invalid members get one all-masked
        chunk so their output slot is defined (zeros).

        ``t_cap`` overrides the chunk-capacity bound for callers whose
        members don't satisfy the wave invariants (the batched replay
        correction: members may share large un-materialized covering
        spans, so its cap is K * (rows/rb + 2) + 1).  A too-small cap
        would silently DROP row-blocks, so the default wave formula must
        cover the wave flows."""
        from .ops.hist_pallas import build_histogram_segments
        W = sm_slot.shape[0]        # wave width (narrow on ramp waves)
        rb = self._seg_rb
        # sortable smaller-child windows are disjoint (<= n_pad rows total);
        # frozen members scan their shared parent span (<= wave cutoff each)
        wc = min(self._wave_cutoff, self._rows_len())
        T = (t_cap if t_cap is not None
             else self._rows_len() // rb + W + W * (wc // rb + 2) + 1)
        first_blk = jnp.where(valid, sm_start // rb, 0)
        last_blk = jnp.where(
            valid, (sm_start + jnp.maximum(sm_cnt, 1) - 1) // rb, 0)
        nblk = jnp.where(valid, last_blk - first_blk + 1, 1)
        leaf_of = jnp.where(valid, sm_slot, -1)
        off = jnp.cumsum(nblk)
        starts = (off - nblk).astype(jnp.int32)
        total = off[W - 1]
        tpos = jnp.arange(T, dtype=jnp.int32)
        started = jnp.zeros(T, jnp.int32).at[starts].add(1, mode="drop")
        mem = jnp.clip(jnp.cumsum(started) - 1, 0, W - 1)
        slot_t = jnp.where(tpos < total, mem, W).astype(jnp.int32)
        block_t = jnp.where(tpos < total, first_blk[mem]
                            + (tpos - starts[mem]), 0).astype(jnp.int32)
        leaf_t = jnp.where(tpos < total, leaf_of[mem], -1).astype(jnp.int32)
        # grid-size buckets: late waves have few real chunks — pick the
        # smallest capacity that holds them so no-op grid cells don't
        # dominate
        Ts = []
        tcap = T
        while tcap > 2 * W:
            Ts.append(tcap)
            tcap //= 2
        Ts.append(max(2 * W, tcap))

        def make_branch(Ti):
            def branch(s_t, b_t, l_t, bins_p, w_p, lid_p):
                return build_histogram_segments(
                    bins_p, w_p, lid_p, s_t[:Ti], b_t[:Ti], l_t[:Ti],
                    num_bins=self._hist_nbins, n_slots=W, row_block=rb,
                    nterms=self._hist_nterms, quant=self._quant)
            return branch

        tarr = jnp.asarray(Ts, dtype=jnp.int32)
        idx = jnp.maximum(jnp.sum(tarr >= total) - 1, 0)
        out = lax.switch(idx, [make_branch(t) for t in Ts], slot_t, block_t,
                         leaf_t, st.bins_p, st.w_p, st.lid_p)
        h = out[:, :self._hist_cols]
        if self._quant:
            # quant kernels duplicate the h lane into the count channel;
            # rescale it to the normalized Σhq/m̄ effective row count
            h = h * jnp.stack([jnp.float32(1.0), jnp.float32(1.0),
                               self._q_cnt])
        return h

    def _wave_step(self, st: WaveState, feature_mask) -> WaveState:
        """One adaptive-width wave.  The ramp (frontier 1→2→4→…) and the
        exhausted bottom pay per-wave costs that scale with the BODY width
        — the (rows, W) member-mask contractions, the 2W-child scans, the
        bookkeeping — regardless of how few leaves actually split, so a
        frontier of ≤ 8 positive-gain leaves runs a W=8 body instead.
        Selection is identical (top-k of the same gain order, same budget
        guard), so the grown forest is exactly the same."""
        ws = min(8, self.W)
        if ws >= self.W:
            return self._wave_body(st, feature_mask)
        small = jnp.sum(self._pool_gains(st) > 0.0) <= ws
        return lax.cond(
            small,
            lambda s: self._wave_body(s, feature_mask, width=ws),
            lambda s: self._wave_body(s, feature_mask), st)

    # -- split-word extraction seams -----------------------------------------
    # the decide pass and the stall partition both need the split feature's
    # packed bin word per row.  Serial and 1-D learners hold every word
    # locally; the 2-D data×feature learner holds only a word SLICE per
    # device and overrides these with a masked-sum + feature-axis psum.

    def _word_select(self, bins_c, widx_r):
        """Per-row split-feature bin words from a (fw, rows) bins chunk.
        ``widx_r`` carries packed-word indices in THIS learner's word
        numbering (global == local here)."""
        word = jnp.zeros(widx_r.shape[0], jnp.int32)
        for wdi in range(self.fw):
            word = word + jnp.where(widx_r == wdi, bins_c[wdi], 0)
        return word

    def _window_word(self, bw, col):
        """One feature's packed bin word over a sliced (fw, S) window;
        ``col`` is the packed column of the split feature."""
        S = bw.shape[1]
        return lax.dynamic_slice(bw, (col // 4, jnp.int32(0)), (1, S))[0]

    # -- the stall split (exact-replay correction) ---------------------------

    def _span_decide(self, bw, ww, lid, off, c, leaf, feat, thr, dleft,
                     is_cat, cat_bits):
        """Per-row split decision over one sliced window — the decode
        (bin-word extraction, EFB un-bundling, missing-value routing,
        categorical bitset) shared by the K=1 sort/frozen stall partition
        and the batched mask-mode one, so a routing fix cannot
        desynchronize them.  Returns (in_seg, go_left, lc_bag, c_bag)."""
        S = lid.shape[0]
        pos = jnp.arange(S, dtype=jnp.int32)
        in_seg = (pos >= off) & (pos < off + c) & (lid == leaf)
        col = self.fw_col[feat]
        word = self._window_word(bw, col)
        code = (word >> ((col % 4) * 8)) & 0xFF
        if self._bundle is not None:
            boffk = self.fw_goff[feat]
            d = self.f_default_bin[feat]
            r = code - boffk
            in_r = (r >= 0) & (r < self.f_num_bin[feat] - 1)
            dec = r + (r >= d).astype(r.dtype)
            frow = jnp.where(self.fw_bnd[feat] == 1,
                             jnp.where(in_r, dec, d), code)
        else:
            frow = code
        mtk = self.f_missing[feat]
        dbk = self.f_default_bin[feat]
        nbk = self.f_num_bin[feat]
        is_missing = ((mtk == MISSING_ZERO) & (frow == dbk)) | \
                     ((mtk == MISSING_NAN) & (frow == nbk - 1))
        go_left = jnp.where(is_missing, dleft, frow <= thr)
        if self.has_categorical:
            cat_left = (cat_bits[frow >> 5]
                        >> (frow & 31).astype(jnp.uint32)) & 1
            go_left = jnp.where(is_cat, cat_left == 1, go_left)
        bag = ww[2] > 0.5
        lc_bag = jnp.sum(in_seg & go_left & bag, dtype=jnp.int32)
        c_bag = jnp.sum(in_seg & bag, dtype=jnp.int32)
        return in_seg, go_left, lc_bag, c_bag

    def _make_stall_branch(self, S: int, sort_mode: bool):
        """Partition of one window outside the wave flow, mirroring the
        sequential compact learner exactly (`learner_compact.py`
        ``_make_partition_branch``) except that BOTH children get fresh
        node slots (the sequential learner reuses the parent's).

        sort_mode: stable window sort physically compacts the children
        (windows above ``tpu_sort_cutoff``).  Otherwise the window is
        frozen and only lid lanes change; the sort_mode invariant matches
        the sequential learner's — frozen (shared) windows are always
        ≤ cutoff, so a sort-mode stall never reorders another leaf's rows.
        """
        fw, n = self.fw, self._rows_len()

        def branch(bins_p, w_p, rid_p, lid_p, s, c, leaf, feat, thr, dleft,
                   is_cat, cat_bits, l0, r0):
            sa = jnp.clip(s, 0, n - S).astype(jnp.int32)
            off = (s - sa).astype(jnp.int32)
            bw = lax.dynamic_slice(bins_p, (jnp.int32(0), sa), (fw, S))
            ww = lax.dynamic_slice(w_p, (jnp.int32(0), sa), (3, S))
            lid = lax.dynamic_slice(lid_p, (sa,), (S,))
            pos = jnp.arange(S, dtype=jnp.int32)
            in_seg, go_left, lc_bag, c_bag = self._span_decide(
                bw, ww, lid, off, c, leaf, feat, thr, dleft, is_cat,
                cat_bits)
            segl = in_seg & go_left
            if sort_mode:
                rid = lax.dynamic_slice(rid_p, (sa,), (S,))
                key = jnp.where(in_seg,
                                jnp.where(go_left, 1, 2),
                                jnp.where(pos < off, 0, 3)).astype(jnp.int32)
                lid2 = jnp.where(in_seg, jnp.where(go_left, l0, r0), lid)
                ops = ([key] + [bw[i] for i in range(fw)]
                       + [ww[0], ww[1], ww[2], rid, lid2])
                sd = lax.sort(ops, num_keys=1, is_stable=True)
                bw2 = jnp.stack(sd[1:1 + fw])
                ww2 = jnp.stack(sd[1 + fw:4 + fw])
                rid2, lid3 = sd[4 + fw], sd[5 + fw]
                lc_w = jnp.sum(segl.astype(jnp.int32)).astype(jnp.int32)
                bins_p = lax.dynamic_update_slice(bins_p, bw2,
                                                  (jnp.int32(0), sa))
                w_p = lax.dynamic_update_slice(w_p, ww2, (jnp.int32(0), sa))
                rid_p = lax.dynamic_update_slice(rid_p, rid2, (sa,))
                lid_p = lax.dynamic_update_slice(lid_p, lid3, (sa,))
                ls, lw = s, lc_w
                rs, rw = s + lc_w, c - lc_w
            else:
                lid2 = jnp.where(in_seg, jnp.where(go_left, l0, r0), lid)
                lid_p = lax.dynamic_update_slice(lid_p, lid2, (sa,))
                ls = rs = s
                lw = rw = c
            return (bins_p, w_p, rid_p, lid_p, ls, lw, rs, rw,
                    lc_bag.astype(jnp.int32), c_bag.astype(jnp.int32))

        return branch

    def _replicated_spans(self, spans):
        """Replicated view of covering-span widths.  ``phys_i`` holds
        LOCAL window geometry in the row-sharded learners, so any gate
        derived from it must see the cross-device maximum or the replay's
        replicated bookkeeping diverges (round-5 advisor, high); identity
        here — the sharded wave learner overrides with ``lax.pmax``."""
        return spans

    def _stall_split(self, st: WaveState, top, feature_mask) -> WaveState:
        """Split one frontier leaf outside the wave flow (the
        ``tpu_wave_stall_batch=1`` replay path)."""
        self._coll_ctx = ("stall_correction", "stall_event")
        crow_i = st.cand_i[top]
        feat = crow_i[CI_FEAT]
        thr = crow_i[CI_THR]
        dleft = (crow_i[CI_FLAGS] & 1) == 1
        is_cat = (crow_i[CI_FLAGS] & 2) == 2
        cat_bits = st.cand_b[top]
        s = st.node_i[top, 0]
        c = st.node_i[top, 1]
        l0 = st.num_nodes
        r0 = l0 + 1
        pidx = self._bucket_idx(c)
        bins_p, w_p, rid_p, lid_p, ls, lw, rs, rw, lc_bag, c_bag = \
            lax.switch(pidx, self._stall_branches, st.bins_p, st.w_p,
                       st.rid_p, st.lid_p, s, c, top, feat, thr, dleft,
                       is_cat, cat_bits, l0, r0)
        st = st._replace(bins_p=bins_p, w_p=w_p, rid_p=rid_p, lid_p=lid_p)
        lc_bag, c_bag = self._sync_counts(lc_bag, c_bag)
        # smaller-child histogram + sibling subtraction
        left_small = lc_bag <= (c_bag - lc_bag)
        sm_slot = jnp.where(left_small, l0, r0)
        sm_start = jnp.where(left_small, ls, rs)
        sm_cnt = jnp.where(left_small, lw, rw)
        hidx = self._bucket_idx(jnp.maximum(sm_cnt, 1))
        h_small = self._reduce_hist(
            lax.switch(hidx, self._hist_branches, st.bins_p, st.w_p,
                       st.lid_p, sm_start, sm_cnt, sm_slot))
        ph = st.hslot[top]
        h_par = st.hist_pool[ph]
        h_large = h_par - h_small
        hl = jnp.where(left_small, h_small, h_large)
        hr = jnp.where(left_small, h_large, h_small)
        rh = 1 + st.num_splits
        st = st._replace(hist_pool=st.hist_pool.at[ph].set(hl)
                         .at[rh].set(hr))
        one = jnp.ones(1, bool)
        li = jnp.stack([ls, lw])[None, :]
        ri = jnp.stack([rs, rw])[None, :]
        return self._children_bookkeeping(
            st, top[None], one, l0[None], r0[None],
            lc_bag[None], c_bag[None], li, ri, ph[None], rh[None],
            jnp.stack([hl, hr]), feature_mask)

    def _make_stall_mask_branch(self, S: int):
        """Lid-only partition of one covering span for the batched replay
        correction.  No row moves: children share the parent's span the
        way frozen (sub-cutoff) wave windows already do, so the
        surrounding fori_loop carries ONLY the lid lane.  (A first cut
        carried bins/weights/rid/pool through the loop; XLA could not
        alias the carries past their other consumers and inserted ~7 ms
        full-array copies per stall event — the copies, not the splits,
        dominated the replay.)"""
        fw, n = self.fw, self._rows_len()

        def branch(bins_p, w_p, lid_p, s, c, leaf, feat, thr, dleft,
                   is_cat, cat_bits, l0, r0):
            sa = jnp.clip(s, 0, n - S).astype(jnp.int32)
            off = (s - sa).astype(jnp.int32)
            bw = lax.dynamic_slice(bins_p, (jnp.int32(0), sa), (fw, S))
            ww = lax.dynamic_slice(w_p, (jnp.int32(0), sa), (3, S))
            lid = lax.dynamic_slice(lid_p, (sa,), (S,))
            in_seg, go_left, lc_bag, c_bag = self._span_decide(
                bw, ww, lid, off, c, leaf, feat, thr, dleft, is_cat,
                cat_bits)
            lid2 = jnp.where(in_seg, jnp.where(go_left, l0, r0), lid)
            lid_p = lax.dynamic_update_slice(lid_p, lid2, (sa,))
            return lid_p, lc_bag, c_bag

        return branch

    # batch extras must fit a bounded slice so the vectorized partition's
    # stacked (K-1, fw, S) transients stay small; bigger-span leaves can
    # only be corrected as the top of their own event (rare: big spans
    # stall early, at the top of the tree)
    _VEC_CAP = 1 << 17

    def _make_stall_vec_branch(self, S: int, Ke: int):
        """Lid-only partition of Ke covering spans at once: Ke slices of
        one bucket, ONE vmapped ``_span_decide``, Ke masked write-backs.
        Replaces Ke sequential bucket switches (~0.2 ms each on v5e) with
        one fused stage; disjoint lid values make the sequential
        dynamic-update chain commute even when members share a span."""
        fw, n = self.fw, self._rows_len()

        def branch(bins_p, w_p, lid_p, starts, cnts, leaves, feats, thrs,
                   dlefts, iscats, catbits, l0v, r0v):
            sas = jnp.clip(starts, 0, n - S).astype(jnp.int32)
            offs = (starts - sas).astype(jnp.int32)
            z = jnp.int32(0)
            bw_k = jnp.stack([lax.dynamic_slice(bins_p, (z, sas[i]),
                                                (fw, S))
                              for i in range(Ke)])
            ww_k = jnp.stack([lax.dynamic_slice(w_p, (z, sas[i]), (3, S))
                              for i in range(Ke)])
            lid_k = jnp.stack([lax.dynamic_slice(lid_p, (sas[i],), (S,))
                               for i in range(Ke)])
            in_seg, go_left, lc, cb = jax.vmap(self._span_decide)(
                bw_k, ww_k, lid_k, offs, cnts, leaves, feats, thrs,
                dlefts, iscats, catbits)
            for i in range(Ke):
                cur = lax.dynamic_slice(lid_p, (sas[i],), (S,))
                new = jnp.where(in_seg[i],
                                jnp.where(go_left[i], l0v[i], r0v[i]), cur)
                lid_p = lax.dynamic_update_slice(lid_p, new, (sas[i],))
            return lid_p, lc, cb

        return branch

    def _stall_split_batch(self, st: WaveState, tops, bvalid,
                           feature_mask, top_fits=None) -> WaveState:
        """Split up to K frontier leaves in ONE replay correction pass.

        Availability advances only by pops (a split never reveals its
        node to the sim), so members beyond the sim's exact-priority top
        are speculation exactly like the growth overshoot: the replay
        still pops exactly ``budget`` splits in the reference's best-first
        order (`serial_tree_learner.cpp:185-218`), and an unused member
        costs one wasted lid-mask partition while a used one saves a whole
        stall (priority sort + sim re-entry + single correction).  The
        members are distinct frontier leaves with disjoint rows, so the
        sequential lid rewrites commute; bookkeeping and the child split
        scans run ONCE, batched over all members."""
        K = tops.shape[0]
        OOBH = jnp.int32(self.H + 7)
        self._coll_ctx = ("stall_correction", "stall_event")
        bv_i = bvalid.astype(jnp.int32)
        pos = jnp.cumsum(bv_i) - bv_i
        l0s = (st.num_nodes + 2 * pos).astype(jnp.int32)
        r0s = l0s + 1
        phs = st.hslot[tops]
        rhs = (1 + st.num_splits + pos).astype(jnp.int32)
        h_t = st.hist_pool[0]
        bins_p, w_p = st.bins_p, st.w_p   # read-only: no rows move
        # MATERIALIZED covering spans: for a child deferred by sort
        # alternation, node_i holds its logical (post-sort) window but the
        # rows physically sit in the parent's span — phys_i tracks that,
        # which also lets the growth loop skip the pre-replay
        # materialization sort entirely
        spans = st.phys_i[tops]           # (K, 2)
        # Partition stage — UNROLLED over the (static, small) K:
        # straight-line code whose only sequential state is the lid-lane
        # dynamic-update chain, which XLA aliases in place (a fori_loop
        # here paid ~0.35 ms of while-loop overhead per correction event
        # on v5e, and a first cut with per-member histograms inside the
        # loop paid ~0.4 ms per member in switch dispatches)
        lid_p = st.lid_p
        cs = jnp.where(bvalid, spans[:, 1], 0)

        def part_two_stage(lid_in):
            # the TOP (member 0) partitions through its own bucket switch
            # — its span is ungated; an invalid/zero-count member
            # degrades to a zero-row no-op in the smallest bucket, writes
            # masked or dropped
            crow0 = st.cand_i[tops[0]]
            lid2, lc0, c0 = lax.switch(
                self._bucket_idx(jnp.maximum(cs[0], 1)),
                self._stall_mask_branches, bins_p, w_p, lid_in,
                spans[0, 0], cs[0], tops[0], crow0[CI_FEAT],
                crow0[CI_THR], (crow0[CI_FLAGS] & 1) == 1,
                (crow0[CI_FLAGS] & 2) == 2, st.cand_b[tops[0]],
                l0s[0], r0s[0])
            if K == 1:
                return lid2, lc0[None], c0[None]
            # the EXTRAS (span-gated <= _VEC_CAP in do_stall) partition
            # in ONE vectorized stage
            ci_e = st.cand_i[tops[1:]]
            vsz = self._vec_sizes_arr
            vidx = jnp.sum(jnp.maximum(jnp.max(cs[1:]), 1)
                           > vsz).astype(jnp.int32)
            vidx = jnp.minimum(vidx, len(self._stall_vec_branches) - 1)
            lid2, lc_e, c_e = lax.switch(
                vidx, self._stall_vec_branches, bins_p, w_p, lid2,
                spans[1:, 0], cs[1:], tops[1:], ci_e[:, CI_FEAT],
                ci_e[:, CI_THR], (ci_e[:, CI_FLAGS] & 1) == 1,
                (ci_e[:, CI_FLAGS] & 2) == 2, st.cand_b[tops[1:]],
                l0s[1:], r0s[1:])
            return (lid2, jnp.concatenate([lc0[None], lc_e]),
                    jnp.concatenate([c0[None], c_e]))

        if K > 1 and self._stall_fuse_top and top_fits is not None:
            # when the top's span ALSO fits the vec cap (the common case
            # — big spans stall early, at the top of the tree), the
            # whole event is ONE masked pass: one switch dispatch instead
            # of two.  Exact: both stages share _span_decide and the lid
            # rewrites are disjoint.  top_fits is REPLICATED (do_stall
            # derives it from the pmax'd spans), so the cond cannot
            # diverge across shards
            def part_fused(lid_in):
                ci_a = st.cand_i[tops]
                vsz = self._vec_sizes_arr
                vidx = jnp.sum(jnp.maximum(jnp.max(cs), 1)
                               > vsz).astype(jnp.int32)
                vidx = jnp.minimum(vidx,
                                   len(self._stall_vec_branches_all) - 1)
                return lax.switch(
                    vidx, self._stall_vec_branches_all, bins_p, w_p,
                    lid_in, spans[:, 0], cs, tops, ci_a[:, CI_FEAT],
                    ci_a[:, CI_THR], (ci_a[:, CI_FLAGS] & 1) == 1,
                    (ci_a[:, CI_FLAGS] & 2) == 2, st.cand_b[tops],
                    l0s, r0s)

            lid_p, lc_s, c_s = lax.cond(top_fits, part_fused,
                                        part_two_stage, lid_p)
        else:
            lid_p, lc_s, c_s = part_two_stage(lid_p)
        # ONE count sync (the sharded learners psum the (K,) pair once
        # instead of per member)
        lc_a, c_a = self._sync_counts(lc_s, c_s)
        left_small = lc_a <= (c_a - lc_a)
        sm_slot = jnp.where(left_small, l0s, r0s)
        # Histogram stage — ONE segment-kernel pass over every member's
        # smaller child (same machinery as the wave member hists), then
        # batched sibling subtraction from the parents' pooled histograms
        st2 = st._replace(lid_p=lid_p)
        if self._use_pallas:
            t_cap = K * (self._rows_len() // self._seg_rb + 2) + 1
            h_small = self._reduce_hist_batch(self._segment_hists(
                st2, sm_slot, spans[:, 0], cs, bvalid, t_cap=t_cap))
        else:
            # stack the K member histograms and reduce ONCE — the sharded
            # seam exchanges one (K, F, B, 3) collective per correction
            # event, matching _wave_member_hists' single psum_scatter per
            # wave (a per-member loop issued K collectives per event)
            h_small = self._reduce_hist_batch(jnp.stack([
                lax.switch(
                    self._bucket_idx(jnp.maximum(cs[i], 1)),
                    self._hist_branches, bins_p, w_p, lid_p, spans[i, 0],
                    cs[i], sm_slot[i])
                for i in range(K)]))
        h_par = st.hist_pool[phs]                     # (K, F, B, 3)
        h_large = h_par - h_small
        lsm = left_small[:, None, None, None]
        hl = jnp.where(lsm, h_small, h_large)
        hr = jnp.where(lsm, h_large, h_small)
        hists2 = jnp.stack([hl, hr], 1).reshape((2 * K,) + h_t.shape)
        # ONE masked pool write outside the loop (the pool never rides
        # the loop carry)
        i2 = jnp.stack([jnp.where(bvalid, phs, OOBH),
                        jnp.where(bvalid, rhs, OOBH)], 1).reshape(-1)
        st = st._replace(
            lid_p=lid_p,
            hist_pool=st.hist_pool.at[i2].set(hists2, mode="drop"))
        return self._children_bookkeeping(
            st, tops, bvalid, l0s, r0s, lc_a, c_a, spans, spans, phs, rhs,
            hists2, feature_mask)

    # -- exact greedy replay --------------------------------------------------

    def _replay(self, st: WaveState, feature_mask):
        """Re-derive the exact best-first pop order over the grown forest
        (`serial_tree_learner.cpp:185-218`), splitting on demand when the
        replay reaches a leaf the growth never split.

        Two-level loop; the INNER sim pops a whole BATCH per iteration
        instead of one leaf.  Every grown node's children's gains are
        already known (``cand_f``), so after sorting the available set by
        (gain desc, leaf-index asc) — the reference's pop priority — the
        leading prefix can pop at once as long as each member's gain
        strictly exceeds every child gain revealed by the members before it
        (such a child could never jump ahead of them); gain TIES against a
        revealed child stop the prefix, deferring to the next iteration
        where the child is available with its leaf index assigned, so the
        lowest-leaf-index tie-break (`serial_tree_learner.cpp:505-520`) is
        preserved exactly.  Real trees pop in a few descending-gain runs,
        so ~254 sequential pops (~28 ms of tiny-op latency on the real
        chip) become ~a dozen batched iterations.

        The OUTER loop — one iteration per speculation miss, usually zero
        total — re-enters after performing a missing split."""
        if self._stall_batch > 1:
            self._stall_mask_branches = [self._make_stall_mask_branch(S)
                                         for S in self._win_sizes]
            vec_sizes = [S for S in self._win_sizes if S <= self._vec_cap]
            if not vec_sizes:
                vec_sizes = [self._win_sizes[0]]
            self._vec_sizes_arr = jnp.asarray(vec_sizes, dtype=jnp.int32)
            self._stall_vec_branches = [
                self._make_stall_vec_branch(S, self._stall_batch - 1)
                for S in vec_sizes]
            if self._stall_fuse_top:
                # K-wide variant for events whose TOP also fits the vec
                # cap: the whole correction partitions in ONE masked pass
                self._stall_vec_branches_all = [
                    self._make_stall_vec_branch(S, self._stall_batch)
                    for S in vec_sizes]
        M, budget = self.M, self.budget
        OOB = jnp.int32(M + 7)
        NEG = jnp.finfo(jnp.float32).min

        def outer_cond(carry):
            return carry[-1] == 0  # 0 = need (another) sim pass

        def outer_body(carry):
            (st, avail_n, refidx, pops, leaf_cnt, poprec, stalls, extras,
             _) = carry
            gains = st.cand_f[:, CF_GAIN].astype(self._acc)
            split_m = st.split_m
            child0 = st.child0
            iota = jnp.arange(M, dtype=jnp.int32)
            # ONE gain-priority sort per pass (gains are fixed within a
            # pass; only availability changes between iterations) — the
            # slot-ascending secondary key is only a stand-in for the
            # refidx tie-break, so batches containing an exact gain tie
            # fall back to a single exact-priority pop
            _, _, order = lax.sort([-gains, iota, iota], num_keys=2,
                                   is_stable=True)
            g_o = gains[order]
            sp_o = split_m[order]
            c0_o = child0[order]
            cg_o = jnp.where(sp_o,
                             jnp.maximum(gains[c0_o], gains[c0_o + 1]),
                             NEG)

            # ---- inner sim: flag 0 = running, 1 = stall, 2 = done
            def icond(ic):
                return ic[-2] == 0

            def ibody(ic):
                avail_n, refidx, pops, leaf_cnt, poprec, _, _ = ic
                cand = avail_n[order]
                gc = jnp.where(cand, g_o, NEG)
                # exclusive running max of revealed-child gains over the
                # available candidates
                pmax = lax.cummax(jnp.concatenate(
                    [jnp.full((1,), NEG, cg_o.dtype),
                     jnp.where(cand, cg_o, NEG)[:-1]]))
                apos = jnp.cumsum(cand.astype(jnp.int32)) - 1
                ok = cand & (g_o > 0.0) & sp_o & (g_o > pmax) & \
                    (apos < budget - pops)
                alive = jnp.cumprod((ok | ~cand).astype(jnp.int32)) == 1
                inb = ok & alive
                # ANY exact gain tie among available candidates -> single
                # exact pop (covers batch-internal ties AND a tie between a
                # prefix member and a blocked/unsplit candidate with lower
                # refidx; a plateau of duplicated-feature gains degrades to
                # sequential pops, which is the exact semantics)
                pa = lax.cummax(jnp.concatenate(
                    [jnp.full((1,), -1, jnp.int32),
                     jnp.where(cand, iota, -1)[:-1]]))
                tie = jnp.any(cand & (pa >= 0) & (g_o > 0.0) &
                              (g_o == g_o[jnp.maximum(pa, 0)]))
                g0 = jnp.max(gc)
                # exact-priority top: lowest refidx among max-gain avail
                tb = jnp.where(cand & (g_o == g0), refidx[order],
                               jnp.int32(1 << 30))
                pstar = jnp.argmin(tb).astype(jnp.int32)
                proceed0 = (g0 > 0.0) & (pops < budget)
                # single-pop mode: a gain tie inside the prefix, or an
                # empty prefix while the exact top is poppable (a same-gain
                # unsplit node ahead of it blocked the prefix)
                npop0 = jnp.sum(inb.astype(jnp.int32))
                single = tie | ((npop0 == 0) & proceed0 & sp_o[pstar])
                inb = jnp.where(single, (iota == pstar) & sp_o[pstar], inb)
                npop = jnp.sum(inb.astype(jnp.int32)).astype(jnp.int32)
                flag = jnp.where(
                    npop > 0, jnp.int32(0),
                    jnp.where(proceed0 & ~sp_o[pstar], jnp.int32(1),
                              jnp.int32(2)))
                top = order[pstar]
                tie = single
                # ---- execute the batch (apos == pop position: the prefix
                # property makes every earlier available node popped; in
                # tie mode the single pop is position 0 by construction)
                bpos = jnp.where(tie, 0, apos)
                nd = jnp.where(inb, order, OOB)
                c0b = jnp.where(inb, c0_o, OOB)
                ref_nd = refidx[jnp.where(inb, order, 0)]
                poprec = poprec.at[jnp.where(inb, pops + bpos,
                                             jnp.int32(budget + 7))].set(
                    jnp.stack([nd, ref_nd], axis=1), mode="drop")
                refidx = refidx.at[c0b].set(ref_nd, mode="drop") \
                               .at[c0b + 1].set(leaf_cnt + bpos,
                                                mode="drop")
                avail_n = avail_n.at[nd].set(False, mode="drop") \
                                 .at[c0b].set(True, mode="drop") \
                                 .at[c0b + 1].set(True, mode="drop")
                return (avail_n, refidx, pops + npop, leaf_cnt + npop,
                        poprec, flag, top)

            ic = lax.while_loop(
                icond, ibody,
                (avail_n, refidx, pops, leaf_cnt, poprec,
                 jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32)))
            avail_n, refidx, pops, leaf_cnt, poprec, flag, top = ic

            Kb = self._stall_batch
            if Kb == 1:
                def do_stall1(s):
                    sort_c = (s.node_i[top, 1]
                              > jnp.int32(self._stall_cutoff))
                    s2 = self._stall_split(s, top, feature_mask)
                    if s2.telem is not None:
                        s2 = s2._replace(
                            telem=s2.telem.at[TEL_STALL_SORT_MODE].add(
                                sort_c.astype(jnp.int32)))
                    return s2, jnp.int32(1)

                st, nsp = lax.cond(flag == 1, do_stall1,
                                   lambda s: (s, jnp.int32(0)), st)
                return (st, avail_n, refidx, pops, leaf_cnt, poprec,
                        stalls + nsp, extras,
                        jnp.where(flag == 1, jnp.int32(0), flag))

            def do_stall(s):
                # split the top-Kb REPLAY-PRIORITY (gain desc, refidx asc)
                # available unsplit leaves at once.  The first is provably
                # the sim's stalled top — flag==1 means the min-refidx
                # max-gain available node is unsplit, and restricting the
                # min to the unsplit subset it belongs to can't change it —
                # so it stays available with its unchanged gain and the
                # next pass pops it; later members are the likeliest
                # upcoming stalls
                cand_u = avail_n & ~s.split_m & (gains > 0.0)
                gk = jnp.where(cand_u, -gains, jnp.inf)
                rk = jnp.where(cand_u, refidx, jnp.int32(1 << 30))
                _, _, osel = lax.sort([gk, rk, iota], num_keys=2,
                                      is_stable=True)
                tops_k = osel[:Kb]
                bv = cand_u[tops_k]
                # EXTRAS (members beyond the top) count against the
                # dedicated _stall_extras_cap reserve and must fit the
                # vectorized partition's slice cap; the top itself is
                # always safe — each top maps to a distinct pop, which the
                # budget-sized share of the reserve covers
                head = (extras + jnp.arange(-1, Kb - 1, dtype=jnp.int32)) \
                    < jnp.int32(self._extras_cap)
                # the gate must be REPLICATED: phys_i spans are local
                # window geometry in the row-sharded learners, and a leaf
                # whose local span straddles the cap on only some shards
                # would otherwise diverge bv (and with it num_nodes /
                # split_m / the extras counter) across devices
                fits = self._replicated_spans(s.phys_i[tops_k, 1]) \
                    <= jnp.int32(self._vec_cap)
                bv = bv & ((head & fits) | (jnp.arange(Kb) == 0))
                s2 = self._stall_split_batch(s, tops_k, bv, feature_mask,
                                             top_fits=fits[0])
                nsp = jnp.sum(bv, dtype=jnp.int32).astype(jnp.int32)
                return s2, nsp, nsp - bv[0].astype(jnp.int32)

            st, nsp, nex = lax.cond(
                flag == 1, do_stall,
                lambda s: (s, jnp.int32(0), jnp.int32(0)), st)
            # stall -> another sim pass (flag back to 0); done stays 2
            return (st, avail_n, refidx, pops, leaf_cnt, poprec,
                    stalls + nsp, extras + nex,
                    jnp.where(flag == 1, jnp.int32(0), flag))

        avail0 = jnp.zeros(M, bool).at[0].set(True)
        init = (st, avail0,
                jnp.full(M, -1, jnp.int32).at[0].set(0),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(1, jnp.int32),
                jnp.zeros((budget, 2), jnp.int32),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(0, jnp.int32))
        (st, avail_n, refidx, pops, leaf_cnt, poprec, stalls, _extras,
         _) = lax.while_loop(outer_cond, outer_body, init)
        if st.telem is not None:
            st = st._replace(telem=st.telem
                             .at[TEL_STALL_SPLITS].set(stalls)
                             .at[TEL_STALL_EXTRAS].set(_extras)
                             .at[TEL_POPS].set(pops))
        pop_nodes, pop_ref = poprec[:, 0], poprec[:, 1]
        # final frontier = revealed (root or child of a popped node) and
        # never popped — reconstructed from the pop list
        vp = jnp.arange(budget) < pops
        ndw = jnp.where(vp, pop_nodes, OOB)
        c0p = jnp.where(vp, st.child0[jnp.where(vp, pop_nodes, 0)], OOB)
        revealed = jnp.zeros(M, bool).at[0].set(True) \
            .at[c0p].set(True, mode="drop") \
            .at[c0p + 1].set(True, mode="drop")
        popped = jnp.zeros(M, bool).at[ndw].set(True, mode="drop")
        avail = revealed & ~popped
        return st, avail, refidx, pops, pop_nodes, pop_ref, stalls

    # -- whole tree -----------------------------------------------------------

    def _train_tree_wave(self, bins_p, grad, hess, bag, feature_mask):
        self._ledger.begin_trace()
        self._use_fused = self._fused_ok()
        self._hist_branches = [self._make_hist_branch(S)
                               for S in self._win_sizes]
        self._stall_branches = [
            self._make_stall_branch(S, sort_mode=S > self._stall_cutoff)
            for S in self._win_sizes]
        st = self._init_root_wave(bins_p, grad, hess, bag, feature_mask)
        # level-wise opening: the first L levels grow unsorted (level d has
        # at most 2^d members), then ONE materialization sort compacts
        # every window; a level with nothing to split is an exact no-op
        for d in range(self.open_levels):
            st = self._wave_body(st, feature_mask,
                                 width=min(1 << d, self.W), opening=True)
        if self.open_levels > 0:
            st = lax.cond(st.pending, self._materialize_sort,
                          lambda s: s, st)

        def gcond(s):
            return (s.num_splits < self.grow_budget) & \
                (jnp.max(self._pool_gains(s)) > 0.0)

        st = lax.while_loop(gcond, lambda s: self._wave_step(s, feature_mask),
                            st)
        if self._defer_sorts and self._stall_batch == 1:
            # the growth loop may exit on a deferring wave — the K=1
            # replay's stall splits slice PHYSICAL windows, so materialize
            # first.  Batched (K>1) corrections mask through phys_i
            # covering spans instead, so they skip this sort
            st = lax.cond(st.pending, self._materialize_sort,
                          lambda s: s, st)
        return self._emit_tree_wave(st, feature_mask)

    def _emit_tree_wave(self, st: WaveState, feature_mask):
        """Exact greedy replay + host-record emission + speculative-leaf
        mapping (shared by the serial and sharded wave learners — the
        replay operates on replicated node state only)."""
        if st.telem is not None:
            st = st._replace(
                telem=st.telem.at[TEL_GROW_SPLITS].set(st.num_splits))
        st, avail, refidx, pops, pop_nodes, pop_ref, _stalls = self._replay(
            st, feature_mask)
        if st.telem is not None:
            st = st._replace(
                telem=st.telem.at[TEL_TOTAL_SPLITS].set(st.num_splits))

        # ---- emit host records in pop order
        budget = self.budget
        vp = jnp.arange(budget) < pops
        nd = jnp.where(vp, pop_nodes, 0)
        cf = st.cand_f[nd].astype(jnp.float32)
        ci = st.cand_i[nd]
        nf = st.node_f[nd].astype(jnp.float32)
        rec_f = jnp.stack([
            vp.astype(jnp.float32),
            pop_ref.astype(jnp.float32),
            ci[:, CI_FEAT].astype(jnp.float32),
            ci[:, CI_THR].astype(jnp.float32),
            (ci[:, CI_FLAGS] & 1).astype(jnp.float32),
            cf[:, CF_GAIN],
            cf[:, CF_LOUT], cf[:, CF_ROUT],
            cf[:, CF_LCNT], cf[:, CF_RCNT],
            nf[:, LF_OUT], nf[:, LF_CNT],
            cf[:, CF_LSH], cf[:, CF_RSH],
            cf[:, CF_LSG], cf[:, CF_RSG],
            ((ci[:, CI_FLAGS] & 2) >> 1).astype(jnp.float32)], axis=1)
        assert rec_f.shape[1] == NUM_REC_FIELDS
        rec_i = st.cnt_i[nd]
        rec_cat = st.cand_b[nd]

        # ---- map speculative leaves to their final ancestors
        final = avail  # revealed and never popped
        iota = jnp.arange(self.M, dtype=jnp.int32)
        T = jnp.where(final, iota, st.parent)
        # pointer-jump doubling: k iterations cover chains of 2^k; chain
        # depth is bounded by the node count M
        for _ in range(max(1, (self.M - 1).bit_length())):
            T = T[T]
        slot2ref = jnp.where(final[T], refidx[T], 0)
        # chunked lookup: the (rows, M_pad) one-hot transient is bounded to
        # ~2^17 rows per step regardless of N (at 10.5M rows an unchunked
        # one-hot would be ~24 GB)
        Cl = 1
        while self._rows_len() // Cl > (1 << 17) and Cl < 1024 \
                and self._rows_len() % (Cl * 2) == 0:
            Cl *= 2
        if Cl == 1:
            leaf_ref = lookup_int(slot2ref, st.lid_p)
        else:
            leaf_ref = lax.map(
                lambda lid_c: lookup_int(slot2ref, lid_c),
                st.lid_p.reshape(Cl, self._rows_len() // Cl)).reshape(-1)
        # descatter to original row order by sorting on rid (a 2-lane sort
        # is ~3x cheaper than the equivalent scatter on TPU)
        leaf_id = lax.sort([st.rid_p, leaf_ref], num_keys=1)[1]
        leaf_out = jnp.zeros(self.num_leaves, jnp.float32).at[
            jnp.where(final, refidx, self.num_leaves + 7)].set(
                st.node_f[:, LF_OUT].astype(jnp.float32))
        if self._quant and self._q_raw is not None:
            # leaf-output RENEWAL (the quantized-training recipe's
            # accuracy anchor): per-leaf sums re-accumulated from the
            # RETAINED f32 gradients over the final leaf assignment, so
            # leaf values carry no discretization error — only the split
            # STRUCTURE sees quantized sums.  Patches both the score
            # update (leaf_out) and the host records' child outputs.
            from .ops.split import calculate_leaf_output
            gb, hb = self._q_raw
            self._q_raw = None
            L = self.num_leaves
            kw = self._split_kwargs
            # FIXED-POINT accumulation: the renewed outputs feed the score,
            # and the next round's stochastic rounding keys on the score's
            # BIT PATTERN — a 1-ulp f32 summation-order difference between
            # serial and sharded would re-roll the rounding and fork the
            # tree stream.  Rounding each row to a pow2 grid and summing
            # int32 makes the reduction exact at any shard order; the grid
            # leaves k = 30 - ceil_log2(N) bits per row (>= 9 bits under
            # the F32_EXACT_ROWS gate), noise far below the quantization
            # the splits already tolerate.
            sg, sh = self._q_scales
            kb = max(30 - int(self.n_pad - 1).bit_length(), 1)
            qg = sg * jnp.float32(2.0 ** (3 - kb))    # sg·GMAX <= sg·2^3
            qh = sh * jnp.float32(2.0 ** (4 - kb))    # sh·HMAX <= sh·2^4
            rg = jnp.rint(gb / qg).astype(jnp.int32)
            rh = jnp.rint(hb / qh).astype(jnp.int32)
            lgh = jnp.zeros((2, L), jnp.int32) \
                .at[0, leaf_id].add(rg).at[1, leaf_id].add(rh)
            lgh = self._global_scalar(lgh)
            lg = lgh[0].astype(jnp.float32) * qg
            lh = lgh[1].astype(jnp.float32) * qh
            has_h = lh > 0.0
            refined = jnp.where(
                has_h,
                calculate_leaf_output(
                    lg, lh, kw["lambda_l1"], kw["lambda_l2"],
                    kw["max_delta_step"]).astype(jnp.float32),
                0.0)
            leaf_out = jnp.where(has_h, refined, leaf_out)
            # pop i's left child keeps ref pop_ref[i]; its right child is
            # ref 1 + i (the replay's leaf numbering)
            lref = jnp.clip(pop_ref, 0, L - 1)
            rref = jnp.minimum(jnp.arange(budget, dtype=jnp.int32) + 1,
                               L - 1)
            from .learner import REC_LEFT_OUT, REC_RIGHT_OUT
            rec_f = rec_f \
                .at[:, REC_LEFT_OUT].set(
                    jnp.where(vp & has_h[lref], refined[lref],
                              rec_f[:, REC_LEFT_OUT])) \
                .at[:, REC_RIGHT_OUT].set(
                    jnp.where(vp & has_h[rref], refined[rref],
                              rec_f[:, REC_RIGHT_OUT]))
        if st.telem is not None:
            return rec_f, rec_i, rec_cat, leaf_id, leaf_out, st.telem
        return rec_f, rec_i, rec_cat, leaf_id, leaf_out

    # -- host orchestration ---------------------------------------------------

    def memory_gauges(self) -> dict:
        """Working-set byte breakdown for the telemetry report — the SAME
        formula the eligibility gate uses (``wave_transient_bytes``), over
        this learner's actual (bundled / local-shard) dimensions."""
        return wave_transient_bytes(self.cfg, self._rows_len(),
                                    self.fw * 4, self._hist_nbins)

    def _pop_telem(self, out):
        """Strip the trailing device counter vector off a tree program's
        outputs (stashed for ``take_telemetry``); identity when telemetry
        is off, so every caller keeps its 5-tuple contract."""
        if self._telemetry:
            self._last_telem = out[5]
            return out[:5]
        return out

    def train_async(self, grad: jax.Array, hess: jax.Array, bag: jax.Array,
                    feature_mask: Optional[jax.Array] = None):
        if feature_mask is None:
            feature_mask = jnp.ones(self.num_features, dtype=bool)
        out = self._jit_tree_w(
            self.bins_packed(), grad, hess, bag, feature_mask)
        if getattr(self, "_tree_w_bitcast", False):
            # undo the donation landing-slot bitcast (see __init__):
            # leaf_id rides out of the donating jit as f32 bits
            leaf_id = jax.lax.bitcast_convert_type(out[3], jnp.int32)
            out = out[:3] + (leaf_id,) + out[4:]
        return self._pop_telem(out)


def wave_transient_bytes(cfg: Config, n_pad: int, f_pad: int, b: int
                         ) -> dict:
    """Working-set byte breakdown of the wave learner (``n_pad`` is the
    PER-DEVICE row count for sharded use).  Single source of truth for
    ``wave_budget_reason``'s gate AND the telemetry memory gauge
    (``WaveTPUTreeLearner.memory_gauges``) — the budget decision and the
    reported gauge can never disagree."""
    budget = max(int(cfg.num_leaves), 2) - 1
    W = min(int(cfg.tpu_wave_width), budget)
    grow = min(budget + int(np.ceil(budget
                                    * _resolve_overshoot(cfg, n_pad))),
               2 * budget)
    corr = _correction_reserve(cfg, budget)
    M = 1 + 2 * (grow + corr)
    h_bytes = (grow + corr + 2) * f_pad * b * 3 * 4
    scan_bytes = 2 * W * f_pad * b * 3 * 4
    # per-wave transients (round-3 advisor): the (rows, W) f32 wave-member
    # mask is CHUNKED to 2^20 rows (lax.map in _wave_body) and the
    # leaf-ref lookup one-hot to 2^17 rows, so neither scales with N; the
    # (N,) derived per-row columns do
    m_pad = ((M + 127) // 128) * 128
    mask_bytes = min(n_pad, 1 << 20) * W * 4 + n_pad * 12
    lookup_bytes = min(n_pad, 1 << 17) * m_pad * 4
    # double-buffered sort operands (key + fw words + 3 weights + rid +
    # lid).  Also covers partition mode: the permute kernel's bf16
    # byte-plane output is (4·fw + 17) * 2 bytes/row ≈ (8·fw + 34)·n vs
    # the sort's (8·fw + 48)·n, so the sort term is the conservative
    # bound for either flow
    sort_bytes = 2 * (f_pad // 4 + 6) * n_pad * 4
    # batched replay correction: the vectorized partition stacks the K-1
    # extras' (fw, S) bin-word + (3, S) weight + (S,) lid slices, S up to
    # the vec cap — on wide datasets (fw in the hundreds) this per-event
    # transient is material and must count against the budget (round-5
    # advisor, low)
    k = _resolve_stall_batch(cfg)
    vc = int(getattr(cfg, "tpu_wave_vec_cap", -1))
    if vc <= 0:
        vc = WaveTPUTreeLearner._VEC_CAP
    # k (not k-1) slices: the fused-top path stacks every member's slice
    stall_vec_bytes = 0 if k == 1 else \
        k * min(vc, n_pad) * (f_pad // 4 + 4) * 4
    out = {"hist_pool_bytes": h_bytes, "child_scan_bytes": scan_bytes,
           "wave_mask_bytes": mask_bytes, "leaf_lookup_bytes": lookup_bytes,
           "sort_buffer_bytes": sort_bytes,
           "stall_vec_bytes": stall_vec_bytes}
    out["total_bytes"] = sum(out.values())
    return out


def wave_budget_reason(cfg: Config, n_pad: int, f_pad: int, b: int
                       ) -> Optional[str]:
    """Shape/byte-budget gates shared by the serial and sharded wave
    learners (``n_pad`` is the PER-DEVICE row count for sharded use)."""
    if f_pad // 4 > 64:
        return f"{f_pad} padded columns > 256 (per-row word extraction is " \
               "a masked sum over words)"
    total = wave_transient_bytes(cfg, n_pad, f_pad, b)["total_bytes"]
    if total > int(cfg.tpu_wave_max_bytes):
        return "estimated working set %.1f GB > tpu_wave_max_bytes %.1f GB" \
            % (total / 2**30, int(cfg.tpu_wave_max_bytes) / 2**30)
    return None


def wave_ineligible_reason(cfg: Config, data: _ConstructedDataset
                           ) -> Optional[str]:
    """Why the wave learner cannot run this config (None = eligible).
    Sizing uses the BUNDLED (EFB) column layout when a bundle exists —
    that is what the learner actually runs on."""
    if cfg.tree_learner != "serial":
        return f"tree_learner={cfg.tree_learner} (wave is serial-only)"
    if data.max_num_bin > 256:
        return f"max_num_bin={data.max_num_bin} > 256 (bin codes must pack " \
               "4-per-word)"
    bundle = getattr(data, "bundle", None)
    if bundle is not None:
        from .dataset import _round_up
        f_pad = _round_up(bundle.num_groups, data.FEATURE_TILE)
        b = max(int(data.max_num_bin), int(bundle.max_group_bin))
        if b > 256:
            return f"EFB bundle max bin {b} > 256"
    else:
        f_pad = data.bins.shape[0]
        b = int(data.max_num_bin)
    return wave_budget_reason(cfg, int(data.num_data_padded), f_pad, b)


def wave_eligible(cfg: Config, data: _ConstructedDataset) -> bool:
    return wave_ineligible_reason(cfg, data) is None
