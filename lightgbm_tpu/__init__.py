"""lightgbm_tpu — a TPU-native gradient-boosted decision tree framework.

A from-scratch re-design of LightGBM (reference mounted at /root/reference)
for TPU hardware: histogram construction runs as MXU one-hot contractions /
Pallas kernels over a dense binned matrix in HBM, split finding is a
vectorized cumsum scan, tree growth is a jitted leaf-wise step, and the
distributed tree learners route histogram reduction through XLA collectives
over ICI instead of the reference's socket/MPI ``Network`` layer.

Public API mirrors `python-package/lightgbm/__init__.py:32-36`.
"""

from .config import Config
from .dataset import Dataset
from .engine import Booster, CVBooster, cv, train
from .callback import (early_stopping, print_evaluation, record_evaluation,
                       record_telemetry, reset_parameter)

try:  # sklearn wrappers are optional on minimal installs
    from .sklearn import (LGBMClassifier, LGBMModel, LGBMRanker,
                          LGBMRegressor)
    _SKLEARN = ["LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"]
except ImportError:  # pragma: no cover
    _SKLEARN = []

try:
    from .plotting import (create_tree_digraph, plot_importance, plot_metric,
                           plot_tree)
    _PLOT = ["plot_importance", "plot_metric", "plot_tree",
             "create_tree_digraph"]
except ImportError:  # pragma: no cover
    _PLOT = []

__version__ = "2.2.4.tpu0"

__all__ = ["Dataset", "Booster", "CVBooster", "Config",
           "train", "cv",
           "early_stopping", "print_evaluation", "record_evaluation",
           "record_telemetry", "reset_parameter"] + _SKLEARN + _PLOT
