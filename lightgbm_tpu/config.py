"""Typed training configuration with full alias resolution.

TPU-native re-design of the reference config system
(`include/LightGBM/config.h:27-880`, `src/io/config.cpp:15-256`,
`src/io/config_auto.cpp:4-155` alias table).  The reference generates its
parameter plumbing from annotated C++ comments; here a plain dataclass is the
single source of truth and the alias table is an explicit dict.

Semantics preserved:
  * ``key=value`` string parsing (``Config::KV2Map``/``Str2Map``,
    `src/io/config.cpp:15-43`), with ``#`` comments and quoted values.
  * alias resolution before parse (``ParameterAlias::KeyAliasTransform``,
    `src/io/config.cpp:41`); duplicate keys keep the first and warn
    (`src/io/config.cpp:22-27`).
  * cross-field fixups in ``Config::Set`` (`src/io/config.cpp:153-256`):
    objective→boosting inferences, ``is_parallel`` from ``tree_learner``,
    metric defaulting from objective.
"""

from __future__ import annotations

import dataclasses
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Alias table — mirrors `src/io/config_auto.cpp:4-155` exactly.
# ---------------------------------------------------------------------------
ALIAS_TABLE: Dict[str, str] = {
    "config_file": "config",
    "task_type": "task",
    "objective_type": "objective", "app": "objective", "application": "objective",
    "boosting_type": "boosting", "boost": "boosting",
    "train": "data", "train_data": "data", "train_data_file": "data",
    "data_filename": "data",
    "test": "valid", "valid_data": "valid", "valid_data_file": "valid",
    "test_data": "valid", "test_data_file": "valid", "valid_filenames": "valid",
    "num_iteration": "num_iterations", "n_iter": "num_iterations",
    "num_tree": "num_iterations", "num_trees": "num_iterations",
    "num_round": "num_iterations", "num_rounds": "num_iterations",
    "num_boost_round": "num_iterations", "n_estimators": "num_iterations",
    "shrinkage_rate": "learning_rate", "eta": "learning_rate",
    "num_leaf": "num_leaves", "max_leaves": "num_leaves", "max_leaf": "num_leaves",
    "tree": "tree_learner", "tree_type": "tree_learner",
    "tree_learner_type": "tree_learner",
    "num_thread": "num_threads", "nthread": "num_threads",
    "nthreads": "num_threads", "n_jobs": "num_threads",
    "device": "device_type",
    "random_seed": "seed", "random_state": "seed",
    "min_data_per_leaf": "min_data_in_leaf", "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "sub_row": "bagging_fraction", "subsample": "bagging_fraction",
    "bagging": "bagging_fraction",
    "subsample_freq": "bagging_freq",
    "bagging_fraction_seed": "bagging_seed",
    "sub_feature": "feature_fraction", "colsample_bytree": "feature_fraction",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "max_tree_output": "max_delta_step", "max_leaf_output": "max_delta_step",
    "reg_alpha": "lambda_l1",
    "reg_lambda": "lambda_l2", "lambda": "lambda_l2",
    "min_split_gain": "min_gain_to_split",
    "rate_drop": "drop_rate",
    "topk": "top_k",
    "mc": "monotone_constraints", "monotone_constraint": "monotone_constraints",
    "feature_contrib": "feature_contri", "fc": "feature_contri",
    "fp": "feature_contri", "feature_penalty": "feature_contri",
    "fs": "forcedsplits_filename", "forced_splits_filename": "forcedsplits_filename",
    "forced_splits_file": "forcedsplits_filename",
    "forced_splits": "forcedsplits_filename",
    "verbose": "verbosity",
    "subsample_for_bin": "bin_construct_sample_cnt",
    "hist_pool_size": "histogram_pool_size",
    "data_seed": "data_random_seed",
    "model_output": "output_model", "model_out": "output_model",
    "save_period": "snapshot_freq",
    "model_input": "input_model", "model_in": "input_model",
    "predict_result": "output_result", "prediction_result": "output_result",
    "predict_name": "output_result", "prediction_name": "output_result",
    "pred_name": "output_result", "name_pred": "output_result",
    "init_score_filename": "initscore_filename",
    "init_score_file": "initscore_filename", "init_score": "initscore_filename",
    "input_init_score": "initscore_filename",
    "valid_data_init_scores": "valid_data_initscores",
    "valid_init_score_file": "valid_data_initscores",
    "valid_init_score": "valid_data_initscores",
    "is_pre_partition": "pre_partition",
    "is_enable_bundle": "enable_bundle", "bundle": "enable_bundle",
    "is_sparse": "is_enable_sparse", "enable_sparse": "is_enable_sparse",
    "sparse": "is_enable_sparse",
    "two_round_loading": "two_round", "use_two_round_loading": "two_round",
    "is_save_binary": "save_binary", "is_save_binary_file": "save_binary",
    "has_header": "header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column", "group_id": "group_column",
    "query_column": "group_column", "query": "group_column",
    "query_id": "group_column",
    "ignore_feature": "ignore_column", "blacklist": "ignore_column",
    "cat_feature": "categorical_feature",
    "categorical_column": "categorical_feature", "cat_column": "categorical_feature",
    "is_predict_raw_score": "predict_raw_score",
    "predict_rawscore": "predict_raw_score", "raw_score": "predict_raw_score",
    "is_predict_leaf_index": "predict_leaf_index", "leaf_index": "predict_leaf_index",
    "is_predict_contrib": "predict_contrib", "contrib": "predict_contrib",
    "convert_model_file": "convert_model",
    "num_classes": "num_class",
    "unbalance": "is_unbalance", "unbalanced_sets": "is_unbalance",
    "metrics": "metric", "metric_types": "metric",
    "output_freq": "metric_freq",
    "training_metric": "is_provide_training_metric",
    "is_training_metric": "is_provide_training_metric",
    "train_metric": "is_provide_training_metric",
    "ndcg_eval_at": "eval_at", "ndcg_at": "eval_at",
    "map_eval_at": "eval_at", "map_at": "eval_at",
    "num_machine": "num_machines",
    "local_port": "local_listen_port", "port": "local_listen_port",
    "machine_list_file": "machine_list_filename",
    "machine_list": "machine_list_filename", "mlist": "machine_list_filename",
    "workers": "machines", "nodes": "machines",
    # multi-host pod (parallel/multihost.py)
    "coordinator": "coordinator_address",
    "num_processes": "num_hosts", "num_process": "num_hosts",
    # elastic pod training (lightgbm_tpu/elastic/)
    "elastic_training": "elastic",
    "max_recoveries": "elastic_max_recoveries",
    "min_ranks": "elastic_min_ranks",
    # out-of-core streaming loader
    "chunk_rows": "stream_chunk_rows",
    "out_of_core": "two_round",
    # observability (so the CLI flags --stats-out / --stats-interval land
    # on the serve_* keys)
    "stats_out": "serve_stats_out",
    "stats_interval": "serve_stats_interval",
    "trace_file": "trace_out",
    "sync_every": "telemetry_sync_every",
    "skew_warn_ratio": "telemetry_skew_warn_ratio",
    "prom_out": "telemetry_prom_out",
}

_OBJECTIVE_ALIASES = {
    # Config::Set maps some objective values (`src/io/config.cpp:175-190` region
    # handled in objective factory `src/objective/objective_function.cpp:10-82`)
    "regression_l2": "regression", "mean_squared_error": "regression",
    "mse": "regression", "l2_root": "regression", "root_mean_squared_error": "regression",
    "rmse": "regression",
    "l1": "regression_l1", "mean_absolute_error": "regression_l1",
    "mae": "regression_l1",
    "mean_absolute_percentage_error": "mape",
    "l2": "regression",
    "multiclass_ova": "multiclassova", "ova": "multiclassova", "ovr": "multiclassova",
    "xentropy": "cross_entropy", "xentlambda": "cross_entropy_lambda",
    "rf": "random_forest",
}

_BOOSTING_ALIASES = {"gbrt": "gbdt", "random_forest": "rf", "dropout": "dart"}


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return bool(v)
    s = str(v).strip().lower()
    if s in ("true", "1", "yes", "on", "+"):
        return True
    if s in ("false", "0", "no", "off", "-"):
        return False
    raise ValueError(f"cannot parse boolean from {v!r}")


def _parse_int_list(v: Any) -> List[int]:
    if isinstance(v, (list, tuple)):
        return [int(x) for x in v]
    s = str(v).strip()
    if not s:
        return []
    return [int(x) for x in s.replace(" ", ",").split(",") if x != ""]


def _parse_float_list(v: Any) -> List[float]:
    if isinstance(v, (list, tuple)):
        return [float(x) for x in v]
    s = str(v).strip()
    if not s:
        return []
    return [float(x) for x in s.replace(" ", ",").split(",") if x != ""]


def _parse_str_list(v: Any) -> List[str]:
    if isinstance(v, (list, tuple)):
        return [str(x) for x in v]
    s = str(v).strip()
    if not s:
        return []
    return [x for x in s.split(",") if x != ""]


@dataclass
class Config:
    """All training parameters (reference: `include/LightGBM/config.h:27-880`)."""

    # --- core ---
    task: str = "train"
    objective: str = "regression"
    boosting: str = "gbdt"
    data: str = ""
    valid: List[str] = field(default_factory=list)
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    tree_learner: str = "serial"
    num_threads: int = 0
    device_type: str = "tpu"
    seed: int = 0

    # --- learning control ---
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    feature_fraction: float = 1.0
    feature_fraction_seed: int = 2
    early_stopping_round: int = 0
    max_delta_step: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    # DART
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4
    # GOSS
    top_rate: float = 0.2
    other_rate: float = 0.1
    # categorical
    min_data_per_group: int = 100
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    # voting parallel
    top_k: int = 20
    monotone_constraints: List[int] = field(default_factory=list)
    feature_contri: List[float] = field(default_factory=list)
    forcedsplits_filename: str = ""
    refit_decay_rate: float = 0.9
    verbosity: int = 1

    # --- IO / dataset ---
    max_bin: int = 255
    min_data_in_bin: int = 3
    bin_construct_sample_cnt: int = 200000
    histogram_pool_size: float = -1.0
    data_random_seed: int = 1
    output_model: str = "LightGBM_model.txt"
    snapshot_freq: int = -1
    # snapshot retention: keep only the newest K snapshot_iter_* files
    # (0 or less = keep everything) — `reliability/resume.py`
    snapshot_keep: int = 3
    # crash-safe resume: auto-detect the newest VALID snapshot of
    # output_model (model text complete + config fingerprint matching),
    # continue-train from it, and train only the remaining iterations.
    # CLI: `--resume`.  No valid snapshot = train from scratch.
    resume: bool = False
    input_model: str = ""
    output_result: str = "LightGBM_predict_result.txt"
    initscore_filename: str = ""
    valid_data_initscores: List[str] = field(default_factory=list)
    pre_partition: bool = False
    enable_bundle: bool = True
    max_conflict_rate: float = 0.0
    is_enable_sparse: bool = True
    sparse_threshold: float = 0.8
    use_missing: bool = True
    zero_as_missing: bool = False
    # out-of-core streaming ingestion (`io/parser.py:iter_data_chunks` +
    # `dataset.py:construct_streaming`): read the text file in passes of
    # stream_chunk_rows-row chunks instead of materializing the full matrix
    # — pass 1 counts rows, pass 2 collects the bin-finding sample, pass 3
    # bins chunkwise straight into the packed device word layout.  Mappers,
    # binned words, and trained models are bit-identical to the in-memory
    # path (tests/test_out_of_core.py).  The reference's two_round flag
    # (`config.h:227` use_two_round_loading) gates the same trade.
    two_round: bool = False
    stream_chunk_rows: int = 65536
    save_binary: bool = False
    header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_feature: str = ""
    # predict
    predict_raw_score: bool = False
    predict_leaf_index: bool = False
    predict_contrib: bool = False
    num_iteration_predict: int = -1
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0
    convert_model_language: str = ""
    convert_model: str = "gbdt_prediction.cpp"

    # --- objective ---
    num_class: int = 1
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0
    sigmoid: float = 1.0
    boost_from_average: bool = True
    reg_sqrt: bool = False
    alpha: float = 0.9
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    tweedie_variance_power: float = 1.5
    max_position: int = 20
    label_gain: List[float] = field(default_factory=list)

    # --- metric ---
    metric: List[str] = field(default_factory=list)
    metric_freq: int = 1
    is_provide_training_metric: bool = False
    eval_at: List[int] = field(default_factory=lambda: [1, 2, 3, 4, 5])

    # --- network ---
    num_machines: int = 1
    # device-mesh shape for the parallel tree learners: "" / "auto" = all
    # local devices (2-D auto-factored for tree_learner=data_feature);
    # "8" = a flat 8-device mesh; "2x4" = a (data=2, feature=4) grid
    # (`parallel/sharding.py:parse_mesh_shape`)
    parallel_mesh: str = ""
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_filename: str = ""
    machines: str = ""
    # --- multi-host pod (parallel/multihost.py) ---
    # jax.distributed coordinator "host:port"; empty = single-host (or the
    # LGBT_COORDINATOR environment variable)
    coordinator_address: str = ""
    # number of participating host PROCESSES (LGBT_NUM_HOSTS); 1 = off.
    # Distinct from num_machines, which is the loader-side row-shard count
    # (`io/distributed.py`) — a 2-host pod normally runs num_hosts=2 with
    # the dataset replicated or num_machines=2 with mod-partitioned shards.
    num_hosts: int = 1
    # this process's rank in [0, num_hosts); -1 = from LGBT_PROCESS_ID
    process_id: int = -1
    # --- elastic pod training (lightgbm_tpu/elastic/) ---
    # supervise the pod with the shrink-and-continue controller: a rank
    # death mid-training re-forms membership over the survivors, re-deals
    # the dead rank's rows via the from_stream loader, and resumes from
    # the last snapshot — no operator action.  Only from_stream (two_round)
    # data sources can re-deal; in-memory Datasets cannot
    elastic: bool = False
    # recovery budget: terminal failure after this many shrinks
    elastic_max_recoveries: int = 3
    # terminal structured failure when the survivor count drops below this
    elastic_min_ranks: int = 1
    # membership generation counter (INTERNAL — stamped by the controller
    # into each epoch's worker config; 0 = the original membership)
    elastic_epoch: int = 0
    # per-epoch coordinator port = elastic_port_base + epoch (each epoch
    # is a fresh jax.distributed cluster); 0 = derive from the port in
    # coordinator_address
    elastic_port_base: int = 0
    # --- reliability (lightgbm_tpu/reliability/) ---
    # hard cap on a single SocketNet/serving wire frame: a corrupt length
    # prefix fails with a ConnectionError instead of a multi-GB allocation
    net_max_frame_mb: int = 256
    # per-collective deadline for the construction-phase SocketNet
    # (seconds; 0 = use time_out).  A rank that cannot produce its payload
    # in time fails the collective on EVERY rank with the late rank named
    net_collective_deadline_s: float = 0.0
    # deterministic fault-injection plan (reliability/faults.py grammar),
    # e.g. "net.send.drop:rank=1;serve.predict.fail:count=-1".  Also
    # armable via the LGBT_FAULTS environment variable.  Empty = off
    fault_spec: str = ""

    # --- device (tpu-specific; gpu_* accepted for compat and ignored) ---
    gpu_platform_id: int = -1
    gpu_device_id: int = -1
    gpu_use_dp: bool = False
    # TPU additions
    tpu_row_block: int = 1024
    tpu_hist_dtype: str = "float32"
    tpu_double_precision: bool = False  # use f64 split accounting (CPU testing)
    # tree-build strategy: "compact" keeps rows permuted so each leaf's rows
    # are contiguous (O(N log L) row-visits/tree); "masked" builds every
    # histogram with a full-data masked pass (O(N L), kept as the reference
    # implementation / fallback); "auto" = compact
    tpu_learner: str = "auto"
    tpu_min_window: int = 2048  # smallest compacted histogram window
    # wave-histogram double buffering (tree_learner=data_feature): the W
    # member histograms accumulate in this many independent groups, each
    # with its own reduce-scatter, so the collective of one group overlaps
    # the next group's compute; 1 = single exchange per wave (round-6 flow)
    tpu_wave_hist_buffers: int = 2
    # packed-histogram MXU precision: "bf16x3" (default; ~24 weight
    # mantissa bits — accuracy/ACCURACY.md measured it AUC-identical to
    # full-f32 on the real chip and the merged-dot kernel makes the third
    # term free), "bf16x2" (~16 bits), or "highest" (full f32 emulation)
    # for validation runs
    tpu_hist_precision: str = "bf16x3"
    # windows at or below this size stop physically compacting (mask-mode
    # partitions): small bitonic sorts are pure stage latency on TPU
    tpu_sort_cutoff: int = 2048
    # frontier-wave learner: split up to this many leaves per batched wave
    # (partition/histogram/scan amortized across the wave; an exact greedy
    # replay trims the speculative forest back to best-first semantics)
    tpu_wave_width: int = 64
    # byte budget for the wave learner's working set (histogram pool,
    # per-wave child histograms, wave-mask transients, sort buffers);
    # configs that exceed it fall back to the sequential compact learner
    tpu_wave_max_bytes: int = 1 << 32
    # speculative growth overshoot as a fraction of (num_leaves - 1):
    # extra bottom waves pre-split the leaves the exact greedy replay will
    # want, trading extra waves (full-array passes, ∝N) for replay
    # stalls.  With batched mask-mode stall corrections (stall_batch > 1,
    # the default) stalls are cheap enough that 0 wins at every measured
    # scale (v5e round 5: 9.28 vs 8.05 it/s at 1M, 0.854 vs 0.770 at
    # 10.5M); -1 = auto: 0.0 when stall_batch > 1, else the round-4
    # scale-dependent optimum (0.7 up to 2M local rows, 0.25 above)
    tpu_wave_overshoot: float = -1.0
    # wave members whose window is at or below this size split in place
    # (lid-lane rewrite, children share the parent span) instead of joining
    # the global re-compaction sort; a wave with no sortable member skips
    # the sort entirely — the sort is the wave learner's top cost and the
    # tree's bottom waves are all small windows
    tpu_wave_sort_cutoff: int = 8192
    # level-wise OPENING: the first L tree levels grow with NO row sorting
    # (rows stay in root order; one multi-slot full-pass histogram kernel
    # serves each level), then a single materialization sort compacts all
    # windows at once.  MEASURED A NET LOSS on v5e (the full-array pass
    # floors at the one-hot cost regardless of member count — see
    # learner_wave.py and profiling/PROFILE.md), so -1 = auto = DISABLED;
    # set an explicit L > 0 to force it (exactness tests do)
    tpu_wave_open_levels: int = -1
    # defer the wave re-compaction sort on alternating waves: a deferring
    # wave assigns logical child windows + sort keys only (member
    # histograms scan the member's materialized span with lid masks, ~2x
    # the child window area); the next wave's single sort materializes
    # both levels.  Halves the number of full-array sorts — the wave
    # learner's largest per-wave cost (~6 ms each on v5e at 1M rows)
    tpu_wave_defer_sorts: bool = True
    # --- observability ---
    # structured training telemetry (observability/): host phase timers,
    # per-tree device counters (waves, sorts, stall/extras, pops) decoded
    # from the async record flush, and collective accounting for the
    # sharded learners.  Off by default — the disabled path traces the
    # exact same jaxpr as a build without telemetry
    telemetry: bool = False
    # write the JSON telemetry report (observability/schema.json) to this
    # path when training finishes (engine.train / the CLI --telemetry-out)
    telemetry_out: str = ""
    # when set, wrap training in jax.profiler.start_trace/stop_trace with
    # this output directory — real per-op device timings over the tunnel
    # (profiling/PROFILE.md); independent of the counter layer above
    profile_trace_dir: str = ""
    # write a Chrome trace-event JSON of the host-side structured spans
    # (observability/trace.py — open in Perfetto / chrome://tracing).
    # Training: spans ride the existing phase timers, so trace_out implies
    # telemetry=True; written when engine.train returns.  Serving
    # (task=serve): per-request/batch/stage spans linked by trace_id,
    # written at server stop.  Host-only + monotonic clocks: the traced
    # XLA programs are untouched (jaxprs byte-identical with tracing off)
    trace_out: str = ""
    # span ring-buffer capacity: a long-lived server overwrites its
    # oldest spans past this instead of growing without bound
    trace_capacity: int = 65536
    # sampled-sync attribution (observability/attribution.py): every Nth
    # iteration the boosting loop drains the dispatch queue and brackets
    # each leg of the jitted step (gradients / tree build / score update
    # / exchange probe) with a forced device sync, landing the per-leg
    # "sync.*" phases the report's distributed.attribution table is built
    # from.  0 (default) = never sync — the pipeline stays fully async.
    # Requires telemetry; ignored otherwise
    telemetry_sync_every: int = 0
    # straggler detection on a multi-host pod: per-rank step timings ride
    # the liveness heartbeat, and when max/median exceeds this ratio a
    # warning names the slowest rank (gauges land regardless).  <= 0
    # disables the warning
    telemetry_skew_warn_ratio: float = 2.0
    # write the lgbt_training_* Prometheus text exposition
    # (observability/metrics_export.py training_prometheus) here when
    # training finishes — the scrape-file analogue of telemetry_out
    telemetry_prom_out: str = ""
    # dev/test knob: override the batched replay correction's vectorized
    # span cap (_VEC_CAP, default 2^17 rows).  Tests shrink it so the
    # replicated span gate is exercised at CI problem sizes
    tpu_wave_vec_cap: int = -1
    # --- serving (lightgbm_tpu/serving/) ---
    # `task=serve` / `python -m lightgbm_tpu serve`: bind address and port
    # (0 = ephemeral, the bound port is logged at startup)
    serve_host: str = "127.0.0.1"
    serve_port: int = 12500
    # micro-batch row budget; requests coalesce up to this many rows and
    # pad to power-of-two buckets so every shape hits a warm jit cache
    serve_max_batch_rows: int = 1024
    # how long the batcher waits for more requests after the first arrives
    serve_deadline_ms: float = 2.0
    # smallest padded row bucket (the floor of the power-of-two ladder)
    serve_min_bucket: int = 32
    # compile every bucket shape at startup so the request path never
    # recompiles; disable only for debugging
    serve_warmup: bool = True
    # bounded admission: at most this many predict requests between
    # admission and response; the rest shed with a structured
    # {"error": "overloaded"} frame (reliability/degrade.py)
    serve_max_inflight: int = 64
    # per-tenant admission caps (fleet gateway): at most this many
    # in-flight requests PER model name, so one hot tenant saturates its
    # own cap and sheds while the rest keep admitting under the global
    # bound.  0 = derive from serve_max_inflight (a single tenant may
    # use the whole capacity — isolation is opt-in)
    serve_tenant_max_inflight: int = 0
    # periodic operator-pollable stats snapshots: every
    # serve_stats_interval seconds the full schema-validated telemetry
    # report is written atomically (tmp + os.replace) to serve_stats_out,
    # so operators poll a file instead of holding a socket op open
    # (aliases: stats_out / stats_interval)
    serve_stats_out: str = ""
    serve_stats_interval: float = 10.0
    # replica fleet (lightgbm_tpu/serving/fleet/): 0 = the legacy
    # single-replica threaded server; -1 = one replica per local device
    # (the production default for fleet serving); N>0 = exactly N
    # replicas round-robined over the local devices.  Any non-zero value
    # serves through the async binary-protocol gateway (FleetServer)
    serve_replicas: int = 0
    # ejection cooldown: a replica whose device path failed is excluded
    # from dispatch for this many seconds, then probed again
    serve_recovery_s: float = 1.0
    # per-tenant SLO: every model name's requests are judged against
    # this latency target; the `serving.tenants[]` report section and
    # the lgbt_serving_tenant_* Prometheus series carry attainment
    # (fraction of requests at or under the target) and error-budget
    # burn ((1 - attainment) / (1 - serve_slo_target))
    serve_slo_p99_ms: float = 50.0
    serve_slo_target: float = 0.99
    # drift detection thresholds (observability/drift.py, fleet serving
    # with lifecycle_record_rows > 0): a feature or the score
    # distribution is "drifted" when its PSI reaches drift_psi_threshold
    # or its two-sample KS statistic reaches drift_ks_threshold with
    # p < 0.05 against the baseline captured at promote time
    drift_psi_threshold: float = 0.2
    drift_ks_threshold: float = 0.15
    # persist captured drift baselines (atomic tmp + os.replace) so a
    # gateway restart resumes drift detection instead of silently
    # disabling it until the next promotion.  "" = derive from
    # input_model (<input_model>.drift_baselines.json) when recording is
    # on; "off" disables persistence
    drift_baseline_path: str = ""
    # --- autopilot (lightgbm_tpu/lifecycle/autopilot.py) ---
    # drift-triggered refit daemon for fleet serving (task=serve with
    # serve_replicas != 0, lifecycle_record_rows > 0 and data= pointing
    # at the original train source).  Checks the drift verdict every
    # autopilot_interval_s; autopilot_consecutive_checks consecutive
    # drifted verdicts over fresh traffic trigger a refit cycle
    # (continued training from the incumbent, shadow-validated,
    # per-replica gated rolling upgrade) under the RefitBudget caps
    autopilot: bool = False
    autopilot_interval_s: float = 30.0
    autopilot_consecutive_checks: int = 3
    autopilot_num_boost_round: int = 10
    # RefitBudget (lifecycle/budget.py): at most autopilot_max_refits
    # refit starts per rolling autopilot_window_s, at least
    # autopilot_min_spacing_s between starts, and a
    # autopilot_cooldown_s freeze after any rollback
    autopilot_max_refits: int = 4
    autopilot_window_s: float = 3600.0
    autopilot_min_spacing_s: float = 60.0
    autopilot_cooldown_s: float = 300.0
    # --- lifecycle (lightgbm_tpu/lifecycle/) ---
    # bounded live-traffic ring in the serving server: the newest this
    # many request feature rows are retained for the lifecycle shadow
    # replay (0 = recording off; memory is capacity x features x 8B)
    lifecycle_record_rows: int = 0
    # shadow metric floor gate: metric name ("auc", "l2",
    # "binary_logloss"; "" = gate off) and the floor the CANDIDATE must
    # clear on labeled shadow data (NaN = gate off)
    lifecycle_metric: str = ""
    lifecycle_metric_floor: float = float("nan")
    # shadow divergence ceiling: mean |candidate - incumbent| over the
    # replayed predictions (output space) must stay under this
    lifecycle_divergence_max: float = 0.25
    # shadow latency ceiling: candidate per-batch p50 may be at most this
    # multiple of the incumbent's p50 from the same replay
    lifecycle_latency_max_ratio: float = 4.0
    # smallest recording the shadow gates accept (fewer rows = reject:
    # an unjudgeable candidate is not a promotable candidate)
    lifecycle_min_shadow_rows: int = 1
    # post-promotion circuit breaker: watch serving health for this many
    # seconds, sampling every watch_interval; breaching the error/
    # fallback rate (error_rate_max, per request/batch) or the shed rate
    # (shed_rate_max, per offered request) auto-rolls-back to the
    # retained incumbent
    lifecycle_rollback_deadline_s: float = 30.0
    lifecycle_watch_interval_s: float = 0.5
    lifecycle_error_rate_max: float = 0.05
    lifecycle_shed_rate_max: float = 0.5
    # replay stall correction batch: when the exact greedy replay reaches
    # a leaf the speculative growth never split, split up to this many of
    # the highest-priority unsplit frontier leaves in ONE correction pass
    # (one batched bookkeeping/scan, one sim re-entry) instead of one
    # re-entry per miss.  Extra members are speculative the same way the
    # growth overshoot is — the replay pops exactly (num_leaves - 1)
    # splits regardless — and the slot/pool sizing already reserves
    # (num_leaves - 1) correction splits, so a guard stops batching near
    # that reserve.  1 = the round-4 one-miss-per-pass behavior;
    # -1 = auto (currently 4 at every scale — the round-5 sweep winner;
    # re-sweep {2,3,4,6} rides profiling/profile_stall_batch.py)
    tpu_wave_stall_batch: int = -1
    # fuse the batched replay correction's TOP member into the
    # span-vectorized partition stage whenever its covering span fits the
    # vec cap: a stall event then runs ONE masked pass (one switch
    # dispatch) instead of top-switch + extras-switch.  Exact — both
    # stages share _span_decide; False = the round-5 two-stage flow
    tpu_wave_stall_fuse_top: bool = True
    # Pallas stable row-partition kernel (ops/partition_pallas.py): the
    # wave learner's full-array re-compaction sort becomes a two-pass
    # stable partition (exact destinations from prefix sums + a chunked
    # byte-plane permute kernel), the port of the reference's OpenCL
    # data-partition kernel.  "auto" = on whenever the Pallas histogram
    # path runs and the shape gates pass (record-exact vs the sort path);
    # "on" forces it (interpret mode off-TPU — tests); "off" keeps the
    # round-5 sort flow.  Partition mode disables sort-deferral (each
    # wave partitions its own windows; a partition pass is cheap enough
    # that halving pass count no longer pays for the deferred waves'
    # double-area member histograms)
    tpu_wave_pallas_partition: str = "auto"
    # Pallas fused split-scan kernel (ops/scan_pallas.py): the
    # (leaves x features x bins) best-split search — cumulative
    # histograms, gain evaluation, validity masks, per-feature argmax —
    # runs as ONE kernel instead of the XLA scan+argmax chain, the port
    # of the reference's OpenCL split-scan kernel.  "auto" = on alongside
    # the Pallas histogram path for plain numerical splits (no monotone
    # constraints / categorical features / feature penalties); "on"
    # forces it (interpret off-TPU); "off" = the XLA path
    tpu_wave_pallas_scan: str = "auto"
    # quantized-gradient training (ops/quant.py — the LightGBM
    # "Quantized Training of GBDT" recipe, NeurIPS 2022): per-round int8
    # gradient / int16 hessian discretization with stochastic rounding
    # and power-of-two scales; histograms carry dequantized lanes (exact
    # in bf16, halving the Pallas expansion work), the sharded learners'
    # hist exchange packs to int16 words (<= half the f32 payload), split
    # gains rescale at scan time and leaf outputs are renewed from the
    # retained f32 gradients.  The count channel becomes a Sigma-hq
    # hessian-mass proxy, so min_data_in_leaf gates approximately —
    # split STRUCTURE may differ from the f32 path on ties.  "on" =
    # enable where eligible (ops/quant.py:quant_ineligible_reason);
    # "auto" = currently OFF pending the on-hardware sweep (ROADMAP
    # item 1; BENCH_r08 records the CPU evidence); "off" = never
    tpu_quantized_grad: str = "auto"
    # cross-iteration buffer donation: gradient/hessian inputs enter the
    # per-tree program with jax.jit donate_argnums, so iteration N+1
    # reuses iteration N's HBM instead of fresh allocations (the score
    # array already donates through _score_add_leaf).  Trees are
    # bit-identical either way.  "auto" = on-TPU only (CPU gains nothing
    # and donation muddies buffer inspection when debugging); "on"/"off"
    # force it
    tpu_donate_buffers: str = "auto"
    # pipelined flush depth: a queued iteration's host tree is assembled
    # once it is this many iterations old (device execution has long
    # finished), so host assembly overlaps device compute instead of
    # draining the whole 16-deep queue in one device-idle stall;
    # 0 = the round-5 batch flush (assemble 16 at once)
    tpu_pipeline_flush_depth: int = 8
    # vectorized host tree assembly (learner.assemble_host): one numpy
    # pass over the record batch instead of ~20 scalar numpy ops per
    # split (15-25 ms/tree inside every pipeline flush — round-5 trace).
    # Trees with categorical splits keep the sequential path (bitset
    # bookkeeping is order-dependent); False = always sequential
    tpu_vec_assemble: bool = True

    # derived (not user-settable)
    is_parallel: bool = field(default=False, repr=False)
    is_parallel_find_bin: bool = field(default=False, repr=False)

    _FIELD_TYPES: "Dict[str, Any]" = field(default=None, repr=False, compare=False)

    # -- construction -------------------------------------------------------

    @classmethod
    def from_params(cls, params: Optional[Dict[str, Any]] = None, **kw) -> "Config":
        cfg = cls()
        merged = dict(params or {})
        merged.update(kw)
        cfg.update(merged)
        return cfg

    def update(self, params: Dict[str, Any]) -> "Config":
        resolved = resolve_aliases(params)
        valid_fields = {f.name: f for f in dataclasses.fields(self)}
        for key, val in resolved.items():
            if key in ("is_parallel", "is_parallel_find_bin", "_FIELD_TYPES"):
                continue
            if key not in valid_fields:
                # The reference warns on unknown params (`c_api.cpp` passthrough)
                warnings.warn(f"Unknown parameter: {key}")
                continue
            setattr(self, key, _coerce(valid_fields[key].type, val, key))
        self._finalize()
        return self

    # -- Config::Set cross-field fixups (`src/io/config.cpp:153-256`) -------

    def _finalize(self) -> None:
        self.objective = _OBJECTIVE_ALIASES.get(self.objective, self.objective)
        self.boosting = _BOOSTING_ALIASES.get(self.boosting, self.boosting)
        if self.objective == "random_forest":
            self.objective = "regression"
            self.boosting = "rf"
        # tree_learner → is_parallel (`config.cpp:221-240`)
        tl = self.tree_learner
        tl = {"serial": "serial", "feature": "feature", "feature_parallel": "feature",
              "data": "data", "data_parallel": "data",
              "voting": "voting", "voting_parallel": "voting",
              "data_feature": "data_feature", "hybrid": "data_feature",
              "data_feature_parallel": "data_feature"}.get(tl, tl)
        self.tree_learner = tl
        self.is_parallel = tl in ("feature", "data", "voting",
                                  "data_feature") and self.num_machines > 1
        self.is_parallel_find_bin = tl in ("data", "data_feature") \
            and self.num_machines > 1
        if self.is_unbalance and abs(self.scale_pos_weight - 1.0) > 1e-6:
            raise ValueError(
                "Cannot set is_unbalance and scale_pos_weight at the same time")
        # default metric from objective (reference: metric.cpp factory behavior)
        if not self.metric:
            self.metric = [_default_metric(self.objective)]
        if self.num_class > 1 and self.objective not in (
                "multiclass", "multiclassova", "none", "custom", ""):
            if self.objective not in ("multiclass", "multiclassova"):
                # reference raises for num_class>1 with non-multiclass objective
                pass
        if self.objective in ("multiclass", "multiclassova") and self.num_class <= 1:
            raise ValueError("Number of classes should be specified and greater"
                             " than 1 for multiclass training")
        if self.bagging_fraction < 1.0 and self.bagging_freq == 0:
            # bagging only active when bagging_freq > 0 (`gbdt.cpp:689` semantics)
            pass

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d.pop("_FIELD_TYPES", None)
        return d


def _default_metric(objective: str) -> str:
    return {
        "regression": "l2", "regression_l1": "l1", "huber": "huber",
        "fair": "fair", "poisson": "poisson", "quantile": "quantile",
        "mape": "mape", "gamma": "gamma", "tweedie": "tweedie",
        "binary": "binary_logloss",
        "multiclass": "multi_logloss", "multiclassova": "multi_logloss",
        "lambdarank": "ndcg",
        "cross_entropy": "cross_entropy", "cross_entropy_lambda": "cross_entropy_lambda",
    }.get(objective, "l2")


def _coerce(ftype: Any, val: Any, key: str) -> Any:
    t = str(ftype)
    if "List[int]" in t:
        return _parse_int_list(val)
    if "List[float]" in t:
        return _parse_float_list(val)
    if "List[str]" in t:
        return _parse_str_list(val)
    if "bool" in t:
        return _parse_bool(val)
    if "int" in t:
        return int(float(val)) if not isinstance(val, bool) else int(val)
    if "float" in t:
        return float(val)
    return str(val)


def resolve_aliases(params: Dict[str, Any]) -> Dict[str, Any]:
    """Alias→canonical key transform; first-wins on duplicates with warning
    (`src/io/config.cpp:22-43`)."""
    out: Dict[str, Any] = {}
    for key, val in params.items():
        canon = ALIAS_TABLE.get(key, key)
        if canon in out:
            warnings.warn(f"{key} is set with {out[canon]}, will be overridden by"
                          f" {val}. Current value: {canon}={out[canon]}")
            continue
        out[canon] = val
    return out


def parse_config_file(path: str) -> Dict[str, str]:
    """Parse ``key=value`` config files (``Config::KV2Map``,
    `src/io/config.cpp:15-43`): ``#`` comments, whitespace-tolerant."""
    out: Dict[str, str] = {}
    with open(path) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            k, v = k.strip(), v.strip().strip('"').strip("'")
            if k:
                out[k] = v
    return out


def parse_parameter_string(s: str) -> Dict[str, str]:
    """Parse space/newline separated ``key=value`` pairs (``Str2Map``)."""
    out: Dict[str, str] = {}
    for tok in s.replace("\n", " ").split(" "):
        tok = tok.strip()
        if not tok or "=" not in tok:
            continue
        k, v = tok.split("=", 1)
        out[k.strip()] = v.strip()
    return out
